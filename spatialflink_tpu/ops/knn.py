"""k-nearest-neighbor window kernels with object-id dedup.

Reference semantics (``knn/PointPointKNNQuery.java:138-191`` +
``knn/KNNQuery.java:204-300``): the radius r selects the neighboring-cell set
(GN ∪ CN) but the exact distance is NOT radius-filtered in windowed mode; the
per-cell windows keep a k-element max-heap, and the global ``windowAll`` merge
deduplicates by objID keeping the *minimum* distance per object.

TPU re-design: instead of per-cell heaps + a parallelism-1 merge, we compute
all masked distances in one shot, deduplicate by objID with a lexicographic
sort (sort by (objID, dist); the first row of each objID run carries its min
distance), then take a single ``lax.top_k``. The same kernel runs per shard
under shard_map, with partial top-k results merged by all-gather + re-top-k
(see spatialflink_tpu.parallel) — that kills the reference's windowAll
bottleneck.

The trajectory variant (tKnn) *does* enforce the exact radius
(``tKnn/PointPointTKNNQuery.java:95-111``); pass ``enforce_radius=True``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from spatialflink_tpu.models.batches import PointBatch
from spatialflink_tpu.utils.deviceplane import instrumented_jit
from spatialflink_tpu.ops import distances as D
from spatialflink_tpu.ops.range import cheb_layers

_BIG = np.float32(3.4e38)
_OID_SENTINEL = np.int32(2**31 - 1)


class KnnResult(NamedTuple):
    obj_id: jnp.ndarray  # (k,) i32; sentinel 2^31-1 in empty slots
    dist: jnp.ndarray    # (k,) f32; +BIG in empty slots
    valid: jnp.ndarray   # (k,) bool


def dedup_min_by_id(obj_id, dist, eligible):
    """Per-object minimum distance via one lexicographic sort (last axis).

    Returns (obj_id_sorted, dist_sorted, keep) where ``keep`` marks the first
    occurrence of each object id (which, after an ascending (id, dist) sort,
    carries that object's min distance). Ineligible rows get a sentinel id so
    they sort to the back and are never kept. Works on 1-D windows and on
    batched (..., C) group layouts alike.
    """
    oid = jnp.where(eligible, obj_id, _OID_SENTINEL)
    d = jnp.where(eligible, dist, _BIG)
    axis = oid.ndim - 1
    oid_s, d_s = jax.lax.sort((oid, d), dimension=axis, num_keys=2)
    pad_shape = oid_s.shape[:-1] + (1,)
    prev = jnp.concatenate(
        [jnp.full(pad_shape, _OID_SENTINEL, oid_s.dtype),
         jax.lax.slice_in_dim(oid_s, 0, oid_s.shape[-1] - 1, axis=axis)],
        axis=axis)
    keep = (oid_s != prev) & (oid_s != _OID_SENTINEL)
    # the sentinel prev-filler can only collide with sentinel rows, which the
    # second conjunct already drops, so the first slot is always kept.
    return oid_s, d_s, keep


def _topk_full_sort(obj_id, dist, eligible, k: int) -> KnnResult:
    """Reference algorithm: full lexicographic sort dedup then top-k. Exact
    for any input, but the O(N log^2 N) bitonic sort dominates on TPU for
    large windows — prefer the grouped/prefiltered paths below there.

    Result is always (k,): when the input holds fewer than k slots (small
    geometry shards, tiny windows) the selection clamps to the input size
    and pads with sentinels — ``lax.top_k`` would otherwise reject
    k > input length at trace time."""
    kk = min(k, obj_id.shape[0])
    oid_s, d_s, keep = dedup_min_by_id(obj_id, dist, eligible)
    d_masked = jnp.where(keep, d_s, _BIG)
    neg_top, idx = jax.lax.top_k(-d_masked, kk)
    top_d = -neg_top
    top_oid = jnp.where(top_d < _BIG, oid_s[idx], _OID_SENTINEL)
    if kk < k:
        pad = k - kk
        top_d = jnp.concatenate([top_d, jnp.full((pad,), _BIG, top_d.dtype)])
        top_oid = jnp.concatenate(
            [top_oid, jnp.full((pad,), _OID_SENTINEL, top_oid.dtype)])
    return KnnResult(obj_id=top_oid, dist=top_d, valid=top_d < _BIG)


def _topk_grouped(obj_id, dist, eligible, k: int, groups: int) -> KnnResult:
    """Exact dedup+top-k via per-group sorts (TPU fast path).

    Reshape the window to (G, N/G), sort each group by (objID, dist), keep
    each group's per-object minima, take the group-local top-k, then run the
    small full-sort path over the G*k survivors.

    Exactness: a final top-k object's global-min point lies in some group; if
    it is not among that group's top-k *distinct* minima, then k distinct
    objects in that group alone have smaller minima, so the global top-k is
    covered by that group's survivors either way. Per-group bitonic sorts are
    O(C log^2 C) with C = N/G — asymptotically and practically cheaper than
    one N-wide sort, and XLA parallelizes the group dimension.
    """
    n = obj_id.shape[0]
    g = max(1, min(groups, n // max(k, 1)))
    c = -(-n // g)  # ceil div
    pad = g * c - n
    oid = jnp.where(eligible, obj_id, _OID_SENTINEL)
    d = jnp.where(eligible, dist, _BIG)
    if pad:
        oid = jnp.concatenate([oid, jnp.full((pad,), _OID_SENTINEL, oid.dtype)])
        d = jnp.concatenate([d, jnp.full((pad,), _BIG, d.dtype)])
    oid_s, d_s, keep = dedup_min_by_id(
        oid.reshape(g, c), d.reshape(g, c), jnp.bool_(True))
    d_masked = jnp.where(keep, d_s, _BIG)
    kk = min(k, c)
    neg_top, idx = jax.lax.top_k(-d_masked, kk)  # batched over groups
    cand_d = (-neg_top).reshape(-1)
    cand_oid = jnp.take_along_axis(oid_s, idx, axis=1).reshape(-1)
    return _topk_full_sort(cand_oid, cand_d, cand_d < _BIG, k)


def _prefilter_fast(obj_id, dist, eligible, k: int, m: int):
    """Prefilter fast path WITHOUT the rescue branch: -> (fast, exact).

    ``lax.top_k(m)`` selects the m smallest distances (duplicates included),
    then a tiny dedup+top-k runs over those m. ``exact`` certifies the fast
    result: at least k distinct objects among the m candidates — or all
    eligible points captured — proves no excluded object can enter the top-k
    (any excluded object's min distance exceeds every candidate's, hence
    exceeds k distinct objects' minima). Split out cond-free so the
    multi-query path can vmap it and rescue with ONE scalar cond (a vmapped
    ``lax.cond`` lowers to ``select`` and would pay the fallback always).
    """
    n = obj_id.shape[0]
    m = min(m, n)
    d_all = jnp.where(eligible, dist, _BIG)
    oid_all = jnp.where(eligible, obj_id, _OID_SENTINEL)
    neg_m, idx = jax.lax.top_k(-d_all, m)
    d_m = -neg_m
    oid_m = oid_all[idx]
    fast = _topk_full_sort(oid_m, d_m, d_m < _BIG, k)
    distinct = jnp.sum(fast.valid)
    n_eligible = jnp.sum(eligible)
    exact = (distinct >= jnp.minimum(k, n_eligible)) | (n_eligible <= m)
    return fast, exact


def _topk_prefiltered(obj_id, dist, eligible, k: int, m: int) -> KnnResult:
    """Exact top-k via the global m-candidate prefilter with verified
    fallback: when the certificate fails, a ``lax.cond`` falls back to the
    full-sort path; with m >> k that branch needs one object to own m-k+1 of
    the m nearest points, which real streams do not do."""
    fast, exact = _prefilter_fast(obj_id, dist, eligible, k, m)
    return jax.lax.cond(
        exact,
        lambda: fast,
        lambda: _topk_full_sort(obj_id, dist, eligible, k),
    )


def _topk_approx_verified(obj_id, dist, eligible, k: int, m: int) -> KnnResult:
    """EXACT top-k riding the TPU-native partial-reduce selection.

    ``lax.approx_min_k`` (the PartialReduce op, near-HBM-bandwidth on TPU)
    proposes m candidates; a tiny dedup+top-k runs over them; then a one-pass
    exactness certificate decides whether to keep the fast result or fall
    back to the provably exact full sort:

    - with >= k distinct candidate objects and T = the kth distinct min, the
      result is exact iff every eligible point at distance <= T is among the
      candidates (a missed point below T would belong to some object whose
      true min beats the kth result; conversely if none is missed the
      candidate set contains every point that could influence the top-k) —
      checked by comparing element counts at threshold T over the full
      window vs over the candidates (ties at exactly T conservatively force
      the fallback);
    - with < k distinct candidates, exact iff EVERY eligible point is a
      candidate.

    The certificate costs one fused elementwise reduction over the window —
    bandwidth-bound, like the distance computation itself. With m >> k the
    fallback fires only on adversarial distributions; recall misses cost a
    recompute, never a wrong answer.
    """
    fast, exact = _approx_verified_fast(obj_id, dist, eligible, k, m)
    return jax.lax.cond(
        exact,
        lambda: fast,
        lambda: _topk_full_sort(obj_id, dist, eligible, k),
    )


def _approx_verified_fast(obj_id, dist, eligible, k: int, m: int):
    """approx_verified fast path WITHOUT the rescue branch: -> (fast, exact).
    Cond-free for the same multi-query reason as :func:`_prefilter_fast`."""
    d_all, d_m, oid_m = _approx_candidates(obj_id, dist, eligible, m)
    fast = _topk_full_sort(oid_m, d_m, d_m < _BIG, k)
    distinct = jnp.sum(fast.valid)
    t = jnp.max(jnp.where(fast.valid, fast.dist, -_BIG))
    cnt_all = jnp.sum(d_all <= t)
    cnt_cand = jnp.sum(d_m <= t)
    n_elig = jnp.sum(eligible)
    cand_elig = jnp.sum(d_m < _BIG)
    exact = ((distinct >= k) & (cnt_all == cnt_cand)) | (cand_elig == n_elig)
    return fast, exact


def _approx_candidates(obj_id, dist, eligible, m: int):
    """Shared approx_min_k prologue: (d_all, candidate dists, candidate ids)
    with ineligible slots sentineled out."""
    m = min(m, obj_id.shape[0])
    d_all = jnp.where(eligible, dist, _BIG)
    oid_all = jnp.where(eligible, obj_id, _OID_SENTINEL)
    d_m, idx = jax.lax.approx_min_k(d_all, m)
    return d_all, d_m, oid_all[idx]


def _topk_approx(obj_id, dist, eligible, k: int, m: int) -> KnnResult:
    """Approximate-mode selection via the TPU-native partial-reduce top-k.

    ``lax.approx_min_k`` maps onto the TPU's PartialReduce op and runs at
    near-HBM-bandwidth, unlike the bitonic networks behind ``sort``/``top_k``.
    It selects ~m smallest distances at its default recall target, then the
    tiny exact dedup+top-k runs over those candidates. Results can miss an
    object whose only near point was dropped by the partial reduce — matching
    the framework's approximate query mode, which already trades exactness
    for speed (bbox distances); not for exact-mode pipelines.
    """
    _d_all, d_m, oid_m = _approx_candidates(obj_id, dist, eligible, m)
    return _topk_full_sort(oid_m, d_m, d_m < _BIG, k)


# Below this window size the full sort is cheap enough that the grouped
# path's extra stages don't pay for themselves.
_GROUPED_MIN_N = 1 << 15
_DEFAULT_GROUPS = 256


def _resolve_auto(n: int) -> str:
    """Measured per-backend "auto" choice, shared by the single- and
    multi-query entries so they cannot drift."""
    if n < _GROUPED_MIN_N:
        return "sort"
    if jax.default_backend() == "cpu":
        # measured (benchmarks/sweep_knn.py): CPU top_k is a linear-time
        # partial selection, so the m-candidate prefilter beats every
        # sort-based path by ~30-50x at 1M points
        return "prefilter"
    # measured on TPU v5e (benchmarks/sweep_knn.py, 1M pts, k=50):
    # approx_min_k lowers to the PartialReduce op and runs the window
    # at ~46us vs ~1.2ms for grouped/prefilter (top_k and sort both
    # lower to bitonic networks there) — 21.5G pts/s, exact via the
    # certificate + full-sort fallback
    return "approx_verified"


def topk_by_distance(obj_id, dist, eligible, k: int,
                     strategy: str = "auto") -> KnnResult:
    """Dedup by object id (keep min dist) then top-k smallest distances.

    strategy: "auto" (full sort for small windows; for large ones the
    measured per-backend winner — prefilter on CPU, approx_verified on TPU),
    "sort", "grouped", "prefilter", "approx_verified" (all exact), or
    "approx" (recall<1, approximate-mode only).
    """
    n = obj_id.shape[0]
    if strategy == "auto":
        strategy = _resolve_auto(n)
    if strategy == "grouped":
        return _topk_grouped(obj_id, dist, eligible, k, _DEFAULT_GROUPS)
    if strategy == "prefilter":
        # m = 8k keeps the exactness fallback (< k distinct among the m
        # nearest) vanishingly rare while minimizing the partial-selection
        # cost (benchmarks/sweep_knn.py: smaller m wins monotonically)
        return _topk_prefiltered(obj_id, dist, eligible, k, max(8 * k, 256))
    if strategy == "approx_verified":
        # m >> k keeps both the recall misses and the <k-distinct case rare,
        # so the certificate almost never triggers the full-sort fallback;
        # cost is monotone in m on TPU (sweep: m=16k beats 32k beats 64k),
        # so use the smallest m with comfortable distinct-object headroom
        return _topk_approx_verified(obj_id, dist, eligible, k,
                                     max(16 * k, 512))
    if strategy == "approx":
        return _topk_approx(obj_id, dist, eligible, k, max(32 * k, 1024))
    if strategy != "sort":
        raise ValueError(f"unknown kNN strategy {strategy!r}; expected "
                         "auto|sort|grouped|prefilter|approx_verified|approx")
    return _topk_full_sort(obj_id, dist, eligible, k)


def topk_by_distance_multi(obj_id, dist, eligible, k: int,
                           strategy: str = "auto") -> KnnResult:
    """Batched dedup+top-k: ``dist``/``eligible`` are (Q, N) over a SHARED
    (N,) ``obj_id`` window; returns a KnnResult with (Q, k) fields — Q
    continuous queries answered in one dispatch.

    No reference analogue: GeoFlink runs one continuous query per job
    (``StreamingJob.java:470`` wires exactly one query object per pipeline),
    so Q queries cost Q Flink jobs re-reading the same stream. Here they are
    one extra array axis over the same resident window.

    Exactness under vmap: the verified strategies' rescue is hoisted OUT of
    the vmap — the cond-free fast paths run batched, and one SCALAR
    ``lax.cond`` over "every query certified exact" re-runs the full sort
    (batched) only when some query's certificate failed, merging per-query
    with ``jnp.where``. A vmapped per-query cond would lower to ``select``
    and execute the O(N log^2 N) fallback unconditionally.
    """
    n = obj_id.shape[-1]
    if strategy == "auto":
        strategy = _resolve_auto(n)
    if strategy in ("sort", "grouped", "approx"):
        fns = {
            "sort": lambda d, e: _topk_full_sort(obj_id, d, e, k),
            "grouped": lambda d, e: _topk_grouped(obj_id, d, e, k,
                                                  _DEFAULT_GROUPS),
            "approx": lambda d, e: _topk_approx(obj_id, d, e, k,
                                                max(32 * k, 1024)),
        }
        return jax.vmap(fns[strategy])(dist, eligible)
    if strategy == "prefilter":
        fast_fn = partial(_prefilter_fast, m=max(8 * k, 256))
    elif strategy == "approx_verified":
        fast_fn = partial(_approx_verified_fast, m=max(16 * k, 512))
    else:
        raise ValueError(f"unknown kNN strategy {strategy!r}; expected "
                         "auto|sort|grouped|prefilter|approx_verified|approx")
    fast, exact = jax.vmap(
        lambda d, e: fast_fn(obj_id, d, e, k))(dist, eligible)

    def rescue():
        full = jax.vmap(lambda d, e: _topk_full_sort(obj_id, d, e, k))(
            dist, eligible)
        pick = lambda a, b: jnp.where(exact[:, None], a, b)  # noqa: E731
        return KnnResult(obj_id=pick(fast.obj_id, full.obj_id),
                         dist=pick(fast.dist, full.dist),
                         valid=pick(fast.valid, full.valid))

    return jax.lax.cond(jnp.all(exact), lambda: fast, rescue)


def _knn_point_parts(points, qx, qy, q_cell, radius, nb_layers, n,
                     enforce_radius):
    """-> (d, eligible, cell_eligible): ``cell_eligible`` is the pre-radius
    candidate set — the slots whose distance was actually evaluated —
    which the radius filter (tKnn semantics) then narrows into ``eligible``."""
    layers = cheb_layers(points.cell, q_cell, n)
    cell_eligible = points.valid & (layers <= nb_layers)
    d = D.pp_dist(points.x, points.y, qx, qy)
    eligible = cell_eligible
    if enforce_radius:
        eligible = eligible & (d <= radius)
    return d, eligible, cell_eligible


@partial(instrumented_jit, static_argnames=("n", "k", "enforce_radius", "strategy"))
def knn_point(
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    nb_layers,
    *,
    n: int,
    k: int,
    enforce_radius: bool = False,
    strategy: str = "auto",
) -> KnnResult:
    """kNN of a query point over a window batch.

    nb_layers: candidate layer count (``UniformGrid.candidate_layers``);
    pass ``n`` (the grid size) to disable cell pruning (radius 0 semantics:
    all cells are neighbors, ``UniformGrid.java:264-266``).
    """
    d, eligible, _ = _knn_point_parts(points, qx, qy, q_cell, radius,
                                      nb_layers, n, enforce_radius)
    return topk_by_distance(points.obj_id, d, eligible, k, strategy)


@partial(instrumented_jit, static_argnames=("n", "k", "enforce_radius", "strategy"))
def knn_point_stats(
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    nb_layers,
    *,
    n: int,
    k: int,
    enforce_radius: bool = False,
    strategy: str = "auto",
):
    """knn_point + the candidate count in the SAME dispatch — every candidate
    costs one distance evaluation (kNN has no GN bypass,
    ``knn/PointPointKNNQuery.java:152-183``), so the count feeds the
    pruning-effectiveness counter (``spatialObjects/Point.java:220-235``)
    without a second kernel launch re-deriving eligibility. The count is the
    PRE-radius candidate set: with ``enforce_radius`` (tKnn semantics) the
    radius filter narrows the result set but the distances were evaluated
    for every cell-eligible slot regardless."""
    d, eligible, cell_eligible = _knn_point_parts(
        points, qx, qy, q_cell, radius, nb_layers, n, enforce_radius)
    res = topk_by_distance(points.obj_id, d, eligible, k, strategy)
    return res, jnp.sum(cell_eligible, dtype=jnp.int32)


@partial(instrumented_jit, static_argnames=("n", "k", "enforce_radius", "strategy"))
def knn_point_multi(
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    nb_layers,
    *,
    n: int,
    k: int,
    enforce_radius: bool = False,
    strategy: str = "auto",
) -> KnnResult:
    """kNN of a (Q,)-batch of query points over ONE window batch in ONE
    dispatch; returns a KnnResult with (Q, k) fields, row q answering query
    q with :func:`knn_point` semantics (same cell pruning, same no-radius
    windowed rule). TPU-native extension with no reference analogue — see
    :func:`topk_by_distance_multi`; the distance/eligibility stage is a
    vmapped :func:`_knn_point_parts`, so XLA fuses all Q queries' masks and
    distances over a single pass of the resident window."""
    def parts(qx_, qy_, qc_):
        d, eligible, _ = _knn_point_parts(points, qx_, qy_, qc_, radius,
                                          nb_layers, n, enforce_radius)
        return d, eligible

    d, eligible = jax.vmap(parts)(qx, qy, q_cell)
    return topk_by_distance_multi(points.obj_id, d, eligible, k, strategy)


@partial(instrumented_jit, static_argnames=("n", "k", "enforce_radius", "strategy"))
def knn_point_multi_stats(
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    nb_layers,
    *,
    n: int,
    k: int,
    enforce_radius: bool = False,
    strategy: str = "auto",
):
    """:func:`knn_point_multi` + per-query candidate counts (Q,) in the SAME
    dispatch — the multi-query analogue of :func:`knn_point_stats`, feeding
    the distance-computation counter (kNN evaluates a distance for every
    cell-eligible slot, per query)."""
    def parts(qx_, qy_, qc_):
        d, eligible, cell_eligible = _knn_point_parts(
            points, qx_, qy_, qc_, radius, nb_layers, n, enforce_radius)
        return d, eligible, jnp.sum(cell_eligible, dtype=jnp.int32)

    d, eligible, evals = jax.vmap(parts)(qx, qy, q_cell)
    res = topk_by_distance_multi(points.obj_id, d, eligible, k, strategy)
    return res, evals


@partial(instrumented_jit, static_argnames=("k", "enforce_radius", "strategy"))
def knn_with_dists(
    obj_id,
    dists,
    nb_mask,
    cell,
    valid,
    radius,
    *,
    k: int,
    enforce_radius: bool = False,
    strategy: str = "auto",
) -> KnnResult:
    """Generic kNN: caller supplies distances (e.g. point->polygon) and a
    dense neighboring-cells mask for the query geometry."""
    eligible = point_stream_eligibility(cell, valid, nb_mask)
    if enforce_radius:
        eligible = eligible & (dists <= radius)
    return topk_by_distance(obj_id, dists, eligible, k, strategy)


def merge_topk_host(parts, k: int, tie_key=None):
    """Host-side merge of per-pane top-k PARTIAL result lists — the pane
    engine's twin of :func:`merge_knn` / ``parallel.ops._gather_topk``
    (concatenate, dedup by id keeping the min distance, re-top-k), operating
    on the already-collected ``[(obj_id, dist), ...]`` lists the operators
    emit instead of device arrays. Exact by the same covering argument as
    the shard merge: a global top-k object's minimum-distance point lies in
    some pane; either it survives that pane's top-k distinct minima, or k
    distinct objects in that pane alone beat it (the argument needs a
    consistent total order, hence the tie rule below). The merge operands
    are tiny (``overlap * k`` tuples), so a dict + sort is the right tool —
    no device dispatch for the merge itself.

    ``tie_key(obj_id)`` MUST reproduce the device tie order for the
    windows to be identical to full recompute: the device top-k breaks
    equal distances by ascending INTERNED id (the post-dedup (oid, dist)
    sort position), so operators pass their interner's ``intern`` —
    falling back to string order would let two objects at the exact same
    distance resolve differently at the k-th place."""
    best: dict = {}
    for part in parts:
        for oid, d in part:
            cur = best.get(oid)
            if cur is None or d < cur:
                best[oid] = d
    tie_key = tie_key if tie_key is not None else str
    out = sorted(best.items(), key=lambda kv: (kv[1], tie_key(kv[0])))[:k]
    return [(oid, d) for oid, d in out]


def merge_knn(results, k: int) -> KnnResult:
    """Merge per-shard/per-window partial KnnResults (the reference's
    ``kNNWinAllEvaluationPointStream`` dedup+merge, without the
    parallelism-1 bottleneck: concatenate, dedup, re-top-k)."""
    obj_id = jnp.concatenate([r.obj_id for r in results])
    dist = jnp.concatenate([r.dist for r in results])
    valid = jnp.concatenate([r.valid for r in results])
    return topk_by_distance(obj_id, dist, valid, k)


@partial(instrumented_jit, static_argnames=("k",))
def _merge_topk_stacked(obj_id, dist, valid, *, k: int) -> KnnResult:
    """(P, k) stacked partials -> merged exact top-k. P*k is tiny (overlap
    panes), so the full-sort dedup is the right strategy and matches the
    per-window kernels' tie order (ascending interned id)."""
    return topk_by_distance(obj_id.reshape(-1), dist.reshape(-1),
                            valid.reshape(-1), k, "sort")


@partial(instrumented_jit, static_argnames=("k",))
def _merge_topk_stacked_multi(obj_id, dist, valid, *, k: int) -> KnnResult:
    """(P, Q, k) stacked multi-query partials -> (Q, k) merged top-k."""
    q = obj_id.shape[1]
    o = jnp.swapaxes(obj_id, 0, 1).reshape(q, -1)
    d = jnp.swapaxes(dist, 0, 1).reshape(q, -1)
    v = jnp.swapaxes(valid, 0, 1).reshape(q, -1)
    return jax.vmap(
        lambda oo, dd, vv: topk_by_distance(oo, dd, vv, k, "sort"))(o, d, v)


def merge_knn_device(results, k: int) -> KnnResult:
    """DEVICE-RESIDENT pane merge: per-pane top-k partials stay in device
    memory across slides; each sealed window dispatches this gather +
    re-top-k over its panes' resident arrays and reads back ONLY the merged
    (k,) result — the device twin of :func:`merge_topk_host` (exact by the
    same covering argument; ties break by interned id exactly like the
    per-window kernel, so pane windows stay identical to full recompute).
    Retraces per distinct pane count P, which is bounded by the window
    overlap."""
    return _merge_topk_stacked(jnp.stack([r.obj_id for r in results]),
                               jnp.stack([r.dist for r in results]),
                               jnp.stack([r.valid for r in results]), k=k)


def merge_knn_device_multi(results, k: int) -> KnnResult:
    """Multi-query :func:`merge_knn_device`: per-pane (Q, k) partials ->
    one merged (Q, k) result per window, all on device."""
    return _merge_topk_stacked_multi(
        jnp.stack([r.obj_id for r in results]),
        jnp.stack([r.dist for r in results]),
        jnp.stack([r.valid for r in results]), k=k)


@partial(instrumented_jit, static_argnames=("k", "strategy"))
def knn_eligible(obj_id, dists, eligible, *, k: int,
                 strategy: str = "auto") -> KnnResult:
    """Jitted dedup+top-k over caller-computed eligibility and distances —
    the generic entry for polygon/linestring streams and geometry queries."""
    return topk_by_distance(obj_id, dists, eligible, k, strategy)


@partial(instrumented_jit, static_argnames=("k", "strategy"))
def knn_eligible_stats(obj_id, dists, eligible, *, k: int,
                       strategy: str = "auto"):
    """knn_eligible + the candidate count in the same dispatch (the generic
    streams' analogue of knn_point_stats — one kernel launch per window)."""
    res = topk_by_distance(obj_id, dists, eligible, k, strategy)
    return res, jnp.sum(eligible, dtype=jnp.int32)


def point_stream_eligibility(cell, valid, nb_mask):
    """Shared point-stream eligibility rule: valid, in-grid, and in a
    neighboring cell of the query (dense mask form). Single source of truth
    for knn_with_dists and the operator layer."""
    return valid & (cell >= 0) & nb_mask[jnp.maximum(cell, 0)]
