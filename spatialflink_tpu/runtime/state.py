"""Keyed operator state with explicit snapshot/restore.

The reference leans on Flink managed state (``ValueState``/``MapState``/
``ListState``) and would get checkpointing from Flink if it were configured
(SURVEY §5: it never is). Here host-side operator state is explicit and
snapshot-able: device state pytrees hop to host numpy for serialization, and
:meth:`CheckpointableState.save` / :meth:`load` round-trip through a single
``.npz`` file — the rebuild's checkpoint/resume story.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Optional

import numpy as np

#: bumped when the on-disk layout of a CheckpointableState changes
#: incompatibly; a reader seeing a NEWER version refuses loudly instead of
#: misinterpreting the arrays
STATE_SCHEMA_VERSION = 1


class CheckpointCorrupt(RuntimeError):
    """A checkpoint file is unreadable: truncated, checksum-mismatched, or
    written by an incompatible schema version. Raised instead of the raw
    ``zipfile``/``np.load``/``json`` traceback so callers (and the
    checkpoint coordinator's retained-file fallback) can distinguish "this
    file is bad" from a bug."""


def _content_checksum(host: Dict[str, np.ndarray], meta: Dict) -> str:
    """sha256 over the meta JSON and every array's dtype/shape/bytes, in
    sorted key order — any torn/truncated/bit-flipped payload changes it."""
    h = hashlib.sha256()
    h.update(json.dumps(meta, sort_keys=True, default=str).encode())
    for k in sorted(host):
        a = np.ascontiguousarray(host[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _parse_meta_envelope(raw: str, path: str, host=None, verify=True):
    """User meta from the ``__meta__`` entry. New-format checkpoints wrap it
    in an envelope ``{"schema": v, "checksum": hex, "meta": {...}}`` that is
    verified; legacy files (bare meta dict) load without verification."""
    try:
        env = json.loads(raw)
    except (ValueError, TypeError) as e:
        raise CheckpointCorrupt(f"{path}: __meta__ is not JSON ({e})") from e
    if not (isinstance(env, dict) and "schema" in env and "meta" in env):
        return env if isinstance(env, dict) else {}
    schema = env.get("schema")
    if not isinstance(schema, int) or schema > STATE_SCHEMA_VERSION:
        raise CheckpointCorrupt(
            f"{path}: checkpoint schema version {schema!r} is newer than "
            f"this build understands ({STATE_SCHEMA_VERSION})")
    if verify and host is not None:
        want = env.get("checksum")
        got = _content_checksum(host, env["meta"])
        if want != got:
            raise CheckpointCorrupt(
                f"{path}: content checksum mismatch (file says "
                f"{str(want)[:12]}…, payload hashes to {got[:12]}…) — "
                "truncated or corrupt checkpoint")
    return env["meta"]


class CheckpointableState:
    """A named bag of numpy/jax arrays + JSON-able metadata."""

    def __init__(self):
        self.arrays: Dict[str, Any] = {}
        self.meta: Dict[str, Any] = {}

    def save(self, path: str) -> None:
        """Atomic write: a crash mid-save never corrupts the previous
        checkpoint (tmp file + rename). The ``__meta__`` entry carries a
        schema version and a content checksum over meta + every array, so
        :meth:`load` detects truncation/corruption instead of returning
        garbage state."""
        host = {k: np.asarray(v) for k, v in self.arrays.items()}
        envelope = {"schema": STATE_SCHEMA_VERSION,
                    "checksum": _content_checksum(host, self.meta),
                    "meta": self.meta}
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=json.dumps(envelope), **host)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # make the rename itself durable across power loss
        dir_fd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    @classmethod
    def load(cls, path: str, verify: bool = True) -> "CheckpointableState":
        """Load + verify. Any unreadable/truncated file and any checksum or
        schema mismatch raises :class:`CheckpointCorrupt` (legacy files
        without an envelope load unverified — they predate the checksum)."""
        out = cls()
        raw_meta: Optional[str] = None
        try:
            with np.load(path, allow_pickle=False) as z:
                for k in z.files:
                    if k == "__meta__":
                        raw_meta = str(z[k])
                    else:
                        out.arrays[k] = z[k]
        except CheckpointCorrupt:
            raise
        except Exception as e:  # zipfile.BadZipFile, OSError, ValueError, …
            raise CheckpointCorrupt(
                f"{path}: unreadable checkpoint ({type(e).__name__}: {e})"
            ) from e
        if raw_meta is not None:
            out.meta = _parse_meta_envelope(raw_meta, path, out.arrays,
                                            verify=verify)
        return out


def checkpoint_consumed(path: str) -> int:
    """Resume offset recorded in a checkpoint (0 if none/absent) — the number
    of source records already reflected in the saved state. Reads only the
    meta entry (np.load on an npz is lazy per-array), not the state arrays;
    the content checksum is therefore NOT verified here — the subsequent
    full restore does that. A file that cannot even surface its meta raises
    :class:`CheckpointCorrupt` instead of a raw traceback."""
    meta = checkpoint_meta(path)
    return int(meta.get("consumed", 0))


def checkpoint_meta(path: str) -> Dict[str, Any]:
    """The (unverified) user meta of a checkpoint file; {} if absent."""
    if not os.path.exists(path):
        return {}
    try:
        with np.load(path, allow_pickle=False) as z:
            if "__meta__" not in z.files:
                return {}
            raw = str(z["__meta__"])
    except Exception as e:
        raise CheckpointCorrupt(
            f"{path}: unreadable checkpoint ({type(e).__name__}: {e})") from e
    meta = _parse_meta_envelope(raw, path, host=None, verify=False)
    return meta if isinstance(meta, dict) else {}


class TrajStateStore:
    """Host wrapper around a device :class:`TrajStatsState` that grows with
    the interner and snapshots to disk."""

    def __init__(self, capacity: int = 256):
        from spatialflink_tpu.ops.trajectory import TrajStatsState

        self.capacity = capacity
        self.state = TrajStatsState.zeros(capacity)

    def ensure(self, min_capacity: int) -> None:
        """Grow (power-of-two) so new interned object ids fit."""
        if min_capacity <= self.capacity:
            return
        from spatialflink_tpu.ops.trajectory import TrajStatsState
        from spatialflink_tpu.utils import bucket_size

        new_cap = bucket_size(min_capacity, self.capacity * 2)
        old = self.state
        grown = TrajStatsState.zeros(new_cap)
        import jax.numpy as jnp

        self.state = TrajStatsState(
            *(g.at[: self.capacity].set(o) for g, o in zip(grown, old))
        )
        self.capacity = new_cap

    def rebase_ts(self, delta_ms: int) -> None:
        """Shift carried ``last_ts`` offsets when the caller moves the batch
        ``ts_base`` forward by ``delta_ms`` — keeps int32 offsets small over
        an unbounded realtime run instead of wrapping after ~24.8 days.
        Entries dormant beyond ~12.4 days clamp to a "very old" floor (any
        new timestamp still compares newer; the next gap's temporal
        contribution saturates at the floor); the uninitialized sentinel is
        kept. The floor is -(2^30) rather than the int32 min so downstream
        subtraction cannot wrap."""
        if delta_ms == 0:
            return
        import jax.numpy as jnp

        from spatialflink_tpu.ops.trajectory import INT32_MIN

        # int32-safe saturating subtraction (int64 is unavailable without
        # jax_enable_x64): thresholds are computed host-side so the device
        # subtraction provably cannot wrap.
        # floor at -(2^30)+1: together with the operators' 2^30 batch-span
        # cap, |ts - last_ts| stays < 2^31 so the kernel's int32 delta is
        # exact (see ops.trajectory.tstats_update)
        floor, imax = -(2**30) + 1, 2**31 - 1
        lt = self.state.last_ts
        if delta_ms >= 2**31:
            shifted = jnp.full_like(lt, floor)
        elif delta_ms <= -(2**31):
            shifted = jnp.full_like(lt, imax)
        elif delta_ms > 0:
            thr = jnp.int32(floor + delta_ms)
            shifted = jnp.where(lt < thr, jnp.int32(floor),
                                lt - jnp.int32(delta_ms))
        else:
            thr = jnp.int32(imax + delta_ms)
            shifted = jnp.where(lt > thr, jnp.int32(imax),
                                lt - jnp.int32(delta_ms))
        self.state = self.state._replace(
            last_ts=jnp.where(lt != INT32_MIN, shifted, lt)
        )

    def snapshot(self) -> CheckpointableState:
        cp = CheckpointableState()
        cp.meta["capacity"] = self.capacity
        for name, arr in self.state._asdict().items():
            cp.arrays[name] = arr
        return cp

    @classmethod
    def restore(cls, cp: CheckpointableState) -> "TrajStateStore":
        from spatialflink_tpu.ops.trajectory import TrajStatsState
        import jax.numpy as jnp

        store = cls(capacity=int(cp.meta["capacity"]))
        # jnp.array (copy) rather than jnp.asarray: the restored state is
        # DONATED on the first tstats_update, and asarray may zero-copy
        # alias the checkpoint's numpy buffers on CPU — donation would then
        # free memory numpy still owns (observed as nondeterministic heap
        # corruption/aborts on the first post-restore update)
        store.state = TrajStatsState(
            **{k: jnp.array(v) for k, v in cp.arrays.items()}
        )
        return store
