"""Multi-query batching benchmark: Q continuous kNN queries answered in ONE
window dispatch (``ops.knn.knn_point_multi``) vs Q single-query dispatches.

The reference runs one continuous query per Flink job
(``StreamingJob.java:470``), so Q queries cost Q jobs each re-reading the
stream; here they share one device residency of the window and one fused
pass. The interesting number is per-QUERY cost as Q grows: near-flat
per-dispatch time means the query axis is almost free until compute
saturates.

Usage: python benchmarks/bench_multi_query.py [--n N] [--qs 1,8,64,256]
       [--strategy S] [--out PATH]

One JSON line per Q, plus a single-query-loop baseline row (q=1 kernel
dispatched Q_max times) for the speedup denominator.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._common import settle_backend  # noqa: E402
from benchmarks.bench_configs import _grid, _points, _slope_time  # noqa: E402

RADIUS = 0.5
K = 50


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None,
                    help="window points (default 1M, 262k on CPU)")
    ap.add_argument("--qs", default="1,8,64,256")
    ap.add_argument("--strategy", default="auto")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    settle_backend()
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.knn import knn_point, knn_point_multi

    backend = jax.default_backend()
    n = args.n or (1_000_000 if backend == "tpu" else 262_144)
    qs = [int(x) for x in args.qs.split(",")]

    grid = _grid()
    batch = jax.device_put(_points(grid, n, seed=0))
    nb = grid.candidate_layers(RADIUS)
    rng = np.random.default_rng(1)
    q_max = max(qs)
    qx_all = rng.uniform(116.0, 117.0, q_max).astype(np.float32)
    qy_all = rng.uniform(40.0, 41.0, q_max).astype(np.float32)
    qc_all = np.asarray([grid.assign_cell(float(x), float(y))[0]
                         for x, y in zip(qx_all, qy_all)], np.int32)

    rows = []

    # baseline: one iteration = one single-query kernel, under EXACTLY the
    # multi rows' dispatch conditions — the query is a hoisted constant with
    # the same i*1e-7 anti-hoist perturbation, no per-iteration gather (a
    # dynamic qx[i % Q] indexing made the round-1 version of this baseline
    # ~1.9x slower than the q=1 multi row, i.e. the "speedup" measured the
    # harness, not the batching)
    def run_single_loop(iters):
        qx0, qy0 = float(qx_all[0]), float(qy_all[0])
        qc0 = jnp.int32(qc_all[0])

        def body(i, acc):
            r = knn_point(batch, qx0 + i * 1e-7, qy0, qc0, RADIUS, nb,
                          n=grid.n, k=K, strategy=args.strategy)
            return acc + r.dist[0]
        return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

    per_query_single = _slope_time(run_single_loop, lo=2, hi=10)
    row = dict(mode="single_loop", queries=1,
               per_query_us=round(per_query_single * 1e6, 2),
               points_x_queries_per_sec=round(n / per_query_single),
               backend=backend, n=n, strategy=args.strategy)
    print(json.dumps(row), flush=True)
    rows.append(row)

    for q in qs:
        qx = jnp.asarray(qx_all[:q])
        qy = jnp.asarray(qy_all[:q])
        qc = jnp.asarray(qc_all[:q])

        def run_n(iters, qx=qx, qy=qy, qc=qc):
            def body(i, acc):
                r = knn_point_multi(batch, qx + i * 1e-7, qy, qc, RADIUS,
                                    nb, n=grid.n, k=K,
                                    strategy=args.strategy)
                return acc + r.dist[0, 0]
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0))

        per = _slope_time(run_n, lo=2, hi=10)  # seconds per multi-dispatch
        per_query = per / q
        row = dict(mode="multi", queries=q,
                   per_dispatch_ms=round(per * 1e3, 3),
                   per_query_us=round(per_query * 1e6, 2),
                   points_x_queries_per_sec=round(n * q / per),
                   speedup_vs_single_loop=round(per_query_single / per_query,
                                                2),
                   backend=backend, n=n, strategy=args.strategy)
        print(json.dumps(row), flush=True)
        rows.append(row)

    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"RESULTS_multiquery_{backend}.json")
    with open(out, "w") as f:
        json.dump({"backend": backend, "n": n, "k": K,
                   "strategy": args.strategy, "rows": rows}, f, indent=1)
    print(f"# wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
