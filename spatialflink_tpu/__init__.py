"""spatialflink_tpu — a TPU-native spatial stream processing framework.

A ground-up rebuild of the capabilities of GeoFlink (mpetrun5/SpatialFlink):
continuous range / kNN / join / trajectory queries over streaming spatial data,
pruned by a uniform grid index — re-designed for TPU:

- The unit of execution is the *window batch*: a padded, fixed-shape
  structure-of-arrays of points / polygons / linestrings plus int32 cell ids.
- All geometry math (distance predicates, top-k, cell-hash joins,
  point-in-polygon) runs as jax.jit / vmap / Pallas kernels on device.
- Grid-cell pruning (the reference's guaranteed/candidate neighboring-cell
  sets, UniformGrid.java:165-444) becomes dense boolean cell masks or pure
  index arithmetic — gathers and compares, not hash-set probes.
- Multi-device scaling replaces Flink's keyBy shuffle with jax.sharding
  meshes + shard_map and XLA collectives (see spatialflink_tpu.parallel).

Host-side Python owns streaming concerns only: sources, ser/de, event-time
watermarks, window assembly, keyed state, sinks (see spatialflink_tpu.streams
and spatialflink_tpu.runtime).
"""

__version__ = "0.1.0"

from spatialflink_tpu.index import UniformGrid, GridParams

__all__ = ["UniformGrid", "GridParams", "__version__"]
