"""Device-truth plane suite (ISSUE 12): the compile registry +
instrumented_jit shim, the recompile sentinel (event/counter/strict abort;
zero post-warmup compiles across PR 9 query-plane churn and a forced PR 8
repartition), /device + /compile endpoint schemas, the dispatch-overlap
ratio, device-plane SLO checks, the flight recorder's crash/SLO/signal
bundles, the doctor CLI, the jit-coverage meta-test, and the extended
telemetry-off hot-path spy."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest
import yaml

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (PointPointRangeQuery,
                                        QueryConfiguration, QueryType)
from spatialflink_tpu.streams.formats import serialize_spatial
from spatialflink_tpu.utils import deviceplane
from spatialflink_tpu.utils.metrics import scoped_registry
from spatialflink_tpu.utils.telemetry import (active, status_snapshot,
                                              telemetry_session)

pytestmark = pytest.mark.deviceplane

GRID = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)

DEVICE_STATUS_KEYS = {"backend", "compiles", "run_compiles", "recompiles",
                      "warm", "strict", "mem_available", "mem_bytes_in_use",
                      "mem_peak_bytes", "d2h_bytes"}


def _lines(n, span_ms=100_000, t0=1_700_000_000_000):
    rng = np.random.default_rng(0)
    return [f"v{i % 53},{t0 + i * span_ms // max(n, 1)},"
            f"{115.5 + rng.random() * 2:.6f},{39.6 + rng.random() * 1.5:.6f}"
            for i in range(n)]


def _write_points(path, n=60, t0=1_700_000_000_000, step_ms=400):
    with open(path, "w") as f:
        for i in range(n):
            p = Point.create(116.5 + 0.001 * i, 40.5, GRID, obj_id=f"o{i}",
                             timestamp=t0 + i * step_ms)
            f.write(serialize_spatial(p, "GeoJSON") + "\n")
    return str(path)


def _cfg():
    from spatialflink_tpu.config import StreamConfig

    return StreamConfig(format="CSV", date_format=None,
                        csv_tsv_schema=[0, 1, 2, 3])


def _range_windows(stream_lines, conf=None, grid=GRID, radius=0.5):
    from spatialflink_tpu import driver

    conf = conf or QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    op = PointPointRangeQuery(conf, grid)
    stream = driver.decode_stream(iter(stream_lines), _cfg(), grid)
    q = Point.create(116.5, 40.3, grid, obj_id="q")
    return [(r.window_start, len(r.records)) for r in op.run(stream, q,
                                                             radius)]


# --------------------------------------------------------------------- #
# compile registry + instrumented_jit


class TestCompileRegistry:
    def test_instrumented_jit_registers_counts_and_signatures(self):
        import jax.numpy as jnp

        def _probe_fn_a(x, *, k):
            return (x * 2).sum() + k

        fn = deviceplane.instrumented_jit(_probe_fn_a,
                                          static_argnames=("k",))
        reg = deviceplane.registry()
        key = f"{_probe_fn_a.__module__}.{_probe_fn_a.__qualname__}"
        entry = reg.entries[key]
        assert entry.compiles == 0
        fn(jnp.arange(4.0), k=1)
        assert entry.compiles == 1
        fn(jnp.arange(4.0), k=1)          # cache hit: no trace
        assert entry.compiles == 1
        fn(jnp.arange(8.0), k=1)          # fresh shape
        fn(jnp.arange(8.0), k=2)          # fresh static
        assert entry.compiles == 3
        assert entry.cache_size() == 3
        sig = entry.signatures[-1]["signature"]
        assert "float32[8]" in sig and "k=2" in sig
        assert entry.trace_ms > 0
        assert entry.first_compile_ms <= entry.last_compile_ms

    def test_cost_analysis_is_lazy_and_cached(self):
        import jax.numpy as jnp

        def _probe_fn_b(x):
            return jnp.sin(x).sum()

        fn = deviceplane.instrumented_jit(_probe_fn_b)
        fn(jnp.arange(16.0))
        entry = deviceplane.registry().entries[
            f"{_probe_fn_b.__module__}.{_probe_fn_b.__qualname__}"]
        ca = entry.cost_analysis()
        assert ca is not None and ca["flops"] is not None
        assert entry.cost_analysis() is ca  # cached

    def test_semantics_identical_to_jax_jit(self):
        # the shim must not change results, including donated buffers
        import jax
        import jax.numpy as jnp

        def body(s, x):
            return s + x

        plain = jax.jit(body)
        shim = deviceplane.instrumented_jit(body, donate_argnums=(0,))
        a = jnp.arange(5.0)
        assert np.allclose(np.asarray(plain(jnp.zeros(5), a)),
                           np.asarray(shim(jnp.zeros(5), a)))

    def test_registry_snapshot_schema(self):
        snap = deviceplane.registry().snapshot()
        assert {"ts_ms", "functions", "total_compiles", "run_compiles",
                "post_warmup_compiles", "warm", "warm_reason", "strict",
                "entries"} <= set(snap)
        assert snap["functions"] == len(snap["entries"])
        e = snap["entries"][0]
        assert {"name", "module", "jit_kwargs", "compiles", "recompiles",
                "trace_ms", "backend_compile_ms", "cache_size",
                "signatures"} <= set(e)


# --------------------------------------------------------------------- #
# recompile sentinel


class TestRecompileSentinel:
    def test_post_warmup_compile_fires_event_and_counter(self):
        import jax.numpy as jnp

        def _sentinel_fn_a(x):
            return x.sum()

        fn = deviceplane.instrumented_jit(_sentinel_fn_a)
        reg = deviceplane.registry()
        with scoped_registry() as mreg, telemetry_session() as tel:
            fn(jnp.arange(4.0))             # pre-warm shape
            reg.begin_run(strict=False)
            reg.mark_warm("test warmup")
            try:
                fn(jnp.arange(4.0))         # cache hit: silent
                assert reg.run_recompiles == 0
                fn(jnp.arange(32.0))        # fresh shape post-warmup
                assert reg.run_recompiles == 1
                assert mreg.counter("device-recompiles").count == 1
                kinds = [e["kind"] for e in tel.events.list()]
                assert "sentinel-warm" in kinds and "recompile" in kinds
                ev = [e for e in tel.events.list()
                      if e["kind"] == "recompile"][-1]
                assert "_sentinel_fn_a" in ev["fn"]
                assert "float32[32]" in ev["signature"]
            finally:
                reg.end_run()

    def test_strict_mode_aborts(self):
        import jax.numpy as jnp

        def _sentinel_fn_b(x):
            return x.sum()

        fn = deviceplane.instrumented_jit(_sentinel_fn_b)
        reg = deviceplane.registry()
        fn(jnp.arange(4.0))
        reg.begin_run(strict=True)
        reg.mark_warm("strict test")
        try:
            fn(jnp.arange(4.0))  # warm shape: fine
            with pytest.raises(deviceplane.RecompileError,
                               match="zero-recompile contract"):
                fn(jnp.arange(64.0))
        finally:
            reg.end_run()

    def test_query_plane_churn_is_recompile_silent(self):
        """The PR 9 contract device-truth-asserted: admit/retire per window
        at constant fleet size (Q=32, in-bucket repad) records ZERO
        post-warmup compiles."""
        from spatialflink_tpu import driver
        from spatialflink_tpu.runtime.queryplane import QueryRegistry

        lines = _lines(6000)
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
        rng = np.random.default_rng(5)
        pts = [(float(115.5 + rng.random() * 2),
                float(39.6 + rng.random() * 1.5)) for _ in range(32)]

        def run_churn():
            qreg = QueryRegistry("range", radius=0.5)
            for i, (x, y) in enumerate(pts):
                qreg.admit({"id": f"q{i}", "x": x, "y": y})
            qreg.apply()
            op = PointPointRangeQuery(conf, GRID)
            stream = driver.decode_stream(iter(lines), _cfg(), GRID)
            i = 0
            for _w in op.run_dynamic(stream, qreg, 0.5):
                qreg.admit({"id": f"c{i}", "x": 116.0 + (i % 9) * 0.1,
                            "y": 40.0 + (i % 9) * 0.1})
                qreg.retire([e.id for e in qreg.active_entries()][0])
                i += 1
            assert i >= 3

        run_churn()  # warm the Q=32 bucket's kernel shapes
        reg = deviceplane.registry()
        reg.begin_run(strict=True)  # strict: a recompile would RAISE here
        reg.mark_warm("churn test (shapes pre-warmed)")
        try:
            run_churn()
            assert reg.run_recompiles == 0
        finally:
            reg.end_run()

    def test_forced_repartition_is_recompile_silent(self):
        """The PR 8 contract device-truth-asserted: mid-run adaptive-grid
        layout churn (splits applied and reverted between windows) never
        recompiles — records keep base cells; adaptivity is a host-side
        prefilter."""
        import dataclasses

        from spatialflink_tpu import driver
        from spatialflink_tpu.index import AdaptiveGrid

        lines = _lines(4000)
        hot = int(GRID.assign_cell(116.5, 40.3)[0])
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)

        def run_churned(ag):
            op = PointPointRangeQuery(
                dataclasses.replace(conf, adaptive_grid=ag), GRID)
            layouts = [([hot], []), ([], []), ([hot, hot + 1], [])]

            def churn(stream):
                for i, r in enumerate(stream):
                    if i % 900 == 0:
                        ag.apply_layout(*layouts[(i // 900) % len(layouts)])
                    yield r

            stream = churn(driver.decode_stream(iter(lines), _cfg(), GRID))
            q = Point.create(116.5, 40.3, GRID, obj_id="q")
            return [(r.window_start, len(r.records))
                    for r in op.run(stream, q, 0.5)]

        baseline = run_churned(AdaptiveGrid(GRID, refine=4))  # warm shapes
        reg = deviceplane.registry()
        reg.begin_run(strict=True)
        reg.mark_warm("repartition test (shapes pre-warmed)")
        try:
            ag = AdaptiveGrid(GRID, refine=4)
            got = run_churned(ag)
            assert ag.version >= 3
            assert got == baseline
            assert reg.run_recompiles == 0
        finally:
            reg.end_run()

    def test_driver_strict_recompile_aborts_with_bundle(self, tmp_path):
        """End-to-end in a FRESH process (the jit cache must be cold so the
        late bucket growth provably compiles): sparse early windows declare
        warmup, a dense burst forces a new padding bucket -> exit 3, a
        'strict-recompile' post-mortem bundle, and doctor summarize reads
        it."""
        t0 = 1_700_000_000_000
        rows = []
        rng = np.random.default_rng(1)
        for i in range(120):   # ~50 records/window over 4 windows: warmup
            rows.append(f"v{i},{t0 + i * 200},"
                        f"{115.5 + rng.random() * 2:.6f},"
                        f"{39.6 + rng.random() * 1.5:.6f}")
        for i in range(3000):  # burst inside later windows: fresh bucket
            rows.append(f"b{i},{t0 + 40_000 + (i % 5000)},"
                        f"{115.5 + rng.random() * 2:.6f},"
                        f"{39.6 + rng.random() * 1.5:.6f}")
        inp = tmp_path / "grow.csv"
        inp.write_text("\n".join(rows) + "\n")
        pm = tmp_path / "pm"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "-m", "spatialflink_tpu.driver",
             "--config", "conf/spatialflink-conf.yml",
             "--input1", str(inp), "--option", "1", "--format", "CSV",
             "--strict-recompile", "--postmortem-dir", str(pm)],
            capture_output=True, text=True, timeout=300, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        assert r.returncode == 3, (r.stdout[-1000:], r.stderr[-2000:])
        assert "STRICT-RECOMPILE ABORT" in r.stderr
        bundles = [d for d in os.listdir(pm) if "strict-recompile" in d]
        assert bundles, os.listdir(pm)
        from spatialflink_tpu import doctor

        bundle = os.path.join(str(pm), bundles[0])
        assert doctor.main(["summarize", bundle]) == 0
        doc = doctor.load_bundle(bundle)
        assert doc["manifest"]["reason"] == "strict-recompile"
        assert "RecompileError" in doc["manifest"]["error"]
        assert doc["compile"]["post_warmup_compiles"] >= 1


# --------------------------------------------------------------------- #
# device telemetry: provenance, snapshots, overlap


class TestDeviceTelemetry:
    def test_backend_provenance_fields(self):
        prov = deviceplane.backend_provenance()
        assert prov["platform"] == "cpu"  # tier-1 pins JAX_PLATFORMS=cpu
        assert prov["device_count"] >= 1
        assert prov["target"] == "tpu"
        assert prov["valid_for_target"] is False
        assert deviceplane.backend_provenance(
            target="cpu")["valid_for_target"] is True

    def test_device_memory_explicit_unavailability_on_cpu(self):
        rows = deviceplane.device_memory()
        assert rows and all(r["available"] is False for r in rows)
        g = deviceplane.memory_gauges()
        assert g["available"] is False and g["bytes_in_use"] is None

    def test_snapshot_and_digest_carry_device_block(self):
        with telemetry_session() as tel:
            snap = status_snapshot(tel)
        assert DEVICE_STATUS_KEYS <= set(snap["device"])
        st = snap["status"]
        assert st["device"]["backend"]["platform"] == "cpu"
        assert "dispatch_overlap" in st
        # registry-only (no session) snapshots carry it too: device truth
        # is process truth, and these are only built on demand
        snap2 = status_snapshot()
        assert DEVICE_STATUS_KEYS <= set(snap2["device"])

    def test_overlap_ratio_recorded_per_window(self):
        lines = _lines(4000)
        _range_windows(lines)  # warm
        with telemetry_session() as tel:
            _range_windows(lines)
            h = tel.histograms.get("dispatch-overlap-ratio")
            assert h is not None and h.count >= 3
            p50 = h.percentile(50)
            assert 0.0 <= p50 <= 1.0
            snap = status_snapshot(tel)
        ov = snap["status"]["dispatch_overlap"]
        assert ov["count"] == h.count and 0.0 <= ov["p99"] <= 1.0

    def test_digest_line_shows_backend_and_overlap(self):
        from spatialflink_tpu.runtime.opserver import format_digest

        with telemetry_session() as tel:
            tel.histogram("dispatch-overlap-ratio").record(0.8)
            line = format_digest(status_snapshot(tel))
        assert "dev cpu" in line and "!=tpu" in line
        assert "ovl" in line


# --------------------------------------------------------------------- #
# endpoints


class TestEndpoints:
    def _get(self, url):
        resp = urllib.request.urlopen(url, timeout=5)
        return resp.status, json.loads(resp.read())

    def test_device_and_compile_endpoints(self):
        from spatialflink_tpu.runtime.opserver import OpServer

        with telemetry_session() as tel:
            tel.histogram("dispatch-overlap-ratio").record(0.5)
            srv = OpServer(port=0).start()
            try:
                code, dev = self._get(srv.url + "/device")
                assert code == 200
                assert {"ts_ms", "backend", "memory", "transfer",
                        "compile", "dispatch_overlap",
                        "recorder"} <= set(dev)
                assert dev["backend"]["platform"] == "cpu"
                assert dev["memory"]["devices"]
                assert dev["dispatch_overlap"]["count"] == 1
                assert dev["recorder"]["active"] is False
                code, comp = self._get(srv.url + "/compile")
                assert code == 200
                assert comp["functions"] >= 30  # every ops/* kernel
                names = {e["name"] for e in comp["entries"]}
                assert "range_filter_point" in names
                assert all("cost_analysis" not in e
                           for e in comp["entries"])
                # ?cost=1: lazy AOT analysis lands on compiled entries
                code, compc = self._get(srv.url + "/compile?cost=1")
                compiled = [e for e in compc["entries"]
                            if e["compiles"] > 0]
                assert compiled and any(
                    (e.get("cost_analysis") or {}).get("flops")
                    for e in compiled)
            finally:
                srv.close()

    def test_device_endpoint_405_and_sessionless(self):
        from spatialflink_tpu.runtime.opserver import OpServer

        assert active() is None
        srv = OpServer(port=0).start()
        try:
            code, dev = self._get(srv.url + "/device")
            assert code == 200 and dev["dispatch_overlap"]["count"] == 0
            req = urllib.request.Request(srv.url + "/device",
                                         data=b"{}", method="POST")
            try:
                urllib.request.urlopen(req, timeout=5)
                assert False, "POST /device must 405"
            except urllib.error.HTTPError as e:
                assert e.code == 405
                assert e.headers["Allow"] == "GET"
        finally:
            srv.close()


# --------------------------------------------------------------------- #
# health checks


class TestDevicePlaneHealth:
    def test_recompiles_check_breaches_on_post_warmup_compiles(self):
        from spatialflink_tpu.runtime.health import HealthEvaluator

        ev = HealthEvaluator({"recompiles": 0})
        with scoped_registry():
            ok = ev.evaluate({"status": {"device": {"recompiles": 0}}})
            assert ok["healthy"]
            bad = ev.evaluate({"status": {"device": {"recompiles": 2}}})
            assert not bad["healthy"]
            assert bad["checks"]["recompiles"]["value"] == 2

    def test_device_mem_unknown_counts_healthy(self):
        from spatialflink_tpu.runtime.health import HealthEvaluator

        ev = HealthEvaluator({"device_mem_bytes": 1})
        with scoped_registry():
            v = ev.evaluate({"status": {"device":
                                        {"mem_bytes_in_use": None}}})
            assert v["healthy"]  # CPU: no stats -> unknown -> healthy
            v = ev.evaluate({"status": {"device":
                                        {"mem_bytes_in_use": 2}}})
            assert not v["healthy"]

    def test_slo_spec_accepts_new_keys(self):
        from spatialflink_tpu.runtime.health import HealthEvaluator

        ev = HealthEvaluator.from_spec("recompiles=0,device_mem_bytes=8e9")
        assert ev.thresholds["device_mem_bytes"] == 8e9


# --------------------------------------------------------------------- #
# flight recorder + doctor


def _bundle_dirs(pm, reason=None):
    out = [os.path.join(str(pm), d) for d in sorted(os.listdir(str(pm)))
           if d.startswith("bundle-") and (reason is None or reason in d)]
    return out


class TestFlightRecorder:
    def test_dump_on_signal(self, tmp_path):
        with telemetry_session():
            rec = deviceplane.FlightRecorder(str(tmp_path / "pm"),
                                             config={"job": "sig"})
            rec.install_signal()
            try:
                rec.note("run-start")
                os.kill(os.getpid(), signal.SIGUSR1)
                time.sleep(0.05)
            finally:
                rec.close()
        bundles = _bundle_dirs(tmp_path / "pm", "signal")
        assert len(bundles) == 1
        with open(os.path.join(bundles[0], "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["reason"] == "signal"
        assert manifest["schema"] == deviceplane.BUNDLE_SCHEMA
        for name in manifest["files"]:
            assert os.path.exists(os.path.join(bundles[0], name))
        with open(os.path.join(bundles[0], "flight.json")) as f:
            notes = json.load(f)["notes"]
        assert [n["kind"] for n in notes][:1] == ["run-start"]
        # the handler was restored
        assert signal.getsignal(signal.SIGUSR1) not in (
            None,) and deviceplane.active_recorder() is None

    def test_dump_on_slo_breach_once(self, tmp_path):
        from spatialflink_tpu.runtime.health import HealthEvaluator

        with scoped_registry(), telemetry_session():
            health = HealthEvaluator({"min_throughput_rps": 1e9})
            rec = deviceplane.FlightRecorder(str(tmp_path / "pm"))
            rec.attach_health(health)
            try:
                snap = {"status": {"records_in": 100,
                                   "throughput_rps": 5.0}}
                health.evaluate(snap)
                health.evaluate(snap)  # still breached: no second dump
            finally:
                rec.close()
        bundles = _bundle_dirs(tmp_path / "pm", "slo-breach")
        assert len(bundles) == 1
        with open(os.path.join(bundles[0], "manifest.json")) as f:
            m = json.load(f)
        assert m["detail"]["check"] == "min_throughput_rps"

    def test_max_dumps_bounds_a_crash_loop(self, tmp_path):
        rec = deviceplane.FlightRecorder(str(tmp_path / "pm"), max_dumps=2)
        try:
            assert rec.dump("a") and rec.dump("b")
            assert rec.dump("c") is None
        finally:
            rec.close()
        assert len(_bundle_dirs(tmp_path / "pm")) == 2

    def test_driver_slo_breach_dumps_bundle(self, tmp_path, capsys):
        """Driver acceptance: an un-meetable throughput SLO under the live
        digest thread dumps exactly one slo-breach bundle mid-run."""
        from spatialflink_tpu.driver import main

        inp = _write_points(tmp_path / "pts.geojson", n=400)
        pm = tmp_path / "pm"
        rc = main(["--config", "conf/spatialflink-conf.yml",
                   "--input1", inp, "--option", "1",
                   "--slo", "min_throughput_rps=1e12",
                   "--live-stats", "--telemetry-interval", "0.05",
                   "--postmortem-dir", str(pm)])
        assert rc == 0
        bundles = _bundle_dirs(pm, "slo-breach")
        assert len(bundles) == 1
        with open(os.path.join(bundles[0], "status.json")) as f:
            status = json.load(f)
        assert status["health"]["healthy"] is False

    def test_crashed_kafka_chaos_run_roundtrips_through_doctor(
            self, tmp_path, monkeypatch):
        """The ISSUE acceptance: a crashed --kafka-follow --chaos run dumps
        a bundle that round-trips through doctor summarize AND diff
        against a healthy-run bundle (SIGUSR1 mid-follow); preflight
        returns non-zero on the CPU-fallback condition."""
        from spatialflink_tpu import doctor, driver
        from spatialflink_tpu.streams.kafka import (reset_memory_brokers,
                                                    resolve_broker)

        def follow_conf(name):
            with open("conf/spatialflink-conf.yml") as f:
                d = yaml.safe_load(f)
            d["kafkaBootStrapServers"] = f"memory://{name}"
            d["window"].update(interval=1, step=1)
            d["query"]["thresholds"]["outOfOrderTuples"] = 0
            p = tmp_path / f"{name}.yml"
            p.write_text(yaml.safe_dump(d))
            return str(p), f"memory://{name}"

        control = json.dumps({"geometry": {"type": "control",
                                           "coordinates": []}})

        def produce(url, n=250, ctrl=True, kill_at=None):
            broker = resolve_broker(url)

            def run():
                for i in range(n):
                    p = Point.create(116.5 + 0.001 * (i % 40), 40.5, GRID,
                                     obj_id=f"veh{i % 7}",
                                     timestamp=int(time.time() * 1000))
                    broker.produce("points.geojson",
                                   serialize_spatial(p, "GeoJSON"))
                    time.sleep(0.01)
                    if kill_at is not None and i == kill_at:
                        os.kill(os.getpid(), signal.SIGUSR1)
                if ctrl:
                    broker.produce("points.geojson", control)

            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t

        reset_memory_brokers()
        try:
            # --- healthy run: SIGUSR1 mid-follow dumps a signal bundle ---
            cfg, url = follow_conf("dp-healthy")
            pm_ok = tmp_path / "pm-ok"
            t = produce(url, n=150, kill_at=60)
            rc = main_rc = driver.main(
                ["--config", cfg, "--kafka", "--kafka-follow",
                 "--option", "1", "--postmortem-dir", str(pm_ok)])
            t.join(timeout=30)
            assert main_rc == 0
            healthy = _bundle_dirs(pm_ok, "signal")
            assert healthy, os.listdir(pm_ok)

            # --- crashed run: injected sink crash under --chaos ---
            reset_memory_brokers()
            cfg2, url2 = follow_conf("dp-crash")
            pm_bad = tmp_path / "pm-bad"
            emits = {"n": 0}
            orig_emit = driver._emit

            def exploding_emit(result, sink):
                # crash on the FIRST emitted window: later windows only
                # seal while the producer keeps advancing the watermark,
                # so waiting for a deeper emission could outlive the
                # bounded produce thread and hang the follow loop
                emits["n"] += 1
                raise RuntimeError("injected mid-run crash")

            monkeypatch.setattr(driver, "_emit", exploding_emit)
            t2 = produce(url2, n=250, ctrl=False)
            with pytest.raises(RuntimeError, match="injected mid-run"):
                driver.main(
                    ["--config", cfg2, "--kafka", "--kafka-follow",
                     "--option", "1",
                     "--chaos", "seed=3,fail_next_fetches=2",
                     "--retry", "attempts=8,base_ms=1",
                     "--postmortem-dir", str(pm_bad)])
            t2.join(timeout=30)
            monkeypatch.setattr(driver, "_emit", orig_emit)
            crashed = _bundle_dirs(pm_bad, "crash")
            assert crashed, os.listdir(pm_bad)
            doc = doctor.load_bundle(crashed[0])
            assert "injected mid-run crash" in doc["manifest"]["error"]
            # chaos degradation visible in the crashed bundle's status
            assert doc["status"]["degradation"].get(
                "chaos-fetch-fail", 0) >= 1

            # --- doctor round-trip: summarize + diff + preflight ---
            assert doctor.main(["summarize", crashed[0]]) == 0
            assert doctor.main(["--json", "summarize", crashed[0]]) == 0
            assert doctor.main(["diff", healthy[0], crashed[0]]) == 0
            # CPU-fallback condition: default target tpu -> non-zero
            assert doctor.main(["--preflight"]) == 1
            assert doctor.main(["preflight",
                                "--require-backend", "cpu"]) == 0
            # unreadable bundle -> usage exit
            assert doctor.main(["summarize", str(tmp_path)]) == 2
        finally:
            reset_memory_brokers()


# --------------------------------------------------------------------- #
# jit-coverage meta-test


class TestJitCoverage:
    """The raw-``jax.jit`` AST walker that lived here is now the
    invariant linter's ``jit-coverage`` rule
    (:mod:`spatialflink_tpu.analysis.rules.jit_coverage`) and runs over
    the whole tree on every tier-1 pass via ``tests/test_analysis.py``.
    What remains here is the thin contract: the rule is registered and
    clean on the real tree, and the RUNTIME half — every decorated
    kernel actually lands in the live compile registry on import — which
    no static pass can prove."""

    OPS_DIRS = ("ops", "parallel")

    def _sources(self):
        root = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "spatialflink_tpu")
        for sub in self.OPS_DIRS:
            d = os.path.join(root, sub)
            for name in sorted(os.listdir(d)):
                if name.endswith(".py"):
                    yield f"spatialflink_tpu.{sub}.{name[:-3]}", \
                        os.path.join(d, name)

    def test_jit_coverage_rule_registered_and_tree_clean(self):
        from spatialflink_tpu import analysis

        assert "jit-coverage" in {r.id for r in analysis.all_rules()}
        report = analysis.run_analysis(rule_ids=["jit-coverage"])
        assert report.ok, [f.render() for f in report.findings]

    def test_every_instrumented_site_is_registered(self):
        """Every ``instrumented_jit``-decorated def in ops/ and parallel/
        appears in the live compile registry after import — a decorator
        typo or a module bypassing the shim fails here. The decorator
        walker is the framework's (``jit_coverage.instrumented_sites``),
        not a local copy."""
        import ast
        import importlib

        from spatialflink_tpu.analysis.rules.jit_coverage import \
            instrumented_sites

        expected = []
        for mod, path in self._sources():
            with open(path) as f:
                tree = ast.parse(f.read(), path)
            expected.extend((mod, name)
                            for name, _ in instrumented_sites(tree))
            importlib.import_module(mod)
        assert len(expected) >= 30  # every kernel family is covered
        entries = deviceplane.registry().entries
        missing = [f"{m}.{n}" for m, n in expected
                   if f"{m}.{n}" not in entries]
        assert not missing, f"decorated but unregistered: {missing}"


# --------------------------------------------------------------------- #
# extended hot-path spy: zero device-plane feeds without a session


class TestDevicePlaneHotPath:
    def test_steady_state_run_feeds_nothing_without_session(
            self, tmp_path, monkeypatch):
        """After a warm first pass (shapes compiled), a session-less run
        must not touch the device plane at all: zero compile-registry
        feeds, zero memory probes, zero flight-recorder notes, zero
        snapshot constructions."""
        from spatialflink_tpu.driver import main
        from spatialflink_tpu.utils import telemetry as telemetry_mod

        inp = _write_points(tmp_path / "pts.geojson")
        assert main(["--config", "conf/spatialflink-conf.yml",
                     "--input1", inp, "--option", "1"]) == 0  # warm pass

        calls = {"trace": 0, "mem": 0, "note": 0, "snap": 0}
        orig_traced = deviceplane.CompileRegistry._on_traced
        monkeypatch.setattr(
            deviceplane.CompileRegistry, "_on_traced",
            lambda self, *a, **k: (calls.__setitem__(
                "trace", calls["trace"] + 1),
                orig_traced(self, *a, **k))[1])
        orig_mem = deviceplane.device_memory
        monkeypatch.setattr(
            deviceplane, "device_memory",
            lambda *a, **k: (calls.__setitem__("mem", calls["mem"] + 1),
                             orig_mem(*a, **k))[1])
        orig_note = deviceplane.FlightRecorder.note
        monkeypatch.setattr(
            deviceplane.FlightRecorder, "note",
            lambda self, *a, **k: (calls.__setitem__(
                "note", calls["note"] + 1),
                orig_note(self, *a, **k))[1])
        orig_snap = telemetry_mod.status_snapshot
        monkeypatch.setattr(
            telemetry_mod, "status_snapshot",
            lambda *a, **k: (calls.__setitem__("snap", calls["snap"] + 1),
                             orig_snap(*a, **k))[1])

        assert active() is None
        assert main(["--config", "conf/spatialflink-conf.yml",
                     "--input1", inp, "--option", "1"]) == 0
        assert calls == {"trace": 0, "mem": 0, "note": 0, "snap": 0}, calls
