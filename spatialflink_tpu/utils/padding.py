"""Bucketed padding.

Per-window candidate counts vary wildly per cell/window; recompiling a jitted
kernel for every distinct batch size would be a recompilation storm. We pad
every batch dimension up to a small set of bucket sizes (powers of two over a
minimum) so the number of distinct compiled shapes stays O(log max_size).
"""

from __future__ import annotations

import numpy as np

MIN_BUCKET = 256


def bucket_size(n: int, min_bucket: int = MIN_BUCKET) -> int:
    """Smallest power-of-two bucket >= n (and >= min_bucket)."""
    if n <= min_bucket:
        return min_bucket
    return 1 << (int(n - 1)).bit_length()


def pad_to(arr: np.ndarray, size: int, axis: int = 0, fill=0) -> np.ndarray:
    """Pad ``arr`` along ``axis`` to ``size`` with ``fill``."""
    n = arr.shape[axis]
    if n == size:
        return arr
    if n > size:
        raise ValueError(f"array dim {n} exceeds pad size {size}")
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, size - n)
    return np.pad(arr, widths, constant_values=fill)
