"""Rule 5 — checkpoint coverage: mutable streaming state must be
snapshotable, FIELD BY FIELD since PR 15.

PR 4's coordinated checkpoints are only exactly-once if *every* piece of
mutable per-run state participates. The heuristic for "holds streaming
state": a class in ``runtime/``/``operators/``/``streams/`` that mutates
an instance attribute *outside* ``__init__`` whose name says it holds
windows, panes, offsets, partials, watermarks, buffers, sealed sets — or
the query plane's registry state (fleets, entries, specs, staged
changes; the PR 9 plane was invisible to PR 12's pattern and is now in
scope).

Two depths of check:

1. **Pair existence** (PR 12's check, kept): such a class must implement
   the ``snapshot``/``restore`` pair the coordinator registers — or
   carry a reviewed exception explaining why its state is legitimately
   ephemeral.
2. **Field coverage** (new): a pair that *exists* is not a pair that
   *covers*. Every state attribute mutated outside ``__init__`` must be
   actually READ somewhere in ``snapshot()`` and actually ASSIGNED
   somewhere in ``restore()`` — directly, or inside an intra-class
   helper the method reaches through self-calls (three levels). This is
   the "added a pane ring, forgot to checkpoint it" bug class: the PR 4
   barriers serialize whatever ``snapshot`` returns and cannot notice a
   field that never made it in.

Mutation detection covers plain stores, ``self.x[k] = v`` subscript
stores, and the container mutators (``append``/``update``/``pop``/…) —
PR 12 saw only ``self.x = …``, so a class that only ever *grew* its
dict looked stateless. ``self.__dict__.update(state)`` and a
non-constant ``setattr(self, name, …)`` in ``restore`` count as
assigning every field (the bulk-restore idiom); a ``restore`` that is a
classmethod constructor is exempt from field checks (it builds a fresh
instance — attribute flow through ``cls(...)`` is a documented blind
spot).

Classes whose state is genuinely derived (caches that recompute, pure
cursors over immutable inputs) belong in the allowlist *with that
sentence as the reason* — the point is that someone decided, not that
the linter guessed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Set, Tuple

from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)

#: attribute-name fragments that mean "streaming state a resume must not
#: lose". fleet/entries/specs/staged bring the query plane's registry
#: state (runtime/queryplane.py) into scope.
_STATE_PAT = re.compile(
    r"window|pane|offset|partial|watermark|seal|buffer"
    r"|fleet|entries|specs|staged", re.IGNORECASE)

#: methods whose writes do not make state "live across the run": setup,
#: the snapshot/restore pair itself, and teardown.
_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__", "snapshot",
                   "restore", "reset", "clear", "close", "__exit__"}

#: method calls that mutate the receiver container in place.
_MUTATORS = {"append", "appendleft", "add", "update", "setdefault",
             "extend", "insert", "pop", "popleft", "popitem", "remove",
             "discard", "clear", "push"}

#: sentinel meaning "every attribute" (self.__dict__.update / dynamic
#: setattr in restore).
_ALL = "*"


def _self_attr(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return ""


def _mutations(meth: ast.AST) -> Dict[str, ast.AST]:
    """attr -> first mutating node in ``meth``: plain/subscript stores,
    augmented assigns, and in-place container mutator calls on
    ``self.<attr>``."""
    out: Dict[str, ast.AST] = {}

    def note(attr: str, node: ast.AST) -> None:
        if attr and attr not in out:
            out[attr] = node

    for stmt in ast.walk(meth):
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                els = ast.walk(t) if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for el in els:
                    note(_self_attr(el), el)
                    if isinstance(el, ast.Subscript):
                        note(_self_attr(el.value), el)
        elif isinstance(stmt, ast.Call) \
                and isinstance(stmt.func, ast.Attribute) \
                and stmt.func.attr in _MUTATORS:
            note(_self_attr(stmt.func.value), stmt)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                note(_self_attr(t), t)
                if isinstance(t, ast.Subscript):
                    note(_self_attr(t.value), t)
    return out


def _assigned_attrs(meth: ast.AST) -> Set[str]:
    """Attributes ``meth`` (re)establishes: everything `_mutations` sees
    plus the bulk-restore idioms."""
    out = set(_mutations(meth))
    for node in ast.walk(meth):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "update":
            tgt = node.func.value
            if isinstance(tgt, ast.Attribute) and tgt.attr == "__dict__" \
                    and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                out.add(_ALL)
        if isinstance(node.func, ast.Name) and node.func.id == "setattr" \
                and node.args and isinstance(node.args[0], ast.Name) \
                and node.args[0].id == "self":
            if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
                out.add(str(node.args[1].value))
            else:
                out.add(_ALL)
    return out


def _read_attrs(meth: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(meth):
        attr = _self_attr(node)
        if attr and isinstance(node.ctx, ast.Load):
            out.add(attr)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in ("getattr", "vars") and node.args \
                and isinstance(node.args[0], ast.Name) \
                and node.args[0].id == "self":
            out.add(_ALL)
    for node in ast.walk(meth):
        if isinstance(node, ast.Attribute) and node.attr == "__dict__" \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            out.add(_ALL)
    return out


def _reachable(graph, cls: ast.ClassDef, start: ast.AST,
               depth: int = 3) -> List[ast.AST]:
    """``start`` plus the intra-class methods it reaches through
    self-calls (call or by-name) within ``depth`` hops."""
    methods = {m.name: m for m in cls.body
               if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
    seen = [start]
    if graph is None:
        return seen
    frontier = [start]
    for _ in range(depth):
        nxt = []
        for meth in frontier:
            for site in graph.calls:
                if site.caller is None or site.caller.node is not meth:
                    continue
                callee = site.callee
                if callee.cls == cls.name and callee.name in methods:
                    node = methods[callee.name]
                    if node not in seen:
                        seen.append(node)
                        nxt.append(node)
        frontier = nxt
        if not frontier:
            break
    return seen


def _is_classmethod(meth: ast.AST) -> bool:
    for dec in meth.decorator_list:
        if isinstance(dec, ast.Name) and dec.id in ("classmethod",
                                                    "staticmethod"):
            return True
    return False


@register
class CheckpointCoverageRule(Rule):
    id = "checkpoint-coverage"
    contract = ("classes with mutable windows/offsets/partials/fleet "
                "state implement snapshot/restore AND cover every such "
                "field in both")
    runtime_twin = ("CheckpointCoordinator barriers + crash/resume "
                    "identity tests (tests/test_recovery.py)")
    severity = "warning"
    depth = "interprocedural (snapshot/restore reach via self-calls)"
    scope = ("spatialflink_tpu/runtime/*.py",
             # named explicitly (already inside runtime/*.py): the fleet
             # manifest's fleet_* fields are supervisor-durable state and
             # MUST stay under snapshot/restore coverage as they grow
             "spatialflink_tpu/runtime/fleet*.py",
             "spatialflink_tpu/operators/*.py",
             "spatialflink_tpu/streams/*.py",
             # the tenant ledger rides coordinated checkpoints (component
             # 'tenants'): its snapshot/restore coverage is linted too
             "spatialflink_tpu/utils/accounting.py")

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        graph = project.graph(mod) if project is not None else None
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {m.name: m for m in cls.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            state_writes: Dict[str, ast.AST] = {}
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                        or meth.name in _EXEMPT_METHODS:
                    continue
                for attr, node in _mutations(meth).items():
                    if _STATE_PAT.search(attr) \
                            and attr not in state_writes:
                        state_writes[attr] = node
            if not state_writes:
                continue
            missing = [m for m in ("snapshot", "restore")
                       if m not in methods]
            if missing:
                attrs = ", ".join(
                    f"{a} (line {n.lineno})" for a, n in sorted(
                        state_writes.items(),
                        key=lambda kv: kv[1].lineno))
                yield self.finding(
                    mod, cls,
                    f"class mutates streaming state outside __init__ "
                    f"[{attrs}] but lacks {' and '.join(missing)} — "
                    "register it as a checkpoint component or allowlist "
                    "with the reason its state may be lost on resume")
                continue
            yield from self._field_coverage(mod, graph, cls, methods,
                                            state_writes)

    def _field_coverage(self, mod: ModuleSource, graph,
                        cls: ast.ClassDef, methods: Dict[str, ast.AST],
                        state_writes: Dict[str, ast.AST]
                        ) -> Iterator[Finding]:
        snap_reads: Set[str] = set()
        for meth in _reachable(graph, cls, methods["snapshot"]):
            snap_reads |= _read_attrs(meth)
        restore = methods["restore"]
        rest_writes: Set[str] = set()
        if _is_classmethod(restore):
            rest_writes.add(_ALL)  # constructor-style restore: blind spot
        else:
            for meth in _reachable(graph, cls, restore):
                rest_writes |= _assigned_attrs(meth)
        for attr, node in sorted(state_writes.items(),
                                 key=lambda kv: kv[1].lineno):
            gaps: List[str] = []
            if attr not in snap_reads and _ALL not in snap_reads:
                gaps.append("never read in snapshot()")
            if attr not in rest_writes and _ALL not in rest_writes:
                gaps.append("never assigned in restore()")
            if not gaps:
                continue
            yield self.finding(
                mod, node,
                f"state attr self.{attr} is mutated outside __init__ "
                f"but {' and '.join(gaps)} — a crash/resume silently "
                "loses it; serialize it in the pair or allowlist with "
                "the reviewed reason it is rebuildable")


def state_attributes(cls: ast.ClassDef) -> List[Tuple[str, int]]:
    """Expose the heuristic for tests/docs: the checkpoint-relevant
    (attr, first-mutation line) pairs a class mutates outside
    ``__init__`` — subscript stores and container mutators included."""
    out: Dict[str, int] = {}
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or meth.name in _EXEMPT_METHODS:
            continue
        for attr, node in _mutations(meth).items():
            if _STATE_PAT.search(attr) and attr not in out:
                out[attr] = node.lineno
    return sorted(out.items(), key=lambda kv: kv[1])
