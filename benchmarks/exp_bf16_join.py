"""A/B the join lattice precisions on-chip (TPU_NOTES §7 experiment 5):

- f32: `join_mask` — `Precision.HIGHEST`, three bf16 MXU passes;
- bf16: `join_mask_bf16_superset` — single pass + margin (the decision
  stays exact via the sparse f32 re-check in `join_pairs_host`, which this
  experiment does NOT time: the lattice is the MXU-bound term).

Usage: python benchmarks/exp_bf16_join.py [--na 262144] [--nb 1024]
Prints one JSON line per strategy with the slope-method per-window time.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks._common import settle_backend  # noqa: E402
from benchmarks.bench_configs import _grid, _points, _slope_time  # noqa: E402

RADIUS = 0.5


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--na", type=int, default=262_144)
    ap.add_argument("--nb", type=int, default=1_024)
    args = ap.parse_args()

    settle_backend()
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops import join as J

    backend = jax.default_backend()
    grid = _grid()
    a = jax.device_put(_points(grid, args.na, seed=0))
    b = jax.device_put(_points(grid, args.nb, seed=1))
    L = grid.candidate_layers(RADIUS)
    cx = (grid.min_x + grid.max_x) / 2
    cy = (grid.min_y + grid.max_y) / 2

    for name, fn in (("f32", J.join_mask),
                     ("bf16_superset", J.join_mask_bf16_superset)):
        @jax.jit  # one compile covers every count (_slope_time's contract)
        def run_n(iters, fn=fn):
            def body(i, acc):
                m = fn(a._replace(x=a.x + i * 1e-9), b, RADIUS, L, cx, cy,
                       n=grid.n)
                return acc + jnp.sum(m, dtype=jnp.int32)
            return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

        per = _slope_time(run_n, lo=2, hi=6)
        print(json.dumps(dict(
            strategy=name, na=args.na, nb=args.nb,
            per_window_ms=round(per * 1e3, 3),
            pair_tests_per_sec=round(args.na * args.nb / per),
            backend=backend)), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
