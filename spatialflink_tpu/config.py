"""YAML config system (reference: ``utils/Params.java:74-489`` +
``utils/ConfigType.java`` + ``conf/geoflink-conf.yml``).

The reference loads a snakeyaml POJO and null-checks every field with typed
exceptions; here the same schema is parsed into dataclasses with explicit
validation errors naming the offending key. The YAML key names are kept
byte-identical to the reference's so an existing ``geoflink-conf.yml`` drops
in unchanged (the leading ``!!GeoFlink.utils.ConfigType`` java type tag is
tolerated and stripped).
"""

from __future__ import annotations

import dataclasses
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import LineString, Point, Polygon

SUPPORTED_FORMATS = ("GeoJSON", "WKT", "CSV", "TSV")
SUPPORTED_AGGREGATES = ("ALL", "SUM", "AVG", "MIN", "MAX", "COUNT")
SUPPORTED_WINDOW_TYPES = ("TIME", "COUNT")


class ConfigError(ValueError):
    """Raised on a missing/invalid config field (the reference throws
    ``NullPointerException``/``IllegalArgumentException`` per field,
    ``utils/Params.java:100-489``)."""


def _req(d: Dict[str, Any], key: str, where: str):
    if key not in d or d[key] is None:
        raise ConfigError(f"{where}: missing required key {key!r}")
    return d[key]


def _opt(d: Dict[str, Any], key: str, default):
    v = d.get(key)
    return default if v is None else v


def _normalize_delimiter(v: str) -> str:
    # the reference conf writes TSV delimiters as a literal TAB, "\t", or
    # "\\\\t" (conf/geoflink-conf.yml:24,40); all map to TAB
    if v in ("\\t", "\\\\t", "\t"):
        return "\t"
    return v


def _coord_pairs(v) -> List[Tuple[float, float]]:
    """queryPoints: YAML list of [x, y] pairs, or the reference's CLI
    bracket-string form '"[116.5, 40.5], [117.0, 40.7]"'
    (``HelperClass.getCoordinates``, :145-161)."""
    if isinstance(v, str):
        from spatialflink_tpu.streams.formats import parse_bracket_coords

        return parse_bracket_coords(v)
    return [tuple(map(float, p)) for p in v]


def _coord_lists(v) -> List[List[Tuple[float, float]]]:
    """queryPolygons/queryLineStrings: YAML nested lists, or the CLI
    bracket-string form '"[[x, y], ...], [[x, y], ...]"'
    (``HelperClass.getListCoordinates``, :163-179) — each group is one
    polygon ring / linestring."""
    if isinstance(v, str):
        from spatialflink_tpu.streams.formats import parse_bracket_rings

        return parse_bracket_rings(v)
    return [[tuple(map(float, c)) for c in grp] for grp in v]


@dataclass
class StreamConfig:
    """One ``inputStream{1,2}`` block (``utils/ConfigType.java:20-40``)."""

    topic_name: str = ""
    format: str = "GeoJSON"
    date_format: Optional[str] = "%Y-%m-%d %H:%M:%S"
    geojson_obj_id_attr: str = "oID"
    geojson_timestamp_attr: str = "timestamp"
    csv_tsv_schema: Sequence[int] = (0, 1, 2, 3)
    grid_bbox: Tuple[float, float, float, float] = (0.0, 0.0, 1.0, 1.0)
    num_grid_cells: int = 100
    cell_length: float = 0.0
    delimiter: str = ","
    charset: str = "UTF-8"

    def geojson_kwargs(self) -> dict:
        """GeoJSON parser kwargs — the single source shared by the record
        path (driver.decode_stream) and both bulk ingest paths, so a
        renamed/added attribute cannot let them diverge."""
        return {"property_obj_id": self.geojson_obj_id_attr,
                "property_timestamp": self.geojson_timestamp_attr,
                "date_format": self.date_format}

    @classmethod
    def from_dict(cls, d: Dict[str, Any], where: str) -> "StreamConfig":
        fmt = str(_req(d, "format", where))
        if fmt not in SUPPORTED_FORMATS:
            raise ConfigError(
                f"{where}.format: {fmt!r} not in {SUPPORTED_FORMATS}")
        bbox = _req(d, "gridBBox", where)
        if len(bbox) != 4:
            raise ConfigError(f"{where}.gridBBox: need [minX, minY, maxX, maxY]")
        num_cells = int(_opt(d, "numGridCells", 0))
        cell_len = float(_opt(d, "cellLength", 0.0))
        if num_cells <= 0 and cell_len <= 0:
            raise ConfigError(
                f"{where}: one of numGridCells/cellLength must be positive")
        gj = list(_opt(d, "geoJSONSchemaAttr", ["oID", "timestamp"]))
        schema = [int(i) for i in _opt(d, "csvTsvSchemaAttr", [0, 1, 2, 3])]
        date_fmt = _java_date_format_to_python(
            _opt(d, "dateFormat", "yyyy-MM-dd HH:mm:ss"))
        return cls(
            topic_name=str(_req(d, "topicName", where)),
            format=fmt,
            date_format=date_fmt,
            geojson_obj_id_attr=gj[0] if gj else "oID",
            geojson_timestamp_attr=gj[1] if len(gj) > 1 else "timestamp",
            csv_tsv_schema=schema,
            grid_bbox=(float(bbox[0]), float(bbox[1]),
                       float(bbox[2]), float(bbox[3])),
            num_grid_cells=num_cells,
            cell_length=cell_len,
            delimiter=_normalize_delimiter(str(_opt(d, "delimiter", ","))),
            charset=str(_opt(d, "charset", "UTF-8")),
        )

    def make_grid(self) -> UniformGrid:
        """Grid per the stream's bbox — cellLength (meters-style) takes
        precedence when positive, like ``StreamingJob.java:309-315``."""
        min_x, min_y, max_x, max_y = self.grid_bbox
        if self.cell_length > 0:
            return UniformGrid(min_x, max_x, min_y, max_y,
                               cell_length=self.cell_length)
        return UniformGrid(min_x, max_x, min_y, max_y,
                           num_grid_partitions=self.num_grid_cells)


def _java_date_format_to_python(fmt: Optional[str]) -> Optional[str]:
    """yyyy-MM-dd HH:mm:ss → %Y-%m-%d %H:%M:%S (SimpleDateFormat subset)."""
    if not fmt:
        return None
    table = [
        ("yyyy", "%Y"), ("yy", "%y"), ("MM", "%m"), ("dd", "%d"),
        ("HH", "%H"), ("mm", "%M"), ("ss", "%S"), ("SSS", "%f"),
    ]
    out = str(fmt)
    for j, p in table:
        out = out.replace(j, p)
    return out


@dataclass
class OutputStreamConfig:
    topic_name: str = "output"
    delimiter: str = ","

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OutputStreamConfig":
        return cls(
            topic_name=str(_opt(d, "topicName", "output")),
            delimiter=_normalize_delimiter(str(_opt(d, "delimiter", ","))),
        )


@dataclass
class QueryConfig:
    """``query:`` block (``conf/geoflink-conf.yml:49-72``)."""

    option: int = 1
    approximate: bool = False
    # answer ALL configured query points/geometries in one dispatch per
    # window (run_multi — TPU-native extension; the reference uses only the
    # FIRST query object, one query per job). Opt-in to preserve that
    # reference parity by default.
    multi_query: bool = False
    # device-mesh width for distributed window evaluation — the TPU analogue
    # of the reference's task parallelism (``env.setParallelism(30)``,
    # StreamingJob.java:221). 0/1 = single device.
    parallelism: int = 0
    # outer (DCN) axis width for multi-host runs: hosts > 1 makes the mesh
    # 2-D (hosts x parallelism/hosts) with two-level ICI->DCN merges; must
    # divide parallelism. 0/1 = flat 1-D mesh.
    hosts: int = 0
    # pane-incremental sliding-window execution (the --panes driver switch):
    # kernel partials computed once per slide-aligned pane and merged across
    # overlapping windows. Execution knob only — results are identical to
    # full-window evaluation (and tumbling/undecomposable specs bypass it).
    panes: bool = False
    # device-resident pane state (the --pane-merge driver switch): pane
    # partials stay in device memory across slides and windows merge them
    # on device, reading back only the sealed window's merged result.
    # Execution knob only — identical results; None = auto (device on
    # accelerator backends, host on CPU), False = host merge (the A/B the
    # pane-state bench row measures).
    pane_device_merge: Optional[bool] = None
    radius: float = 0.0
    aggregate_function: str = "SUM"
    k: int = 10
    omega_duration_s: int = 10
    traj_ids: List[str] = field(default_factory=list)
    query_points: List[Tuple[float, float]] = field(default_factory=list)
    query_polygons: List[List[Tuple[float, float]]] = field(default_factory=list)
    query_linestrings: List[List[Tuple[float, float]]] = field(default_factory=list)
    traj_deletion_threshold_s: int = 0
    allowed_lateness_s: int = 0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "QueryConfig":
        agg = str(_opt(d, "aggregateFunction", "SUM")).upper()
        if agg not in SUPPORTED_AGGREGATES:
            raise ConfigError(
                f"query.aggregateFunction: {agg!r} not in {SUPPORTED_AGGREGATES}")
        th = _opt(d, "thresholds", {})
        parallelism = int(_opt(d, "parallelism", 0))
        if parallelism < 0 or (parallelism & (parallelism - 1)):
            raise ConfigError(
                "query.parallelism: must be 0 (off) or a power of two "
                "(window batch capacities are power-of-two buckets; the "
                "point dim must divide evenly across the mesh)")
        hosts = int(_opt(d, "hosts", 0))
        if hosts < 0 or (hosts & (hosts - 1)):
            raise ConfigError("query.hosts: must be 0 (off) or a power of two")
        # hosts-divides-parallelism is checked AFTER CLI overrides (driver
        # applies --devices/--hosts on top of the YAML; validate_mesh) and
        # again in the operator ctor as the backstop
        return cls(
            option=int(_req(d, "option", "query")),
            approximate=bool(_opt(d, "approximate", False)),
            multi_query=bool(_opt(d, "multiQuery", False)),
            parallelism=parallelism,
            hosts=hosts,
            panes=bool(_opt(d, "panes", False)),
            pane_device_merge=(None if _opt(d, "paneDeviceMerge", None)
                               is None
                               else bool(_opt(d, "paneDeviceMerge", None))),
            radius=float(_opt(d, "radius", 0.0)),
            aggregate_function=agg,
            k=int(_opt(d, "k", 10)),
            omega_duration_s=int(_opt(d, "omegaDuration", 10)),
            traj_ids=[str(t) for t in _opt(d, "trajIDs", [])],
            query_points=_coord_pairs(_opt(d, "queryPoints", [])),
            query_polygons=_coord_lists(_opt(d, "queryPolygons", [])),
            query_linestrings=_coord_lists(_opt(d, "queryLineStrings", [])),
            traj_deletion_threshold_s=int(_opt(th, "trajDeletion", 0)),
            allowed_lateness_s=int(_opt(th, "outOfOrderTuples", 0)),
        )


@dataclass
class WindowConfig:
    """``window:`` block — TIME windows in seconds (``geoflink-conf.yml:74-78``)."""

    type: str = "TIME"
    interval_s: float = 5.0
    step_s: float = 5.0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WindowConfig":
        wt = str(_opt(d, "type", "TIME")).upper()
        if wt not in SUPPORTED_WINDOW_TYPES:
            raise ConfigError(
                f"window.type: {wt!r} not in {SUPPORTED_WINDOW_TYPES}")
        interval = float(_req(d, "interval", "window"))
        step = float(_opt(d, "step", interval))
        if interval <= 0 or step <= 0:
            raise ConfigError("window.interval/step must be positive")
        return cls(type=wt, interval_s=interval, step_s=step)


@dataclass
class Params:
    """Validated full config (``utils/Params.java``)."""

    cluster_mode: bool = False
    kafka_bootstrap_servers: str = "localhost:9092"
    input1: StreamConfig = field(default_factory=StreamConfig)
    input2: StreamConfig = field(default_factory=StreamConfig)
    output: OutputStreamConfig = field(default_factory=OutputStreamConfig)
    query: QueryConfig = field(default_factory=QueryConfig)
    window: WindowConfig = field(default_factory=WindowConfig)
    # CLI-only knobs (no YAML field in the reference schema): state
    # checkpointing for stateful realtime queries
    checkpoint_path: Optional[str] = None
    checkpoint_every: int = 16
    # job fingerprint stored in (and verified against) checkpoint meta so a
    # resume under a different query/window config refuses instead of
    # producing wrong state; set by the driver from job_fingerprint()
    checkpoint_job: Optional[str] = None

    # ------------------------------------------------------------------ #

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Params":
        in1 = StreamConfig.from_dict(_req(d, "inputStream1", "config"),
                                     "inputStream1")
        in2_raw = d.get("inputStream2")
        in2 = (StreamConfig.from_dict(in2_raw, "inputStream2")
               if in2_raw else in1)
        return cls(
            cluster_mode=bool(_opt(d, "clusterMode", False)),
            kafka_bootstrap_servers=str(
                _opt(d, "kafkaBootStrapServers", "localhost:9092")),
            input1=in1,
            input2=in2,
            output=OutputStreamConfig.from_dict(_opt(d, "outputStream", {})),
            query=QueryConfig.from_dict(_req(d, "query", "config")),
            window=WindowConfig.from_dict(_req(d, "window", "config")),
        )

    @classmethod
    def from_yaml(cls, path: str) -> "Params":
        import yaml

        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        # strip the java type tag the reference's snakeyaml needs
        text = re.sub(r"^!!\S+\s*\n", "", text)
        data = yaml.safe_load(text)
        if not isinstance(data, dict):
            raise ConfigError(f"{path}: not a mapping")
        return cls.from_dict(data)

    # -------------------------- derived objects ----------------------- #

    def grids(self) -> Tuple[UniformGrid, UniformGrid]:
        """(uGrid, qGrid) like ``StreamingJob.java:309-315``."""
        return self.input1.make_grid(), self.input2.make_grid()

    def query_point_objects(self, grid: UniformGrid) -> List[Point]:
        return [Point.create(x, y, grid=grid)
                for x, y in self.query.query_points]

    def query_polygon_objects(self, grid: UniformGrid) -> List[Polygon]:
        return [Polygon.create([list(c)], grid=grid)
                for c in self.query.query_polygons]

    def query_linestring_objects(self, grid: UniformGrid) -> List[LineString]:
        return [LineString.create(list(c), grid=grid)
                for c in self.query.query_linestrings]

    def window_ms(self) -> Tuple[int, int]:
        return (int(self.window.interval_s * 1000),
                int(self.window.step_s * 1000))

    def validate_mesh(self) -> None:
        """Cross-field mesh validation — called AFTER CLI overrides land on
        top of the YAML (--devices/--hosts), so a valid combination split
        between the two sources isn't rejected at load time and an invalid
        CLI value fails with a config error, not a deep traceback."""
        h, p = self.query.hosts, self.query.parallelism
        if h < 0 or (h & (h - 1)):
            raise ConfigError("hosts: must be 0 (off) or a power of two")
        if p < 0 or (p & (p - 1)):
            raise ConfigError("parallelism: must be 0 (off) or a power of two")
        if h > 1 and (p == 0 or p % h):
            raise ConfigError(
                "hosts must divide parallelism (the 2-D mesh is "
                f"hosts x parallelism/hosts; got hosts={h}, parallelism={p})")

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def job_fingerprint(self, group: str = "") -> str:
        """Stable 8-hex digest of what makes two runs "the same job":
        consumer group + the full query and window blocks. Folded into
        KafkaWindowSink's idempotency keys so the dedup markers of one job
        configuration never suppress the windows of a different one sharing
        the output topic (two runs differing only in e.g. queryPoints or
        the window size answer different questions and must both produce).
        Transport and execution knobs (bootstrap servers, topic names,
        formats, mesh shape) are deliberately excluded: moving the same job
        to a different broker, re-encoding its input, or changing its
        device parallelism does not change what its windows mean — a
        sharded re-run must dedup against a single-device run's markers."""
        import hashlib
        import json

        query = dataclasses.asdict(self.query)
        query.pop("parallelism", None)
        query.pop("hosts", None)
        # pane mode (and its merge placement) is an execution strategy, not
        # a semantic change: a panes-on re-run must dedup against a
        # panes-off run's markers
        query.pop("panes", None)
        query.pop("pane_device_merge", None)
        payload = {
            "group": group,
            "query": query,
            "window": dataclasses.asdict(self.window),
        }
        return hashlib.sha1(
            json.dumps(payload, sort_keys=True, default=str).encode()
        ).hexdigest()[:8]
