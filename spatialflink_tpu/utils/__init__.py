"""Cross-cutting host utilities."""

from spatialflink_tpu.utils.padding import bucket_size, pad_to
from spatialflink_tpu.utils.interner import IdInterner

__all__ = ["bucket_size", "pad_to", "IdInterner"]
