"""Batch kernels over polygon / linestring edge-array geometries.

These replace the per-tuple JTS calls in the reference's polygon/linestring
operators (``range/PointPolygonRangeQuery.java``, ``PolygonPointRangeQuery``
etc.) with masked array math over :class:`EdgeGeomBatch`.

Distance semantics follow JTS ``Geometry.distance``:
- point -> polygon: 0 if the point is inside the areal geometry, else min
  boundary distance; point -> linestring: min boundary distance.
- polygon/linestring -> polygon/linestring: 0 if they intersect (boundary
  crossing or containment), else min boundary-boundary distance.

Shapes: a trailing broadcast convention — points (N,), geometries (G, E, 4)
— producing (N, G) results. The elementwise lattices ((N, G, E) etc.) are
reduction operands that XLA fuses; nothing of that size is materialized.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from spatialflink_tpu.models.batches import EdgeGeomBatch, PointBatch
from spatialflink_tpu.ops import distances as D
from spatialflink_tpu.utils.deviceplane import instrumented_jit

_BIG = np.float32(3.4e38)


@instrumented_jit
def points_in_geoms(px, py, edges, edge_mask):
    """(N, G) even-odd containment of each point in each geometry's rings."""
    return D.point_in_rings(
        px[:, None, None], py[:, None, None], edges[None], edge_mask[None]
    )


@instrumented_jit
def points_to_edges_dist(px, py, edges, edge_mask):
    """(N, G) min boundary distance from each point to each edge set."""
    d2 = D.point_segment_dist2(
        px[:, None, None],
        py[:, None, None],
        edges[None, ..., 0],
        edges[None, ..., 1],
        edges[None, ..., 2],
        edges[None, ..., 3],
    )
    return jnp.sqrt(jnp.min(jnp.where(edge_mask[None], d2, _BIG), axis=-1))


@instrumented_jit
def points_to_geoms_dist(points: PointBatch, geoms: EdgeGeomBatch):
    """(N, G) JTS-style distance from each point to each geometry."""
    bdist = points_to_edges_dist(points.x, points.y, geoms.edges, geoms.edge_mask)
    inside = points_in_geoms(points.x, points.y, geoms.edges, geoms.edge_mask)
    return jnp.where(inside & geoms.is_areal[None, :], 0.0, bdist)


@partial(instrumented_jit, static_argnames=("k", "strategy", "approximate"))
def knn_points_to_geom_queries(points: PointBatch, geoms: EdgeGeomBatch,
                               nb_masks, *, k: int, strategy: str = "auto",
                               approximate: bool = False):
    """kNN of each of Q geometry QUERIES over one point-window batch in ONE
    dispatch: -> (KnnResult with (Q, k) fields, dist_evals (Q,)).

    Multi-query companion of the ``PointPolygonKNNQuery`` path (reference
    runs one query polygon per job, ``StreamingJob.java:470``): ``geoms``
    holds the Q query polygons/linestrings as one padded edge batch,
    ``nb_masks`` is the (Q, n*n) dense neighboring-cells mask per query
    (``GeomQueryMixin._query_nb`` per geometry). Exact mode reuses the
    (N, G) point->geometry lattice; approximate mode substitutes bbox
    distances per the reference's approximate flag. Selection is the
    batched dedup+top-k (``ops.knn.topk_by_distance_multi`` — exactness
    rescue included).
    """
    from spatialflink_tpu.ops.knn import topk_by_distance_multi

    if approximate:
        b = geoms.bbox  # (Q, 4)
        d = D.point_bbox_dist(
            points.x[None, :], points.y[None, :],
            b[:, 0, None], b[:, 1, None], b[:, 2, None], b[:, 3, None])
    else:
        d = points_to_geoms_dist(points, geoms).T  # (Q, N)
    cell = jnp.maximum(points.cell, 0)
    in_grid = points.valid & (points.cell >= 0)
    elig = in_grid[None, :] & nb_masks[:, cell]
    res = topk_by_distance_multi(points.obj_id, d, elig, k, strategy)
    return res, jnp.sum(elig, axis=1, dtype=jnp.int32)


def points_to_single_geom_dist(points: PointBatch, edges, edge_mask, is_areal: bool):
    """(N,) distance from every point to ONE query geometry (the common
    point-stream x polygon-query case).

    Delegates to :func:`ops.pallas_kernels.pip_dist`, which self-dispatches:
    fused pallas kernel on TPU, the jnp twin everywhere else."""
    from spatialflink_tpu.ops import pallas_kernels as PK

    return PK.pip_dist(points.x, points.y, edges, edge_mask, bool(is_areal))


@instrumented_jit
def points_to_single_edges_raw(px, py, edges, edge_mask):
    """(inside, min_dist2) of each point vs ONE edge set — the shared jnp twin
    of the pallas pip kernel. Empty/fully-masked edge sets yield +inf dist2."""
    d2 = D.point_segment_dist2(
        px[:, None],
        py[:, None],
        edges[None, :, 0],
        edges[None, :, 1],
        edges[None, :, 2],
        edges[None, :, 3],
    )
    pad = jnp.full((d2.shape[0], 1), _BIG)  # keeps the reduction non-empty-safe
    mind2 = jnp.min(jnp.concatenate([jnp.where(edge_mask[None], d2, _BIG), pad], axis=-1), axis=-1)
    inside = D.point_in_rings(px[:, None], py[:, None], edges[None], edge_mask[None])
    return inside, mind2


@instrumented_jit
def geoms_to_single_geom_dist(geoms: EdgeGeomBatch, q_edges, q_mask, q_areal: bool):
    """(G,) JTS-style distance from each batch geometry to ONE query geometry.

    Intersection => 0 falls out of the segment-segment kernel (crossing
    boundaries have a zero-distance segment pair). Containment with disjoint
    boundaries is resolved by vertex tests — over ALL valid vertices on both
    sides, so multi-part geometries (one component far, another contained)
    are handled: with disjoint boundaries, any vertex inside <=> that whole
    component inside. Padded geometry slots (no valid edges) report +inf.
    """
    bdist2 = jax.vmap(
        lambda e, m: D.edges_edges_dist2(e, m, q_edges, q_mask)
    )(geoms.edges, geoms.edge_mask)

    # any valid vertex of the geometry inside the (areal) query: (G, E) -> (G,)
    g_in_q = D.point_in_rings(
        geoms.edges[..., 0:1], geoms.edges[..., 1:2], q_edges[None, None], q_mask[None, None]
    )
    g_in_q = jnp.any(g_in_q & geoms.edge_mask, axis=-1) & q_areal

    # any valid query vertex inside the (areal) geometry: (G, Eq) -> (G,)
    q_in_g = D.point_in_rings(
        q_edges[None, :, 0:1], q_edges[None, :, 1:2],
        geoms.edges[:, None], geoms.edge_mask[:, None],
    )
    q_in_g = jnp.any(q_in_g & q_mask[None, :], axis=-1) & geoms.is_areal

    has_edges = jnp.any(geoms.edge_mask, axis=-1)
    zero = (g_in_q | q_in_g) & has_edges
    return jnp.where(zero, 0.0, jnp.sqrt(bdist2))


@instrumented_jit
def geoms_bbox_dist(geoms: EdgeGeomBatch, q_bbox):
    """(G,) bbox-bbox distance to a query bbox — the approximate-mode
    prefilter (DistanceFunctions.java:298-421)."""
    return D.bbox_bbox_dist(geoms.bbox, q_bbox[None, :])


@instrumented_jit
def point_to_geoms_dist(px, py, geoms: EdgeGeomBatch):
    """(G,) distance from ONE query point to each batch geometry (the
    polygon-stream x point-query case, ``PolygonPointRangeQuery``)."""
    d2 = D.point_segment_dist2(
        px, py,
        geoms.edges[..., 0], geoms.edges[..., 1],
        geoms.edges[..., 2], geoms.edges[..., 3],
    )
    bdist = jnp.sqrt(jnp.min(jnp.where(geoms.edge_mask, d2, _BIG), axis=-1))
    inside = D.point_in_rings(px, py, geoms.edges, geoms.edge_mask)
    return jnp.where(inside & geoms.is_areal, 0.0, bdist)


def _geom_elig_multi(geoms: EdgeGeomBatch, nb_masks):
    """(Q, G) eligibility of each batch geometry for each query: valid and
    ANY overlapped cell inside that query's dense neighboring-cells mask
    (the multi-query form of :func:`geom_cells_any_within`)."""
    hit = nb_masks[:, jnp.maximum(geoms.cells, 0)]  # (Q, G, C)
    any_in = jnp.any(hit & geoms.cells_mask[None], axis=-1)
    return geoms.valid[None, :] & any_in


@partial(instrumented_jit, static_argnames=("k", "strategy", "approximate"))
def knn_geoms_to_point_queries(geoms: EdgeGeomBatch, qx, qy, nb_masks, *,
                               k: int, strategy: str = "auto",
                               approximate: bool = False):
    """kNN of Q query POINTS over one polygon/linestring window batch in ONE
    dispatch (multi-query ``PolygonPointKNNQuery``/``LineStringPoint...``):
    -> (KnnResult with (Q, k) fields, dist_evals (Q,)). Approximate mode
    substitutes point->bbox distances like the single-query path."""
    from spatialflink_tpu.ops.knn import topk_by_distance_multi

    if approximate:
        b = geoms.bbox
        # vmap of the single-query expression (not a 2-D broadcast): the
        # per-row computation graph then matches GeomPointKNNQuery._elig_dists
        # bit-for-bit, so run() and run_multi() results are identical
        d = jax.vmap(lambda x, y: D.point_bbox_dist(
            x, y, b[:, 0], b[:, 1], b[:, 2], b[:, 3]))(qx, qy)
    else:
        d = jax.vmap(lambda x, y: point_to_geoms_dist(x, y, geoms))(qx, qy)
    elig = _geom_elig_multi(geoms, nb_masks)
    res = topk_by_distance_multi(geoms.obj_id, d, elig, k, strategy)
    return res, jnp.sum(elig, axis=1, dtype=jnp.int32)


@partial(instrumented_jit, static_argnames=("k", "strategy", "approximate"))
def knn_geoms_to_geom_queries(geoms: EdgeGeomBatch, queries: EdgeGeomBatch,
                              nb_masks, *, k: int, strategy: str = "auto",
                              approximate: bool = False):
    """kNN of Q query GEOMETRIES over one polygon/linestring window batch in
    ONE dispatch (multi-query ``PolygonPolygonKNNQuery`` and the other
    geometry-geometry pairs): ``queries`` is the Q query geometries as one
    exact-capacity padded edge batch; distances are the vmapped
    geometry->geometry kernel (:func:`geoms_to_single_geom_dist`), bbox-bbox
    in approximate mode."""
    from spatialflink_tpu.ops.knn import topk_by_distance_multi

    if approximate:
        d = jax.vmap(lambda b: geoms_bbox_dist(geoms, b))(queries.bbox)
    else:
        d = jax.vmap(
            lambda e, m, a: geoms_to_single_geom_dist(geoms, e, m, a)
        )(queries.edges, queries.edge_mask, queries.is_areal)
    elig = _geom_elig_multi(geoms, nb_masks)
    res = topk_by_distance_multi(geoms.obj_id, d, elig, k, strategy)
    return res, jnp.sum(elig, axis=1, dtype=jnp.int32)


@partial(instrumented_jit, static_argnames=("approximate",))
def range_points_to_geom_queries(points: PointBatch, queries: EdgeGeomBatch,
                                 gn_masks, cn_masks, radius, *,
                                 approximate: bool = False):
    """Range filter of Q geometry QUERIES over one point window batch in ONE
    dispatch (multi-query ``PointPolygonRangeQuery``/``PointLineString...``):
    -> (masks (Q, N), gn_bypassed (Q,), dist_evals (Q,)). Per query, a vmap
    of the single-query expressions — dense GN/CN masks + exact geometry
    distance (bbox distance in approximate mode, which still passes through
    the radius check like the single path).

    Exact mode computes distances via the (N, G) lattice while the
    single-query path uses the static-``is_areal`` single-geom kernel, so
    ``run()`` and ``run_multi()`` may disagree on radius-BOUNDARY records
    in the last ulp on TPU (different reduction orders); CPU parity tests
    cannot observe this. TPU_NOTES §7 carries the on-chip parity check."""
    from spatialflink_tpu.ops.range import range_filter_masks_stats

    if approximate:
        def one(bb, gn, cn):
            d = D.point_bbox_dist(points.x, points.y, bb[0], bb[1], bb[2],
                                  bb[3])
            return range_filter_masks_stats(points, gn, cn, d, radius)

        return jax.vmap(one)(queries.bbox, gn_masks, cn_masks)
    # exact mode rides the (N, G) lattice like the kNN multi path (the
    # single-geom kernel's pallas dispatch needs a STATIC is_areal, which a
    # vmapped per-query flag cannot provide)
    d_all = points_to_geoms_dist(points, queries).T  # (Q, N)
    return jax.vmap(
        lambda d, gn, cn: range_filter_masks_stats(points, gn, cn, d, radius)
    )(d_all, gn_masks, cn_masks)


@partial(instrumented_jit, static_argnames=("approximate",))
def range_geoms_to_point_queries(geoms: EdgeGeomBatch, qx, qy, gn_masks,
                                 nb_masks, radius, *,
                                 approximate: bool = False):
    """Range filter of Q query POINTS over one polygon/linestring window
    batch in ONE dispatch (multi-query ``PolygonPointRangeQuery``/
    ``LineStringPoint...``): -> (masks (Q, G), gn_bypassed (Q,),
    dist_evals (Q,)). Applies the GN-subset rule per query (ALL of a
    geometry's cells guaranteed -> no distance math,
    ``range/PolygonPointRangeQuery.java:54-87``)."""
    from spatialflink_tpu.ops.range import range_filter_geom_stream_stats

    def one(x, y, gn, nbm):
        all_gn = geom_cells_all_within(geoms.cells, geoms.cells_mask, gn)
        any_nb = geom_cells_any_within(geoms.cells, geoms.cells_mask, nbm)
        if approximate:
            b = geoms.bbox
            d = D.point_bbox_dist(x, y, b[:, 0], b[:, 1], b[:, 2], b[:, 3])
        else:
            d = point_to_geoms_dist(x, y, geoms)
        return range_filter_geom_stream_stats(all_gn, any_nb, d, radius,
                                              geoms.valid)

    return jax.vmap(one)(qx, qy, gn_masks, nb_masks)


@partial(instrumented_jit, static_argnames=("approximate",))
def range_geoms_to_geom_queries(geoms: EdgeGeomBatch, queries: EdgeGeomBatch,
                                gn_masks, nb_masks, radius, *,
                                approximate: bool = False):
    """Range filter of Q query GEOMETRIES over one polygon/linestring window
    batch in ONE dispatch (multi-query ``PolygonPolygonRangeQuery`` and
    siblings): -> (masks (Q, G), gn_bypassed (Q,), dist_evals (Q,))."""
    from spatialflink_tpu.ops.range import range_filter_geom_stream_stats

    def one(e, m, a, bb, gn, nbm):
        all_gn = geom_cells_all_within(geoms.cells, geoms.cells_mask, gn)
        any_nb = geom_cells_any_within(geoms.cells, geoms.cells_mask, nbm)
        if approximate:
            d = geoms_bbox_dist(geoms, bb)
        else:
            d = geoms_to_single_geom_dist(geoms, e, m, a)
        return range_filter_geom_stream_stats(all_gn, any_nb, d, radius,
                                              geoms.valid)

    return jax.vmap(one)(queries.edges, queries.edge_mask, queries.is_areal,
                         queries.bbox, gn_masks, nb_masks)


def geom_cells_all_within(cells, cells_mask, target_mask):
    """(G,) True iff ALL of a geometry's grid cells fall inside
    ``target_mask`` — the PolygonPointRangeQuery GN-subset rule: a polygon is
    a guaranteed result only if every cell it overlaps is guaranteed
    (``range/PolygonPointRangeQuery.java:54-87``)."""
    hit = target_mask[jnp.maximum(cells, 0)] | ~cells_mask
    return jnp.all(hit, axis=-1) & jnp.any(cells_mask, axis=-1)


def geom_cells_any_within(cells, cells_mask, target_mask):
    """(G,) True iff ANY of a geometry's cells falls inside ``target_mask``
    (the cell-filter rule for candidate membership of multi-cell geometries)."""
    hit = target_mask[jnp.maximum(cells, 0)] & cells_mask
    return jnp.any(hit, axis=-1)
