"""Test environment: force an 8-device virtual CPU platform.

Note: this image's axon sitecustomize imports jax at interpreter start and
calls ``jax.config.update("jax_platforms", "axon,cpu")``, which overrides the
JAX_PLATFORMS env var. Setting env vars is therefore not enough — we must
write the config value back (and do it before any jax backend initializes,
which conftest import order guarantees)."""

import os

# XLA_FLAGS is read at backend-init time, so the env route works for it.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (sitecustomize may have imported it already)

jax.config.update("jax_platforms", "cpu")
