#!/bin/bash
# Watch for a healthy axon TPU tunnel and fire the measurement session the
# moment it answers (windows are short and unpredictable — see TPU_NOTES §4;
# probing between work items by hand misses them).
#
#   bash benchmarks/tpu_watch.sh [probe_interval_s]
#
# One successful tpu_session.sh run, then exit. Designed to live in a tmux
# session; progress in benchmarks/TPU_ATTEMPTS.log. The probe is a separate
# short-lived python so a wedged tunnel never hangs the watcher itself.
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-420}"
LOG=benchmarks/TPU_ATTEMPTS.log

# tpu_probe.sh is the single probe implementation: `env -u JAX_PLATFORMS`
# (an inherited CPU guard would otherwise fail the probe forever on a
# healthy tunnel), rejects JAX's silent CPU fallback, and logs each
# attempt to TPU_ATTEMPTS.log itself
echo "$(date -u +%FT%TZ) watch: start (interval ${INTERVAL}s)" >> "$LOG"
while true; do
  if bash benchmarks/tpu_probe.sh 50 >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) watch: tunnel ANSWERED - running session" >> "$LOG"
    bash benchmarks/tpu_session.sh >> "$LOG" 2>&1
    echo "$(date -u +%FT%TZ) watch: session finished - exiting" >> "$LOG"
    exit 0
  fi
  sleep "$INTERVAL"
done
