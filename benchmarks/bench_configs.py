"""BASELINE.md benchmark ledger: all five canonical configs + p50 latency.

Usage: python benchmarks/bench_configs.py [--scale small|full] [--out PATH]

Emits one JSON line per config and writes the full table to
``benchmarks/RESULTS_<backend>.json``. Configs (BASELINE.md):

1. Point-Point range, Beijing 100x100 grid, r=0.5, 1M-point window
2. Point-Point kNN k=50, 1M-point window  (the bench.py headline)
3. Stream-stream join, grid-cell hash join (a sharded x b replicated lattice)
4. Point-Polygon range, 10k-polygon query set, batched point-in-polygon
5. Polygon-Polygon range over data-parallel windows on an 8-device mesh
   (virtual CPU mesh here; the multi-host SHAPE, not a hardware number)

Throughput uses the slope method (index-dependent fori_loop timed at two
iteration counts — isolates steady-state per-window device time from
dispatch overhead; see bench.py). p50 window latency is the dispatch->
readback wall clock of a single window, the latency a realtime caller sees.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BEIJING = (115.50, 117.60, 39.60, 41.10)

# shared escalation constants (bench.py keeps its own copy by design — the
# driver runs it standalone at round end; keep the values in sync)
SLOPE_MIN_GAP_S = 0.2
SLOPE_MAX_HI = 40_000


def _slope_time_ex(run_n, lo=2, hi=10):
    """Steady-state (seconds per iteration, gap_cleared_floor) of run_n(iters).

    ``run_n`` must take the loop count as a DYNAMIC (traced) argument so one
    compile covers every count (warm-up runs once, not per count). The high
    count escalates (×5) until the timed gap clears the axon tunnel's RTT
    jitter — a fixed 4-8 window gap is a few ms for the fast kernels, well
    inside that jitter (the round-3 "non-positive slope" failure mode).
    ``ok=False`` marks a measurement whose gap never cleared the floor even
    at the cap; callers must surface it (sweep rows, warnings)."""
    import jax
    import jax.numpy as jnp

    warmed = False

    def timed(iters):
        nonlocal warmed
        it = jnp.int32(iters)
        if not warmed:  # compile + warm, once
            jax.block_until_ready(run_n(it))
            warmed = True
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(run_n(it))
            best = min(best, time.perf_counter() - t0)
        return best

    t_lo = timed(lo)
    while True:
        t_hi = timed(hi)
        gap = t_hi - t_lo
        if gap >= SLOPE_MIN_GAP_S or hi >= SLOPE_MAX_HI:
            break
        hi = min(hi * 5, SLOPE_MAX_HI)
    per = gap / (hi - lo)
    return (per if per > 0 else t_hi / hi), gap >= SLOPE_MIN_GAP_S


def _slope_time(run_n, lo=2, hi=10) -> float:
    per, ok = _slope_time_ex(run_n, lo=lo, hi=hi)
    if not ok:
        print("warning: slope gap stayed below the floor at the window cap; "
              "result may be noise-dominated", file=sys.stderr)
    return per


def _p50_latency_ms(dispatch, n=21) -> float:
    """p50 of single-window dispatch->readback wall clock."""
    import jax

    jax.block_until_ready(dispatch())  # compile
    lats = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(dispatch())
        lats.append((time.perf_counter() - t0) * 1000)
    return float(np.percentile(lats, 50))


def _grid():
    from spatialflink_tpu.index import UniformGrid

    return UniformGrid(BEIJING[0], BEIJING[1], BEIJING[2], BEIJING[3],
                       num_grid_partitions=100)


def _points(grid, n, seed=0, oid_mod=None):
    from spatialflink_tpu.models import PointBatch

    rng = np.random.default_rng(seed)
    return PointBatch.from_arrays(
        rng.uniform(grid.min_x, grid.max_x, n),
        rng.uniform(grid.min_y, grid.max_y, n),
        grid=grid,
        obj_id=rng.integers(0, oid_mod or max(4, n // 4), n).astype(np.int32),
    )


def bench_config1_range(scale) -> dict:
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.range import range_filter_point

    grid = _grid()
    n = 1_000_000 if scale == "full" else 262_144
    batch = jax.device_put(_points(grid, n))
    qx, qy = 116.5, 40.5
    qc = jnp.int32(grid.assign_cell(qx, qy)[0])
    r = 0.5
    gn, cn = grid.guaranteed_layers(r), grid.candidate_layers(r)

    @jax.jit
    def run_n(iters):
        def body(i, acc):
            mask, _ = range_filter_point(
                batch, qx + i * 1e-7, qy, qc, r, gn, cn, n=grid.n)
            return acc + jnp.sum(mask, dtype=jnp.int32)
        return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

    per = _slope_time(run_n)
    # batch must be a traced ARGUMENT: a zero-arg jit closure is all
    # constants and XLA folds the whole window at compile time
    win = jax.jit(lambda b: range_filter_point(b, qx, qy, qc, r, gn, cn,
                                               n=grid.n)[0])
    p50 = _p50_latency_ms(lambda: win(batch))
    return dict(config=1, name="pp_range_r0.5", window_points=n,
                points_per_sec=round(n / per), p50_window_latency_ms=round(p50, 3))


def bench_config3_join(scale) -> dict:
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.join import join_counts

    grid = _grid()
    na = 262_144 if scale == "full" else 65_536
    nb = 1_024
    a = jax.device_put(_points(grid, na, seed=1))
    b = jax.device_put(_points(grid, nb, seed=2))
    r = 0.05
    layers = grid.candidate_layers(r)
    cx = grid.min_x + grid.cell_length * grid.n / 2
    cy = grid.min_y + grid.cell_length * grid.n / 2

    @jax.jit
    def run_n(iters):
        def body(i, acc):
            per_a, total = join_counts(a, b, r + i * 1e-9, layers, cx, cy,
                                       n=grid.n)
            return acc + total
        return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

    per = _slope_time(run_n)
    win = jax.jit(lambda aa, bb: join_counts(aa, bb, r, layers, cx, cy,
                                             n=grid.n)[1])
    p50 = _p50_latency_ms(lambda: win(a, b))
    return dict(config=3, name="pp_join_lattice", a_points=na, b_points=nb,
                pair_tests_per_sec=round(na * nb / per),
                a_points_per_sec=round(na / per),
                p50_window_latency_ms=round(p50, 3))


def bench_config4_pip(scale) -> dict:
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.models import Polygon
    from spatialflink_tpu.models.batches import EdgeGeomBatch
    from spatialflink_tpu.ops.geom import points_to_geoms_dist

    grid = _grid()
    n = 65_536 if scale == "full" else 8_192
    g = 10_240 if scale == "full" else 1_024
    rng = np.random.default_rng(3)
    polys = []
    for i in range(g):
        cx = rng.uniform(grid.min_x + 0.1, grid.max_x - 0.1)
        cy = rng.uniform(grid.min_y + 0.1, grid.max_y - 0.1)
        w, h = rng.uniform(0.01, 0.05, 2)
        polys.append(Polygon.create(
            [[(cx - w, cy - h), (cx + w, cy - h), (cx + w, cy + h),
              (cx - w, cy + h), (cx - w, cy - h)]], grid))
    gb = jax.device_put(EdgeGeomBatch.from_objects(polys, grid))
    pts = jax.device_put(_points(grid, n, seed=4))

    @jax.jit
    def run_n(iters):
        def body(i, acc):
            d = points_to_geoms_dist(
                pts._replace(x=pts.x + i * 1e-9), gb)
            return acc + jnp.sum(d <= 0.0)
        return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

    per = _slope_time(run_n, lo=2, hi=6)
    win = jax.jit(points_to_geoms_dist)
    p50 = _p50_latency_ms(lambda: win(pts, gb))
    return dict(config=4, name="point_polygon_pip", points=n, polygons=g,
                pip_tests_per_sec=round(n * g / per),
                points_per_sec=round(n / per),
                p50_window_latency_ms=round(p50, 3))


def bench_config5_multidevice(scale) -> dict:
    """Data-parallel windows over a mesh: polygon-polygon range THROUGH THE
    OPERATOR (``GeomGeomRangeQuery`` with conf.devices — the same path
    ``run_option(option=21, parallelism=N)`` drives; VERDICT r3 missing #3).
    On CPU this validates the SHAPE on 8 virtual devices (not a hardware
    number); on a real multi-chip slice the same code is the measurement."""
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.models import Polygon
    from spatialflink_tpu.operators import (
        PolygonPolygonRangeQuery,
        QueryConfiguration,
        QueryType,
    )

    n_dev = len(jax.devices())
    grid = _grid()
    g = 8_192 if scale == "full" else 2_048
    rng = np.random.default_rng(5)
    polys = []
    for i in range(g):
        cx = rng.uniform(grid.min_x + 0.1, grid.max_x - 0.1)
        cy = rng.uniform(grid.min_y + 0.1, grid.max_y - 0.1)
        w, h = rng.uniform(0.01, 0.05, 2)
        polys.append(Polygon.create(
            [[(cx - w, cy - h), (cx + w, cy - h), (cx + w, cy + h),
              (cx - w, cy + h), (cx - w, cy - h)]], grid,
            obj_id=f"g{i}", timestamp=1_700_000_000_000 + i))
    q = Polygon.create([[(116.2, 40.2), (117.0, 40.2), (117.0, 40.9),
                         (116.2, 40.9), (116.2, 40.2)]], grid)
    r = 0.5

    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 10_000,
                              devices=n_dev)
    op = PolygonPolygonRangeQuery(conf, grid)
    # sanity: the full driver-reachable path emits the window
    n_matched = sum(len(w.records) for w in op.run(iter(polys), q, r))

    # steady-state timing over the operator's own kernels: the same
    # mask_stats closure + mesh dispatch run() uses, on its own geom batch
    mask_stats = op._mask_stats_fn(q, r)
    gb = op._shard(op._geom_batch(polys, 1_700_000_000_000))

    @jax.jit
    def run_n(iters):
        def body(i, acc):
            m, _gn, _ev = op._filter_stream(
                gb._replace(edges=gb.edges + i * 1e-9), mask_stats)
            return acc + jnp.sum(m, dtype=jnp.int32)
        return jax.lax.fori_loop(0, iters, body, jnp.int32(0))

    per = _slope_time(run_n, lo=2, hi=6)
    win = jax.jit(lambda b: op._filter_stream(b, mask_stats)[0])
    p50 = _p50_latency_ms(lambda: win(gb))
    return dict(config=5, name="polygon_polygon_range_mesh_operator",
                polygons=g, devices=n_dev, matched=n_matched,
                geoms_per_sec=round(g / per),
                p50_window_latency_ms=round(p50, 3))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=("small", "full"), default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--configs", default="1,3,4,5",
                    help="comma-separated subset")
    args = ap.parse_args()

    from benchmarks._common import settle_backend

    settle_backend()  # a wedged tunnel downgrades to CPU instead of hanging
    import jax

    backend = jax.default_backend()
    scale = args.scale or ("full" if backend == "tpu" else "small")
    fns = {1: bench_config1_range, 3: bench_config3_join,
           4: bench_config4_pip, 5: bench_config5_multidevice}
    rows = []
    for c in (int(x) for x in args.configs.split(",")):
        row = fns[c](scale)
        row["backend"] = backend
        row["scale"] = scale
        print(json.dumps(row), flush=True)
        rows.append(row)
    # a SUBSET run must not silently replace the full ledger (compare the
    # parsed sets — order/whitespace in --configs must not matter)
    requested = {int(x) for x in args.configs.split(",")}
    name = (f"RESULTS_{backend}.json" if requested >= set(fns)
            else f"RESULTS_{backend}_partial.json")
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), name)
    with open(out, "w") as f:
        json.dump({"backend": backend, "scale": scale, "rows": rows}, f,
                  indent=1)
    print(f"# wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
