"""End-to-end streaming pipeline benchmark: sustained records/s through the
WHOLE pipeline — host ingest -> watermarks -> window assembly -> device
kernel -> results — not just the device hot loop.

The kernel benches (bench.py, bench_configs.py) isolate per-window device
time; bench_ingest.py isolates the parsers. This harness measures what the
reference's Kafka->Flink jobs were actually measured by (throughput meters
wrapping the live pipeline, ``spatialObjects/Point.java:237-253``): wall
clock from the first raw record entering deserialization to the last window
sealed, for the same driver paths a user runs:

- ``record``: per-record parse -> ``driver.run_option`` (the
  reference-shaped path; one Python object per tuple)
- ``bulk``:   native C++ ingest -> ``driver.run_option_bulk`` (columnar
  windowing; the ``--bulk`` CLI flag)

Usage: python benchmarks/bench_e2e.py [--n N] [--options 1,51,101]
       [--out PATH]

Emits one JSON line per (option, path) and writes the table to
``benchmarks/RESULTS_e2e_<backend>.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BEIJING = (115.50, 117.60, 39.60, 41.10)
WINDOW_S, SLIDE_S = 10, 5
SPAN_S = 100  # event time spanned by the stream -> ~20 sliding windows


def _write_stream(path: str, n: int, seed: int = 0) -> None:
    """CSV point rows ``oid,ts_ms,x,y`` spanning SPAN_S of event time,
    timestamps nondecreasing (in-order stream; lateness is the lateness
    tests' concern, throughput is this bench's)."""
    rng = np.random.default_rng(seed)
    xs = rng.uniform(BEIJING[0], BEIJING[1], n)
    ys = rng.uniform(BEIJING[2], BEIJING[3], n)
    oid = rng.integers(0, max(n // 4, 1), n)
    t0 = 1_700_000_000_000
    ts = t0 + (np.arange(n) * (SPAN_S * 1000) // max(n, 1))
    with open(path, "w") as f:
        for i in range(n):
            f.write(f"v{oid[i]},{ts[i]},{xs[i]:.6f},{ys[i]:.6f}\n")


def _params(option: int):
    from spatialflink_tpu.config import Params

    conf = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "conf", "spatialflink-conf.yml")
    p = Params.from_yaml(conf)
    p.query.option = option
    p.query.radius = 0.5
    p.query.k = 50
    p.input1.format = "CSV"
    p.input1.date_format = None  # epoch-millisecond timestamps
    p.input2.format = "CSV"
    p.input2.date_format = None
    p.window.interval_s = WINDOW_S
    p.window.step_s = SLIDE_S
    return p


def _drain(it) -> int:
    windows = 0
    for _ in it:
        windows += 1
    return windows


def bench_option(option: int, path: str, path2, n: int) -> list:
    from spatialflink_tpu import driver

    rows = []
    needs2 = driver.CASES[option].family == "join"

    # bulk first: it warms the jit cache the record path reuses, so the
    # record row measures steady-state host cost, not compiles
    p = _params(option)
    t0 = time.perf_counter()
    it = driver.run_option_bulk(p, path, path2 if needs2 else None)
    windows = _drain(it) if it is not None else None
    dt = time.perf_counter() - t0
    if windows is not None:
        rows.append(dict(option=option, path="bulk", records=n,
                         windows=windows, wall_s=round(dt, 3),
                         records_per_sec=round(n / dt)))
    else:
        # visible, not silent: without the bulk pass the record row below
        # also pays jit compiles instead of measuring steady-state host cost
        print(f"warning: option {option}: bulk path declined "
              "(run_option_bulk returned None); bulk row omitted and the "
              "record row includes jit-compile time", file=sys.stderr)

    p = _params(option)
    with open(path) as f1:
        streams = [f1]
        if needs2:
            streams.append(open(path2))
        try:
            t0 = time.perf_counter()
            windows = _drain(driver.run_option(p, *streams))
            dt = time.perf_counter() - t0
        finally:
            for s in streams[1:]:
                s.close()
    rows.append(dict(option=option, path="record", records=n,
                     windows=windows, wall_s=round(dt, 3),
                     records_per_sec=round(n / dt)))
    return rows


class _BulkDeclined(Exception):
    pass


def _window_table(results, option: int) -> list:
    """Canonical (start, end, sorted-records) table for the pane identity
    check: bulk range windows carry original-record index lists, kNN
    windows (objID, distance) pairs."""
    table = []
    for r in results:
        recs = r.records
        if recs and isinstance(recs[0], tuple):
            recs = [(o, round(float(d), 6)) for o, d in recs]
        table.append((r.window_start, r.window_end, sorted(recs)))
    return table


def bench_panes(option: int, path: str, n: int, overlap: int) -> list:
    """Pane-incremental vs full-recompute at sliding overlap ``overlap``
    (window = overlap * slide), same backend, same replay — with window-
    table IDENTITY asserted in the same run (panes are an execution
    strategy, not a semantics change). The replay is parsed ONCE outside
    the timed region and both modes drive the operator's bulk windowed
    pipeline over it: the rows measure window assembly + kernels +
    readback — the stage panes optimize; ingest is byte-identical in both
    modes. The on-row carries the measured speedup."""
    from spatialflink_tpu import driver

    p = _params(option)
    p.window.interval_s = SLIDE_S * overlap
    p.window.step_s = SLIDE_S
    spec = driver.CASES[option]
    parsed = driver._bulk_parse_stream(p.input1, path,
                                       p.query.allowed_lateness_s)
    if parsed is None:
        print(f"warning: option {option}: bulk ingest declined for the "
              "pane rows; rows omitted", file=sys.stderr)
        raise _BulkDeclined
    u_grid, _ = p.grids()
    q = driver._query_object(p, u_grid, spec.query)

    def run(panes: bool):
        p.query.panes = panes
        conf = driver._query_conf(p, spec)
        op = driver._operator_class(spec)(conf, u_grid)
        t0 = time.perf_counter()
        if spec.family == "range":
            it = op.run_bulk(parsed, q, p.query.radius)
        else:
            it = op.run_bulk(parsed, q, p.query.radius, p.query.k)
        table = _window_table(it, option)
        return table, time.perf_counter() - t0

    run(False)  # warm the jit caches both modes share
    run(True)   # (pane batches have their own bucketed shapes)
    table_off, dt_off = run(False)
    table_on, dt_on = run(True)
    assert table_on == table_off, (
        f"option {option} overlap {overlap}: pane window table diverged "
        "from full recompute")
    base = dict(option=option, overlap=overlap, records=n,
                windows=len(table_off), identical=True)
    return [
        dict(base, path="panes_off", wall_s=round(dt_off, 3),
             records_per_sec=round(n / dt_off)),
        dict(base, path="panes_on", wall_s=round(dt_on, 3),
             records_per_sec=round(n / dt_on),
             speedup_vs_panes_off=round(dt_off / dt_on, 2)),
    ]


def bench_pane_state(option: int, path: str, n: int, overlap: int) -> list:
    """Device-resident vs host-merged pane state (the --pane-merge A/B) at
    sliding overlap ``overlap``: same replay, same backend, window-table
    identity asserted in-run. Device mode keeps pane kernel partials in
    device memory and merges each window ON device (one merged readback per
    window); host mode resolves every partial to host and merges there.
    Rows carry the measured per-slide readback bytes/transfers from the
    always-on registry counters (the same numbers the bytes_moved cost
    profile accumulates), so the data-motion contract is part of the
    ledger. Runs unchanged on any backend — on the TPU the per-readback
    saving is a tunnel RTT, not just bytes."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.utils.metrics import REGISTRY, scoped_registry

    p = _params(option)
    p.window.interval_s = SLIDE_S * overlap
    p.window.step_s = SLIDE_S
    p.query.panes = True
    spec = driver.CASES[option]
    parsed = driver._bulk_parse_stream(p.input1, path,
                                       p.query.allowed_lateness_s)
    if parsed is None:
        print(f"warning: option {option}: bulk ingest declined for the "
              "pane-state rows; rows omitted", file=sys.stderr)
        raise _BulkDeclined
    u_grid, _ = p.grids()
    q = driver._query_object(p, u_grid, spec.query)

    def run(device: bool):
        p.query.pane_device_merge = device
        conf = driver._query_conf(p, spec)
        op = driver._operator_class(spec)(conf, u_grid)
        with scoped_registry() as reg:
            t0 = time.perf_counter()
            if spec.family == "range":
                it = op.run_bulk(parsed, q, p.query.radius)
            else:
                it = op.run_bulk(parsed, q, p.query.radius, p.query.k)
            table = _window_table(it, option)
            dt = time.perf_counter() - t0
            snap = reg.snapshot()
        return table, dt, snap

    run(True)   # warm both modes' jit shapes outside the timed rows
    run(False)
    t_dev, dt_dev, snap_dev = run(True)
    t_host, dt_host, snap_host = run(False)
    assert t_dev == t_host, (
        f"option {option} overlap {overlap}: device pane merge diverged "
        "from host merge")
    slides = max(len(t_dev), 1)
    base = dict(option=option, overlap=overlap, records=n,
                windows=len(t_dev), identical=True)

    def row(path_name, dt, snap):
        rb_b = int(snap.get("pane-partial-readback-bytes", 0)
                   + snap.get("pane-merged-readback-bytes", 0))
        rb_n = int(snap.get("pane-partial-readbacks", 0)
                   + snap.get("pane-merged-readbacks", 0))
        return dict(base, path=path_name, wall_s=round(dt, 3),
                    records_per_sec=round(n / dt),
                    pane_readback_bytes=rb_b, pane_readbacks=rb_n,
                    readback_bytes_per_slide=round(rb_b / slides, 1))

    r_host = row("panes_host_merge", dt_host, snap_host)
    r_dev = row("panes_device_merge", dt_dev, snap_dev)
    r_dev["speedup_vs_host_merge"] = round(dt_host / dt_dev, 2)
    r_dev["readback_bytes_vs_host"] = round(
        r_dev["pane_readback_bytes"] / max(r_host["pane_readback_bytes"], 1),
        3)
    return [r_host, r_dev]


def bench_checkpoint(option: int, path: str, n: int, every: int) -> list:
    """Coordinated-checkpoint overhead (the robustness cost BASELINE.md
    tracks): the record path with checkpointing OFF vs a coordinator
    snapshotting every ``every`` windows — sustained throughput plus the
    per-window latency distribution (a checkpoint writes at a window
    barrier, so its cost lands on individual windows' p99, not the mean)."""
    import shutil

    from spatialflink_tpu import driver

    def run(ckpt_dir):
        p = _params(option)
        if ckpt_dir is not None:
            from spatialflink_tpu.runtime.checkpoint import (
                CheckpointCoordinator)

            p.checkpointer = CheckpointCoordinator(
                ckpt_dir, every_batches=every, job="bench")
        lat = []
        with open(path) as f1:
            t0 = time.perf_counter()
            it = iter(driver.run_option(p, f1))
            while True:
                w0 = time.perf_counter()
                try:
                    next(it)
                except StopIteration:
                    break
                lat.append(time.perf_counter() - w0)
            dt = time.perf_counter() - t0
        return dt, lat

    def pct(lat, q):
        return round(float(np.percentile(np.asarray(lat) * 1e3, q)), 2)

    run(None)  # warm the jit caches both modes share
    dt_off, lat_off = run(None)
    td = tempfile.mkdtemp(prefix="bench-ckpt-")
    try:
        dt_on, lat_on = run(td)
        n_ckpt = len([f for f in os.listdir(td) if f.endswith(".npz")])
    finally:
        shutil.rmtree(td, ignore_errors=True)
    base = dict(option=option, records=n, windows=len(lat_off),
                checkpoint_every=every)
    return [
        dict(base, path="checkpoint_off", wall_s=round(dt_off, 3),
             records_per_sec=round(n / dt_off),
             window_latency_ms=dict(p50=pct(lat_off, 50),
                                    p99=pct(lat_off, 99))),
        dict(base, path="checkpoint_on", wall_s=round(dt_on, 3),
             records_per_sec=round(n / dt_on),
             checkpoints_written=n_ckpt,
             window_latency_ms=dict(p50=pct(lat_on, 50),
                                    p99=pct(lat_on, 99)),
             overhead_vs_off=round(dt_on / dt_off - 1.0, 4)),
    ]


def bench_live_plane(option: int, path: str, n: int) -> list:
    """Overhead of the live operations plane on the record path, four
    configurations over the same replay: plane OFF, a bound-but-UNQUERIED
    status server with no telemetry session (the contract is a
    byte-identical record loop — snapshots are built per HTTP request
    only, so this must be ~0), the full plane (telemetry session +
    status server + live-stats digest thread at an interval longer than
    the run — the session's per-record instrumentation is the cost), and
    the full plane WITH window trace lineage on (``--trace-dir``'s
    recording cost: per-WINDOW trace notes + per-record cost-profile
    pending accumulation — the trace-on overhead row BASELINE.md
    tracks)."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.runtime.opserver import LiveStats, OpServer
    from spatialflink_tpu.utils.telemetry import telemetry_session

    def run():
        p = _params(option)
        with open(path) as f1:
            t0 = time.perf_counter()
            windows = _drain(driver.run_option(p, f1))
            return windows, time.perf_counter() - t0

    run()  # warm the jit caches all four configurations share
    windows, dt_off = run()
    srv = OpServer(port=0).start()
    try:
        _, dt_srv = run()
    finally:
        srv.close()

    def run_plane(trace: bool):
        from spatialflink_tpu.utils import deviceplane

        with telemetry_session(trace=trace) as tel:
            srv = OpServer(port=0).start()
            live = LiveStats(interval_s=3600.0).start()
            dp = deviceplane.registry()
            dp.begin_run()
            dp.mark_warm("bench live-plane (pre-warmed shapes)")
            try:
                dt = run()[1]
            finally:
                dp.end_run()
                live.close()
                srv.close()
            # the device-truth fields the full-plane ledger row carries:
            # post-warmup compiles (0 = the sentinel stayed silent) and
            # the per-window dispatch→ready overlap distribution
            h = tel.histograms.get("dispatch-overlap-ratio")
            overlap = h.to_dict() if h is not None else {"count": 0}
            return dt, dp.run_recompiles, overlap

    dt_full, rc_full, ovl_full = run_plane(trace=False)
    dt_trace, _rc_t, _ovl_t = run_plane(trace=True)
    base = dict(option=option, records=n, windows=windows)
    return [
        dict(base, path="live_plane_off", wall_s=round(dt_off, 3),
             records_per_sec=round(n / dt_off)),
        dict(base, path="status_server_idle", wall_s=round(dt_srv, 3),
             records_per_sec=round(n / dt_srv),
             overhead_vs_off=round(dt_srv / dt_off - 1.0, 4)),
        dict(base, path="live_plane_full", wall_s=round(dt_full, 3),
             records_per_sec=round(n / dt_full),
             overhead_vs_off=round(dt_full / dt_off - 1.0, 4),
             post_warmup_compiles=rc_full,
             dispatch_overlap=ovl_full),
        dict(base, path="live_plane_trace", wall_s=round(dt_trace, 3),
             records_per_sec=round(n / dt_trace),
             overhead_vs_off=round(dt_trace / dt_off - 1.0, 4),
             overhead_vs_full=round(dt_trace / dt_full - 1.0, 4)),
    ]


def bench_multi_vs_jobs(option: int, path: str, n: int, q: int) -> list:
    """ONE multiQuery pipeline vs Q sequential single-query pipelines over
    the same replay — the end-to-end form of the 'Q standing queries cost Q
    reference jobs re-reading the stream' claim. Bulk path for both sides
    (the throughput configuration)."""
    from spatialflink_tpu import driver

    hotspots = [(116.0 + 0.9 * i / max(q - 1, 1),
                 40.0 + 0.9 * i / max(q - 1, 1)) for i in range(q)]

    def _drain_bulk(p):
        it = driver.run_option_bulk(p, path)
        if it is None:  # eligibility gate declined — degrade visibly,
            print(f"warning: option {option}: bulk path declined for the "
                  "multi-vs-jobs rows; rows omitted", file=sys.stderr)
            raise _BulkDeclined
        return _drain(it)

    def run_multi():
        p = _params(option)
        p.query.multi_query = True
        p.query.query_points = hotspots
        return _drain_bulk(p)

    def run_jobs():
        for hx, hy in hotspots:
            p = _params(option)
            p.query.query_points = [(hx, hy)]
            _drain_bulk(p)

    # warm both sides (jit compiles; the sequential side would otherwise
    # free-ride on kernels the single-query rows above already compiled
    # while the (Q,)-shaped multi kernels compile inside the timed region)
    run_multi()
    run_jobs()
    t0 = time.perf_counter()
    windows = run_multi()
    dt_multi = time.perf_counter() - t0
    t0 = time.perf_counter()
    run_jobs()
    dt_jobs = time.perf_counter() - t0

    return [dict(option=option, path="multi_query", queries=q, records=n,
                 windows=windows, wall_s=round(dt_multi, 3),
                 record_x_queries_per_sec=round(n * q / dt_multi),
                 speedup_vs_sequential_jobs=round(dt_jobs / dt_multi, 2)),
            dict(option=option, path="sequential_jobs", queries=q, records=n,
                 wall_s=round(dt_jobs, 3),
                 record_x_queries_per_sec=round(n * q / dt_jobs))]


def bench_query_plane(path: str, n: int, q: int = 32) -> list:
    """Standing-query control plane rows (ISSUE 10):

    - ``query_plane_static``  a Q-query fleet served through the DYNAMIC
                              registry path with no churn — the control
                              plane's baseline cost over run_multi
    - ``query_plane_churn``   the same fleet with one admit + one retire
                              per window interval (fleet size constant, so
                              every change repads within the same size
                              bucket) — admission churn must not collapse
                              throughput
    - ``query_plane_q<Q>``    Q-sweep amortization THROUGH the registry:
                              registry fleet vs Q dedicated single-query
                              pipelines re-reading the stream
    """
    from spatialflink_tpu import driver
    from spatialflink_tpu.config import StreamConfig
    from spatialflink_tpu.models import Point
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.runtime.queryplane import QueryRegistry

    import numpy as np

    with open(path) as f:
        lines = f.read().splitlines()
    cfg = StreamConfig(format="CSV", date_format=None,
                       csv_tsv_schema=[0, 1, 2, 3])
    grid = _params(1).grids()[0]
    conf = QueryConfiguration(QueryType.WindowBased,
                              int(WINDOW_S * 1000), int(SLIDE_S * 1000))
    rng = np.random.default_rng(5)
    radius = 0.5

    def mkpts(m):
        return [(float(grid.min_x + rng.random() * (grid.max_x - grid.min_x)),
                 float(grid.min_y + rng.random() * (grid.max_y - grid.min_y)))
                for _ in range(m)]

    def mkreg(pts):
        reg = QueryRegistry("range", radius=radius)
        for i, (x, y) in enumerate(pts):
            reg.admit({"id": f"q{i}", "x": x, "y": y})
        reg.apply()
        return reg

    def run_registry(pts, churn=False):
        reg = mkreg(pts)
        op = PointPointRangeQuery(conf, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        seq = {"i": 0}
        results = op.run_dynamic(stream, reg, radius)
        windows = 0
        t0 = time.perf_counter()
        for _w in results:
            windows += 1
            if churn:
                # one admit + one retire per emitted window: constant
                # fleet size — every change repads within the same bucket
                i = seq["i"]
                reg.admit({"id": f"churn{i}",
                           "x": float(grid.min_x + (i % 10) * 0.1),
                           "y": float(grid.min_y + (i % 10) * 0.1)})
                live = [e.id for e in reg.active_entries()]
                reg.retire(live[0])
                seq["i"] += 1
        dt = time.perf_counter() - t0
        return windows, dt, reg

    def run_jobs(pts):
        t0 = time.perf_counter()
        for x, y in pts:
            op = PointPointRangeQuery(conf, grid)
            stream = driver.decode_stream(iter(lines), cfg, grid)
            for _ in op.run(stream, Point.create(x, y, grid), radius):
                pass
        return time.perf_counter() - t0

    rows = []
    pts = mkpts(q)
    run_registry(pts)  # warm the bucket's jit shapes
    windows, dt_static, _ = run_registry(pts)
    w2, dt_churn, reg = run_registry(pts, churn=True)
    from spatialflink_tpu.ops.range import range_filter_point_multi_masks
    compiles_before = range_filter_point_multi_masks._cache_size()
    _w3, _dt3, _ = run_registry(pts, churn=True)
    recompiles = (range_filter_point_multi_masks._cache_size()
                  - compiles_before)
    rows.append(dict(path="query_plane_static", queries=q, records=n,
                     windows=windows, wall_s=round(dt_static, 3),
                     records_per_sec=round(n / dt_static)))
    rows.append(dict(path="query_plane_churn", queries=q, records=n,
                     windows=w2, wall_s=round(dt_churn, 3),
                     records_per_sec=round(n / dt_churn),
                     churn_per_interval="1 admit + 1 retire per window",
                     fleet_repads=reg.repads.count,
                     xla_recompiles_in_bucket=recompiles,
                     churn_vs_static=round(dt_static / dt_churn, 2)))
    # Q-sweep amortization through the registry path
    for m in (1, 8, q):
        spts = mkpts(m)
        run_registry(spts)
        _wn, dt_reg, _ = run_registry(spts)
        dt_jobs = run_jobs(spts)
        rows.append(dict(
            path=f"query_plane_q{m}", queries=m, records=n,
            wall_s=round(dt_reg, 3),
            record_x_queries_per_sec=round(n * m / dt_reg),
            speedup_vs_sequential_jobs=round(dt_jobs / dt_reg, 2)))
    return rows


def bench_tenant_plane(path: str, n: int, q: int = 8) -> list:
    """Tenant accounting plane rows (ISSUE 20): the same Q-query dynamic
    registry fleet (two tenants, Q/2 queries each) over the same replay
    with the ledger OFF (no telemetry session — the gated hot path) vs
    ON (a telemetry session: per-dispatch ``note_dispatch`` + the
    proportional ``resolve`` split). Window-table identity is asserted
    in the same run — attribution is bookkeeping, never a semantics
    change — and the on-row carries the ledger's own conservation stats
    (resolved == dispatched, max residual from the exact-split fold)."""
    from spatialflink_tpu import driver
    from spatialflink_tpu.config import StreamConfig
    from spatialflink_tpu.operators import (PointPointRangeQuery,
                                            QueryConfiguration, QueryType)
    from spatialflink_tpu.runtime.queryplane import QueryRegistry
    from spatialflink_tpu.utils import telemetry as _telemetry
    from spatialflink_tpu.utils.telemetry import telemetry_session

    import numpy as np

    with open(path) as f:
        lines = f.read().splitlines()
    cfg = StreamConfig(format="CSV", date_format=None,
                       csv_tsv_schema=[0, 1, 2, 3])
    grid = _params(1).grids()[0]
    conf = QueryConfiguration(QueryType.WindowBased,
                              int(WINDOW_S * 1000), int(SLIDE_S * 1000))
    rng = np.random.default_rng(9)
    pts = [(float(grid.min_x + rng.random() * (grid.max_x - grid.min_x)),
            float(grid.min_y + rng.random() * (grid.max_y - grid.min_y)))
           for _ in range(q)]

    def run():
        reg = QueryRegistry("range", radius=0.5)
        for i, (x, y) in enumerate(pts):
            reg.admit({"id": f"q{i}", "x": x, "y": y,
                       "tenant": "acme" if i % 2 == 0 else "free"})
        reg.apply()
        op = PointPointRangeQuery(conf, grid)
        stream = driver.decode_stream(iter(lines), cfg, grid)
        t0 = time.perf_counter()
        table = [(w.window_start, tuple(len(r) for r in w.records))
                 for w in op.run_dynamic(stream, reg, 0.5)]
        return table, time.perf_counter() - t0

    run()  # warm the Q-bucket's jit shapes both configurations share
    assert _telemetry.active() is None
    table_off, dt_off = run()
    with telemetry_session() as tel:
        table_on, dt_on = run()
        ledger = tel.tenants.to_dict()
    assert table_on == table_off, (
        "tenant ledger changed the window table — attribution must be "
        "bookkeeping, not semantics")
    assert ledger["resolved"] > 0 and ledger["pending"] == 0
    assert ledger["max_residual_ms"] < 1e-6, ledger["max_residual_ms"]
    base = dict(records=n, queries=q, windows=len(table_off),
                identical=True)
    return [
        dict(base, path="tenant_plane_off", wall_s=round(dt_off, 3),
             records_per_sec=round(n / dt_off)),
        dict(base, path="tenant_plane_on", wall_s=round(dt_on, 3),
             records_per_sec=round(n / dt_on),
             overhead_vs_off=round(dt_on / dt_off - 1.0, 4),
             tenants=sorted(ledger["tenants"]),
             dispatches_resolved=ledger["resolved"],
             max_residual_ms=ledger["max_residual_ms"],
             fairness=ledger["fairness"]),
    ]


def bench_fleet(n: int) -> list:
    """Supervised multi-worker fleet rows (``--fleet``): wall clock and
    records/s for N=1/2/4 worker fleets over the 95%-hot clustered
    GeoJSON stream, plus the plain single-process run of the same replay
    as the overhead reference (``fleet_solo``). Merged-digest identity is
    asserted across every N — the exactly-once global merge — and each
    fleet row carries the supervisor's restart and post-warmup-recompile
    ledger fields. On a one-host CPU box these rows are honest about the
    supervision price: spawn + per-line routing dominate, so N>1 buys
    fault isolation, not throughput (BASELINE.md). A final
    ``fleet_plane_overhead`` row prices the observability plane at N=2
    (plane on vs ``--fleet-plane off``) with the merged digest asserted
    identical either way, and a ``fleet_rescale`` row prices a live
    mid-run scale-out (N=2 -> 4 via ``--fleet-rescale``) with the merged
    digest asserted identical to the fixed-N runs — the fenced
    exactly-once rescale contract, end-to-end."""
    import contextlib
    import io

    from spatialflink_tpu.driver import main as driver_main
    from spatialflink_tpu.runtime import fleet as fleet_mod
    from spatialflink_tpu.streams.synthetic import clustered_lines

    conf = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "conf", "spatialflink-conf.yml")
    grid = _params(1).grids()[0]
    lines = clustered_lines(grid, n, 0.95, seed=7, fmt="geojson", dt_ms=1)
    rows = []
    with tempfile.TemporaryDirectory(prefix="bench-fleet-") as td:
        # workers are fresh processes: a persistent compile cache lets the
        # per-N warm run actually warm the measured one
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                              os.path.join(td, "xla-cache"))
        os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                              "0")
        path1 = os.path.join(td, "in.geojson")
        with open(path1, "w") as f:
            f.write("\n".join(lines) + "\n")

        def solo():
            t0 = time.perf_counter()
            with contextlib.redirect_stdout(io.StringIO()):
                rc = driver_main(["--config", conf, "--option", "1",
                                  "--input1", path1])
            dt = time.perf_counter() - t0
            assert rc == 0
            return dt

        def fleet(workers, tag, *extra):
            fdir = os.path.join(td, f"fleet-{tag}")
            t0 = time.perf_counter()
            with contextlib.redirect_stdout(sys.stderr):
                rc = driver_main([
                    "--config", conf, "--option", "1", "--input1", path1,
                    "--fleet", str(workers), "--fleet-dir", fdir,
                    # no mid-run rebalance inside a timed row
                    "--fleet-epoch-records", str(10**9)] + list(extra))
            dt = time.perf_counter() - t0
            assert rc == 0
            res = fleet_mod.read_json(os.path.join(fdir,
                                                   fleet_mod.RESULT_FILE))
            return res, dt

        solo()  # warm the in-process jit shapes
        dt_solo = solo()
        rows.append(dict(path="fleet_solo", workers=0, records=n,
                         wall_s=round(dt_solo, 3),
                         records_per_sec=round(n / dt_solo)))
        digest = None
        dt_f1 = None
        for workers in (1, 2, 4):
            fleet(workers, f"warm{workers}")  # per-N padding buckets
            res, dt = fleet(workers, f"n{workers}")
            if digest is None:
                digest = res["digest"]
                dt_f1 = dt
            else:
                assert res["digest"] == digest, (
                    f"fleet N={workers} merged digest diverged — the "
                    "exactly-once global merge is partition-dependent")
            row = dict(path=f"fleet_n{workers}", workers=workers,
                       records=n, wall_s=round(dt, 3),
                       records_per_sec=round(n / dt),
                       merged_windows=res["merged_windows"],
                       merged_digest=res["digest"],
                       restarts=sum(int(v)
                                    for v in res["restarts"].values()),
                       post_warmup_compiles=res["post_warmup_compiles"],
                       overhead_vs_solo=round(dt / dt_solo, 2))
            if workers > 1:
                row["speedup_vs_fleet1"] = round(dt_f1 / dt, 2)
            rows.append(row)
        # fleet observability plane overhead at N=2: sidecar + monitor +
        # timeline harvesting + lineage vs --fleet-plane off. The merged
        # digest is asserted identical — the plane must be invisible to
        # exactly-once identity, so this row prices it and nothing else
        res_on, dt_on = fleet(2, "plane-on")
        res_off, dt_off = fleet(2, "plane-off", "--fleet-plane", "off")
        assert res_on["digest"] == res_off["digest"] == digest, (
            "fleet observability plane changed the merged digest — the "
            "lineage sidecar leaked into exactly-once identity")
        rows.append(dict(
            path="fleet_plane_overhead", workers=2, records=n,
            wall_s=round(dt_on, 3), wall_s_plane_off=round(dt_off, 3),
            records_per_sec=round(n / dt_on),
            overhead_vs_plane_off=round(dt_on / dt_off, 2),
            merged_p99_ms=((res_on.get("latency") or {})
                           .get("record_emit") or {}).get("p99"),
            sum_check_windows=((res_on.get("latency") or {})
                               .get("sum_check") or {}).get("windows"),
            digest_identical=True))
        # live rescale: start at N=2, scale out to N=4 mid-run at an
        # epoch boundary. The merged digest is asserted identical to the
        # fixed-N runs above — a fenced rescale must be invisible to
        # exactly-once identity — and the supervisor's rescale ledger
        # rides along. Epoch cadence is re-enabled here (the sibling rows
        # pin it huge) so the threshold can actually be consumed.
        res_rs, dt_rs = fleet(
            2, "rescale",
            "--fleet-rescale", f"{max(1, n // 3)}:4",
            "--fleet-epoch-records", str(max(1, n // 8)))
        assert res_rs["digest"] == digest, (
            "fleet_rescale merged digest diverged from the fixed-N runs "
            "— the fenced rescale leaked into exactly-once identity")
        rows.append(dict(
            path="fleet_rescale", workers=2,
            workers_final=res_rs.get("workers_final"),
            records=n, wall_s=round(dt_rs, 3),
            records_per_sec=round(n / dt_rs),
            rescales=[[r["n_from"], r["n_to"]]
                      for r in res_rs.get("rescales", [])],
            merged_windows=res_rs["merged_windows"],
            restarts=sum(int(v) for v in res_rs["restarts"].values()),
            post_warmup_compiles=res_rs["post_warmup_compiles"],
            overhead_vs_fleet1=round(dt_rs / dt_f1, 2),
            digest_identical=True))
    return rows


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=None,
                    help="records per stream (default 1M, 100k on CPU)")
    ap.add_argument("--options", default="1,51,101",
                    help="comma-separated driver queryOptions")
    ap.add_argument("--multi", type=int, default=8,
                    help="query count for the multi-query-vs-sequential-"
                         "jobs rows (values < 2 disable them — a 1-query "
                         "'batch' measures nothing the single rows don't)")
    ap.add_argument("--checkpoint-every", type=int, default=0,
                    help="coordinated-checkpoint overhead rows (record "
                         "path, checkpointing off vs every N windows) over "
                         "the range option. 0 (default) disables them")
    ap.add_argument("--live-plane", action="store_true",
                    help="live-operations-plane overhead rows (record "
                         "path: plane off vs an idle --status-port server "
                         "vs the full server+session+--live-stats plane) "
                         "over the range option")
    ap.add_argument("--pane-overlap", type=int, default=0,
                    help="sliding overlap (window = overlap * slide) for "
                         "the pane-incremental vs full-recompute rows over "
                         "the range/kNN options; window-table identity is "
                         "asserted in the same run. 0 (default) disables "
                         "the pane rows")
    ap.add_argument("--pane-state-overlap", type=int, default=0,
                    help="sliding overlap for the device-resident vs "
                         "host-merged pane-state rows (--pane-merge A/B "
                         "over the kNN option, identity asserted in-run, "
                         "per-slide readback bytes attached). 0 (default) "
                         "disables them")
    ap.add_argument("--query-plane", type=int, default=0, metavar="Q",
                    help="standing-query control plane rows: a Q-query "
                         "dynamic registry fleet static vs under "
                         "1-admit+1-retire-per-window churn (rec/s, fleet "
                         "repads, in-bucket XLA recompiles — must be 0), "
                         "plus a Q-sweep amortization row through the "
                         "registry path vs dedicated per-query pipelines. "
                         "0 (default) disables them")
    ap.add_argument("--tenant-plane", action="store_true",
                    help="tenant accounting plane overhead rows: the same "
                         "two-tenant dynamic registry fleet with the "
                         "per-dispatch cost ledger off (no telemetry "
                         "session) vs on, window-table identity asserted "
                         "in-run; the on-row carries the ledger's "
                         "conservation stats")
    ap.add_argument("--fleet", action="store_true",
                    help="supervised multi-worker fleet rows: a single-"
                         "process reference run vs --fleet N=1/2/4 worker "
                         "fleets over a 95%%-hot clustered stream "
                         "(merged-digest identity asserted across every "
                         "N; rows carry restart + post-warmup-recompile "
                         "ledger fields), plus a live mid-run N=2->4 "
                         "rescale row with the digest asserted identical "
                         "to the fixed-N runs")
    ap.add_argument("--require-backend", choices=("cpu", "tpu", "gpu"),
                    default=None,
                    help="fail fast (exit 2) when the process would run on "
                         "any other backend — the BENCH r05 silent-CPU-"
                         "fallback condition becomes a refusal instead of "
                         "an invalid ledger row")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from benchmarks._common import settle_backend

    settle_backend()
    import jax

    from spatialflink_tpu.utils import deviceplane

    backend = jax.default_backend()
    if args.require_backend and backend != args.require_backend:
        print(f"bench_e2e: --require-backend {args.require_backend} but "
              f"the process landed on '{backend}' "
              f"({deviceplane.backend_provenance()['device_kind']}); "
              "refusing to measure — run python -m spatialflink_tpu.doctor "
              "--preflight for the readiness breakdown", file=sys.stderr)
        return 2
    n = args.n or (1_000_000 if backend == "tpu" else 100_000)

    from benchmarks._common import bench_telemetry

    # backend provenance on EVERY row (not just the file header): a ledger
    # row must carry its own device truth so bench_diff can refuse
    # cross-backend pairings and a CPU fallback is visible per row
    prov = deviceplane.backend_provenance()

    def _stamp(row: dict) -> dict:
        row["backend"] = backend
        row["device_kind"] = prov["device_kind"]
        row["valid_for_target"] = prov["valid_for_target"]
        return row

    rows = []
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "stream1.csv")
        path2 = os.path.join(td, "stream2.csv")
        _write_stream(path, n, seed=0)
        _write_stream(path2, max(n // 64, 1), seed=1)  # small query stream
        for opt in (int(x) for x in args.options.split(",")):
            # one telemetry session — and ONE snapshot — per option: the
            # snapshot is cumulative across the option's rows, so attaching
            # the same object (not one per row) keeps the output honest
            # about that and avoids N near-identical copies in the file
            with bench_telemetry() as tel:
                opt_rows = list(bench_option(opt, path, path2, n))
                snap = tel.snapshot()
            for row in opt_rows:
                row["telemetry"] = snap
                _stamp(row)
                print(json.dumps(row), flush=True)
                rows.append(row)
        if args.multi > 1:
            for opt in (1, 51):
                if opt not in [int(x) for x in args.options.split(",")]:
                    continue
                try:
                    multi_rows = bench_multi_vs_jobs(opt, path, n, args.multi)
                except _BulkDeclined:
                    continue
                for row in multi_rows:
                    _stamp(row)
                    print(json.dumps(row), flush=True)
                    rows.append(row)
        if args.checkpoint_every > 0:
            for opt in (1,):
                if opt not in [int(x) for x in args.options.split(",")]:
                    continue
                for row in bench_checkpoint(opt, path, n,
                                            args.checkpoint_every):
                    _stamp(row)
                    print(json.dumps(row), flush=True)
                    rows.append(row)
        if args.live_plane:
            for opt in (1,):
                if opt not in [int(x) for x in args.options.split(",")]:
                    continue
                for row in bench_live_plane(opt, path, n):
                    _stamp(row)
                    print(json.dumps(row), flush=True)
                    rows.append(row)
        if args.pane_state_overlap > 1:
            for opt in (51,):
                if opt not in [int(x) for x in args.options.split(",")]:
                    continue
                try:
                    ps_rows = bench_pane_state(opt, path, n,
                                               args.pane_state_overlap)
                except _BulkDeclined:
                    continue
                for row in ps_rows:
                    _stamp(row)
                    print(json.dumps(row), flush=True)
                    rows.append(row)
        if args.query_plane > 1:
            for row in bench_query_plane(path, n, args.query_plane):
                _stamp(row)
                print(json.dumps(row), flush=True)
                rows.append(row)
        if args.tenant_plane:
            for row in bench_tenant_plane(path, n):
                _stamp(row)
                print(json.dumps(row), flush=True)
                rows.append(row)
        if args.fleet:
            for row in bench_fleet(n):
                _stamp(row)
                print(json.dumps(row), flush=True)
                rows.append(row)
        if args.pane_overlap > 1:
            for opt in (1, 51):
                if opt not in [int(x) for x in args.options.split(",")]:
                    continue
                try:
                    pane_rows = bench_panes(opt, path, n, args.pane_overlap)
                except _BulkDeclined:
                    continue
                for row in pane_rows:
                    _stamp(row)
                    print(json.dumps(row), flush=True)
                    rows.append(row)

    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"RESULTS_e2e_{backend}.json")
    with open(out, "w") as f:
        json.dump({"backend": backend, "n": n, "rows": rows}, f, indent=1)
    print(f"# wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
