"""The examples/ scripts are part of the user-facing surface — run each as
a real subprocess (CPU platform, virtual mesh for the distributed demo) and
assert the banner output they promise."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script: str) -> str:
    env = dict(os.environ)
    env["SPATIALFLINK_EXAMPLE_PLATFORM"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", script)],
        capture_output=True, text=True, timeout=480, env=env, cwd=_ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


@pytest.mark.parametrize("script,expect", [
    ("streaming_range_query.py", "delivered windows:"),
    ("distributed_knn.py", "matches single-device bit-for-bit"),
    ("checkpoint_resume.py", "matches uninterrupted run"),
    ("multi_query_hotspots.py", "standing queries x"),
    ("live_kafka_stream.py", "live latency p50="),
])
def test_example_runs(script, expect):
    out = _run(script)
    assert expect in out, out[-2000:]
