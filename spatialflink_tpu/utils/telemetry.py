"""Structured telemetry: spans, streaming histograms, gauges, reporter.

The reference exposes its pipeline through Flink's web UI and Dropwizard
meters (SURVEY §5); the rebuild's counters (:mod:`.metrics`) say *how much*
work happened but not *where the time went*. This layer adds the missing
dimensions, all host-side and all O(1) per observation:

- :meth:`Telemetry.span` — a context manager recording count / total / max /
  self (minus-children) wall-clock per named stage, nesting-aware via a
  thread-local stack, composing with :func:`~.metrics.trace` so every span
  is also a jax.profiler annotation when a ``--profile`` capture is running.
  Stage names are query-scoped (``knn.kernel`` vs one flat namespace) so
  ``--multi-query`` and multi-family runs stay separable.
- :class:`StreamingHistogram` — fixed log-bucket histogram (geometric
  buckets, O(1) record, constant memory) exposing p50/p95/p99/max; the
  per-record and per-window latency distributions ride it instead of an
  unbounded sample list.
- :class:`Gauge` — last-value (or callable) gauges: watermark lag, window
  backlog, breaker state.
- :class:`CellOccupancy` — grid-cell assignment counts from
  :meth:`~spatialflink_tpu.index.uniform_grid.UniformGrid.assign_cell`
  (installed as the grid module's observer hook only while a session is
  active): top-k hottest cells and a max/mean skew factor — the keyBy(grid)
  hot-spot signal the reference reads off Flink's backpressure UI.
- :class:`TelemetryReporter` — a daemon thread emitting one JSONL snapshot
  to ``--telemetry-dir`` immediately, every ``--telemetry-interval``
  seconds, and at close (so even a short run yields >= 2 snapshots), and
  REWRITING the Prometheus text dump (``metrics.prom``) on every snapshot
  so a file-pointed scraper sees live values, not only the final state.
  Snapshots embed the ambient registry's counters AND
  :func:`~.metrics.degradation_snapshot`, so PR 1's retry/breaker/DLQ
  events correlate with stage timings by timestamp in one stream.
- :class:`EventRing` / :func:`emit_event` — a bounded ring of structured
  lifecycle events (checkpoint committed/fallback, breaker transitions,
  DLQ quarantine, mesh degradation, SLO breach/recovery) served by the
  status server's ``/events`` endpoint and dropped for free when no
  session is active.
- :func:`status_snapshot` / :func:`status_digest` — THE definition of
  "current pipeline state": the raw snapshot plus a derived operator
  digest (throughput, latency percentiles, watermark lag, backlogs,
  pane-cache hit rate, checkpoint age/seq, breaker/DLQ/mesh state, top
  cells) shared verbatim by the reporter's JSONL lines, the status
  server's ``/status``, and the ``--live-stats`` stderr digest — one
  schema, three consumers. With no active session it degrades to a
  registry-only view (the always-on counters/meters), so a bare
  ``--status-port`` run serves real numbers while the record loop stays
  byte-identical to the uninstrumented path.

OFF BY DEFAULT: :func:`active` returns None until a
:func:`telemetry_session` is entered, and every instrumented hot path
checks that once per stream/loop (not per record) — a disabled run executes
the exact pre-telemetry code.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from spatialflink_tpu.utils import metrics as _metrics
from spatialflink_tpu.utils.metrics import trace


class SpanStats:
    """Aggregate wall-clock stats for one named stage."""

    __slots__ = ("name", "count", "total_s", "max_s", "self_s", "errors")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        #: total minus time spent in CHILD spans (the nesting-aware part:
        #: an outer "window" span wrapping a "kernel" span reports how much
        #: of the window was NOT kernel)
        self.self_s = 0.0
        self.errors = 0

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_ms": round(self.total_s * 1e3, 3),
            "max_ms": round(self.max_s * 1e3, 3),
            "self_ms": round(self.self_s * 1e3, 3),
            "errors": self.errors,
        }


class _Span:
    """One span activation. Class-based (not a generator contextmanager) so
    a StopIteration raised INSIDE the block propagates normally — spans wrap
    ``next()`` calls on the window assembly path."""

    __slots__ = ("tel", "name", "t0", "child_s", "_trace")

    def __init__(self, tel: "Telemetry", name: str):
        self.tel = tel
        self.name = name
        self.child_s = 0.0

    def __enter__(self) -> "_Span":
        self._trace = trace(self.name)
        self._trace.__enter__()
        self.tel._stack().append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        dt = time.perf_counter() - self.t0
        stack = self.tel._stack()
        if stack and stack[-1] is self:
            stack.pop()
        if stack:
            stack[-1].child_s += dt
        st = self.tel._span_stats(self.name)
        st.count += 1
        st.total_s += dt
        st.self_s += max(0.0, dt - self.child_s)
        if dt > st.max_s:
            st.max_s = dt
        # StopIteration through a span is normal control flow (the span
        # times the pull from an exhausted iterator), not a stage failure
        if et is not None and et is not StopIteration:
            st.errors += 1
        self._trace.__exit__(et, ev, tb)
        return False


class StreamingHistogram:
    """Fixed log-bucket streaming histogram: O(1) per record, constant
    memory, percentiles by cumulative bucket walk.

    Bucket ``i >= 1`` covers ``[lo * growth**(i-1), lo * growth**i)``;
    bucket 0 is the underflow bucket (values <= lo, including zeros and
    negatives); the last bucket absorbs overflow. A percentile returns the
    geometric midpoint of its bucket clamped to the observed [min, max], so
    the relative error is bounded by ``sqrt(growth)`` (~4.4% at the default
    8-buckets-per-octave growth) — the Dropwizard-reservoir answer without
    sampling jitter or per-record allocation.
    """

    __slots__ = ("name", "lo", "growth", "_log_lo", "_log_g", "_nb",
                 "counts", "count", "total", "min", "max")

    def __init__(self, name: str = "", lo: float = 1e-3, hi: float = 1e7,
                 growth: float = 2.0 ** 0.125):
        if not (lo > 0 and hi > lo and growth > 1.0):
            raise ValueError("need 0 < lo < hi and growth > 1")
        self.name = name
        self.lo = lo
        self.growth = growth
        self._log_lo = math.log(lo)
        self._log_g = math.log(growth)
        self._nb = int(math.ceil((math.log(hi) - self._log_lo) / self._log_g))
        self.counts: List[int] = [0] * (self._nb + 2)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if value <= self.lo:
            idx = 0
        else:
            idx = int((math.log(value) - self._log_lo) / self._log_g) + 1
            if idx > self._nb + 1:
                idx = self._nb + 1
        self.counts[idx] += 1

    def _bucket_value(self, idx: int) -> float:
        if idx == 0:
            return self.min if self.min < math.inf else self.lo
        if idx == self._nb + 1:
            # overflow bucket: the midpoint would lie about anything past
            # hi; the observed max is the honest representative
            return self.max
        # geometric midpoint of the bucket
        return math.exp(self._log_lo + (idx - 0.5) * self._log_g)

    def percentile(self, p: float) -> float:
        if not self.count:
            return 0.0
        target = max(1, math.ceil(self.count * min(max(p, 0.0), 100.0) / 100.0))
        cum = 0
        for idx, n in enumerate(self.counts):
            cum += n
            if cum >= target:
                v = self._bucket_value(idx)
                return float(min(max(v, self.min), self.max))
        return float(self.max)  # pragma: no cover - cum always reaches count

    def to_dict(self) -> dict:
        if not self.count:
            return {"count": 0}
        return {
            "count": self.count,
            "sum": round(self.total, 3),
            "min": round(self.min, 3),
            "max": round(self.max, 3),
            "p50": round(self.percentile(50), 3),
            "p95": round(self.percentile(95), 3),
            "p99": round(self.percentile(99), 3),
        }


class Gauge:
    """Last-value gauge; construct with ``fn`` for pull-style gauges that
    are read at snapshot time."""

    __slots__ = ("name", "fn", "_value")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.fn = fn
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def get(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")
        return self._value


class CellOccupancy:
    """Grid-cell assignment counts: top-k hottest cells + skew (max/mean
    over occupied cells). Fed int arrays (or scalars) of cell ids; invalid
    cells (-1) are dropped. Vectorized bincount accumulation — cheap even
    on the 1M-point bulk ingest paths."""

    def __init__(self):
        import numpy as np

        self._np = np
        self._counts = np.zeros(0, dtype=np.int64)

    def _ensure(self, hi: int) -> None:
        if hi > self._counts.size:
            np = self._np
            grown = np.zeros(max(hi, 2 * self._counts.size), dtype=np.int64)
            grown[: self._counts.size] = self._counts
            self._counts = grown

    def record(self, cells) -> None:
        np = self._np
        # scalar fast path: the per-record streaming ingest assigns one
        # cell at a time — a single bounds check + increment, O(1), no
        # array construction (the vectorized branch below would cost
        # O(num_cells) per record and dwarf the parse it observes)
        if isinstance(cells, (int, np.integer)) or (
                isinstance(cells, np.ndarray) and cells.ndim == 0):
            ci = int(cells)
            if ci < 0:
                return
            self._ensure(ci + 1)
            self._counts[ci] += 1
            return
        c = np.asarray(cells).ravel()
        c = c[c >= 0]
        if c.size == 0:
            return
        hi = int(c.max()) + 1
        self._ensure(hi)
        self._counts[:hi] += np.bincount(c, minlength=hi).astype(np.int64)

    def top_k(self, k: int = 8) -> List[Tuple[int, int]]:
        np = self._np
        nz = np.nonzero(self._counts)[0]
        if nz.size == 0:
            return []
        order = nz[np.argsort(self._counts[nz])[::-1][:k]]
        return [(int(c), int(self._counts[c])) for c in order]

    def skew(self) -> float:
        """max/mean over occupied cells; 1.0 = perfectly uniform."""
        np = self._np
        nz = self._counts[self._counts > 0]
        if nz.size == 0:
            return 0.0
        return float(nz.max() / nz.mean())

    def to_dict(self, k: int = 8) -> dict:
        occ = int((self._counts > 0).sum())
        return {"occupied_cells": occ, "skew": round(self.skew(), 3),
                "top_cells": self.top_k(k)}


class EventRing:
    """Bounded ring buffer of structured lifecycle events. Appends are
    O(1) and lock-guarded (emitters live on pipeline, reporter, and HTTP
    threads); ``list()`` copies so readers never hold the lock while
    serializing. ``total`` counts every event ever appended, including
    those the ring has since evicted."""

    def __init__(self, capacity: int = 256):
        from collections import deque

        self._ring = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self.total = 0

    def append(self, kind: str, **fields) -> dict:
        ev = {"ts_ms": int(time.time() * 1000), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
            self.total += 1
        return ev

    def list(self) -> List[dict]:
        with self._lock:
            return list(self._ring)


class Telemetry:
    """One session's span/histogram/gauge/occupancy state.

    ``registry`` pins the metrics registry whose counters ride the
    snapshots; None reads the ambient :data:`~.metrics.REGISTRY` at
    snapshot time (so :func:`~.metrics.scoped_registry` composes).
    Mutations on the hot path are single attribute bumps under the GIL;
    only entry creation and snapshotting take the lock, so a reporter
    thread reading mid-window sees a consistent-enough view (telemetry,
    not accounting).
    """

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        self.registry = registry
        self.spans: Dict[str, SpanStats] = {}
        self.histograms: Dict[str, StreamingHistogram] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.cells = CellOccupancy()
        self.events = EventRing()
        #: optional runtime.health.HealthEvaluator attached by the driver
        #: (--slo): status_snapshot() stamps its verdict into every
        #: snapshot this session emits
        self.health = None
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._tls = threading.local()

    def event(self, kind: str, **fields) -> None:
        """Record one structured lifecycle event (see :class:`EventRing`).
        Emitters are stage boundaries (checkpoint commits, breaker
        transitions, quarantines), never per-record paths."""
        self.events.append(kind, **fields)

    # ------------------------------ spans ---------------------------- #

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _span_stats(self, name: str) -> SpanStats:
        st = self.spans.get(name)
        if st is None:
            with self._lock:
                st = self.spans.setdefault(name, SpanStats(name))
        return st

    def span(self, stage: str, query: Optional[str] = None) -> _Span:
        """Context manager timing one activation of ``stage``; ``query``
        scopes the stage name (``knn.kernel``) so families/queries stay
        separable. Exceptions propagate (and bump ``errors``)."""
        return _Span(self, f"{query}.{stage}" if query else stage)

    def observe(self, stage: str, dt_s: float,
                query: Optional[str] = None) -> None:
        """Record one pre-timed observation — the per-record loops use this
        instead of a context manager (no object churn on the ingest path)."""
        st = self._span_stats(f"{query}.{stage}" if query else stage)
        st.count += 1
        st.total_s += dt_s
        st.self_s += dt_s
        if dt_s > st.max_s:
            st.max_s = dt_s

    # --------------------------- histograms/gauges -------------------- #

    def histogram(self, name: str, **kw) -> StreamingHistogram:
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.setdefault(
                    name, StreamingHistogram(name, **kw))
        return h

    def gauge(self, name: str,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            with self._lock:
                g = self.gauges.setdefault(name, Gauge(name, fn))
        elif fn is not None and g.fn is None:
            g.fn = fn
        return g

    def record_cells(self, cells) -> None:
        self.cells.record(cells)

    # ------------------------------ snapshot -------------------------- #

    def _registry(self) -> _metrics.MetricsRegistry:
        return self.registry if self.registry is not None else _metrics.REGISTRY

    def snapshot(self) -> dict:
        """One JSON-safe snapshot: stage spans, histogram percentiles,
        gauges, the registry's counters/meters, the degradation digest
        (PR 1's retry/breaker/DLQ/chaos counters — same stream, same
        timestamp, correlation for free), and grid occupancy."""
        reg = self._registry()
        with self._lock:
            spans = {n: s.to_dict() for n, s in self.spans.items()}
            hists = {n: h.to_dict() for n, h in self.histograms.items()}
            gauges = {n: g.get() for n, g in self.gauges.items()}
        return {
            "ts_ms": int(time.time() * 1000),
            "uptime_s": round(time.time() - self.started_at, 3),
            "spans": spans,
            "histograms": hists,
            "gauges": gauges,
            "counters": reg.snapshot(),
            "degradation": _metrics.degradation_snapshot(reg),
            "grid": self.cells.to_dict(),
        }


# --------------------------------------------------------------------- #
# the active session (module-global, like metrics.REGISTRY)

_ACTIVE: Optional[Telemetry] = None
_NULL_CM = contextlib.nullcontext()


def active() -> Optional[Telemetry]:
    """The active session's :class:`Telemetry`, or None when telemetry is
    off. Hot paths call this ONCE per stream/loop and branch to the
    uninstrumented code when it is None."""
    return _ACTIVE


def set_active(tel: Optional[Telemetry]) -> Optional[Telemetry]:
    global _ACTIVE
    old = _ACTIVE
    _ACTIVE = tel
    return old


def span(stage: str, query: Optional[str] = None):
    """Module-level convenience for call-once sites (stage boundaries, CLI
    plumbing): a real span when a session is active, a shared nullcontext
    otherwise. Per-record loops should capture :func:`active` instead."""
    tel = _ACTIVE
    return tel.span(stage, query) if tel is not None else _NULL_CM


def emit_event(kind: str, **fields) -> None:
    """Append a lifecycle event to the active session's ring; a no-op when
    telemetry is off (one attribute read — safe at stage boundaries even
    in uninstrumented runs)."""
    tel = _ACTIVE
    if tel is not None:
        tel.event(kind, **fields)


# --------------------------------------------------------------------- #
# the shared "current pipeline state" snapshot (reporter JSONL lines, the
# status server's /status, and the --live-stats stderr digest all render
# exactly this — one schema definition)

def _hist_digest(hists: dict, name: str) -> dict:
    h = hists.get(name)
    if not h or not h.get("count"):
        return {"count": 0}
    return {k: h.get(k) for k in ("count", "p50", "p95", "p99", "max")}


def status_digest(snap: dict) -> dict:
    """Derive the compact operator view from a raw snapshot dict: the
    numbers an operator reads FIRST, by name, instead of fishing them out
    of the spans/histograms/gauges/counters maps. Keys are stable schema
    (ARCHITECTURE.md § Live operations); absent instruments render as
    None / zero-count, never as missing keys."""
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    hists = snap.get("histograms") or {}
    grid = snap.get("grid") or {}
    hits = int(counters.get("pane-cache-hits", 0))
    misses = int(counters.get("pane-cache-misses", 0))
    return {
        "records_in": int(counters.get("ingest-throughput.count", 0)),
        "throughput_rps": round(
            float(counters.get("ingest-throughput.rate", 0.0)), 3),
        "windows_evaluated": int(counters.get("batches-evaluated", 0)),
        "record_latency_ms": _hist_digest(hists, "record-latency-ms"),
        "window_latency_ms": _hist_digest(hists, "window-latency-ms"),
        "watermark_lag_ms": gauges.get("kafka.watermark-lag-ms"),
        "commit_backlog": gauges.get("kafka.commit-backlog"),
        "window_backlog": gauges.get("window-backlog"),
        "pane_cache": {
            "hits": hits, "misses": misses,
            "hit_rate": (round(hits / (hits + misses), 4)
                         if hits + misses else None),
        },
        "checkpoint": {
            "seq": gauges.get("checkpoint.seq"),
            "age_s": (round(gauges["checkpoint.age-s"], 3)
                      if "checkpoint.age-s" in gauges else None),
            "written": int(counters.get("checkpoints-written", 0)),
            "replay_depth": gauges.get("recovery.replay-depth"),
            "write_ms": _hist_digest(hists, "checkpoint-write-ms"),
            "size_bytes": _hist_digest(hists, "checkpoint-size-bytes"),
        },
        "breaker_state": gauges.get("broker.breaker-state"),
        "dlq_depth": int(counters.get("dlq-records", 0)),
        "mesh_degradations": int(counters.get("mesh-degradations", 0)),
        "slo_breaches": int(counters.get("slo-breaches", 0)),
        "top_cells": grid.get("top_cells", []),
    }


def registry_snapshot(registry: Optional[_metrics.MetricsRegistry] = None
                      ) -> dict:
    """A snapshot with the raw-snapshot SHAPE built from the always-on
    metrics registry alone — what a bare ``--status-port`` run (no
    telemetry session) serves. Spans/histograms/gauges are empty by
    construction: populating them needs the per-record instrumentation a
    session activates, and the no-session contract is a byte-identical
    record loop."""
    reg = registry if registry is not None else _metrics.REGISTRY
    return {
        "ts_ms": int(time.time() * 1000),
        "uptime_s": None,
        "spans": {},
        "histograms": {},
        "gauges": {},
        "counters": reg.snapshot(),
        "degradation": _metrics.degradation_snapshot(reg),
        "grid": {},
    }


def status_snapshot(tel: Optional[Telemetry] = None, health=None,
                    registry: Optional[_metrics.MetricsRegistry] = None
                    ) -> dict:
    """One full "current pipeline state" document: the raw snapshot (or
    the registry-only fallback), the derived ``status`` digest, and —
    when an SLO evaluator is attached (explicitly or on the session) —
    the ``health`` verdict. Built ON DEMAND only: per HTTP request, per
    reporter interval, per digest line; never per record."""
    tel = tel if tel is not None else _ACTIVE
    snap = tel.snapshot() if tel is not None else registry_snapshot(registry)
    snap["status"] = status_digest(snap)
    if health is None and tel is not None:
        health = tel.health
    if health is not None:
        # evaluated AFTER the digest so checks read the same numbers the
        # operator sees; breach transitions count in the SAME registry the
        # snapshot was built from (a pinned/scoped registry must see its
        # own slo-breaches), landing in the NEXT snapshot's status
        reg = (tel._registry() if tel is not None
               else registry if registry is not None else _metrics.REGISTRY)
        snap["health"] = health.evaluate(snap, registry=reg)
    return snap


# --------------------------------------------------------------------- #
# reporter

def prometheus_text(tel: Optional[Telemetry] = None,
                    registry: Optional[_metrics.MetricsRegistry] = None
                    ) -> str:
    """Prometheus text exposition of a session: spans as count/total/max
    seconds, histograms as count/sum plus p50/p95/p99 quantile gauges,
    gauges and registry counters as-is. Metric names are fixed; the
    span/histogram/counter name rides a label (dots and dashes are legal
    in label VALUES, so the query-scoped names survive unmangled).
    ``tel=None`` renders the registry-only view (counter families only) —
    the no-session ``/metrics`` endpoint. Rendered live by both the
    reporter (every snapshot rewrites ``metrics.prom``) and the status
    server's ``/metrics`` — one renderer, two transports."""
    lines: List[str] = []

    def emit(metric: str, mtype: str, rows: List[Tuple[str, float]]):
        lines.append(f"# TYPE {metric} {mtype}")
        for labels, v in rows:
            lines.append(f"{metric}{{{labels}}} {v}")

    if tel is None:
        reg = registry if registry is not None else _metrics.REGISTRY
        emit("spatialflink_counter", "counter",
             [(f'name="{n}"', v) for n, v in sorted(reg.snapshot().items())])
        return "\n".join(lines) + "\n"

    snap_reg = tel._registry()
    with tel._lock:
        spans = dict(tel.spans)
        hists = dict(tel.histograms)
        gauges = dict(tel.gauges)
    emit("spatialflink_span_count", "counter",
         [(f'stage="{n}"', s.count) for n, s in sorted(spans.items())])
    emit("spatialflink_span_seconds_total", "counter",
         [(f'stage="{n}"', round(s.total_s, 6))
          for n, s in sorted(spans.items())])
    emit("spatialflink_span_seconds_max", "gauge",
         [(f'stage="{n}"', round(s.max_s, 6))
          for n, s in sorted(spans.items())])
    emit("spatialflink_histogram_count", "counter",
         [(f'name="{n}"', h.count) for n, h in sorted(hists.items())])
    emit("spatialflink_histogram_sum", "counter",
         [(f'name="{n}"', round(h.total, 6))
          for n, h in sorted(hists.items())])
    qrows = []
    for n, h in sorted(hists.items()):
        for q in (50, 95, 99):
            qrows.append((f'name="{n}",quantile="0.{q}"',
                          round(h.percentile(q), 6)))
    emit("spatialflink_histogram_quantile", "gauge", qrows)
    emit("spatialflink_gauge", "gauge",
         [(f'name="{n}"', g.get()) for n, g in sorted(gauges.items())])
    emit("spatialflink_counter", "counter",
         [(f'name="{n}"', v) for n, v in sorted(snap_reg.snapshot().items())])
    return "\n".join(lines) + "\n"


class TelemetryReporter:
    """Daemon thread writing shared-schema :func:`status_snapshot` JSONL
    lines to ``<out_dir>/telemetry.jsonl`` — one immediately at
    :meth:`start`, one per ``interval_s``, one final at :meth:`close` (so
    every run yields >= 2) — and REWRITING the Prometheus text dump
    ``<out_dir>/metrics.prom`` on every snapshot (atomic tmp+rename, so a
    scraper tailing the file never reads a torn exposition). Each line
    embeds the derived ``status`` digest and, when the session carries an
    SLO evaluator, the ``health`` verdict."""

    def __init__(self, telemetry: Telemetry, out_dir: str,
                 interval_s: float = 5.0):
        os.makedirs(out_dir, exist_ok=True)
        self.telemetry = telemetry
        self.interval_s = max(0.01, float(interval_s))
        self.jsonl_path = os.path.join(out_dir, "telemetry.jsonl")
        self.prom_path = os.path.join(out_dir, "metrics.prom")
        self.snapshots_written = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _emit(self) -> None:
        snap = status_snapshot(self.telemetry)
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(snap, sort_keys=True) + "\n")
        self.snapshots_written += 1
        tmp = self.prom_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(prometheus_text(self.telemetry))
        os.replace(tmp, self.prom_path)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._emit()

    def start(self) -> "TelemetryReporter":
        self._emit()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-reporter")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5.0)
            self._thread = None
        self._emit()


@contextlib.contextmanager
def telemetry_session(out_dir: Optional[str] = None, interval_s: float = 5.0,
                      registry: Optional[_metrics.MetricsRegistry] = None,
                      health=None):
    """Activate telemetry for the enclosed block: installs the
    :class:`Telemetry` as the active session, hooks the grid's cell-
    assignment observer, and (when ``out_dir`` is given) runs a
    :class:`TelemetryReporter`. ``health`` attaches an SLO evaluator
    (``runtime.health.HealthEvaluator``) so every snapshot carries its
    verdict. Everything is restored on exit — including after an
    exception — so a crashed run still gets its final snapshot."""
    from spatialflink_tpu.index import uniform_grid as _ug

    tel = Telemetry(registry)
    tel.health = health
    old = set_active(tel)
    old_obs = _ug._CELL_OBSERVER
    _ug._CELL_OBSERVER = tel.record_cells
    reporter = None
    if out_dir:
        reporter = TelemetryReporter(tel, out_dir, interval_s).start()
    try:
        yield tel
    finally:
        try:
            if reporter is not None:
                reporter.close()
        finally:
            # restore the globals even when the final snapshot/prom write
            # fails (disk full, dir deleted mid-run): a dead session left
            # active would instrument every later run in the process
            _ug._CELL_OBSERVER = old_obs
            set_active(old)
