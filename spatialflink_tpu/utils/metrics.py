"""Metrics / observability (reference parity map):

- :class:`Counter` ≙ Flink metric ``Counter`` "Distance Computation Count"
  (``spatialObjects/Point.java:220-235``);
- :class:`Meter` ≙ Dropwizard "Throughput-Meter" (``Point.java:237-253``) —
  event rate over a sliding time window;
- :class:`MetricsRegistry` — named counters/meters, one place to scrape;
- :func:`check_exit_control_tuple` ≙ the remote-stop hook that kills the job
  when a tuple with ``geometry.type == "control"`` arrives
  (``utils/HelperClass.java:441-453``);
- :func:`trace` / :func:`profile_to` — named-stage visibility, the analogue
  of the reference's named Flink operators in the web UI (SURVEY §5):
  ``jax.profiler`` annotations when available, no-ops otherwise.

Per-record latency sinks live in :mod:`spatialflink_tpu.streams.sinks`
(:class:`LatencySink`).
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Dict, Iterable, Iterator, Optional


class ControlTupleExit(Exception):
    """Raised when a control tuple arrives (the reference throws IOException
    to crash the Flink job — a crude remote stop)."""


class GracefulShutdown(ControlTupleExit):
    """A SIGTERM-style stop request: drain buffered records into the
    pipeline, let sealed windows emit, write a final checkpoint, exit 0.
    Subclasses :class:`ControlTupleExit` so every existing stop path
    (decode buffer flush, driver summary, conservative Kafka commits)
    treats it as the graceful stop it is; the driver additionally writes
    a final coordinated checkpoint when the stop came from a signal."""


#: process-wide shutdown request flag (set from the driver's SIGTERM
#: handler; checked at record boundaries so no in-flight record is lost)
_SHUTDOWN = threading.Event()


def request_shutdown() -> None:
    """Ask the running pipeline to stop gracefully at the next record
    boundary (signal-handler safe: just sets an event)."""
    _SHUTDOWN.set()


def shutdown_requested() -> bool:
    return _SHUTDOWN.is_set()


def clear_shutdown() -> None:
    """Reset the flag (run start / test isolation)."""
    _SHUTDOWN.clear()


def check_exit_control_tuple(record) -> None:
    """Raise :class:`ControlTupleExit` if ``record`` is a control tuple.

    Accepts raw GeoJSON strings/dicts (pre-parse, like the reference's
    filter on the Kafka ObjectNode) — cheap substring guard first.
    """
    obj = record
    if isinstance(obj, str):
        if '"control"' not in obj:
            return
        try:
            obj = json.loads(obj)
        except ValueError:
            return
    if isinstance(obj, dict):
        env = obj.get("value")
        if isinstance(env, dict):  # Kafka envelope
            obj = env
        geom = obj.get("geometry", obj)
        if isinstance(geom, dict) and geom.get("type") == "control":
            raise ControlTupleExit("control tuple received")


class Counter:
    __slots__ = ("name", "count")

    def __init__(self, name: str):
        self.name = name
        self.count = 0

    def inc(self, n: int = 1) -> None:
        self.count += n


class Meter:
    """Events/sec over a sliding time window (default 60s).

    O(1) memory on the per-record hot path: marks aggregate into fixed
    one-second buckets (at most ``window_s`` of them), like Dropwizard's
    constant-space meters — NOT one entry per event."""

    def __init__(self, name: str, window_s: float = 60.0):
        self.name = name
        self.window_s = window_s
        self.count = 0
        self._buckets = deque()  # (whole_second, n), ascending

    def mark(self, n: int = 1, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self.count += n
        sec = int(now)
        if self._buckets and self._buckets[-1][0] == sec:
            self._buckets[-1][1] += n
        else:
            self._buckets.append([sec, n])
            self._evict(now)

    def _evict(self, now: float) -> None:
        horizon = now - self.window_s - 1
        while self._buckets and self._buckets[0][0] < horizon:
            self._buckets.popleft()

    def rate(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        self._evict(now)
        if not self._buckets:
            return 0.0
        span = max(now - self._buckets[0][0], 1.0)
        return sum(n for _, n in self._buckets) / span


class MetricsRegistry:
    """Named counters and meters; ``snapshot()`` for scraping/logging.

    The registry is cross-thread (pipeline threads create handles while
    the reporter/opserver threads snapshot), so handle creation, reset,
    and snapshot iteration hold the instance lock. The handles themselves
    stay lock-free: ``Counter.inc``/``Meter.mark`` are the per-record hot
    path and rely on the GIL's atomic int bump."""

    def __init__(self):
        self.counters: Dict[str, Counter] = {}
        self.meters: Dict[str, Meter] = {}
        self._lock = threading.Lock()

    def reset(self) -> None:
        """Drop every counter and meter. Handles created before the reset
        stay usable but are no longer scraped — callers that cache a
        counter across a reset should re-fetch it."""
        with self._lock:
            self.counters.clear()
            self.meters.clear()

    def counter(self, name: str) -> Counter:
        with self._lock:
            if name not in self.counters:
                self.counters[name] = Counter(name)
            return self.counters[name]

    def meter(self, name: str, window_s: float = 60.0) -> Meter:
        with self._lock:
            if name not in self.meters:
                self.meters[name] = Meter(name, window_s)
            return self.meters[name]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            counters = list(self.counters.items())
            meters = list(self.meters.items())
        out: Dict[str, float] = {}
        for n, c in counters:
            out[n] = c.count
        for n, m in meters:
            out[f"{n}.count"] = m.count
            out[f"{n}.rate"] = m.rate()
        return out


#: process-wide default registry (the reference's per-job metric group).
#: Pipelines read it through ``metrics.REGISTRY`` at CALL time (function-
#: level imports), so :func:`scoped_registry` can swap it for a run/test
#: without process-global counter bleed-through; the driver's kafka summary
#: keeps its baseline-delta logic only for true cross-run accumulation in
#: this default registry.
REGISTRY = MetricsRegistry()


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Install ``registry`` as the ambient default; returns the previous
    one. Prefer :func:`scoped_registry` — it restores on exit."""
    global REGISTRY
    old = REGISTRY
    REGISTRY = registry
    return old


@contextlib.contextmanager
def scoped_registry(registry: Optional[MetricsRegistry] = None
                    ) -> Iterator[MetricsRegistry]:
    """Run the enclosed block against a fresh (or given) registry, restoring
    the previous one on exit — the test/driver isolation hook, so counters
    from one run cannot bleed into the next's snapshot."""
    reg = MetricsRegistry() if registry is None else registry
    old = set_registry(reg)
    try:
        yield reg
    finally:
        set_registry(old)

#: counter-name prefixes that mean "the transport or pipeline degraded and
#: recovery machinery engaged" — injected faults (runtime/faults.py), retry
#: and breaker activity, verified-produce recoveries, and dead-lettered
#: records (runtime/supervisor.py). One namespace so the driver's run
#: summary can surface every degradation event without naming each counter.
DEGRADATION_PREFIXES = ("chaos-", "retry-", "breaker-", "dlq-",
                        "produce-verified")


def degradation_snapshot(registry: Optional[MetricsRegistry] = None
                         ) -> Dict[str, int]:
    """Non-zero degradation counters (see :data:`DEGRADATION_PREFIXES`) —
    the summary line's "how rough was the transport" digest."""
    reg = REGISTRY if registry is None else registry
    return {n: c.count for n, c in sorted(reg.counters.items())
            if c.count and n.startswith(DEGRADATION_PREFIXES)}


def metered(stream: Iterable, meter: Meter,
            control_check: bool = False) -> Iterator:
    """Wrap a record stream: marks the meter per record and (optionally)
    raises on control tuples — the reference's map-stage metric wrappers."""
    for rec in stream:
        if control_check:
            check_exit_control_tuple(rec)
        meter.mark()
        yield rec


@contextlib.contextmanager
def trace(name: str):
    """Named trace annotation visible in a jax.profiler capture; no-op when
    profiling machinery is unavailable. Only the annotation SETUP is
    guarded — an exception raised by the enclosed block must propagate
    unchanged (a try around the yield would swallow it and break the
    generator contract)."""
    try:
        import jax.profiler as _prof

        cm = _prof.TraceAnnotation(name)
    except Exception:
        cm = None
    if cm is None:
        yield
    else:
        with cm:
            yield


@contextlib.contextmanager
def profile_to(log_dir: str):
    """Capture a jax.profiler trace for the enclosed block (the rebuild's
    answer to the reference's Flink web UI, SURVEY §5)."""
    import jax.profiler as _prof

    _prof.start_trace(log_dir)
    try:
        yield
    finally:
        _prof.stop_trace()
