"""CLI for the invariant linter.

``python -m spatialflink_tpu.analysis [--rule ID]... [--format text|json]
[--check] [--root DIR] [--allowlist FILE] [--list-rules]``

Exit codes: 0 clean (or report-only mode), 1 non-allowlisted findings or
stale allowlist entries under ``--check``, 2 usage/configuration errors
(unknown rule, malformed allowlist).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from spatialflink_tpu.analysis.core import (ALLOWLIST_PATH, REPO_ROOT,
                                            AllowlistError, all_rules,
                                            run_analysis)


def _render_text(report, check: bool, out) -> None:
    for f in report.findings:
        print(f.render(), file=out)
    for f, entry in report.suppressed:
        print(f"{f.render()}  [allowlisted: {entry.reason}]", file=out)
    for e in report.stale:
        print(f"stale allowlist entry — remove stale entry: {e.render()}",
              file=out)
    n_active = len(report.findings)
    print(f"{n_active} finding(s), {len(report.suppressed)} allowlisted, "
          f"{len(report.stale)} stale allowlist entr"
          f"{'y' if len(report.stale) == 1 else 'ies'} across "
          f"{report.files} file(s) [{', '.join(report.rules)}]", file=out)
    if check:
        print("check: " + ("PASS" if report.ok else "FAIL"), file=out)


def main(argv: Optional[List[str]] = None,
         out=sys.stdout) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m spatialflink_tpu.analysis",
        description="invariant linter: prove the engine's contracts at "
                    "the AST level")
    ap.add_argument("--rule", action="append", default=None,
                    metavar="ID", help="run only this rule (repeatable)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on non-allowlisted findings or stale "
                         "allowlist entries (the tier-1 gate mode)")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="tree to scan (default: this repo)")
    ap.add_argument("--allowlist", default=ALLOWLIST_PATH,
                    help="allowlist TOML (default: the committed "
                         "analysis/ALLOWLIST.toml); 'none' disables")
    ap.add_argument("--list-rules", action="store_true",
                    help="print rule ids + contracts and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in all_rules():
            print(f"{rule.id:<22} {rule.contract}", file=out)
        return 0
    allowlist = None if args.allowlist == "none" else args.allowlist
    try:
        report = run_analysis(root=args.root, rule_ids=args.rule,
                              allowlist=allowlist)
    except AllowlistError as e:
        print(f"analysis: {e}", file=sys.stderr)
        return 2
    if args.format == "json":
        print(json.dumps(report.to_dict(), sort_keys=True), file=out)
    else:
        _render_text(report, args.check, out)
    if args.check and not report.ok:
        return 1
    return 0
