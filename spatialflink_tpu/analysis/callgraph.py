"""Project-wide call graph for the interprocedural rules.

PR 12's rules were lexical: each looked at one expression, one method,
one decorator at a time. The deep invariants — lock discipline, field
checkpoint coverage, host-sync taint, the recompile surface — are
properties of *paths through calls*, so this module gives the rules a
shared, deliberately small call graph:

- :class:`FunctionInfo` — one function or method: qualname, params, the
  ``instrumented_jit`` statics when the def is a kernel.
- :class:`ModuleGraph` — per-module resolution + edges. Three
  resolution rules (documented in ARCHITECTURE.md with their blind
  spots):

  1. **module-level names** — ``f(...)`` resolves to the module's
     top-level ``def f`` unless a *later* top-level binding (an import,
     an assignment, a second def) shadows it, or any enclosing function
     rebinds the name (param, local assign, nested def). A
     function-level ``from m import f`` re-points the name at ``m.f``.
  2. **self-methods** — ``self.m(...)`` inside ``class C`` resolves to
     ``C.m`` when ``C`` defines it (base classes are out of scope: an
     inherited or overridden method is a documented blind spot).
  3. **by-name references** — a function passed *by name* as a call
     argument (``Thread(target=self._loop)``, ``_defer(collect)``)
     creates a ``by-name`` edge: the callee will run later, from a
     context the caller's lexical locks/gates do not cover.

- :class:`Project` — the cross-module layer: ``from pkg.mod import f``
  and ``import pkg.mod as m; m.f(...)`` resolve into the other module's
  graph when that module is part of the scanned tree. This is what lets
  the recompile-surface rule see every call site of a kernel that
  ``ops/*`` defines and ``operators/*`` invokes.

Everything here is name-based AST resolution — no imports are executed,
so a scan can never run engine code.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from spatialflink_tpu.analysis.core import ModuleSource
from spatialflink_tpu.analysis.astutils import (dotted, function_params,
                                                 jit_static_names)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class FunctionInfo:
    """One function or method in the project."""

    qualname: str
    name: str
    node: ast.AST
    module: str  # repo-relative path of the defining module
    cls: Optional[str]  # immediate enclosing class (methods only)
    params: List[str]
    #: ``instrumented_jit`` static parameter names; None when not jitted.
    statics: Optional[Set[str]]

    @property
    def is_method(self) -> bool:
        return self.cls is not None

    @property
    def is_kernel(self) -> bool:
        return self.statics is not None


@dataclasses.dataclass
class CallSite:
    """One resolved edge: ``caller`` invokes (or references) ``callee``."""

    caller: Optional[FunctionInfo]  # None for module-level code
    callee: FunctionInfo
    node: ast.AST  # the Call node; for by-name edges, the Name/Attribute
    kind: str  # "direct" | "self" | "by-name"

    @property
    def deferred(self) -> bool:
        """By-name references run later, outside the caller's lexical
        context (locks taken at the reference site are NOT held)."""
        return self.kind == "by-name"


class ModuleGraph:
    """Call graph of one module (see the module docstring for the
    resolution rules)."""

    def __init__(self, mod: ModuleSource):
        self.mod = mod
        #: qualname -> FunctionInfo for every def in the module.
        self.functions: Dict[str, FunctionInfo] = {}
        self._by_node: Dict[ast.AST, FunctionInfo] = {}
        #: top-level name -> FunctionInfo | "class" | "import" | "other"
        #: (last top-level binding wins — the shadowing rule).
        self.module_bindings: Dict[str, object] = {}
        #: imported name -> (dotted module, symbol-or-None), module- and
        #: function-level alike (used for cross-module resolution).
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        self.calls: List[CallSite] = []
        self._callers: Dict[str, List[CallSite]] = {}
        self._collect_functions()
        self._collect_bindings(mod.tree.body)
        self._collect_imports()
        self._collect_calls()

    # ------------------------------ indexing -------------------------- #

    def _collect_functions(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, _FUNC_NODES):
                continue
            parent = self.mod.parent(node)
            cls = parent.name if isinstance(parent, ast.ClassDef) else None
            statics = jit_static_names(node) \
                if isinstance(node, ast.FunctionDef) else None
            info = FunctionInfo(
                qualname=self.mod.qualname(node), name=node.name,
                node=node, module=self.mod.relpath, cls=cls,
                params=function_params(node), statics=statics)
            self.functions[info.qualname] = info
            self._by_node[node] = info

    def _collect_bindings(self, body: Sequence[ast.stmt]) -> None:
        """Top-level bindings in statement order — the last binder of a
        name wins, so an import after a def shadows the def (and vice
        versa). Recurses into top-level If/Try suites (TYPE_CHECKING
        blocks) in order."""
        for stmt in body:
            if isinstance(stmt, _FUNC_NODES):
                self.module_bindings[stmt.name] = self._by_node[stmt]
            elif isinstance(stmt, ast.ClassDef):
                self.module_bindings[stmt.name] = "class"
            elif isinstance(stmt, ast.Import):
                for a in stmt.names:
                    bound = a.asname or a.name.split(".")[0]
                    self.module_bindings[bound] = "import"
            elif isinstance(stmt, ast.ImportFrom):
                for a in stmt.names:
                    if a.name != "*":
                        self.module_bindings[a.asname or a.name] = "import"
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                for t in targets:
                    for el in ast.walk(t):
                        if isinstance(el, ast.Name):
                            self.module_bindings[el.id] = "other"
            elif isinstance(stmt, (ast.If, ast.Try)):
                for suite in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(stmt, suite, None) or []
                    for h in sub:
                        if isinstance(h, ast.ExceptHandler):
                            self._collect_bindings(h.body)
                    self._collect_bindings(
                        [s for s in sub if isinstance(s, ast.stmt)])

    def _collect_imports(self) -> None:
        """Every import binding in the module (any nesting level) — this
        repo imports kernels *inside* methods routinely, so the
        cross-module map must see function-level imports too."""
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name != "*":
                        self.imports[a.asname or a.name] = (node.module,
                                                            a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.imports[a.asname] = (a.name, None)
                    else:
                        self.imports[a.name.split(".")[0]] = (
                            a.name.split(".")[0], None)

    # ------------------------------ resolution ------------------------ #

    def _local_shadow(self, node: ast.AST, name: str) -> Optional[str]:
        """How the innermost enclosing function binding of ``name``
        (param / local assign / nested def / local import) shadows it:
        "import" (resolvable via self.imports), "other" (opaque), or
        None (no function-level binding)."""
        for fn in self.mod.enclosing_functions(node):
            if name in function_params(fn):
                return "other"
            verdict = None
            for sub in ast.walk(fn):
                if isinstance(sub, _FUNC_NODES) and sub is not fn \
                        and sub.name == name:
                    verdict = "def"
                elif isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        for el in ast.walk(t):
                            if isinstance(el, ast.Name) and el.id == name:
                                verdict = "other"
                elif isinstance(sub, ast.ImportFrom):
                    if any((a.asname or a.name) == name
                           for a in sub.names):
                        verdict = "import"
                elif isinstance(sub, (ast.For, ast.comprehension)):
                    tgt = sub.target
                    for el in ast.walk(tgt):
                        if isinstance(el, ast.Name) and el.id == name:
                            verdict = "other"
            if verdict == "def":
                # a nested def by this name: resolve to it
                return "nested-def"
            if verdict is not None:
                return verdict
        return None

    def resolve_local(self, node: ast.AST,
                      func: ast.AST) -> Optional[FunctionInfo]:
        """Resolve ``func`` (the callable expression, at ``node``'s
        position) to a function defined in THIS module; None when the
        target is imported, dynamic, or shadowed."""
        # self.m(...) -> method of the enclosing class
        chain = dotted(func)
        if chain is not None and chain.startswith("self.") \
                and chain.count(".") == 1:
            cls = self.mod.enclosing_class(node)
            if cls is not None:
                return self.functions.get(f"{cls.name}.{chain[5:]}")
            return None
        if isinstance(func, ast.Name):
            shadow = self._local_shadow(node, func.id)
            if shadow == "nested-def":
                for fn in self.mod.enclosing_functions(node):
                    for sub in ast.walk(fn):
                        if isinstance(sub, _FUNC_NODES) \
                                and sub.name == func.id:
                            return self._by_node.get(sub)
            if shadow is not None:
                return None
            bound = self.module_bindings.get(func.id)
            return bound if isinstance(bound, FunctionInfo) else None
        return None

    def info_for(self, fn_node: ast.AST) -> Optional[FunctionInfo]:
        return self._by_node.get(fn_node)

    def enclosing_info(self, node: ast.AST) -> Optional[FunctionInfo]:
        fns = self.mod.enclosing_functions(node)
        for fn in fns:
            info = self._by_node.get(fn)
            if info is not None:
                return info
        return None

    # ------------------------------ edges ----------------------------- #

    def _collect_calls(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            caller = self.enclosing_info(node)
            callee = self.resolve_local(node, node.func)
            if callee is not None:
                kind = "self" if (isinstance(node.func, ast.Attribute)
                                  and callee.is_method) else "direct"
                self._add(CallSite(caller, callee, node, kind))
            # by-name references handed into any call
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                ref = self._resolve_reference(node, arg)
                if ref is not None:
                    self._add(CallSite(caller, ref, arg, "by-name"))

    def _resolve_reference(self, at: ast.AST,
                           expr: ast.AST) -> Optional[FunctionInfo]:
        """A bare Name / self.attr argument that names a known function —
        a callback passed by name."""
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return self.resolve_local(at, expr)
        return None

    def _add(self, site: CallSite) -> None:
        self.calls.append(site)
        self._callers.setdefault(site.callee.qualname, []).append(site)

    def callers_of(self, qualname: str) -> List[CallSite]:
        """Every intra-module site that calls (or by-name references)
        ``qualname``."""
        return list(self._callers.get(qualname, ()))

    def class_sites(self, cls_name: str) -> Dict[str, List[CallSite]]:
        """method name -> intra-class call/reference sites, for every
        method of ``cls_name`` (the lockset rule's edge map)."""
        out: Dict[str, List[CallSite]] = {}
        for site in self.calls:
            if site.callee.cls == cls_name:
                out.setdefault(site.callee.name, []).append(site)
        return out


class Project:
    """All scanned modules + cross-module resolution."""

    def __init__(self, mods: Sequence[ModuleSource]):
        self.modules: Dict[str, ModuleSource] = {m.relpath: m for m in mods}
        self.graphs: Dict[str, ModuleGraph] = {
            rel: ModuleGraph(m) for rel, m in self.modules.items()}
        self._by_dotted: Dict[str, str] = {}
        for rel in self.modules:
            name = rel[:-3].replace("/", ".")
            if name.endswith(".__init__"):
                self._by_dotted[name[:-len(".__init__")]] = rel
            self._by_dotted[name] = rel

    @classmethod
    def of_module(cls, mod: ModuleSource) -> "Project":
        """Single-module project — the fixture-test entry point."""
        return cls([mod])

    def graph(self, mod: ModuleSource) -> ModuleGraph:
        g = self.graphs.get(mod.relpath)
        if g is None:  # a module outside the scanned set (fixtures)
            g = ModuleGraph(mod)
            self.graphs[mod.relpath] = g
        return g

    def function(self, module_dotted: str,
                 symbol: str) -> Optional[FunctionInfo]:
        rel = self._by_dotted.get(module_dotted)
        if rel is None:
            return None
        return self.graphs[rel].functions.get(symbol)

    def resolve_call(self, mod: ModuleSource,
                     call: ast.Call) -> Optional[FunctionInfo]:
        """Full resolution of a call: local first, then through the
        module's import map (``from m import f`` / ``m.f(...)``)."""
        graph = self.graph(mod)
        local = graph.resolve_local(call, call.func)
        if local is not None:
            return local
        func = call.func
        if isinstance(func, ast.Name):
            if graph._local_shadow(call, func.id) == "other":
                return None  # a param/local rebinding, not the import
            origin = graph.imports.get(func.id)
            if origin is not None and origin[1] is not None:
                return self.function(origin[0], origin[1])
            return None
        chain = dotted(func)
        if chain is None or "." not in chain:
            return None
        root, rest = chain.split(".", 1)
        origin = graph.imports.get(root)
        if origin is None:
            return None
        base, symbol = origin
        if symbol is not None:  # from pkg import mod; mod.f(...)
            base = f"{base}.{symbol}"
        if "." in rest:  # alias.sub.f(...) — alias of a package
            prefix, rest = rest.rsplit(".", 1)
            base = f"{base}.{prefix}"
        return self.function(base, rest)

    def kernels(self) -> Iterator[FunctionInfo]:
        for graph in self.graphs.values():
            for info in graph.functions.values():
                if info.is_kernel:
                    yield info
