"""Chaos suite: fault injection + supervised recovery (runtime/faults.py,
runtime/supervisor.py, driver --chaos/--retry/--dlq).

Headline invariant, end to end: for windowed range/kNN/join broker
pipelines under EVERY injected fault class — transient produce/consume
errors, lost acks, latency spikes, duplicate deliveries, delivery
reordering, torn payloads, and crash/restart — the final per-window output
(marker-keyed window table: keys AND record counts) is identical to a
fault-free run, and the consumer group commits the full input. Poison
records (corrupt IN the log, not just in transport) quarantine to the
dead-letter topic with failure metadata while the pipeline keeps producing.

Everything is seeded (FaultPlan + RetryPolicy jitter), so the chaos runs
replay deterministically; the fast subset is marked ``chaos_smoke``.
"""

import json
import time

import pytest
import yaml

from spatialflink_tpu.driver import main
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.runtime.faults import (ChaosBroker, FaultPlan,
                                             TransientBrokerError)
from spatialflink_tpu.runtime.supervisor import (CircuitBreaker,
                                                 CircuitOpenError,
                                                 DeadLetterQueue, RetryError,
                                                 RetryPolicy,
                                                 SupervisedBroker)
from spatialflink_tpu.streams import (
    InMemoryBroker,
    KafkaSource,
    KafkaWindowSink,
    SyntheticPointSource,
    reset_memory_brokers,
    resolve_broker,
    serialize_spatial,
)
from spatialflink_tpu.utils.metrics import REGISTRY

CONF = "conf/spatialflink-conf.yml"
IN1, IN2, OUT = "points.geojson", "queries.geojson", "output"

#: every fault class at a rate high enough to fire many times over a
#: ~50-record run, low enough that the seeded retry budget always wins
ALL_FAULTS = ("seed={seed},produce_fail=0.2,ack_lost=0.2,fetch_fail=0.2,"
              "duplicate=0.3,reorder=0.5,torn=0.15,latency=0.1,latency_ms=1")
RETRY = "attempts=12,base_ms=1,max_ms=20,breaker_threshold=4,cooldown_ms=5"


@pytest.fixture(autouse=True)
def _fresh_brokers():
    reset_memory_brokers()
    yield
    reset_memory_brokers()


def _conf(tmp_path, name, fname="conf.yml", **query_overrides):
    with open(CONF) as f:
        d = yaml.safe_load(f)
    d["kafkaBootStrapServers"] = f"memory://{name}"
    d["query"].update(query_overrides)
    p = tmp_path / fname
    p.write_text(yaml.safe_dump(d))
    return str(p), f"memory://{name}"


def _lines(n_traj=8, steps=6, seed=3):
    grid = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)
    pts = list(SyntheticPointSource(grid, num_trajectories=n_traj,
                                    steps=steps, seed=seed))
    return [serialize_spatial(p, "GeoJSON") for p in pts]


def _window_table(broker, topic=OUT):
    """{window key: record count} from the marker records — the unit of
    output identity (keys cover window bounds + job; counts cover
    contents)."""
    out = {}
    for r in broker.fetch(topic, 0, 1_000_000):
        if isinstance(r.key, str) and r.key.startswith(KafkaWindowSink.MARKER):
            out[r.key[len(KafkaWindowSink.MARKER):]] = int(r.value)
    return out


def _oracle(tmp_path, option, lines, name, extra=()):
    """Fault-free run on its own broker: the expected window table."""
    cfg, url = _conf(tmp_path, name, f"{name}.yml")
    broker = resolve_broker(url)
    for ln in lines:
        broker.produce(IN1, ln)
    assert main(["--config", cfg, "--kafka", "--option", str(option)]
                + list(extra)) == 0
    table = _window_table(broker)
    assert table, "oracle run produced no windows"
    return table


# ------------------------------------------------------------- e2e identity


@pytest.mark.chaos_smoke
@pytest.mark.parametrize("fault", [
    "fetch_fail=0.35",
    "produce_fail=0.3",
    "ack_lost=0.3",
    "duplicate=0.5",
    "reorder=0.8",
    "torn=0.2",
    "latency=0.3,latency_ms=1",
])
def test_chaos_range_output_identical_per_fault_class(tmp_path, fault):
    """Option 1 (windowed range) under each single fault class: window
    table identical to the fault-free run, full input committed, nothing
    dead-lettered (transport faults all heal)."""
    lines = _lines()
    expected = _oracle(tmp_path, 1, lines, f"oracle-{fault[:6]}")
    cfg, url = _conf(tmp_path, f"chaos-{fault[:6]}", "c.yml")
    broker = resolve_broker(url)
    for ln in lines:
        broker.produce(IN1, ln)
    assert main(["--config", cfg, "--kafka", "--option", "1",
                 "--chaos", f"seed=11,{fault}",
                 "--retry", RETRY, "--dlq"]) == 0
    assert _window_table(broker) == expected
    assert broker.committed(IN1, "spatialflink") == len(lines)
    assert broker.end_offset(OUT + "-dlq") == 0, \
        "transport-only faults must not dead-letter records"


@pytest.mark.chaos_smoke
@pytest.mark.parametrize("opt,needs2", [(1, False), (51, False), (101, True)])
def test_chaos_all_faults_range_knn_join(tmp_path, opt, needs2):
    """The headline: range, kNN and join window pipelines under EVERY fault
    class at once produce bitwise-identical window tables."""
    lines = _lines()
    lines2 = _lines(seed=8)
    cfg_o, url_o = _conf(tmp_path, f"all-oracle-{opt}", "o.yml")
    bo = resolve_broker(url_o)
    for ln in lines:
        bo.produce(IN1, ln)
    if needs2:
        for ln in lines2:
            bo.produce(IN2, ln)
    assert main(["--config", cfg_o, "--kafka", "--option", str(opt)]) == 0
    expected = _window_table(bo)
    assert expected

    cfg, url = _conf(tmp_path, f"all-chaos-{opt}", "c.yml")
    broker = resolve_broker(url)
    for ln in lines:
        broker.produce(IN1, ln)
    if needs2:
        for ln in lines2:
            broker.produce(IN2, ln)
    assert main(["--config", cfg, "--kafka", "--option", str(opt),
                 "--chaos", ALL_FAULTS.format(seed=23),
                 "--retry", RETRY, "--dlq"]) == 0
    assert _window_table(broker) == expected
    assert broker.committed(IN1, "spatialflink") == len(lines)
    if needs2:
        assert broker.committed(IN2, "spatialflink") == len(lines2)
    assert broker.end_offset(OUT + "-dlq") == 0


def test_chaos_crash_restart_output_identical(tmp_path, monkeypatch):
    """Crash at the 3rd fresh window UNDER transport chaos, restart (still
    under chaos, different seed): the final window table equals the
    fault-free oracle — at-least-once redelivery + marker-seeded
    suppression survive a degraded transport too."""
    lines = _lines(6, 30)
    expected = _oracle(tmp_path, 1, lines, "crash-oracle")
    assert len(expected) >= 4

    cfg, url = _conf(tmp_path, "crash-chaos", "c.yml")
    broker = resolve_broker(url)
    for ln in lines:
        broker.produce(IN1, ln)
    argv = ["--config", cfg, "--kafka", "--option", "1",
            "--retry", RETRY, "--dlq"]
    orig = KafkaWindowSink.emit
    state = {"fresh": 0}

    def boom(self, result):
        if self.window_key(result) not in self.delivered:
            state["fresh"] += 1
            if state["fresh"] == 3:
                raise RuntimeError("injected crash under chaos")
        orig(self, result)

    with monkeypatch.context() as m:
        m.setattr(KafkaWindowSink, "emit", boom)
        with pytest.raises(RuntimeError, match="injected crash"):
            main(argv + ["--chaos", ALL_FAULTS.format(seed=31)])
    assert broker.committed(IN1, "spatialflink") < len(lines)

    assert main(argv + ["--chaos", ALL_FAULTS.format(seed=32)]) == 0
    assert _window_table(broker) == expected
    assert broker.committed(IN1, "spatialflink") == len(lines)


@pytest.mark.chaos_smoke
def test_poison_records_quarantined_pipeline_progresses(tmp_path):
    """Records corrupt IN the log (not transport-torn) fail every
    redelivery and land in the DLQ with failure metadata; the windows from
    the clean records match the oracle run on poison-free input, and the
    group commits past the poison (quarantine = reflected in output)."""
    lines = _lines()
    expected = _oracle(tmp_path, 1, lines, "poison-oracle")

    poison = ['{"definitely": "not a spatial feature"}',
              "%% torn beyond recognition \x00\x00",
              '{"geometry": {"type": "Poi']
    records = lines[:10] + poison[:2] + lines[10:-5] + [poison[2]] + lines[-5:]
    cfg, url = _conf(tmp_path, "poison", "c.yml")
    broker = resolve_broker(url)
    for r in records:
        broker.produce(IN1, r)
    assert main(["--config", cfg, "--kafka", "--option", "1",
                 "--retry", RETRY, "--dlq"]) == 0
    assert _window_table(broker) == expected
    assert broker.committed(IN1, "spatialflink") == len(records)

    dlq = DeadLetterQueue(broker, OUT + "-dlq")
    entries = dlq.entries()
    assert len(entries) == len(poison)
    for e in entries:
        assert e["topic"] == IN1
        assert e["error"] and e["error_type"]
        assert e["attempts"] > 1, "poison must be retried before quarantine"
        assert records[e["offset"]] == e["raw"], \
            "DLQ metadata must point at the quarantined record"


@pytest.mark.chaos_smoke
def test_circuit_breaker_trips_and_run_completes(tmp_path):
    """A scripted burst of consecutive produce failures trips the breaker
    (threshold 3 < burst 5); the supervisor waits out the cool-down,
    half-opens, recovers, and the run still produces the oracle table."""
    lines = _lines()
    expected = _oracle(tmp_path, 1, lines, "breaker-oracle")
    cfg, url = _conf(tmp_path, "breaker", "c.yml")
    broker = resolve_broker(url)
    for ln in lines:
        broker.produce(IN1, ln)
    trips0 = REGISTRY.counter("breaker-trips").count
    assert main(["--config", cfg, "--kafka", "--option", "1",
                 "--chaos", "seed=5,fail_next_produces=5",
                 "--retry", "attempts=10,base_ms=1,breaker_threshold=3,"
                            "cooldown_ms=5"]) == 0
    assert REGISTRY.counter("breaker-trips").count > trips0
    assert _window_table(broker) == expected
    assert broker.committed(IN1, "spatialflink") == len(lines)


def test_chaos_bulk_drain_falls_back_and_heals(tmp_path, capsys):
    """--kafka --bulk under torn/duplicate/reorder chaos: the drained
    content fails the bulk parse gates, the run falls back to the
    streaming path (whose redelivery heals torn payloads), and the window
    table still matches the fault-free oracle with nothing dead-lettered."""
    lines = _lines()
    expected = _oracle(tmp_path, 1, lines, "bulkchaos-oracle")
    cfg, url = _conf(tmp_path, "bulkchaos", "c.yml")
    broker = resolve_broker(url)
    for ln in lines:
        broker.produce(IN1, ln)
    assert main(["--config", cfg, "--kafka", "--option", "1", "--bulk",
                 "--chaos", "seed=3,torn=0.3,fetch_fail=0.2,duplicate=0.3,"
                            "reorder=0.5",
                 "--retry", RETRY, "--dlq"]) == 0
    assert _window_table(broker) == expected
    assert broker.committed(IN1, "spatialflink") == len(lines)
    assert broker.end_offset(OUT + "-dlq") == 0


def test_chaos_without_retry_crashes_loudly(tmp_path):
    """--chaos without --retry: the injected transient error propagates —
    the contrast that shows the supervisor is doing the surviving."""
    cfg, url = _conf(tmp_path, "no-retry", "c.yml")
    broker = resolve_broker(url)
    for ln in _lines():
        broker.produce(IN1, ln)
    with pytest.raises(TransientBrokerError):
        main(["--config", cfg, "--kafka", "--option", "1",
              "--chaos", "seed=3,fail_next_fetches=1"])


def test_chaos_flags_require_kafka(tmp_path):
    cfg, _ = _conf(tmp_path, "gate", "c.yml")
    for extra in (["--chaos", "seed=1"], ["--retry"], ["--dlq"]):
        with pytest.raises(SystemExit):
            main(["--config", cfg, "--option", "1"] + extra)


# ------------------------------------------------------------------ units


def test_fault_plan_spec_parse_and_validation():
    p = FaultPlan.from_spec("seed=7,fetch_fail=0.25,torn=0.1,"
                            "fail_next_produces=3,latency_ms=4")
    assert (p.seed, p.fetch_fail, p.torn) == (7, 0.25, 0.1)
    assert p.fail_next_produces == 3 and p.latency_ms == 4.0
    with pytest.raises(ValueError, match="unknown field"):
        FaultPlan.from_spec("fetch_failz=0.2")
    with pytest.raises(ValueError, match="not in"):
        FaultPlan(duplicate=1.5)
    with pytest.raises(ValueError, match="malformed"):
        FaultPlan.from_spec("seed")


def test_chaos_broker_is_deterministic_and_log_preserving():
    """Same seed + same call sequence → the same fault schedule; torn
    payloads corrupt only the delivered COPY, never the log."""
    def run(seed):
        inner = InMemoryBroker()
        ch = ChaosBroker(inner, FaultPlan(seed=seed, fetch_fail=0.3,
                                          torn=0.5, reorder=0.5))
        for i in range(20):
            ch.produce("t", f"v{i}")
        seen = []
        for _ in range(30):
            try:
                seen.append([(r.offset, r.value) for r in ch.fetch("t", 0, 20)])
            except TransientBrokerError:
                seen.append("FAIL")
        return inner, seen

    inner_a, a = run(9)
    _, b = run(9)
    assert a == b, "same seed must replay the same fault schedule"
    assert [r.value for r in inner_a._topics["t"]] == \
        [f"v{i}" for i in range(20)], "chaos must never corrupt the log"
    assert any(s == "FAIL" for s in a)
    assert any(s != "FAIL" and any("TORN" in v for _, v in s) for s in a)


def test_kafka_source_resequences_duplicates_and_reordering():
    """The source delivers every record exactly once, in offset order, over
    a transport that duplicates and permutes every batch."""
    inner = InMemoryBroker()
    for i in range(200):
        inner.produce("t", i)
    chaos = ChaosBroker(inner, FaultPlan(seed=13, duplicate=1.0, reorder=1.0))
    src = KafkaSource(chaos, "t", "g", poll_batch=16, auto_commit=False)
    assert list(src) == list(range(200))
    assert src.position == 200


def test_retry_policy_backoff_schedule_and_give_up():
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        raise TransientBrokerError("nope")

    pol = RetryPolicy(max_attempts=4, base_delay_s=0.01, multiplier=2.0,
                      jitter=0.0, seed=0)
    with pytest.raises(RetryError) as ei:
        pol.call(flaky, sleep=sleeps.append)
    assert calls["n"] == 4
    assert sleeps == [0.01, 0.02, 0.04]
    assert isinstance(ei.value.__cause__, TransientBrokerError)

    # non-retryable errors propagate unchanged on the first attempt
    def boom():
        raise KeyError("not transient")

    with pytest.raises(KeyError):
        RetryPolicy(max_attempts=5).call(boom, sleep=sleeps.append)

    # seeded jitter is deterministic
    import itertools

    d1 = list(itertools.islice(RetryPolicy(seed=3).delays(), 5))
    d2 = list(itertools.islice(RetryPolicy(seed=3).delays(), 5))
    assert d1 == d2


def test_retry_policy_deadline_and_attempt_timeout():
    # deadline: no retry is scheduled past it (fake clock advances 1s/call)
    t = {"now": 0.0}

    def clock():
        t["now"] += 1.0
        return t["now"]

    def flaky():
        raise TransientBrokerError("nope")

    pol = RetryPolicy(max_attempts=10, base_delay_s=0.01, deadline_s=2.5)
    with pytest.raises(RetryError, match="deadline"):
        pol.call(flaky, clock=clock, sleep=lambda s: None)

    # per-attempt timeout: a stalled attempt counts as a retryable failure
    calls = {"n": 0}

    def stalls_once():
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(0.2)
        return "done"

    pol = RetryPolicy(max_attempts=3, base_delay_s=0.001,
                      attempt_timeout_s=0.05)
    assert pol.call(stalls_once) == "done"
    assert calls["n"] == 2


def test_circuit_breaker_state_machine():
    t = {"now": 0.0}
    cb = CircuitBreaker(failure_threshold=3, cooldown_s=10.0,
                        clock=lambda: t["now"])
    assert cb.state == "closed" and cb.allow()
    cb.record_failure()
    cb.record_failure()
    assert cb.state == "closed", "below threshold"
    cb.record_failure()
    assert cb.state == "open" and cb.trips == 1
    assert not cb.allow()
    with pytest.raises(CircuitOpenError):
        cb.check()
    t["now"] = 5.0
    assert not cb.allow(), "cool-down not elapsed"
    t["now"] = 10.5
    assert cb.allow(), "cool-down elapsed: half-open probe"
    assert cb.state == "half-open"
    cb.record_failure()  # probe failed: re-open, cool-down restarts
    assert not cb.allow()
    t["now"] = 21.0
    assert cb.allow()
    cb.record_success()
    assert cb.state == "closed" and cb.allow()
    # success resets the consecutive count: 2 failures don't re-trip
    cb.record_failure()
    cb.record_failure()
    assert cb.state == "closed" and cb.trips == 1


def test_supervised_produce_verifies_lost_acks():
    """ack_lost on EVERY produce: each record lands exactly once (the
    verified retry finds the landed record instead of re-sending)."""
    inner = InMemoryBroker()
    chaos = ChaosBroker(inner, FaultPlan(seed=1, ack_lost=1.0))
    sup = SupervisedBroker(chaos, RetryPolicy(max_attempts=4,
                                              base_delay_s=0.0001),
                           CircuitBreaker(10, 0.001))
    offs = [sup.produce("t", f"v{i}", key=f"k{i}") for i in range(30)]
    assert offs == list(range(30))
    assert [r.value for r in inner.fetch("t", 0, 100)] == \
        [f"v{i}" for i in range(30)]


def test_breaker_cooldown_wait_not_charged_to_attempt_timeout():
    """Regression: the open-circuit cool-down wait runs OUTSIDE the
    per-attempt timeout. With the wait inside it, every attempt on an open
    circuit timed out, each timeout re-opened the breaker, and a recovered
    5-failure burst escalated into RetryError on a healthy transport."""
    inner = InMemoryBroker()
    inner.produce("t", "a")
    chaos = ChaosBroker(inner, FaultPlan(seed=2, fail_next_fetches=5))
    sup = SupervisedBroker(
        chaos,
        RetryPolicy(max_attempts=10, base_delay_s=0.001,
                    attempt_timeout_s=0.05),
        CircuitBreaker(failure_threshold=5, cooldown_s=0.2))
    recs = sup.fetch("t", 0, 10)  # must recover, not RetryError
    assert [r.value for r in recs] == ["a"]
    assert sup.breaker.trips == 1 and sup.breaker.state == "closed"


def test_supervised_fetch_waits_out_open_circuit():
    """A fetch burst longer than the breaker threshold trips the circuit;
    the supervisor sleeps out the cool-down and completes the call."""
    inner = InMemoryBroker()
    inner.produce("t", "a")
    chaos = ChaosBroker(inner, FaultPlan(seed=2, fail_next_fetches=4))
    slept = []
    sup = SupervisedBroker(
        chaos, RetryPolicy(max_attempts=10, base_delay_s=0.0001),
        CircuitBreaker(failure_threshold=3, cooldown_s=0.002),
        sleep=lambda s: slept.append(s) or time.sleep(min(s, 0.002)))
    recs = sup.fetch("t", 0, 10)
    assert [r.value for r in recs] == ["a"]
    assert sup.breaker.trips >= 1


def test_torn_control_tuple_heals_to_stop_not_dlq():
    """A remote-stop control tuple torn in transport must, once healed by
    the DLQ's redelivery, STOP the pipeline (ControlTupleExit) — not be
    quarantined as poison or passed through as data."""
    from dataclasses import replace as _replace

    from spatialflink_tpu.streams import WindowCommitTap
    from spatialflink_tpu.utils.metrics import ControlTupleExit

    inner = InMemoryBroker()
    inner.produce("t", json.dumps(
        {"geometry": {"type": "control", "coordinates": []}}))

    class TearFirstDelivery:
        """Corrupt the first delivery of each offset; redeliveries heal."""

        def __init__(self, b):
            self.b = b
            self.seen = set()

        def fetch(self, topic, offset, max_records=500):
            out = []
            for r in self.b.fetch(topic, offset, max_records):
                if r.offset not in self.seen:
                    self.seen.add(r.offset)
                    r = _replace(r, value=r.value[:5] + "\x00TORN")
                out.append(r)
            return out

        def __getattr__(self, name):
            return getattr(self.b, name)

    src = KafkaSource(TearFirstDelivery(inner), "t", "g", auto_commit=False)
    dlq = DeadLetterQueue(inner, "dead")
    tap = WindowCommitTap(src, 10_000, 5_000, parse=json.loads, dlq=dlq)
    with pytest.raises(ControlTupleExit):
        list(tap)
    assert len(dlq) == 0, "healed control tuple must not be quarantined"


def test_torn_control_tuple_in_chunk_flushes_parsed_prefix():
    """Bulk-decode path: when a torn STOP tuple heals mid-chunk, the
    records buffered BEFORE it must still reach the pipeline before the
    stop propagates (the intact-control path's contract)."""
    from dataclasses import replace as _replace

    from spatialflink_tpu.streams import WindowCommitTap
    from spatialflink_tpu.utils.metrics import ControlTupleExit

    inner = InMemoryBroker()
    for i in range(3):
        inner.produce("t", json.dumps({"v": i, "timestamp": 1000 + i}))
    inner.produce("t", json.dumps(
        {"geometry": {"type": "control", "coordinates": []}}))

    class TearFirstDelivery:
        def __init__(self, b):
            self.b = b
            self.seen = set()

        def fetch(self, topic, offset, max_records=500):
            out = []
            for r in self.b.fetch(topic, offset, max_records):
                if r.offset not in self.seen:
                    self.seen.add(r.offset)
                    r = _replace(r, value=r.value[:5] + "\x00TORN")
                out.append(r)
            return out

        def __getattr__(self, name):
            return getattr(self.b, name)

    def broken_bulk(raws):
        raise ValueError("chunk not bulk-decodable")

    src = KafkaSource(TearFirstDelivery(inner), "t", "g", auto_commit=False)
    tap = WindowCommitTap(src, 10_000, 5_000, parse=json.loads,
                          bulk_decode=broken_bulk,
                          dlq=DeadLetterQueue(inner, "dead"))
    got = []
    with pytest.raises(ControlTupleExit):
        for obj in tap:
            got.append(obj)
    assert [o["v"] for o in got] == [0, 1, 2], \
        "records before the stop tuple were dropped"
    assert inner.end_offset("dead") == 0


def test_dlq_quarantine_metadata_and_compactable_keys():
    broker = InMemoryBroker()
    dlq = DeadLetterQueue(broker, "dead", raw_limit=8)
    try:
        json.loads("{broken")
    except ValueError as e:
        dlq.quarantine(source_topic="in", offset=42,
                       raw="{broken-and-long-payload", error=e, attempts=5)
    assert len(dlq) == 1
    (e,) = dlq.entries()
    assert (e["topic"], e["offset"], e["attempts"]) == ("in", 42, 5)
    assert e["error_type"] == "JSONDecodeError"
    assert e["raw"] == "{broken-"  # truncated to raw_limit
    rec = broker.fetch("dead", 0, 10)[0]
    assert rec.key == f"{DeadLetterQueue.KEY_PREFIX}in:42"


def test_degradation_counters_surface_in_summary(tmp_path, capsys):
    """The driver's kafka summary line reports the degradation digest."""
    lines = _lines()
    cfg, url = _conf(tmp_path, "summary", "c.yml")
    broker = resolve_broker(url)
    for ln in lines:
        broker.produce(IN1, ln)
    assert main(["--config", cfg, "--kafka", "--option", "1",
                 "--chaos", "seed=9,fetch_fail=0.3",
                 "--retry", RETRY]) == 0
    err = capsys.readouterr().err
    assert "degraded:" in err
    assert "chaos-fetch-fail=" in err
    assert "retry-attempts=" in err
