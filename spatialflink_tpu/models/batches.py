"""Padded device batches — the unit of TPU execution.

A *window batch* is what the host streaming runtime hands to device kernels:
a fixed-shape structure-of-arrays with validity masks. NamedTuples are JAX
pytrees, so batches pass transparently through jit / vmap / shard_map.

Device-side conventions:
- coordinates: float32 (degree space, like the reference's hot paths)
- object ids: int32 (interned from strings by the host, utils.IdInterner)
- timestamps: int32 milliseconds relative to the batch's ``ts_base`` — an
  epoch-millis int64 kept host-side as a static aux field — so device arrays
  avoid x64 mode while windows spanning ±24 days stay exact.
- cell ids:   int32 ``cx * n + cy``; -1 marks out-of-grid
- ``valid``:  bool; padded slots are False and must be masked by every kernel
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence, Tuple

import numpy as np

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import objects as sobj
from spatialflink_tpu.utils import IdInterner, bucket_size, pad_to


class PointBatch(NamedTuple):
    """A batch of N points (N padded to a bucket size)."""

    x: np.ndarray        # (N,) f32
    y: np.ndarray        # (N,) f32
    obj_id: np.ndarray   # (N,) i32
    ts: np.ndarray       # (N,) i32, millis offset from ts_base
    cell: np.ndarray     # (N,) i32, -1 = outside grid
    valid: np.ndarray    # (N,) bool

    @property
    def capacity(self) -> int:
        return self.x.shape[-1]

    @staticmethod
    def from_arrays(
        x,
        y,
        *,
        grid: Optional[UniformGrid] = None,
        obj_id=None,
        ts=None,
        ts_base: int = 0,
        pad: Optional[int] = None,
        cell=None,
    ) -> "PointBatch":
        """Build from host float64 arrays; assigns cells and pads.

        ``cell`` may carry precomputed cell ids (−1 for out-of-grid), letting
        bulk/sliding-window callers assign cells once per record instead of
        once per window membership."""
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        n = x.shape[0]
        obj_id = np.zeros(n, np.int32) if obj_id is None else np.asarray(obj_id, np.int32)
        if ts is None:
            ts32 = np.zeros(n, np.int32)
        else:
            ts32 = (np.asarray(ts, np.int64) - int(ts_base)).astype(np.int32)
        if cell is not None:
            cell = np.asarray(cell, np.int32)
        elif grid is not None:
            cell, _ = grid.assign_cell(x, y)
        else:
            cell = np.full(n, -1, np.int32)
        size = bucket_size(n) if pad is None else pad
        valid = pad_to(np.ones(n, bool), size)
        return PointBatch(
            x=pad_to(x.astype(np.float32), size),
            y=pad_to(y.astype(np.float32), size),
            obj_id=pad_to(obj_id, size),
            ts=pad_to(ts32, size),
            cell=pad_to(cell, size, fill=-1),
            valid=valid,
        )

    @staticmethod
    def from_points(
        points: Sequence[sobj.Point],
        grid: Optional[UniformGrid] = None,
        interner: Optional[IdInterner] = None,
        ts_base: int = 0,
        pad: Optional[int] = None,
    ) -> "PointBatch":
        interner = interner if interner is not None else IdInterner()
        x = np.array([p.x for p in points], np.float64)
        y = np.array([p.y for p in points], np.float64)
        oid = np.array([interner.intern(p.obj_id) for p in points], np.int32)
        ts = np.array([p.timestamp for p in points], np.int64)
        return PointBatch.from_arrays(
            x, y, grid=grid, obj_id=oid, ts=ts, ts_base=ts_base, pad=pad
        )


class EdgeGeomBatch(NamedTuple):
    """A batch of G polygon/linestring geometries as padded edge arrays.

    ``is_areal`` distinguishes polygons (areal: containment counts, distance 0
    inside) from linestrings (curve: boundary distance only). Mixed batches
    are allowed — the flag is per geometry.
    """

    edges: np.ndarray      # (G, E, 4) f32 — [x1,y1,x2,y2] per edge
    edge_mask: np.ndarray  # (G, E) bool
    bbox: np.ndarray       # (G, 4) f32 — [minx,miny,maxx,maxy]
    obj_id: np.ndarray     # (G,) i32
    ts: np.ndarray         # (G,) i32
    cell: np.ndarray       # (G,) i32 representative cell
    cells: np.ndarray      # (G, C) i32 overlapped cells, -1 padded
    cells_mask: np.ndarray # (G, C) bool
    is_areal: np.ndarray   # (G,) bool
    valid: np.ndarray      # (G,) bool

    @property
    def capacity(self) -> int:
        return self.edges.shape[-3]

    @staticmethod
    def from_objects(
        geoms: Sequence[sobj._EdgeGeom],
        grid: Optional[UniformGrid] = None,
        interner: Optional[IdInterner] = None,
        ts_base: int = 0,
        pad: Optional[int] = None,
        edge_pad: Optional[int] = None,
        cell_pad: Optional[int] = None,
    ) -> "EdgeGeomBatch":
        interner = interner if interner is not None else IdInterner()
        g = len(geoms)
        edge_arrays = [geo.edge_array() for geo in geoms]
        max_e = max((e.shape[0] for e, _ in edge_arrays), default=1)
        E = bucket_size(max_e, 8) if edge_pad is None else edge_pad
        max_c = max((len(geo.cells) for geo in geoms), default=1) or 1
        C = bucket_size(max_c, 8) if cell_pad is None else cell_pad

        edges = np.zeros((g, E, 4), np.float32)
        emask = np.zeros((g, E), bool)
        cells = np.full((g, C), -1, np.int32)
        cmask = np.zeros((g, C), bool)
        for i, (e, m) in enumerate(edge_arrays):
            edges[i, : e.shape[0]] = e.astype(np.float32)
            emask[i, : e.shape[0]] = m
            cs = sorted(geoms[i].cells)[:C]
            cells[i, : len(cs)] = cs
            cmask[i, : len(cs)] = True

        bbox = np.asarray([geo.bbox for geo in geoms], np.float32).reshape(g, 4)
        oid = np.array([interner.intern(geo.obj_id) for geo in geoms], np.int32)
        ts = (np.array([geo.timestamp for geo in geoms], np.int64) - int(ts_base)).astype(np.int32)
        cell = np.array([geo.cell for geo in geoms], np.int32)
        areal = np.array(
            [isinstance(geo, (sobj.Polygon, sobj.MultiPolygon)) for geo in geoms], bool
        )

        size = bucket_size(g, 8) if pad is None else pad
        return EdgeGeomBatch(
            edges=pad_to(edges, size),
            edge_mask=pad_to(emask, size),
            bbox=pad_to(bbox, size),
            obj_id=pad_to(oid, size),
            ts=pad_to(ts, size),
            cell=pad_to(cell, size, fill=-1),
            cells=pad_to(cells, size, fill=-1),
            cells_mask=pad_to(cmask, size),
            is_areal=pad_to(areal, size),
            valid=pad_to(np.ones(g, bool), size),
        )


def single_query_edges(
    geom: sobj._EdgeGeom, edge_pad: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Padded (E,4)/(E,) edge arrays for one query geometry."""
    e, m = geom.edge_array()
    E = bucket_size(e.shape[0], 8) if edge_pad is None else edge_pad
    return (
        pad_to(e.astype(np.float32), E),
        pad_to(m, E),
    )
