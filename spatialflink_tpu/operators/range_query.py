"""Point-stream x point-query continuous range query.

Reference: ``spatialOperators/range/PointPointRangeQuery.java`` — realtime
(:43-83), window (:85-141), incremental (:144-245). Semantics preserved:
guaranteed-cell points are emitted without distance computation; candidate
points pass iff exact distance <= r; approximate mode emits all GN∪CN points.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional

import jax.numpy as jnp

from spatialflink_tpu.models import Point
from spatialflink_tpu.operators.base import (
    Deferred,
    GeomQueryMixin,
    QueryType,
    SpatialOperator,
    WindowResult,
)
from spatialflink_tpu.ops.range import range_filter_point_stats


class _RangeMultiBulkMixin:
    """One run_multi_bulk body for every range pair: subclasses provide
    the window source (:meth:`_bulk_batches`) and the per-class multi-mask
    closure (:meth:`_multi_mask_stats`)."""

    def _bulk_batches(self, parsed, pad):
        raise NotImplementedError

    def run_multi_bulk(self, parsed, queries, radius: float, *,
                       pad: Optional[int] = None) -> Iterator[WindowResult]:
        """Bulk-replay multi-query (the ``--bulk --multi-query`` path):
        per-query original-record index lists from one (Q, N) mask dispatch
        per window."""
        batched = (
            (start, end, (idx, batch))
            for start, end, idx, batch in self._bulk_batches(parsed, pad)
        )
        return self._run_multi_filter_bulk(
            batched, len(queries), self._multi_mask_stats(queries, radius))


class _PointStreamBulkSource:
    """Point-stream bulk window source shared by the point-stream range
    classes' multi-bulk paths."""

    def _bulk_batches(self, parsed, pad):
        from spatialflink_tpu.streams.bulk import bulk_window_batches

        return bulk_window_batches(parsed, self.conf.window_spec(),
                                   self.grid, pad=pad)


class PointPointRangeQuery(_PointStreamBulkSource, _RangeMultiBulkMixin,
                           SpatialOperator):
    telemetry_label = "range"

    #: pane-incremental hooks (``--panes``): the window evaluator IS the
    #: per-pane partial evaluator (the same mask kernel over a pane-sized
    #: batch), and disjoint panes union by concatenation — one definition
    #: for every filter-shaped range pair.
    merge_partials = staticmethod(SpatialOperator._pane_concat)

    def run(self, stream: Iterable[Point], query_point: Point, radius: float
            ) -> Iterator[WindowResult]:
        # --adaptive-grid: the query's GN∪CN leaf mask, version-cached so a
        # mid-run repartition invalidates it on the next window (the point
        # query tightens to its exact fine cell inside a split hot cell)
        mask_cache = self._leaf_mask_cache(
            lambda: self.conf.adaptive_grid.neighboring_leaf_mask(
                radius, query_point.cell,
                point=(query_point.x, query_point.y)))
        return self._drive(
            stream, lambda records, ts_base: self._eval(records, query_point,
                                                        radius, ts_base,
                                                        mask_cache),
            pane_merge=self.merge_partials,
        )

    # ---------------------------------------------------------------- #

    def _eval(self, records: List[Point], query_point: Point, radius: float,
              ts_base: int, mask_cache=None) -> List[Point]:
        if not records:
            return []
        pre = self._prefilter(records, mask_cache, ts_base)
        if pre is not None:
            idx, batch = pre
            if batch is None:  # no candidate leaves in this window
                return []
            mask, stats = self._range_mask(batch, query_point, radius)
            return self._defer_mask_select_at(mask, records, idx, stats)
        batch = self._point_batch(records, ts_base)
        mask, stats = self._range_mask(batch, query_point, radius)
        return self._defer_mask_select(mask, records, stats)

    def _mask_stats_fn(self, query_point: Point, radius: float):
        """Per-batch (mask, gn_bypassed, dist_evals) closure — the same
        shape every range operator exposes; _filter_stream runs it whole
        single-device or per shard on the mesh."""
        args = (
            query_point.x, query_point.y, jnp.int32(query_point.cell), radius,
            self.grid.guaranteed_layers(radius),
            self.grid.candidate_layers(radius),
        )

        def mask_stats(b):
            mask, _, gn_c, evals = range_filter_point_stats(
                b, *args, n=self.grid.n, approximate=self.conf.approximate,
            )
            return mask, gn_c, evals

        return mask_stats

    def _range_mask(self, batch, query_point: Point, radius: float):
        """(mask, (gn_bypassed, dist_evals)) for one window batch — the
        pruning-counter scalars are psum-merged on the distributed path like
        every other operator family."""
        mask, gn_bypassed, dist_evals = self._filter_stream(
            batch, self._mask_stats_fn(query_point, radius))
        return mask, (gn_bypassed, dist_evals)

    # ---------------------------------------------------------------- #

    def run_bulk(self, parsed, query_point: Point, radius: float, *,
                 pad: Optional[int] = None) -> Iterator[WindowResult]:
        """Bulk-replay fast path: windows come from the vectorized assembler
        (``streams.bulk.bulk_window_batches``) and results are original-record
        index lists — no per-record Python objects anywhere.

        Windowed mode only (a bounded replay has no realtime trigger).
        """
        return self._drive_bulk(
            parsed, self._bulk_mask_eval(self._mask_stats_fn(query_point, radius)),
            pad=pad, pane_merge=self.merge_partials)

    def _multi_mask_stats(self, query_points, radius: float):
        """The per-batch multi-mask closure shared by run_multi and
        run_multi_bulk."""
        from spatialflink_tpu.ops.range import range_filter_point_multi_masks

        qx, qy, qc = self._query_point_arrays(query_points)
        args = (radius, self.grid.guaranteed_layers(radius),
                self.grid.candidate_layers(radius))

        def multi_mask_stats(b):
            return range_filter_point_multi_masks(
                b, qx, qy, qc, *args, n=self.grid.n,
                approximate=self.conf.approximate)

        return multi_mask_stats

    def run_multi(self, stream: Iterable[Point],
                  query_points: List[Point], radius: float
                  ) -> Iterator[WindowResult]:
        """Q continuous range queries over ONE stream in ONE dispatch per
        window (TPU-native extension; the reference runs one query per job,
        ``StreamingJob.java:470``). ``records[q]`` holds the records within
        ``radius`` of ``query_points[q]`` under the usual GN-bypass/CN
        semantics; ``extras["queries"] = Q``. Pruning counters aggregate
        across the Q queries of each dispatch; with ``conf.devices`` the
        stream batch shards over the mesh like every other operator."""
        def union_leaf_mask():
            # --adaptive-grid: a record outside EVERY query's GN∪CN leaf
            # set cannot appear in any per-query result — the Q×N kernel
            # shrinks to Q×kept (one leaf-space sweep for the whole fleet)
            return self.conf.adaptive_grid.union_neighboring_leaf_mask(
                radius, [(q.cell, (q.x, q.y)) for q in query_points])

        return self._run_multi_filter(
            stream, len(query_points),
            self._multi_mask_stats(query_points, radius),
            self._point_batch, leaf_mask_builder=union_leaf_mask)

    def run_dynamic(self, stream: Iterable[Point], registry, radius: float
                    ) -> Iterator[WindowResult]:
        """Standing-query serving: the Q-axis fleet comes from a live
        ``runtime.queryplane.QueryRegistry`` — queries admitted/updated/
        retired MID-RUN take effect at the next window, padded to size
        buckets so fleet changes within a bucket never recompile, with
        the adaptive-grid union leaf mask rebuilt on every fleet-version
        bump (exactly as it is on grid-version bumps)."""
        ag = self.conf.adaptive_grid
        leaf_union = None
        if ag is not None:
            def leaf_union(pts):
                return ag.union_neighboring_leaf_mask(
                    radius, [(p.cell, (p.x, p.y)) for p in pts])

        return self._run_dynamic_filter(
            stream, registry, radius, self._multi_mask_stats,
            self._point_batch, leaf_union_builder=leaf_union)

    def run_incremental(self, stream: Iterable[Point], query_point: Point,
                        radius: float) -> Iterator[WindowResult]:
        """Incremental sliding windows: carry the previous window's survivors
        and only evaluate records newer than the previous slide
        (``PointPointRangeQuery.queryIncremental``, ``:144-245``)."""
        if self.conf.query_type is QueryType.CountBased:
            raise NotImplementedError(
                "run_incremental carries survivors by TIME cutoff; count "
                "windows have no fixed temporal slide — use run()")
        prev: dict = {}  # id(record) -> record surviving from previous window
        prev_window_start = None
        for start, end, records in self._windows(stream):
            if prev_window_start is None:
                fresh = records
            else:
                cutoff = start + self.conf.window_size_ms - self.conf.slide_ms
                # records at/after the previous window's end are new
                fresh = [r for r in records if r.timestamp >= cutoff]
            sel = self._eval(fresh, query_point, radius, start)
            selected_new = sel.finish() if isinstance(sel, Deferred) else sel
            carried = [
                r for r in prev.values() if r.timestamp >= start
            ]
            out = {id(r): r for r in carried}
            out.update({id(r): r for r in selected_new})
            prev = out
            prev_window_start = start
            yield WindowResult(start, end, list(out.values()))


class PointGeomRangeQuery(_PointStreamBulkSource, _RangeMultiBulkMixin,
                          SpatialOperator, GeomQueryMixin):
    telemetry_label = "range"

    merge_partials = staticmethod(SpatialOperator._pane_concat)

    """Point stream x polygon/linestring query
    (``range/PointPolygonRangeQuery.java``, ``PointLineStringRangeQuery``).

    Approximate mode filters on the bbox distance instead of the exact
    geometry distance (the reference's approximateQuery flag)."""

    def _mask_stats_fn(self, query_geom, radius: float):
        """Per-batch (mask, gn_bypassed, dist_evals) closure over the
        precomputed query-side arrays — the single source for both the
        single-device and mesh paths (and the bench harness)."""
        gn, cn, _nb = self._query_masks(query_geom, radius)
        q_edges, q_mask, q_areal = self._query_edges(query_geom)
        q_bbox = self._query_bbox(query_geom)

        def mask_stats(batch):
            from spatialflink_tpu.ops.distances import point_bbox_dist
            from spatialflink_tpu.ops.geom import points_to_single_geom_dist
            from spatialflink_tpu.ops.range import range_filter_masks_stats

            if self.conf.approximate:
                dists = point_bbox_dist(batch.x, batch.y,
                                        q_bbox[0], q_bbox[1], q_bbox[2], q_bbox[3])
            else:
                dists = points_to_single_geom_dist(batch, q_edges, q_mask, q_areal)
            return range_filter_masks_stats(batch, gn, cn, dists, radius)

        return mask_stats

    def run(self, stream: Iterable[Point], query_geom, radius: float
            ) -> Iterator[WindowResult]:
        mask_stats = self._mask_stats_fn(query_geom, radius)
        # --adaptive-grid: leaf mask unioned over the geometry's base cells
        # (UniformGrid.java:193-222 union semantics, refined per level)
        mask_cache = self._leaf_mask_cache(
            lambda: self.conf.adaptive_grid.neighboring_leaf_mask(
                radius, self._query_cells(query_geom)))

        def eval_batch(records, ts_base):
            if not records:
                return []
            pre = self._prefilter(records, mask_cache, ts_base)
            if pre is not None:
                idx, batch = pre
                if batch is None:
                    return []
                mask, gn_c, evals = self._filter_stream(batch, mask_stats)
                return self._defer_mask_select_at(mask, records, idx,
                                                 (gn_c, evals))
            batch = self._point_batch(records, ts_base)
            mask, gn_c, evals = self._filter_stream(batch, mask_stats)
            return self._defer_mask_select(mask, records, (gn_c, evals))

        return self._drive(stream, eval_batch, pane_merge=self.merge_partials)

    def run_bulk(self, parsed, query_geom, radius: float, *,
                 pad: Optional[int] = None) -> Iterator[WindowResult]:
        """Bulk-replay fast path over point-stream windows (native ingest;
        results are original-record index lists)."""
        return self._drive_bulk(
            parsed, self._bulk_mask_eval(self._mask_stats_fn(query_geom, radius)),
            pad=pad, pane_merge=self.merge_partials)

    def _multi_mask_stats(self, query_geoms, radius: float):
        from spatialflink_tpu.ops.geom import range_points_to_geom_queries

        qgb = self._query_geom_batch(query_geoms)
        gn, cn = self._stack_query_masks(query_geoms, radius,
                                         which=("gn", "cn"))
        return lambda batch: range_points_to_geom_queries(
            batch, qgb, gn, cn, radius, approximate=self.conf.approximate)

    def run_multi(self, stream: Iterable[Point], query_geoms,
                  radius: float) -> Iterator[WindowResult]:
        """Q polygon/linestring QUERIES over one point stream in ONE
        dispatch per window (``ops.geom.range_points_to_geom_queries``);
        same contract as ``PointPointRangeQuery.run_multi``."""
        def union_leaf_mask():
            return self.conf.adaptive_grid.union_neighboring_leaf_mask(
                radius, [(self._query_cells(q), None) for q in query_geoms])

        return self._run_multi_filter(
            stream, len(query_geoms),
            self._multi_mask_stats(query_geoms, radius),
            self._point_batch, leaf_mask_builder=union_leaf_mask)


class _GeomStreamBulkMixin:
    """Bulk-replay fast path for geometry STREAMS: native WKT ingest ->
    vectorized window assembly (``streams.bulk.bulk_geom_window_batches``)
    -> the operator's own mask_stats kernels; results are original-record
    index lists, no per-record Python objects."""

    def _bulk_batches(self, parsed, pad):
        from spatialflink_tpu.streams.bulk import bulk_geom_window_batches

        # like base._geom_batch: the geometry dim must divide across the
        # mesh, so the per-window bucket floor rises to the device count
        min_bucket = max(8, self.conf.devices) if self.distributed else 8
        return bulk_geom_window_batches(parsed, self.conf.window_spec(),
                                        self.grid, pad=pad,
                                        min_bucket=min_bucket)

    def run_bulk(self, parsed, query, radius: float, *,
                 pad: Optional[int] = None) -> Iterator[WindowResult]:
        batched = (
            (start, end, (idx, batch))
            for start, end, idx, batch in self._bulk_batches(parsed, pad)
        )
        return self._drive_batched(
            batched, self._bulk_mask_eval(self._mask_stats_fn(query, radius)),
            count=lambda p: len(p[0]))


class GeomPointRangeQuery(SpatialOperator, GeomQueryMixin,
                          _GeomStreamBulkMixin, _RangeMultiBulkMixin):
    telemetry_label = "range"

    merge_partials = staticmethod(SpatialOperator._pane_concat)

    """Polygon/linestring stream x point query
    (``range/PolygonPointRangeQuery.java``, ``LineStringPointRangeQuery``).
    GN-subset rule: a geometry passes without distance math only if ALL its
    cells are guaranteed neighbors (``:54-87``)."""

    def _mask_stats_fn(self, query_point: Point, radius: float):
        gn, _cn, nb = self._query_masks(query_point, radius)

        def mask_stats(geoms):
            from spatialflink_tpu.ops.distances import point_bbox_dist
            from spatialflink_tpu.ops.geom import (
                geom_cells_all_within,
                geom_cells_any_within,
                point_to_geoms_dist,
            )
            from spatialflink_tpu.ops.range import range_filter_geom_stream_stats

            all_gn = geom_cells_all_within(geoms.cells, geoms.cells_mask, gn)
            any_nb = geom_cells_any_within(geoms.cells, geoms.cells_mask, nb)
            if self.conf.approximate:
                dists = point_bbox_dist(query_point.x, query_point.y,
                                        geoms.bbox[:, 0], geoms.bbox[:, 1],
                                        geoms.bbox[:, 2], geoms.bbox[:, 3])
            else:
                dists = point_to_geoms_dist(query_point.x, query_point.y, geoms)
            return range_filter_geom_stream_stats(
                all_gn, any_nb, dists, radius, geoms.valid)

        return mask_stats

    def run(self, stream: Iterable, query_point: Point, radius: float
            ) -> Iterator[WindowResult]:
        mask_stats = self._mask_stats_fn(query_point, radius)

        def eval_batch(records, ts_base):
            if not records:
                return []
            geoms = self._geom_batch(records, ts_base)
            mask, gn_c, evals = self._filter_stream(geoms, mask_stats)
            return self._defer_mask_select(mask, records, (gn_c, evals))

        return self._drive(stream, eval_batch, pane_merge=self.merge_partials)

    def _multi_mask_stats(self, query_points, radius: float):
        from spatialflink_tpu.ops.geom import range_geoms_to_point_queries

        qx, qy, _qc = self._query_point_arrays(query_points)
        gn, nb = self._stack_query_masks(query_points, radius,
                                         which=("gn", "nb"))
        return lambda geoms: range_geoms_to_point_queries(
            geoms, qx, qy, gn, nb, radius,
            approximate=self.conf.approximate)

    def run_multi(self, stream: Iterable, query_points,
                  radius: float) -> Iterator[WindowResult]:
        """Q query POINTS over one polygon/linestring stream in ONE dispatch
        per window (``ops.geom.range_geoms_to_point_queries`` — GN-subset
        rule applied per query)."""
        return self._run_multi_filter(
            stream, len(query_points),
            self._multi_mask_stats(query_points, radius),
            self._geom_batch)

    def run_dynamic(self, stream: Iterable, registry, radius: float
                    ) -> Iterator[WindowResult]:
        """Standing point-query serving over a geometry STREAM — the same
        live-registry contract as ``PointPointRangeQuery.run_dynamic``
        (no leaf prefilter: geometry streams keep their full batch)."""
        return self._run_dynamic_filter(
            stream, registry, radius, self._multi_mask_stats,
            self._geom_batch)


class GeomGeomRangeQuery(SpatialOperator, GeomQueryMixin,
                         _GeomStreamBulkMixin, _RangeMultiBulkMixin):
    telemetry_label = "range"

    merge_partials = staticmethod(SpatialOperator._pane_concat)

    """Polygon/linestring stream x polygon/linestring query
    (``range/PolygonPolygonRangeQuery.java`` and the 3 sibling pairs)."""

    def _mask_stats_fn(self, query_geom, radius: float):
        gn, _cn, nb = self._query_masks(query_geom, radius)
        q_edges, q_mask, q_areal = self._query_edges(query_geom)
        q_bbox = self._query_bbox(query_geom)

        def mask_stats(geoms):
            from spatialflink_tpu.ops.geom import (
                geom_cells_all_within,
                geom_cells_any_within,
                geoms_bbox_dist,
                geoms_to_single_geom_dist,
            )
            from spatialflink_tpu.ops.range import range_filter_geom_stream_stats

            all_gn = geom_cells_all_within(geoms.cells, geoms.cells_mask, gn)
            any_nb = geom_cells_any_within(geoms.cells, geoms.cells_mask, nb)
            if self.conf.approximate:
                dists = geoms_bbox_dist(geoms, q_bbox)
            else:
                dists = geoms_to_single_geom_dist(geoms, q_edges, q_mask, q_areal)
            return range_filter_geom_stream_stats(
                all_gn, any_nb, dists, radius, geoms.valid)

        return mask_stats

    def run(self, stream: Iterable, query_geom, radius: float
            ) -> Iterator[WindowResult]:
        mask_stats = self._mask_stats_fn(query_geom, radius)

        def eval_batch(records, ts_base):
            if not records:
                return []
            geoms = self._geom_batch(records, ts_base)
            mask, gn_c, evals = self._filter_stream(geoms, mask_stats)
            return self._defer_mask_select(mask, records, (gn_c, evals))

        return self._drive(stream, eval_batch, pane_merge=self.merge_partials)

    def _multi_mask_stats(self, query_geoms, radius: float):
        from spatialflink_tpu.ops.geom import range_geoms_to_geom_queries

        qgb = self._query_geom_batch(query_geoms)
        gn, nb = self._stack_query_masks(query_geoms, radius,
                                         which=("gn", "nb"))
        return lambda geoms: range_geoms_to_geom_queries(
            geoms, qgb, gn, nb, radius, approximate=self.conf.approximate)

    def run_multi(self, stream: Iterable, query_geoms,
                  radius: float) -> Iterator[WindowResult]:
        """Q query GEOMETRIES over one polygon/linestring stream in ONE
        dispatch per window (``ops.geom.range_geoms_to_geom_queries`` — the
        Q queries ride one exact-capacity padded edge batch)."""
        return self._run_multi_filter(
            stream, len(query_geoms),
            self._multi_mask_stats(query_geoms, radius),
            self._geom_batch)


# Reference-named aliases (stream type x query type), SURVEY §2.2
PointPolygonRangeQuery = PointGeomRangeQuery
PointLineStringRangeQuery = PointGeomRangeQuery
PolygonPointRangeQuery = GeomPointRangeQuery
LineStringPointRangeQuery = GeomPointRangeQuery
PolygonPolygonRangeQuery = GeomGeomRangeQuery
PolygonLineStringRangeQuery = GeomGeomRangeQuery
LineStringPolygonRangeQuery = GeomGeomRangeQuery
LineStringLineStringRangeQuery = GeomGeomRangeQuery
