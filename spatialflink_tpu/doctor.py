"""Post-mortem / preflight doctor for the device-truth plane.

``python -m spatialflink_tpu.doctor`` reads what the flight recorder
(``--postmortem-dir``) writes and answers the questions an operator has
BEFORE and AFTER a run:

- ``--preflight [--require-backend tpu]`` — readiness check for the
  accelerator: backend provenance vs the required target (the BENCH r05
  silent-CPU-fallback condition exits non-zero instead of being discovered
  in a ledger tail), device visibility, memory-stats availability, a tiny
  instrumented-jit probe compile (proves the compile path + registry), and
  the persistent compilation-cache configuration. Exit 0 = ready.
- ``summarize BUNDLE`` — one human digest of a post-mortem bundle: dump
  reason, error, backend, throughput/window counters, health verdict,
  compile/recompile counts with the hottest trigger signatures, last
  flight-recorder notes and lifecycle events.
- ``diff A B`` — compare two bundles (e.g. a crashed run against a healthy
  baseline): backend equality (cross-backend comparisons are flagged the
  way ``bench_diff`` refuses them), counter deltas, compile/recompile
  deltas, health verdicts side by side. Exit 0; structural problems
  (unreadable bundle, schema mismatch) exit 2.
- ``tenants BUNDLE`` — the per-tenant cost table from a bundle's
  ``tenants.json``: attributed kernel-ms (+ share), bytes, records,
  windows, SLO/shed/quota counters, the fairness line, and the worst
  attribution residual — "who was paying for the pipeline when it died".

All output is line-oriented text by default; ``--json`` emits one JSON
document instead (machine-readable — the same dict the text renders).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from spatialflink_tpu.utils import deviceplane


# --------------------------------------------------------------------- #
# bundle IO


def load_bundle(path: str) -> dict:
    """Read one flight-recorder bundle directory into a dict keyed by file
    stem (manifest/status/compile/device/events/traces/flight/config).
    Raises ValueError on a missing/unreadable manifest or a schema this
    doctor does not speak."""
    manifest_path = os.path.join(path, "manifest.json")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"{path}: not a post-mortem bundle "
                         f"(manifest.json unreadable: {e})")
    schema = manifest.get("schema")
    if schema != deviceplane.BUNDLE_SCHEMA:
        raise ValueError(f"{path}: bundle schema {schema!r} != "
                         f"{deviceplane.BUNDLE_SCHEMA} (this doctor is too "
                         "old or the bundle too new)")
    out = {"manifest": manifest, "path": path}
    for name in manifest.get("files", []):
        stem = name[:-5] if name.endswith(".json") else name
        try:
            with open(os.path.join(path, name)) as f:
                out[stem] = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            out[stem] = {"error": f"unreadable: {e}"}
    return out


def _bundle_digest(b: dict) -> dict:
    """The comparable core of one bundle (summarize renders it, diff
    subtracts it)."""
    manifest = b.get("manifest", {})
    status = b.get("status", {}) or {}
    st = status.get("status", {}) or {}
    device = b.get("device", {}) or {}
    compile_ = b.get("compile", {}) or {}
    health = status.get("health")
    return {
        "path": b.get("path"),
        "reason": manifest.get("reason"),
        "ts_ms": manifest.get("ts_ms"),
        "error": manifest.get("error"),
        "backend": (device.get("backend") or {}).get("platform"),
        "device_kind": (device.get("backend") or {}).get("device_kind"),
        "valid_for_target": (device.get("backend") or {}).get(
            "valid_for_target"),
        "records_in": st.get("records_in", 0),
        "windows": st.get("windows_evaluated", 0),
        "throughput_rps": st.get("throughput_rps", 0.0),
        "slo_breaches": st.get("slo_breaches", 0),
        "healthy": None if health is None else health.get("healthy"),
        "unhealthy_checks": ([] if health is None else
                             sorted(n for n, c in health["checks"].items()
                                    if not c["ok"])),
        "compiles": compile_.get("total_compiles", 0),
        "post_warmup_compiles": compile_.get("post_warmup_compiles", 0),
        "warm": compile_.get("warm"),
        "mem_bytes_in_use": (device.get("memory") or {}).get("bytes_in_use"),
        "d2h_bytes": (device.get("transfer") or {}).get("d2h_bytes", 0),
        "dispatch_overlap_p50": (device.get("dispatch_overlap") or {}).get(
            "p50"),
        "events": len((b.get("events") or {}).get("events", [])),
        "notes": (b.get("flight") or {}).get("total", 0),
        "record_emit_p99_ms": ((b.get("latency") or {}).get("record_emit")
                               or {}).get("p99"),
        "budgeted_windows": ((b.get("latency") or {}).get("sum_check")
                             or {}).get("windows", 0),
    }


def _latency_table(latency: dict) -> List[str]:
    """The stage-budget table of a bundle's latency decomposition — the
    offline answer to "which stage blew the budget": per-stage count /
    p50 / p99 / total, chain stages first (their totals decompose
    record→emit), downstream sink stages after."""
    stages = latency.get("stages") or {}
    if not stages:
        return []
    chain = list(latency.get("chain_stages")
                 or ("buffer", "queue", "dispatch", "inflight", "merge",
                     "emit"))
    order = [s for s in chain if s in stages] + sorted(
        s for s in stages if s not in chain)
    total_ms = sum((stages[s].get("sum") or 0.0) for s in order
                   if s in chain)
    lines = ["stage        windows      p50 ms      p99 ms    total ms  "
             "share"]
    for s in order:
        h = stages[s]
        share = ((h.get("sum") or 0.0) / total_ms * 100) if total_ms \
            and s in chain else None
        lines.append(
            f"{s:<12} {h.get('count', 0):>7} {h.get('p50', 0.0):>11.3f} "
            f"{h.get('p99', 0.0):>11.3f} {h.get('sum', 0.0):>11.1f}  "
            + (f"{share:>4.0f}%" if share is not None else "    -"))
    re_h = latency.get("record_emit") or {}
    if re_h.get("count"):
        lines.append(
            f"{'record→emit':<12} {re_h['count']:>7} {re_h['p50']:>11.3f} "
            f"{re_h['p99']:>11.3f} {re_h.get('sum', 0.0):>11.1f}   100%")
    check = latency.get("sum_check") or {}
    if check.get("windows"):
        lines.append(f"sum check    {check['windows']} window(s), max "
                     f"residual {check.get('max_residual_ms', 0.0)} ms")
    bp = (latency.get("backpressure") or {}).get("series") or []
    stalls = sum(1 for bkt in bp if bkt.get("stall"))
    if bp:
        lines.append(f"backpressure {len(bp)} bucket(s), {stalls} "
                     "stalled")
    return lines


# --------------------------------------------------------------------- #
# commands


def summarize(path: str, as_json: bool = False,
              out=None) -> int:
    # resolve at call time: a def-time sys.stdout default would pin
    # whatever stream was installed at first import (pytest capture)
    out = sys.stdout if out is None else out
    b = load_bundle(path)
    d = _bundle_digest(b)
    if as_json:
        print(json.dumps(d, sort_keys=True), file=out)
        return 0
    print(f"bundle     {path}", file=out)
    print(f"reason     {d['reason']}" + (f" — {d['error']}" if d["error"]
                                         else ""), file=out)
    print(f"backend    {d['backend']} ({d['device_kind']}), "
          f"valid_for_target={d['valid_for_target']}", file=out)
    print(f"pipeline   {d['records_in']} records in, {d['windows']} windows, "
          f"{d['throughput_rps']:.0f} rec/s", file=out)
    if d["healthy"] is not None:
        bad = ",".join(d["unhealthy_checks"]) or "-"
        print(f"health     {'ok' if d['healthy'] else 'BREACH'} "
              f"(failing: {bad}; {d['slo_breaches']} breach transition(s))",
              file=out)
    print(f"compiles   {d['compiles']} total, "
          f"{d['post_warmup_compiles']} post-warmup (warm={d['warm']})",
          file=out)
    for e in (b.get("compile") or {}).get("entries", [])[:5]:
        sig = e["signatures"][-1]["signature"] if e["signatures"] else "?"
        print(f"  {e['compiles']:3d}x {e['name']}  last {sig[:80]}",
              file=out)
    if d["dispatch_overlap_p50"] is not None:
        print(f"overlap    p50 {d['dispatch_overlap_p50']:.2f}", file=out)
    for line in _latency_table(b.get("latency") or {}):
        print(f"latency    {line}", file=out)
    print(f"transfer   d2h {d['d2h_bytes']} B; device mem in use "
          f"{d['mem_bytes_in_use']}", file=out)
    notes = (b.get("flight") or {}).get("notes", [])[-5:]
    for nte in notes:
        extra = {k: v for k, v in nte.items() if k not in ("ts_ms", "kind")}
        print(f"note       {nte.get('kind')} {extra}", file=out)
    evs = (b.get("events") or {}).get("events", [])[-5:]
    for ev in evs:
        print(f"event      #{ev.get('seq')} {ev.get('kind')}", file=out)
    return 0


def diff(path_a: str, path_b: str, as_json: bool = False,
         out=None) -> int:
    out = sys.stdout if out is None else out
    a, b = load_bundle(path_a), load_bundle(path_b)
    da, db = _bundle_digest(a), _bundle_digest(b)
    rows = []
    for key in ("reason", "error", "backend", "device_kind", "healthy",
                "unhealthy_checks", "records_in", "windows",
                "throughput_rps", "slo_breaches", "compiles",
                "post_warmup_compiles", "d2h_bytes",
                "dispatch_overlap_p50", "mem_bytes_in_use",
                "record_emit_p99_ms", "budgeted_windows"):
        va, vb = da.get(key), db.get(key)
        rows.append({"field": key, "a": va, "b": vb, "equal": va == vb})
    doc = {"a": path_a, "b": path_b,
           "cross_backend": da["backend"] != db["backend"],
           "rows": rows}
    if as_json:
        print(json.dumps(doc, sort_keys=True), file=out)
        return 0
    print(f"A: {path_a}  ({da['reason']})", file=out)
    print(f"B: {path_b}  ({db['reason']})", file=out)
    if doc["cross_backend"]:
        print(f"WARNING: cross-backend diff ({da['backend']} vs "
              f"{db['backend']}) — throughput/latency deltas are not "
              "comparable (the bench_diff pairing rule)", file=out)
    for r in rows:
        mark = " " if r["equal"] else "*"
        print(f"{mark} {r['field']:<22} {r['a']!r:>24} | {r['b']!r}",
              file=out)
    return 0


def preflight(require_backend: str = "tpu", as_json: bool = False,
              out=None) -> int:
    """Backend/memory/compile-cache readiness check; exit non-zero when the
    chip the operator asked for is not what the process would run on."""
    out = sys.stdout if out is None else out
    import time as _time

    checks: List[dict] = []

    def check(name: str, ok: Optional[bool], detail) -> None:
        checks.append({"check": name, "ok": ok, "detail": detail})

    prov = None
    try:
        prov = deviceplane.backend_provenance(target=require_backend)
        check("backend", prov["platform"] == require_backend,
              f"platform={prov['platform']} device_kind="
              f"{prov['device_kind']} x{prov['device_count']} "
              f"(required: {require_backend})")
    except Exception as e:
        check("backend", False, f"backend probe failed: {e}")
    mem = deviceplane.memory_gauges()
    check("memory_stats", None if not mem["available"] else True,
          ("memory_stats available, "
           f"in_use={mem['bytes_in_use']}" if mem["available"]
           else "no memory_stats on this backend (normal on CPU)"))
    # compile probe: a tiny instrumented jit through the registry — proves
    # the XLA compile path AND that the sentinel would see it
    try:
        import jax.numpy as jnp

        reg = deviceplane.registry()
        before = reg.total_compiles
        t0 = _time.perf_counter()
        fn = deviceplane.instrumented_jit(lambda x: (x * 2 + 1).sum())
        float(fn(jnp.arange(8.0)))
        dt_ms = (_time.perf_counter() - t0) * 1e3
        check("compile_probe", reg.total_compiles == before + 1,
              f"1 compile in {dt_ms:.0f}ms, registry saw it "
              f"({reg.total_compiles - before} recorded)")
    except Exception as e:
        check("compile_probe", False, f"probe compile failed: {e}")
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
        check("compilation_cache", None if not cache_dir else True,
              (f"persistent compilation cache at {cache_dir}" if cache_dir
               else "no persistent compilation cache configured "
                    "(jax_compilation_cache_dir unset — every process "
                    "pays cold compiles)"))
    except Exception as e:
        check("compilation_cache", None, f"unreadable: {e}")
    # static invariants: the same pass the tier-1 gate runs — a dirty
    # tree fails preflight exactly like a CPU fallback would
    analysis_summary = None
    try:
        from spatialflink_tpu.analysis import run_analysis

        rep = run_analysis()
        rep_doc = rep.to_dict()
        by_rule = rep_doc["findings_by_rule"]
        analysis_summary = {
            "ok": rep.ok,
            "findings": len(rep_doc["findings"]),
            "findings_by_rule": by_rule,
            "allowlisted": len(rep_doc["allowlisted"])
            + len(rep_doc["pragma_allowlisted"]),
            "stale_allowlist_entries": len(
                rep_doc["stale_allowlist_entries"]),
            "stale_pragmas": len(rep_doc["stale_pragmas"]),
            "files": rep_doc["files"],
            "rules": rep_doc["rules"],
        }
        stale = analysis_summary["stale_allowlist_entries"] \
            + analysis_summary["stale_pragmas"]
        dirty = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items())
                          if n) or "all rules clean"
        check("static_analysis", rep.ok,
              f"per-rule findings: {dirty}; "
              f"{analysis_summary['allowlisted']} allowlisted,"
              f" {stale} stale suppression"
              f"{'' if stale == 1 else 's'} across "
              f"{analysis_summary['files']} file(s)"
              + ("" if rep.ok else
                 " — run `python -m spatialflink_tpu.analysis --check`"))
    except Exception as e:
        check("static_analysis", False, f"analysis pass failed: {e}")
    failed = [c for c in checks if c["ok"] is False]
    doc = {"ready": not failed, "require_backend": require_backend,
           "provenance": prov, "checks": checks,
           "analysis": analysis_summary}
    if as_json:
        print(json.dumps(doc, sort_keys=True), file=out)
    else:
        for c in checks:
            mark = {True: "ok  ", False: "FAIL", None: "note"}[c["ok"]]
            print(f"{mark} {c['check']:<18} {c['detail']}", file=out)
        print(("ready" if not failed else
               f"NOT READY ({', '.join(c['check'] for c in failed)})"),
              file=out)
    return 0 if not failed else 1


def tenants(path: str, as_json: bool = False, out=None) -> int:
    """The per-tenant cost table of one bundle's ``tenants.json`` —
    the post-mortem answer to "who was paying when it died": attributed
    kernel-ms with shares, bytes moved, records in/out, windows, and the
    SLO/shed/quota counters, plus the fairness summary and the worst
    per-dispatch attribution residual (the conservation check)."""
    out = sys.stdout if out is None else out
    b = load_bundle(path)
    ten = b.get("tenants") or {}
    rows = ten.get("tenants") or {}
    doc = {"path": path, "tenants": rows,
           "fairness": ten.get("fairness"),
           "default_tenant": ten.get("default_tenant"),
           "pending": ten.get("pending"),
           "max_residual_ms": ten.get("max_residual_ms")}
    if as_json:
        print(json.dumps(doc, sort_keys=True), file=out)
        return 0
    print(f"bundle     {path}", file=out)
    if not rows:
        print("tenants    (no tenant ledger in this bundle — no telemetry "
              "session at dump time)", file=out)
        return 0
    total_ms = sum(float(r.get("kernel_ms") or 0.0) for r in rows.values())
    print(f"{'tenant':<16} {'kernel ms':>10} {'share':>6} {'bytes':>12} "
          f"{'rec in':>9} {'rec out':>8} {'windows':>8} {'slo':>4} "
          f"{'shed':>5} {'quota':>6}", file=out)
    for t, r in sorted(rows.items(),
                       key=lambda kv: -float(kv[1].get("kernel_ms") or 0.0)):
        kms = float(r.get("kernel_ms") or 0.0)
        share = f"{kms / total_ms * 100:.0f}%" if total_ms else "-"
        print(f"{t:<16} {kms:>10.1f} {share:>6} "
              f"{int(r.get('bytes_moved') or 0):>12} "
              f"{int(r.get('records_in') or 0):>9} "
              f"{int(r.get('records_out') or 0):>8} "
              f"{int(r.get('windows') or 0):>8} "
              f"{int(r.get('slo_breaches') or 0):>4} "
              f"{int(r.get('shed') or 0):>5} "
              f"{int(r.get('quota_rejections') or 0):>6}", file=out)
    fair = ten.get("fairness") or {}
    if fair.get("top") is not None:
        print(f"fairness   top {fair.get('top')} "
              f"({(fair.get('top_share') or 0.0) * 100:.0f}%), max/min "
              f"share {(fair.get('max_share') or 0.0) * 100:.0f}%/"
              f"{(fair.get('min_share') or 0.0) * 100:.0f}%, "
              f"gini {fair.get('gini') or 0.0:.2f}", file=out)
    resid = ten.get("max_residual_ms")
    if resid is not None:
        print(f"residual   max attribution residual {float(resid):.6f} ms "
              "(per-dispatch conservation: attributed sums to measured)",
              file=out)
    return 0


def fleet(path: str, as_json: bool = False, out=None) -> int:
    """One table over a whole fleet directory: per worker, every
    incarnation's run summary (``runs.jsonl``), the newest post-mortem
    bundle's verdict when one exists, restart reasons from the fleet
    result, recompile events, and record→emit p99 — "who died, why, and
    did the respawn stay warm" in one read. With the observability plane
    on the read widens: the end-to-end record→merged-emit stage-budget
    table from ``fleet_latency.json`` and the merged timeline tail from
    ``fleet_events.jsonl`` (both optional — plane-off and pre-plane
    fleet dirs still render). Elastic fleets add the fence history
    (which incarnations were superseded, how many stale zombie rows the
    merge dropped), the rescale log, and the quarantine log."""
    from spatialflink_tpu.runtime import fleet as fleet_mod

    out = sys.stdout if out is None else out
    if not os.path.isdir(path):
        raise ValueError(f"{path}: not a fleet directory")
    result = fleet_mod.read_json(
        os.path.join(path, fleet_mod.RESULT_FILE)) or {}
    manifest_state = fleet_mod.read_json(
        os.path.join(path, fleet_mod.MANIFEST_FILE)) or {}
    fence_log = manifest_state.get("fence_log") or []
    rescale_log = manifest_state.get("rescale_log") or []
    quarantine_log = manifest_state.get("quarantine_log") or []
    fences = {int(k): int(v) for k, v in
              (manifest_state.get("fences") or {}).items()}
    fleet_lat = fleet_mod.read_json(
        os.path.join(path, fleet_mod.LATENCY_FILE))
    timeline_tail: List[dict] = []
    try:
        with open(os.path.join(path, fleet_mod.EVENTS_FILE)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    timeline_tail.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        timeline_tail = timeline_tail[-20:]
    except OSError:
        pass  # plane off / pre-plane fleet dir: no timeline to show
    worker_ids = sorted(
        int(name[len("worker"):]) for name in os.listdir(path)
        if name.startswith("worker")
        and name[len("worker"):].isdigit()
        and os.path.isdir(os.path.join(path, name)))
    if not worker_ids:
        raise ValueError(f"{path}: no worker directories (is this a "
                         "--fleet-dir?)")
    restart_reasons: dict = {}
    for r in result.get("restart_log", []):
        restart_reasons.setdefault(int(r.get("worker", -1)),
                                   []).append(r.get("reason"))
    rows = []
    for wid in worker_ids:
        wd = fleet_mod.worker_dir(path, wid)
        runs = fleet_mod.read_runs(wd)
        last = runs[-1] if runs else {}
        bundle_digest = None
        pm_dir = os.path.join(wd, "postmortem")
        if os.path.isdir(pm_dir):
            bundles = sorted(
                os.path.join(pm_dir, b) for b in os.listdir(pm_dir)
                if os.path.isdir(os.path.join(pm_dir, b)))
            for b in reversed(bundles):  # newest bundle that loads
                try:
                    bundle_digest = _bundle_digest(load_bundle(b))
                    break
                except ValueError:
                    continue
        # fence-aware read: apply the manifest's byte cutoffs so the
        # doctor's window counts match what the merge actually admitted,
        # and surface how many zombie rows were dropped per worker
        ob_stats: dict = {}
        cutoffs = {f: c["outbox"] for f, c in fleet_mod.fence_cutoffs_from(
            {"fence_log": fence_log}, wid).items()}
        windows = fleet_mod.read_outbox(
            os.path.join(wd, fleet_mod.OUTBOX_FILE),
            fence_cutoffs=cutoffs, stats=ob_stats)
        rows.append({
            "worker": wid,
            "incarnations": len(runs),
            "restarts": len(restart_reasons.get(wid, [])),
            "restart_reasons": restart_reasons.get(wid, []),
            "fence": fences.get(wid, 0),
            "stale_fence_rows": ob_stats.get("stale_fence_rows", 0),
            "fence_conflicts": ob_stats.get("fence_conflicts", 0),
            "windows": len(windows),
            "emitted": last.get("emitted"),
            "last_rc": last.get("rc"),
            "graceful": last.get("graceful"),
            "resumed": last.get("resumed"),
            "post_warmup_compiles": sum(
                int(r.get("post_warmup_compiles") or 0) for r in runs),
            "last_verdict": (None if bundle_digest is None
                             else bundle_digest.get("reason")),
            "bundle_healthy": (None if bundle_digest is None
                               else bundle_digest.get("healthy")),
            "record_emit_p99_ms": (
                last.get("record_emit_p99_ms")
                if last.get("record_emit_p99_ms") is not None
                else (bundle_digest or {}).get("record_emit_p99_ms")),
        })
    doc = {"path": path,
           "digest": result.get("digest"),
           "merged_windows": result.get("merged_windows"),
           "routed": result.get("routed"),
           "epochs": result.get("epochs"),
           "graceful": result.get("graceful"),
           "post_warmup_compiles": result.get("post_warmup_compiles"),
           "workers": rows,
           "fences": {str(k): v for k, v in sorted(fences.items())},
           "fence_log": fence_log,
           "rescale_log": rescale_log,
           "quarantine_log": quarantine_log,
           "stale_fence_rows": sum(r["stale_fence_rows"] for r in rows),
           "latency": fleet_lat,
           "timeline_tail": timeline_tail}
    if as_json:
        print(json.dumps(doc, sort_keys=True), file=out)
        return 0
    print(f"fleet      {path}", file=out)
    if result:
        digest = result.get("digest") or "?"
        print(f"result     {result.get('merged_windows')} merged windows "
              f"from {result.get('workers')} workers, "
              f"{result.get('routed')} routed, digest {digest[:16]}",
              file=out)
        print(f"compiles   {result.get('post_warmup_compiles')} "
              "post-warmup across all incarnations", file=out)
    else:
        print("result     (no fleet_result.json — run incomplete or "
              "killed)", file=out)
    hdr = (f"{'worker':>6} {'inc':>4} {'restarts':>8} {'fence':>5} "
           f"{'windows':>8} {'last rc':>7} {'compiles':>8} {'p99 ms':>8}"
           "  last verdict")
    print(hdr, file=out)
    for r in rows:
        p99 = r["record_emit_p99_ms"]
        verdict = r["last_verdict"] or (
            "graceful stop" if r.get("graceful") else "-")
        print(f"{r['worker']:>6} {r['incarnations']:>4} "
              f"{r['restarts']:>8} {r['fence']:>5} {r['windows']:>8} "
              f"{('-' if r['last_rc'] is None else r['last_rc']):>7} "
              f"{r['post_warmup_compiles']:>8} "
              f"{('-' if p99 is None else f'{p99:.1f}'):>8}  {verdict}",
              file=out)
        for reason in r["restart_reasons"]:
            print(f"{'':>6} restart: {reason}", file=out)
        if r["stale_fence_rows"] or r["fence_conflicts"]:
            print(f"{'':>6} fenced: {r['stale_fence_rows']} stale zombie "
                  f"row(s) dropped, {r['fence_conflicts']} cross-fence "
                  "conflict(s) resolved", file=out)
    for e in fence_log:
        print(f"fence      w{e.get('worker')} -> fence {e.get('fence')} "
              f"({e.get('reason')}; outbox cutoff "
              f"{e.get('outbox_bytes')}B, journal "
              f"{e.get('journal_bytes')}B)", file=out)
    for e in rescale_log:
        print(f"rescale    {e.get('n_from')} -> {e.get('n_to')} workers "
              f"at {e.get('at_records')} routed records "
              f"(epoch {e.get('epoch')})", file=out)
    for e in quarantine_log:
        extra = {k: v for k, v in e.items()
                 if k not in ("ts_ms", "worker", "action")}
        print(f"quarantine w{e.get('worker')} {e.get('action')}"
              + (f" {extra}" if extra else ""), file=out)
    if fleet_lat:
        # end-to-end record→merged-emit decomposition: the worker chain
        # plus spread/outbox-visible/merge/merged-emit — same renderer as
        # a bundle's table, so the two reads line up stage by stage
        for line in _latency_table(fleet_lat):
            print(f"e2e        {line}", file=out)
        skipped = fleet_lat.get("skipped_no_lat")
        if skipped:
            print(f"e2e        {skipped} merged window(s) without a "
                  "lineage sidecar (plane off for part of the run, or "
                  "budget rows evicted)", file=out)
        for wid, s in sorted((fleet_lat.get("workers") or {}).items()):
            dom = s.get("dominant_stage") or "-"
            p99 = s.get("record_emit_p99_ms")
            print(f"sample     w{wid} "
                  f"p99 {('-' if p99 is None else f'{p99:.1f}ms')} "
                  f"dom {dom} "
                  f"backlog {s.get('backlog_residency_ms') or 0:.0f}ms "
                  f"inc {s.get('incarnation')}", file=out)
    for ev in timeline_tail:
        who = (f"w{ev.get('worker')}" if ev.get("src") == "worker"
               else "sup")
        extra = {k: v for k, v in ev.items()
                 if k not in ("ts_ms", "mono_ms", "seq", "kind", "src",
                              "worker", "worker_seq")}
        print(f"timeline   #{ev.get('seq'):>4} {who:<4} {ev.get('kind')}"
              + (f" {extra}" if extra else ""), file=out)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # `doctor --preflight` and `doctor preflight` both work (the flag form
    # is what the flight-recorder banner and ISSUE spell)
    if "--preflight" in argv:
        argv[argv.index("--preflight")] = "preflight"
    ap = argparse.ArgumentParser(
        prog="python -m spatialflink_tpu.doctor",
        description="preflight the device plane; summarize/diff "
                    "post-mortem bundles")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document instead of text")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("preflight", help="backend/memory/compile readiness")
    p.add_argument("--require-backend", default="tpu",
                   choices=("cpu", "tpu", "gpu"),
                   help="platform the run must land on (default tpu: the "
                        "CPU-fallback condition exits non-zero)")
    s = sub.add_parser("summarize", help="digest one bundle")
    s.add_argument("bundle")
    d = sub.add_parser("diff", help="compare two bundles")
    d.add_argument("bundle_a")
    d.add_argument("bundle_b")
    tn = sub.add_parser("tenants", help="per-tenant cost table from one "
                                        "bundle: attributed kernel-ms "
                                        "shares, quota/shed counters, "
                                        "fairness, attribution residual")
    tn.add_argument("bundle")
    fl = sub.add_parser("fleet", help="one table over a --fleet-dir: "
                                      "who died, restarts, recompiles, "
                                      "per-worker p99, the end-to-end "
                                      "stage-budget table, and the fleet "
                                      "timeline tail")
    fl.add_argument("fleet_dir")
    args = ap.parse_args(argv)
    try:
        if args.cmd == "preflight":
            return preflight(args.require_backend, as_json=args.json)
        if args.cmd == "summarize":
            return summarize(args.bundle, as_json=args.json)
        if args.cmd == "fleet":
            return fleet(args.fleet_dir, as_json=args.json)
        if args.cmd == "tenants":
            return tenants(args.bundle, as_json=args.json)
        return diff(args.bundle_a, args.bundle_b, as_json=args.json)
    except ValueError as e:
        print(f"doctor: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
