"""Operator plumbing: query configuration, window/micro-batch drivers.

Reference parity:
- :class:`QueryType` — ``spatialOperators/QueryType.java:3-7`` (RealTime,
  WindowBased, CountBased; the reference declares CountBased and throws
  "Not yet support" everywhere except tAggregate — here sliding count
  windows are IMPLEMENTED for every single-stream windowed operator).
- :class:`QueryConfiguration` — ``spatialOperators/QueryConfiguration.java``
  plus the window/approximate fields the reference passes via ``Params``.
- Real-time mode: the reference uses tiny tumbling windows with
  fire-per-element triggers (``tJoin/TJoinQuery.java:216-268``). The TPU
  equivalent is micro-batching: arrivals are grouped into batches of at most
  ``realtime_batch_size`` records and evaluated in one kernel launch, giving
  per-arrival-group latency without per-tuple kernel dispatch.
"""

from __future__ import annotations

import enum
import sys
import time
from collections import deque
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point, PointBatch
from spatialflink_tpu.runtime import WindowAssembler, WindowSpec
from spatialflink_tpu.utils import IdInterner


class QueryType(enum.Enum):
    RealTime = "realtime"
    WindowBased = "window"
    # the reference DECLARES CountBased and throws "Not yet support" in
    # every operator except tAggregate (QueryType.java:6); here it is
    # implemented: sliding count windows (every `slide` arrivals, the last
    # `size` records) for every single-stream windowed operator — see
    # SpatialOperator._windows. tAggregate keeps its per-cell counting
    # (reference parity); two-stream joins and the apps with bespoke window
    # logic still reject.
    CountBased = "count"


@dataclass
class QueryConfiguration:
    query_type: QueryType = QueryType.WindowBased
    window_size_ms: int = 10_000
    slide_ms: int = 5_000
    allowed_lateness_ms: int = 0
    # approximate mode: range queries skip the CN distance check (reference
    # parity); kNN uses lax.approx_min_k, trading RECALL (< 1, neighbors may
    # drop) where the reference traded ranking accuracy — see _knn_strategy
    approximate: bool = False
    realtime_batch_size: int = 512
    k: int = 10  # kNN only
    # max windows in flight on device before the driver blocks on the oldest;
    # >=2 overlaps host batch assembly with device compute (SURVEY §7's
    # host/device-overlap requirement — JAX dispatch is async until read)
    pipeline_depth: int = 2
    # device-mesh width: when > 1, EVERY operator family's window batches
    # are sharded (contiguously — see parallel.mesh on why not cell-bucketed)
    # across a 1-D mesh on the stream dim and merged with XLA collectives
    # (parallel.ops) — the keyBy(gridID) data parallelism of SURVEY §2.5,
    # minus the reference's parallelism-1 windowAll merge.
    # Must be a power of two (batch capacities are power-of-two buckets).
    devices: Optional[int] = None
    # outer (DCN) axis width: hosts > 1 builds a 2-D (hosts x devices/hosts)
    # mesh — kNN merges become two-level ICI->DCN (k * hosts DCN traffic,
    # window-size independent), filters/joins shard over both axes. Must be
    # a power of two dividing ``devices``.
    hosts: Optional[int] = None
    # pane-incremental execution (the --panes driver switch): sliding-window
    # batches are sliced into non-overlapping slide-aligned PANES, the
    # device kernel runs once per sealed pane, and each window merges its
    # size/slide cached pane partials instead of re-evaluating the full
    # window — at overlap o the per-slide kernel work drops ~o-fold. OFF by
    # default; bypassed (full recompute, identical results) for tumbling
    # windows (overlap 1: nothing to share), non-pane-decomposable specs
    # (slide must divide size), realtime/count modes, and operators without
    # a mergeable partial (run_incremental, tKnn's sub-trajectory windows).
    # Composes with pipeline_depth (pane kernels dispatch async and merge at
    # readback) and with the device mesh (each pane batch shards like a
    # window batch would).
    panes: bool = False
    # device-resident pane state (the --pane-merge driver switch): pane
    # kernel partials stay in HBM across slides and each window's merge is
    # a DEVICE op (kNN gather+re-top-k mirroring the shard merge), with only
    # the sealed window's merged result read back — instead of resolving
    # each partial to host (a blocking sync per pane, a full tunnel RTT on
    # a remote TPU) and merging there. None = AUTO: device on accelerator
    # backends, host on CPU (measured: the per-window merge dispatch costs
    # more than the host dict-merge of k-sized partials there, and
    # steady-state readback bytes are ~equal because PR 3's memoized
    # partials already cross at most once). Families without a device merge
    # (filter-shaped partials, whose host union is a plain concat of masks
    # each read exactly once) and host-resident partials
    # (checkpoint-restored) fall back to the host merge — results identical
    # either way.
    pane_device_merge: Optional[bool] = None
    # elastic-degradation bound: at most this many mesh halvings may absorb
    # dispatch failures before the operator raises instead of retrying
    # narrower. None = halvings down to TWO devices; the final halving to 1
    # ALWAYS raises — a failure surviving every multi-device width is a
    # distributed-path bug (or total hardware loss), and silently running
    # single-device forever hides it (the tradeoff VERDICT r4 flagged).
    # Deliberate single-device operation is devices=1/None, not degradation.
    max_degradations: Optional[int] = None
    # coordinated-checkpointing hook (the --checkpoint-dir driver switch):
    # a runtime.checkpoint.CheckpointCoordinator the operator registers its
    # window/pane state with and barriers against between processing units.
    # None (default) = no checkpointing — every hot path checks once.
    checkpointer: Optional[Any] = field(default=None, repr=False,
                                        compare=False)
    # skew-adaptive refinement layer (the --adaptive-grid driver switch):
    # an index.AdaptiveGrid whose leaf-space GN∪CN masks gate window-batch
    # membership HOST-SIDE before the kernel dispatch (the pre-kernel
    # candidate prefilter). Records keep their base cells, device kernels
    # and masks are untouched, and the leaf masks are a sound
    # over-approximation for every layout — exact-mode results are
    # identical to the uniform grid; the win is the smaller padded batch
    # on skewed streams. None (default) = uniform grid only.
    adaptive_grid: Optional[Any] = field(default=None, repr=False,
                                         compare=False)
    # mesh shard placement (--shard-order): "arrival" keeps the default
    # contiguous sharding; "cell" applies parallel.mesh.cell_hash_order so
    # whole grid cells co-locate per shard (keyBy(gridID) parity), with the
    # inverse permutation restoring mask alignment at readback. Results
    # are identical either way; see BASELINE.md for the measured verdict.
    shard_order: str = "arrival"

    def window_spec(self) -> WindowSpec:
        if self.query_type is QueryType.CountBased:
            # count windows trigger on ARRIVAL ORDER (operators/base.py
            # _count_windows); every caller of this method builds
            # event-time windows (the bulk replay assemblers), which would
            # silently reinterpret the count values as milliseconds
            raise NotImplementedError(
                "count windows are record-path only; bulk replay builds "
                "event-time windows — run() implements CountBased")
        return WindowSpec.sliding(self.window_size_ms, self.slide_ms)


@dataclass
class Deferred:
    """A window's result that has been *dispatched* to the device but not
    read back. ``device_result`` holds live jax arrays (computation already
    enqueued — JAX dispatch is asynchronous); ``collect`` turns them into the
    final host-side record list, forcing the device→host transfer.

    Operators return this from eval_batch so the window driver can keep
    ``pipeline_depth`` windows in flight: while the device works on window i,
    the host assembles and dispatches window i+1 (the double-buffering the
    reference gets for free from Flink's pipelined operator chains).
    """

    device_result: Any
    collect: Callable[[Any], List]

    def finish(self) -> List:
        return self.collect(self.device_result)


class PaneCache:
    """Shared pane-partial cache bookkeeping: get-or-evaluate with the
    ``pane-cache-hits``/``pane-cache-misses`` registry counters and
    ascending-window eviction — ONE implementation for the generic driver
    (:meth:`SpatialOperator._pane_eval`), the trajectory pane loops, and
    the join pane-pair blocks (whose keys are (pane_a, pane_b) tuples:
    ``key_floor`` maps a key to the pane start its eviction hinges on).

    Eviction contract: windows arrive in ascending start order, so once
    window ``s`` has looked up its panes, no later window can need a key
    whose floor is below ``s + slide``. ``None`` is a legitimate cached
    value (an empty-after-filter pane), hence the ``in`` check."""

    __slots__ = ("slide", "cache", "hits", "misses", "key_floor")

    def __init__(self, slide_ms: int, key_floor=None):
        from spatialflink_tpu.utils.metrics import REGISTRY

        self.slide = slide_ms
        self.cache: dict = {}
        self.hits = REGISTRY.counter("pane-cache-hits")
        self.misses = REGISTRY.counter("pane-cache-misses")
        self.key_floor = key_floor if key_floor is not None else (lambda k: k)

    def get(self, key, evaluate):
        if key in self.cache:
            self.hits.inc()
            return self.cache[key]
        self.misses.inc()
        value = self.cache[key] = evaluate()
        return value

    def evict_before(self, window_start: int) -> None:
        limit = window_start + self.slide
        for dead in [k for k in self.cache if self.key_floor(k) < limit]:
            del self.cache[dead]

    def snapshot(self, encode_value) -> dict:
        """JSON-able cache contents for the checkpoint coordinator, so a
        resumed run does not redo pane kernels. ``encode_value`` is the
        pane-partial codec (``runtime.checkpoint.value_codec``); entries it
        cannot encode are SKIPPED with a counter — on resume they are plain
        cache misses and recompute, never wrong. :class:`PanePartial`
        wrappers are resolved first (forcing the device readback — snapshot
        time is off the critical path) and re-wrapped on restore."""
        import json as _json

        from spatialflink_tpu.utils.metrics import REGISTRY

        entries = []
        skipped = REGISTRY.counter("pane-cache-snapshot-skipped")
        for key, value in self.cache.items():
            wrapped = isinstance(value, PanePartial)
            try:
                enc = encode_value(value.resolve() if wrapped else value)
            except TypeError:
                skipped.inc()
                continue
            entries.append([_json.dumps(key), wrapped, enc])
        return {"entries": entries}

    def restore(self, state: dict, decode_value) -> None:
        """Inverse of :meth:`snapshot` (keys round-trip through JSON:
        list-form keys — the join path's pane pairs — become tuples)."""
        import json as _json

        for raw_key, wrapped, enc in state.get("entries", []):
            key = _json.loads(raw_key)
            if isinstance(key, list):
                key = tuple(key)
            value = decode_value(enc)
            self.cache[key] = PanePartial(value) if wrapped else value


def _device_nbytes(x) -> int:
    """Summed ``nbytes`` over the array leaves of a deferred device payload
    (tuples/NamedTuples/lists of jax or numpy arrays) — the readback-bytes
    accounting the device-vs-host pane-state bench reads."""
    total = 0
    stack = [x]
    while stack:
        v = stack.pop()
        if v is None:
            continue
        nb = getattr(v, "nbytes", None)
        if nb is not None:
            total += int(nb)
        elif isinstance(v, (tuple, list)):
            stack.extend(v)
    return total


class PanePartial:
    """One pane's cached kernel partial. Holds the raw evaluator output —
    a :class:`Deferred` (device work in flight / resident in device memory)
    or an already-final host value — and memoizes the readback so every
    window sharing the pane pays the device→host transfer at most once.
    Under the device pane merge the Deferred is typically NEVER resolved:
    the merge kernel consumes the resident arrays and only the merged
    window result crosses to host (``resolve`` still works — the
    checkpoint snapshot uses it, which is the readback-on-snapshot
    contract). ``stats_done`` marks pruning-counter scalars already
    consumed by a device merge, so they count once per pane."""

    __slots__ = ("value", "stats_done")

    def __init__(self, value):
        self.value = value
        self.stats_done = False

    def resolve(self):
        if isinstance(self.value, Deferred):
            from spatialflink_tpu.utils.metrics import REGISTRY

            REGISTRY.counter("pane-partial-readbacks").inc()
            REGISTRY.counter("pane-partial-readback-bytes").inc(
                _device_nbytes(self.value.device_result))
            self.value = self.value.finish()
        return self.value


@dataclass
class WindowResult:
    """One emitted result event: the records selected in [start, end).

    Count-window mode is the one exception to the half-open contract:
    there the bounds are the buffered records' min/max event timestamps,
    so ``window_end`` is INCLUSIVE (count windows have no wall-clock
    extent — see ``SpatialOperator._count_windows``). Consumers that key
    on spans must not mix the two conventions."""

    window_start: int
    window_end: int
    records: List = field(default_factory=list)
    extras: dict = field(default_factory=dict)

    def flat_records(self) -> List:
        """Records flattened across the multi-query axis: ``records`` is one
        list per query when ``extras['queries']`` is set (run_multi
        windows); every record sink flattens through here so the
        one-record-per-line/message contract cannot drift per sink."""
        if "queries" in self.extras:
            return [r for per_query in self.records for r in per_query]
        return self.records


def merge_window_records(family: str, parts: List[List], *, k=None,
                         tie_key=None) -> List:
    """The per-family GLOBAL merge seam: combine one window's record lists
    from disjoint partitions of the stream into the windowAll result a
    single unpartitioned run would have produced.

    This is the fleet's merge stage, and it deliberately reuses the pane/
    shard merge twins rather than inventing a third semantics: filter
    families (range/tRange/join — any family whose window is a SELECTION
    of its input records) merge by union, exactly the host pane
    concatenation, because a record routed to exactly one partition
    appears in exactly one part; kNN merges through
    :func:`~spatialflink_tpu.ops.knn.merge_topk_host` (concatenate, dedup
    by id keeping the min distance, re-top-k) — exact by the same covering
    argument as the pane/shard merges, since every partition emits its
    local top-k over a superset-free subset of the candidates.

    ``tie_key`` for kNN must reproduce the single-run tie order at the
    k-th place (see ``merge_topk_host``); partitioned runs that cannot
    share an interner pass a content key (e.g. ``str``) and accept that
    exact-distance ties may order differently from a single-process run.
    """
    if family == "knn":
        if not k:
            raise ValueError("kNN merge needs k (the fleet's per-window "
                             "re-top-k bound)")
        from spatialflink_tpu.ops.knn import merge_topk_host

        return merge_topk_host(parts, int(k), tie_key=tie_key)
    # selection families: disjoint-partition union (the host pane merge)
    out: List = []
    for part in parts:
        out.extend(part)
    return out


class _LeafMaskCache:
    """One query's leaf-space mask under the adaptive grid, invalidated by
    the grid's monotonic version stamp: a repartition bumps ``version`` and
    the next window rebuilds the mask (counted on
    ``prefilter-mask-recomputes``). The cache is per run()-closure, so
    every standing query owns exactly one."""

    __slots__ = ("grid", "build", "version", "mask")

    def __init__(self, grid, build):
        self.grid = grid
        self.build = build
        self.version = -1
        self.mask = None

    def get(self):
        if self.mask is None or self.version != self.grid.version:
            from spatialflink_tpu.utils.metrics import REGISTRY

            if self.mask is not None:
                REGISTRY.counter("prefilter-mask-recomputes").inc()
            self.mask = self.build()
            self.version = self.grid.version
        return self.mask


class SpatialOperator:
    """Shared driver: turns a record stream into point-window batches."""

    # CountBased: implemented for every single-stream windowed operator
    # (the _windows assembler branches on it); the reference declares the
    # mode and throws "Not yet support" everywhere except tAggregate's
    # per-cell count windows (``TAggregateQuery.java:381-494``), which keep
    # their keyed semantics. Two-stream joins (whose count trigger is
    # ambiguous across sides) and apps with bespoke window logic opt OUT.
    supports_count_windows = True

    #: query-family label scoping telemetry span names (``knn.kernel`` vs a
    #: flat namespace) so multi-family / --multi-query runs stay separable
    #: in one snapshot stream; subclasses set "range"/"knn"/"join"/"tknn"/…
    #: (None falls back to the class name)
    telemetry_label: Optional[str] = None

    #: window payloads may be columnar LazyRecords views over the batched
    #: decode's SoA chunks (device batches build straight from the slices;
    #: obj ids live in the STREAM's decode-interner space). Operators whose
    #: cross-window state or result resolution is keyed by the OPERATOR
    #: interner (the trajectory families' TrajStateStore, the apps) opt out
    #: — their windows materialize per-record objects as before (the decode
    #: itself stays chunk-vectorized either way).
    columnar_windows = True

    def __init__(self, conf: QueryConfiguration, grid: UniformGrid,
                 grid2: Optional[UniformGrid] = None):
        if (conf.query_type is QueryType.CountBased
                and not self.supports_count_windows):
            raise NotImplementedError("CountBased queries are not yet supported")
        if conf.devices and (conf.devices & (conf.devices - 1)):
            raise ValueError(
                f"conf.devices={conf.devices}: must be a power of two")
        if conf.hosts and conf.hosts > 1:
            if conf.hosts & (conf.hosts - 1):
                raise ValueError(
                    f"conf.hosts={conf.hosts}: must be a power of two")
            if not conf.devices or conf.devices % conf.hosts:
                raise ValueError(
                    f"conf.hosts={conf.hosts} must divide "
                    f"conf.devices={conf.devices}")
        # own copy: degraded mode mutates conf.devices, and a caller-shared
        # config must not silently degrade sibling operators (their cached
        # meshes would go stale against the mutated width)
        self.conf = dataclasses.replace(conf)
        self.grid = grid
        self.grid2 = grid2 or grid
        self.interner = IdInterner()
        self._mesh_obj = None
        self._degradations = 0  # elastic halvings absorbed so far

    @property
    def distributed(self) -> bool:
        return bool(self.conf.devices and self.conf.devices > 1)

    def _mesh(self):
        """Lazy device mesh for ``conf.devices`` (device access is deferred
        until the first window actually evaluates): 1-D, or 2-D
        (hosts x devices/hosts) when ``conf.hosts`` > 1 — the multi-host
        shape whose outer-axis collectives ride DCN."""
        if self._mesh_obj is None:
            from spatialflink_tpu.parallel.mesh import make_mesh, make_mesh_2d

            if self.conf.hosts and self.conf.hosts > 1:
                self._mesh_obj = make_mesh_2d(
                    self.conf.hosts, self.conf.devices // self.conf.hosts)
            else:
                self._mesh_obj = make_mesh(self.conf.devices)
        return self._mesh_obj

    def _shard(self, batch):
        """Place a window batch with its point dim sharded over the mesh
        (over BOTH axes of a 2-D mesh)."""
        from spatialflink_tpu.parallel.mesh import shard_batch

        mesh = self._mesh()
        return shard_batch(batch, mesh, axis=tuple(mesh.axis_names))

    def _degrade_mesh(self, err: BaseException) -> None:
        """Elastic degraded mode (SURVEY §7 phase 7): a device failure during
        a distributed window halves the mesh (keeping the power-of-two
        invariant — any smaller power of two still divides the bucketed
        batch capacities) and the window is re-dispatched. Host-side state
        (window assembler, trajectory maps, checkpoints) is untouched, so
        degradation is purely a dispatch concern. The reference inherits its
        equivalent (restart from checkpoint on a task-manager loss) from
        Flink; here a recompile at the new shard count is the only cost.

        BOUNDED: degradation stops at two devices (or after
        ``conf.max_degradations`` halvings) and then raises loudly — a
        failure that survives every multi-device width is a deterministic
        distributed-path bug or total hardware loss, and absorbing it as a
        permanent silent single-device run would hide it (the counter-only
        tradeoff VERDICT r4 asked to bound)."""
        from spatialflink_tpu.utils.metrics import REGISTRY

        new = max(1, (self.conf.devices or 1) // 2)
        limit = self.conf.max_degradations
        if new < 2 or (limit is not None and self._degradations >= limit):
            raise RuntimeError(
                f"distributed dispatch failed after {self._degradations} "
                f"elastic degradation(s) (mesh width {self.conf.devices}); "
                "refusing to silently fall back to a permanent single-device "
                "run — a failure at every multi-device width is almost "
                "certainly a distributed-path bug (check the "
                "'mesh-degradations' counter and the chained error); run "
                "with devices=1 to bypass the mesh deliberately"
            ) from err
        print(f"warning: device failure during distributed window "
              f"({type(err).__name__}: {str(err)[:200]}); degrading mesh "
              f"{self.conf.devices} -> {new}", file=sys.stderr)
        REGISTRY.counter("mesh-degradations").inc()
        from spatialflink_tpu.utils.telemetry import emit_event

        emit_event("mesh-degradation", error_type=type(err).__name__,
                   from_devices=self.conf.devices, to_devices=new)
        self._degradations += 1
        self.conf.devices = new
        # a 2-D mesh drops to flat 1-D: after losing devices the hosts x
        # chips factorization no longer reflects the hardware, and results
        # are mesh-layout invariant anyway
        self.conf.hosts = None
        self._mesh_obj = None

    def _eval_degradable(self, single_fn, dist_fn, batch=None):
        """Run ``dist_fn(mesh)`` — or ``dist_fn(mesh, sharded_batch)`` when
        ``batch`` is given — with elastic retry at halved mesh widths;
        ``single_fn()`` serves callers invoking this on a non-distributed
        operator (degradation itself never reaches it: the final halving
        to one device raises instead — see ``_degrade_mesh``).

        Catches ``RuntimeError`` (``XlaRuntimeError``'s base — device loss,
        transfer failures) raised at DISPATCH time. Two documented
        tradeoffs: (1) with async dispatch (``pipeline_depth >= 2``) a
        failure can instead surface at the deferred readback, after this
        frame has returned — there it PROPAGATES to the caller (the
        window's inputs are gone); recovery is the framework's normal
        resume story (checkpoint ``--resume`` for stateful operators,
        source replay for stateless windows). (2) availability is BOUNDED:
        transient failures absorb as halvings down to two devices (or
        ``conf.max_degradations``), but a failure surviving every
        multi-device width — the signature of a deterministic
        distributed-path bug rather than hardware — raises loudly from
        ``_degrade_mesh`` instead of becoming a permanent silent
        single-device run. Bugs in the shared per-shard closure still
        re-raise from the single-device path; non-RuntimeError exceptions
        (shape/type bugs) propagate unchanged."""

        while self.distributed:
            try:
                mesh = self._mesh()
                if batch is not None:
                    return dist_fn(mesh, self._shard(batch))
                return dist_fn(mesh)
            except RuntimeError as e:
                self._degrade_mesh(e)
        return single_fn()

    # ------------------------- checkpointing -------------------------- #

    @property
    def _ckpt(self):
        """The run's CheckpointCoordinator (None = checkpointing off)."""
        return self.conf.checkpointer

    def _record_codec(self):
        from spatialflink_tpu.runtime.checkpoint import record_codec

        return record_codec(self.grid)

    def _register_ckpt_windows(self, name: str, wa) -> None:
        """Register a WindowAssembler/PaneBuffer (both expose the same
        ``snapshot(encode)``/``restore(state, decode)`` shape) with the
        coordinator; loaded state restores the moment it registers."""
        coord = self._ckpt
        if coord is None:
            return
        enc, dec = self._record_codec()
        coord.register(name, lambda: ({}, wa.snapshot(enc)),
                       lambda _arrays, meta: wa.restore(meta, dec))

    def _register_ckpt_pane_cache(self, name: str, cache: "PaneCache"
                                  ) -> None:
        coord = self._ckpt
        if coord is None:
            return
        from spatialflink_tpu.runtime.checkpoint import value_codec

        enc, dec = value_codec(self.grid)
        coord.register(name, lambda: ({}, cache.snapshot(enc)),
                       lambda _arrays, meta: cache.restore(meta, dec))
        # pane partials of the trajectory families index by INTERNED object
        # id across windows — restored partials are only meaningful against
        # the interner that minted those ids, so it checkpoints alongside
        # every pane cache (harmless for families whose partials carry
        # resolved string ids)
        self._register_ckpt_interner()

    def _register_ckpt_interner(self) -> None:
        coord = self._ckpt
        if coord is None:
            return

        def restore(_arrays, meta):
            from spatialflink_tpu.utils import IdInterner

            self.interner = IdInterner.from_list(meta["ids"])

        coord.register("interner",
                       lambda: ({}, {"ids": self.interner.to_list()}),
                       restore)

    def _checkpoint_barrier(self) -> None:
        """Barrier for the NON-pipelined drive loops (no deferred windows in
        flight): call at the end of a loop body, after any ``yield`` — at
        that point the yielded result has been fully consumed downstream,
        so every snapshotted structure is consistent with the noted source
        positions."""
        coord = self._ckpt
        if coord is not None:
            coord.barrier()

    # ---------------------------------------------------------------- #

    # --------------------- adaptive-grid prefilter -------------------- #

    def _leaf_mask_cache(self, build) -> Optional[_LeafMaskCache]:
        """A version-stamped cache of one query's GN∪CN leaf mask, or None
        when the adaptive refinement layer is off (``conf.adaptive_grid``
        unset) — the single gate every prefiltering operator checks."""
        ag = self.conf.adaptive_grid
        return _LeafMaskCache(ag, build) if ag is not None else None

    @staticmethod
    def _record_arrays(records):
        """(x, y, ts, obj_id, cell) numpy arrays for a window's records —
        zero-copy from a columnar LazyRecords window, one materializing
        pass for plain record lists (obj_id is None there: the prefiltered
        range batches never read it)."""
        from spatialflink_tpu.streams.bulk import LazyRecords

        if isinstance(records, LazyRecords):
            flat = records._flat()
            if flat is not None:
                return flat[0], flat[1], flat[2], flat[3], flat[4]
        xs = np.array([r.x for r in records], np.float64)
        ys = np.array([r.y for r in records], np.float64)
        ts = np.array([r.timestamp for r in records], np.int64)
        cells = np.array([r.cell for r in records], np.int32)
        return xs, ys, ts, None, cells

    @staticmethod
    def _chunk_leaves(chunk, ag) -> np.ndarray:
        """Per-CHUNK leaf assignment, cached on the chunk and stamped with
        the grid version: sliding windows revisit each chunk size/slide
        times, so the two-stage assignment runs once per chunk per layout
        (exactly how base cells are assigned once per chunk in
        ``PointChunk.build``), not once per window membership."""
        cache = getattr(chunk, "_leaf_cache", None)
        if cache is not None and cache[0] == ag.version:
            return cache[1]
        leaf = ag.assign_leaf(chunk.parsed.x, chunk.parsed.y)
        chunk._leaf_cache = (ag.version, leaf)
        return leaf

    def _prefilter(self, records, mask_cache: Optional[_LeafMaskCache],
                   ts_base: int):
        """Pre-kernel candidate prefilter over the refined leaf space: keep
        exactly the records whose leaf is in the query's GN∪CN leaf set and
        build the (smaller) device batch from the kept rows. Returns
        ``(keep_idx, PointBatch)`` — ``batch`` None when NO leaf survives
        (the window skips its kernel dispatch entirely) — or None when the
        layer is off.

        Identity: the leaf masks over-approximate the kernel's own
        GN/CN-and-distance selection for EVERY layout (every selected
        record lies within ``radius``, hence in a leaf the mask keeps), so
        the filtered dispatch emits the same records — the counters
        ``prefilter-records``/``prefilter-kept`` are the candidate-set
        selectivity the skew bench reports, and the only behavior change.
        Approximate mode is the one documented exception: the prefilter
        removes candidates that are provably outside ``radius``, making
        the approximate result set TIGHTER than the uniform grid's (never
        looser).

        Cost shape: leaf ids come from the per-chunk cache (amortized over
        the window overlap), the mask test is one boolean gather per
        window, and the kept batch builds from O(kept) per-segment
        gathers — the overhead stays far under the kernel/batch work it
        eliminates."""
        if mask_cache is None:
            return None
        from spatialflink_tpu.streams.bulk import LazyRecords
        from spatialflink_tpu.utils.metrics import REGISTRY

        ag = self.conf.adaptive_grid
        mask = mask_cache.get()
        segs = (records._segs if isinstance(records, LazyRecords)
                else None)
        if segs is not None and all(isinstance(s, tuple) for s in segs):
            # columnar window: per-seg leaf gathers + O(kept) batch build
            total = 0
            keep_pos: List[np.ndarray] = []
            xs, ys, tss, oids, cells = [], [], [], [], []
            for (chunk, idx), off in zip(segs, records._offsets):
                leaf = self._chunk_leaves(chunk, ag)[idx]
                # one gather + one AND (invalid leaves read slot 0, gated)
                k = mask[np.where(leaf >= 0, leaf, 0)] & (leaf >= 0)
                total += int(idx.size)
                kp = np.nonzero(k)[0]
                if kp.size:
                    keep_pos.append(off + kp)
                    sel = idx[kp]
                    p = chunk.parsed
                    xs.append(p.x[sel])
                    ys.append(p.y[sel])
                    tss.append(p.ts[sel])
                    oids.append(p.obj_id[sel])
                    cells.append(chunk.cells[sel])
            REGISTRY.counter("prefilter-records").inc(total)
            if not keep_pos:
                REGISTRY.counter("prefilter-windows-skipped").inc()
                return np.empty(0, np.int64), None
            idx = np.concatenate(keep_pos)
            REGISTRY.counter("prefilter-kept").inc(int(idx.size))
            batch = PointBatch.from_arrays(
                np.concatenate(xs), np.concatenate(ys),
                obj_id=np.concatenate(oids), ts=np.concatenate(tss),
                ts_base=ts_base, cell=np.concatenate(cells))
            return idx, batch
        # generic fallback (plain record lists / mixed streams)
        x, y, ts, oid, cell = self._record_arrays(records)
        leaf = ag.assign_leaf(x, y)
        keep = np.zeros(leaf.shape, bool)
        v = leaf >= 0
        keep[v] = mask[leaf[v]]
        idx = np.nonzero(keep)[0]
        REGISTRY.counter("prefilter-records").inc(int(leaf.size))
        REGISTRY.counter("prefilter-kept").inc(int(idx.size))
        if idx.size == 0:
            REGISTRY.counter("prefilter-windows-skipped").inc()
            return idx, None
        batch = PointBatch.from_arrays(
            x[idx], y[idx],
            obj_id=None if oid is None else oid[idx],
            ts=ts[idx], ts_base=ts_base, cell=cell[idx])
        return idx, batch

    def _defer_mask_select_at(self, mask, records: List, keep_idx,
                              stats=None) -> Deferred:
        """:meth:`_defer_mask_select` for a PREFILTERED batch: kernel mask
        positions map back to original records through ``keep_idx``."""
        take = getattr(records, "take", None)

        def rows(m):
            sel = np.nonzero(np.asarray(m))[0]
            sel = sel[sel < keep_idx.size]
            orig = keep_idx[sel]
            if take is not None:
                return take(orig)
            return [records[int(i)] for i in orig]

        return self._defer_with_stats(mask, stats, rows)

    # ------------------------------------------------------------------ #

    def _point_batch(self, records, ts_base: int) -> PointBatch:
        from spatialflink_tpu.streams.bulk import LazyRecords

        if isinstance(records, LazyRecords):
            # batched record path: the window's device batch builds straight
            # from the decoded SoA slices (cells assigned once per chunk, obj
            # ids in the stream's decode-interner space — kNN resolution and
            # pane tie-breaking read through `records.interner`)
            return records.point_batch(self.grid, ts_base)
        return PointBatch.from_points(records, self.grid, self.interner, ts_base=ts_base)

    def _windows(self, stream: Iterable[Point]) -> Iterator[Tuple[int, int, List[Point]]]:
        if self.conf.query_type is QueryType.CountBased:
            yield from self._count_windows(stream)
            return
        wa = WindowAssembler(self.conf.window_spec(), self.conf.allowed_lateness_ms)
        self._register_ckpt_windows("windows", wa)
        if not self.columnar_windows:
            stream = iter(stream)  # flatten any chunked decode stream
        # chunk-vectorized assignment (WindowSpec.assign_bulk under the
        # hood): identical window tables, late drops, and emission timing to
        # the per-record add loop, minus its per-record assign/seal cost
        yield from wa.assemble(stream)

    # ------------------------- pane-incremental ----------------------- #

    def _panes_active(self) -> bool:
        """Pane-incremental mode applies: the ``--panes`` switch is on, the
        query runs event-time windows, and the spec is pane-decomposable
        (slide divides size; tumbling bypasses — overlap 1 shares
        nothing)."""
        return (self.conf.panes
                and self.conf.query_type is QueryType.WindowBased
                and self.conf.window_spec().pane_decomposable())

    def _pane_windows(self, stream: Iterable[Point]
                      ) -> Iterator[Tuple[int, int, List]]:
        """Pane-sliced window source: same window set/sealing as
        :meth:`_windows`, but each window's payload is its list of
        ``(pane_start, records)`` panes and every record is buffered ONCE
        (not ``size/slide`` times)."""
        from spatialflink_tpu.runtime.windows import PaneBuffer

        pb = PaneBuffer(self.conf.window_spec(),
                        self.conf.allowed_lateness_ms)
        self._register_ckpt_windows("panes", pb)
        if not self.columnar_windows:
            stream = iter(stream)  # flatten any chunked decode stream
        # chunk-aware: a batched decode stream (driver.decode_stream) hands
        # columnar chunks straight into the pane buffer; plain record
        # streams keep the per-record add loop
        yield from pb.assemble(stream)

    def _pane_eval(self, pane_partial, merge_partials, device_merge=None):
        """The partial-cache evaluator for pane-window payloads: the window
        kernel (``pane_partial(payload, pane_start)`` — the same eval_batch
        the full-window path uses) runs ONCE per sealed pane; windows merge
        their cached partials via ``merge_partials(parts)`` at readback.
        Cache hits/misses ride the ``pane-cache-hits``/``pane-cache-misses``
        registry counters and the merge is a ``pane-merge`` telemetry span,
        so snapshots show both the reuse rate and where the merge time
        goes. Eviction: windows arrive in ascending start order, so once
        window ``s`` dispatches, no later window can need a pane below
        ``s + slide``.

        ``device_merge(parts)`` (optional, gated by
        ``conf.pane_device_merge``) is the family's DEVICE merge: it
        consumes the parts' resident device arrays and returns a
        :class:`Deferred` whose readback is the merged window result —
        partials never individually cross to host. It returns None when
        ineligible (e.g. a checkpoint-restored host-resident partial in the
        window), which falls back to the host merge with identical
        results."""
        from spatialflink_tpu.utils import telemetry as _telemetry
        from spatialflink_tpu.utils.metrics import REGISTRY

        cache = PaneCache(self.conf.slide_ms)
        self._register_ckpt_pane_cache("pane-cache", cache)
        tel = _telemetry.active()
        label = self.telemetry_label or type(self).__name__
        book = tel.traces if tel is not None else None
        costs = tel.costs if tel is not None else None
        want_device = self.conf.pane_device_merge
        if want_device is None:  # auto: device placement off the CPU backend
            import jax

            want_device = jax.default_backend() != "cpu"
        use_device = device_merge is not None and want_device
        rb_bytes = REGISTRY.counter("pane-partial-readback-bytes")

        def eval_batch(panes, ts_base):
            h0, m0 = ((cache.hits.count, cache.misses.count)
                      if costs is not None else (0, 0))

            def seal_pane(p_start, payload):
                # a cache MISS is a pane sealing: the kernel runs once,
                # here — trace it against the window that triggered it
                if book is None:
                    return PanePartial(pane_partial(payload, p_start))
                t0 = time.time()
                part = PanePartial(pane_partial(payload, p_start))
                # payload is the pane's record list on the record path, an
                # (idx, batch) pair on the bulk path — count accordingly
                n = (len(payload[0]) if isinstance(payload, tuple)
                     else len(payload))
                book.note(label, ts_base, "pane-seal", t0, time.time(),
                          pane=int(p_start), records=int(n))
                return part

            parts = [
                cache.get(p_start, lambda: seal_pane(p_start, payload))
                for p_start, payload in panes
            ]
            cache.evict_before(ts_base)
            if costs is not None:
                costs.note_pane(label, cache.hits.count - h0,
                                cache.misses.count - m0)

            merged = device_merge(parts) if use_device else None
            if merged is not None:
                # device-resident path: the partials stay in HBM; only the
                # merged window result crosses, counted as the window's
                # readback
                def collect_dev(_):
                    nb = _device_nbytes(merged.device_result)
                    REGISTRY.counter("pane-merged-readbacks").inc()
                    REGISTRY.counter("pane-merged-readback-bytes").inc(nb)
                    if tel is not None:
                        with tel.span("pane-merge", query=label):
                            out = merged.finish()
                        if costs is not None:
                            costs.note_readback(label, nb)
                        return out
                    return merged.finish()

                return Deferred(None, collect_dev)

            def collect(_):
                b0 = rb_bytes.count
                if tel is not None:
                    with tel.span("pane-merge", query=label):
                        out = merge_partials([h.resolve() for h in parts])
                    if costs is not None:
                        costs.note_readback(label, rb_bytes.count - b0)
                    return out
                return merge_partials([h.resolve() for h in parts])

            return Deferred(None, collect)

        return eval_batch

    @staticmethod
    def _pane_concat(parts: List[List]) -> List:
        """Default merge for filter-shaped partials: panes are disjoint, so
        the window's selection is the concatenation (pane-time order)."""
        return [r for part in parts for r in part]

    @staticmethod
    def _pane_count(panes) -> int:
        """records-evaluated metric for a pane-window payload: the window's
        record count, like the full-window paths report."""
        return sum(len(rs) for _, rs in panes)

    def _count_windows(self, stream: Iterable[Point]
                       ) -> Iterator[Tuple[int, int, List[Point]]]:
        """Sliding COUNT windows over the whole stream: every ``slide``
        arrivals, evaluate the last ``size`` records (Flink
        ``countWindow(size, slide)`` semantics on an un-keyed stream). In
        count mode ``window_size_ms``/``slide_ms`` are COUNTS — the
        reference hands the same config values to ``countWindow`` un-scaled
        (the convention tAggregate's per-cell count windows already use).
        Window bounds are the buffered records' min/max event times (count
        windows have no wall-clock extent) — note ``window_end`` is
        therefore INCLUSIVE here, unlike the half-open time windows; see
        :class:`WindowResult`."""
        from collections import deque

        size = max(1, int(self.conf.window_size_ms))
        slide = max(1, int(self.conf.slide_ms))
        buf: deque = deque(maxlen=size)
        n = 0
        for rec in stream:
            buf.append(rec)
            n += 1
            if n % slide == 0:
                records = list(buf)
                yield (min(r.timestamp for r in records),
                       max(r.timestamp for r in records), records)

    def _micro_batches(self, stream: Iterable[Point]) -> Iterator[List[Point]]:
        buf: List[Point] = []
        for rec in stream:
            buf.append(rec)
            if len(buf) >= self.conf.realtime_batch_size:
                yield buf
                buf = []
        if buf:
            yield buf

    def _geom_batch(self, records: List, ts_base: int):
        from spatialflink_tpu.models.batches import EdgeGeomBatch

        pad = None
        if self.distributed:
            # shard-ready capacity: the geometry dim must divide across the
            # mesh (point batches already bucket at >= 256)
            from spatialflink_tpu.utils.padding import bucket_size

            pad = bucket_size(len(records), max(8, self.conf.devices))
        return EdgeGeomBatch.from_objects(records, self.grid, self.interner,
                                          ts_base=ts_base, pad=pad)

    def _bulk_mask_eval(self, mask_stats_fn):
        """eval_batch for bulk window payloads ((idx, batch)): one shared
        mask->original-record-index selection for every stream-filter
        operator's run_bulk (point and geometry alike)."""
        import numpy as np

        def eval_batch(payload, ts_base):
            idx, batch = payload
            mask, gn_c, evals = self._filter_stream(batch, mask_stats_fn)
            return self._defer_with_stats(
                mask, (gn_c, evals),
                lambda m: idx[np.asarray(m)[: len(idx)]].tolist())

        return eval_batch

    def _maybe_cell_order(self, batch):
        """``--shard-order cell``: pre-permute the batch so whole grid
        cells co-locate per shard (``parallel.mesh.cell_hash_order`` —
        keyBy(gridID) placement parity) and return the inverse permutation
        that restores per-record mask alignment at readback. Returns
        ``(batch, None)`` untouched in arrival order (the default), on
        single-device runs, and for batches without a 1-D cell column."""
        cell = getattr(batch, "cell", None)
        if (not self.distributed or self.conf.shard_order != "cell"
                or cell is None or getattr(cell, "ndim", 0) != 1):
            return batch, None
        from spatialflink_tpu.parallel.mesh import cell_hash_order

        perm = cell_hash_order(np.asarray(cell), self.conf.devices)
        inv = np.empty_like(perm)
        inv[perm] = np.arange(perm.size)
        batch = type(batch)(*(np.asarray(a)[perm] for a in batch))
        return batch, inv

    def _filter_stream(self, batch, mask_stats_fn):
        """(mask, gn_bypassed, dist_evals) for a stream batch: the
        single-device path calls ``mask_stats_fn(batch)`` directly; with
        ``conf.devices`` the batch is sharded and the SAME closure runs per
        shard with psum-merged stats (parallel.ops.distributed_stream_filter)
        — the mesh dispatch every reference pipeline gets from
        ``env.setParallelism(30)`` (``StreamingJob.java:221``). Under
        ``--shard-order cell`` the batch is cell-bucketed before sharding
        and the mask is un-permuted on device at the end."""
        from spatialflink_tpu.parallel.ops import distributed_stream_filter

        batch, inv = self._maybe_cell_order(batch)
        out = self._stream_dispatch(
            batch, mask_stats_fn,
            lambda mesh, sb: distributed_stream_filter(
                mesh, sb, mask_stats_fn))
        if inv is None:
            return out
        import jax.numpy as jnp

        mask, gn_c, evals = out
        return jnp.asarray(mask)[inv], gn_c, evals

    @staticmethod
    def _record_pruning_stats(gn_bypassed, dist_evals) -> None:
        """Pruning-effectiveness counters (the reference's "Distance
        Computation Count", ``spatialObjects/Point.java:220-235``, plus its
        complement): read device scalars and bump the registry."""
        from spatialflink_tpu.utils.metrics import REGISTRY

        REGISTRY.counter("gn-bypassed").inc(int(gn_bypassed))
        REGISTRY.counter("distance-computations").inc(int(dist_evals))

    def _defer_with_stats(self, dev, stats, rows) -> Deferred:
        """Single owner of the stats-payload protocol: ``stats`` is None or a
        (gn_bypassed, dist_evals) device-scalar pair; it rides the Deferred
        payload (no extra host sync — same readback as the main result) and
        bumps the pruning counters at collect time. ``rows(main_result)``
        turns the non-stats part into host rows."""
        def collect(payload):
            if stats is not None:
                main, gn, evals = payload
                self._record_pruning_stats(gn, evals)
            else:
                main = payload
            return rows(main)
        return Deferred((dev, *stats) if stats is not None else dev, collect)

    def _defer_mask_select(self, mask, records: List, stats=None) -> Deferred:
        """Deferred selection of ``records`` by a device boolean mask
        (columnar windows gather their selection in one vectorized
        ``LazyRecords.take``)."""
        take = getattr(records, "take", None)

        def rows(m):
            idx = np.nonzero(np.asarray(m))[0]
            idx = idx[idx < len(records)]
            if take is not None:
                return take(idx)
            return [records[i] for i in idx]
        return self._defer_with_stats(mask, stats, rows)

    def _defer_knn(self, res, interner=None, dist_evals=None) -> Deferred:
        """Deferred (objID, distance) list from a device KnnResult; ids
        resolve through ``interner`` (default: the operator's own — bulk
        paths pass the parse-time interner). ``dist_evals`` (device scalar)
        feeds the distance-computation counter — kNN has no GN bypass
        (``knn/PointPointKNNQuery.java:152-183`` computes a distance for
        every candidate-cell point)."""
        interner = interner if interner is not None else self.interner

        def rows(r):
            valid = np.asarray(r.valid)
            oids = np.asarray(r.obj_id)[valid]
            dists = np.asarray(r.dist)[valid]
            return [(interner.lookup(int(o)), float(d))
                    for o, d in zip(oids, dists)]
        stats = None if dist_evals is None else (0, dist_evals)
        return self._defer_with_stats(res, stats, rows)

    @staticmethod
    def _query_point_arrays(query_points):
        """(qx, qy, qc) device-ready arrays from a query-point batch."""
        qx = np.asarray([q.x for q in query_points], np.float32)
        qy = np.asarray([q.y for q in query_points], np.float32)
        qc = np.asarray([q.cell for q in query_points], np.int32)
        return qx, qy, qc

    def _defer_knn_multi(self, res, dist_evals, interner=None) -> Deferred:
        """Deferred per-query (objID, distance) lists from a (Q, k)
        KnnResult; ``dist_evals`` (device scalar, summed over the Q
        queries) feeds the distance-computation counter like every other
        kNN path. Bulk paths pass the parse-time ``interner``."""
        interner = interner if interner is not None else self.interner

        def rows(r):
            valid = np.asarray(r.valid)
            oids = np.asarray(r.obj_id)
            dists = np.asarray(r.dist)
            return [
                [(interner.lookup(int(o)), float(d))
                 for o, d in zip(oids[q][valid[q]], dists[q][valid[q]])]
                for q in range(valid.shape[0])
            ]

        return self._defer_with_stats(res, (0, dist_evals), rows)

    def _stream_dispatch(self, batch, local_fn, dist_entry):
        """SINGLE owner of the whole-batch-vs-mesh dispatch shape shared by
        every stream evaluation (filter/kNN, single- and multi-query):
        ``local_fn(batch)`` runs the single-device kernels; on a mesh,
        ``dist_entry(mesh, sharded_batch)`` runs the distributed twin with
        elastic degraded retry. One place to change the contract."""
        if self.distributed:
            return self._eval_degradable(
                lambda: local_fn(batch), dist_entry, batch)
        return local_fn(batch)

    def _multi_filter_stream(self, batch, multi_mask_stats):
        """(masks (Q, N), gn (Q,), evals (Q,)) for one batch — the same
        closure whole-batch or per shard with psum-merged per-query counters
        (parallel.ops.distributed_stream_filter_multi). ``--shard-order
        cell`` permutes/un-permutes around the dispatch like
        :meth:`_filter_stream` (the mask's record axis is the last)."""
        from spatialflink_tpu.parallel.ops import (
            distributed_stream_filter_multi,
        )

        batch, inv = self._maybe_cell_order(batch)
        out = self._stream_dispatch(
            batch, multi_mask_stats,
            lambda mesh, sb: distributed_stream_filter_multi(
                mesh, sb, multi_mask_stats))
        if inv is None:
            return out
        import jax.numpy as jnp

        masks, gn_c, evals = out
        return jnp.asarray(masks)[:, inv], gn_c, evals

    def _knn_multi_result(self, batch, local_fn, k: int):
        """(KnnResult (Q, k), evals (Q,)) for one batch — whole-batch, or
        per-shard partials merged per query
        (parallel.ops.distributed_stream_knn_multi)."""
        from spatialflink_tpu.parallel.ops import distributed_stream_knn_multi

        return self._stream_dispatch(
            batch, local_fn,
            lambda mesh, sb: distributed_stream_knn_multi(
                mesh, sb, local_fn, k=k))

    @staticmethod
    def _pane_concat_multi(n_queries: int):
        """Per-query concat merge for multi-query filter partials (each
        partial is a list of Q per-query lists)."""
        def merge(parts):
            return [[r for part in parts for r in part[q]]
                    for q in range(n_queries)]
        return merge

    def _run_multi_filter(self, stream: Iterable, n_queries: int,
                          multi_mask_stats, batch_builder,
                          leaf_mask_builder=None
                          ) -> Iterator["WindowResult"]:
        """Shared run_multi driver for FILTER-shaped operators (range):
        ``multi_mask_stats(batch) -> (masks (Q, N), gn_c (Q,), evals (Q,))``;
        records become Q per-query record lists, pruning counters aggregate
        across the query batch. With ``conf.devices`` the batch is sharded
        and the same closure runs per shard.

        ``leaf_mask_builder`` (adaptive grid only) builds the UNION of the
        Q queries' GN∪CN leaf masks: a record outside every query's
        candidate set cannot appear in any per-query result, so the
        prefilter shrinks the Q×N kernel to Q×kept — on a skewed stream
        this is where the adaptive win is largest, because the whole
        standing-query fleet shares one batch residency."""
        import jax.numpy as jnp

        mask_cache = (self._leaf_mask_cache(leaf_mask_builder)
                      if leaf_mask_builder is not None else None)
        empty = [[] for _ in range(n_queries)]

        def eval_batch(records, ts_base):
            if not records:
                return [list(e) for e in empty]
            pre = self._prefilter(records, mask_cache, ts_base)
            if pre is not None:
                keep, batch = pre
                if batch is None:
                    return [list(e) for e in empty]
            else:
                keep, batch = None, batch_builder(records, ts_base)
            masks, gn_c, evals = self._multi_filter_stream(
                batch, multi_mask_stats)
            take = getattr(records, "take", None)
            limit = keep.size if keep is not None else len(records)

            def rows(m):
                m = np.asarray(m)  # ONE (Q, N) device->host transfer
                out = []
                for q in range(n_queries):
                    idx = np.nonzero(m[q])[0]
                    idx = idx[idx < limit]
                    if keep is not None:
                        idx = keep[idx]
                    out.append(take(idx) if take is not None
                               else [records[int(i)] for i in idx])
                return out

            return self._defer_with_stats(
                masks, (jnp.sum(gn_c), jnp.sum(evals)), rows)

        for result in self._multi_results(
                stream, eval_batch,
                pane_merge=self._pane_concat_multi(n_queries)):
            result.extras["queries"] = n_queries
            yield result

    def _run_multi_filter_bulk(self, batched, n_queries: int,
                               multi_mask_stats
                               ) -> Iterator["WindowResult"]:
        """Bulk twin of :meth:`_run_multi_filter`: ``batched`` yields
        (start, end, (idx, batch)) window payloads; records become Q
        per-query ORIGINAL-RECORD-INDEX lists from one (Q, N) mask dispatch
        per window."""
        import jax.numpy as jnp

        def eval_batch(payload, ts_base):
            idx, batch = payload
            masks, gn_c, evals = self._multi_filter_stream(
                batch, multi_mask_stats)

            def rows(m):
                m = np.asarray(m)  # ONE (Q, N) device->host transfer
                return [idx[m[q][: len(idx)]].tolist()
                        for q in range(n_queries)]

            return self._defer_with_stats(
                masks, (jnp.sum(gn_c), jnp.sum(evals)), rows)

        for result in self._drive_batched(batched, eval_batch,
                                          count=lambda p: len(p[0])):
            result.extras["queries"] = n_queries
            yield result

    def _run_multi_knn_bulk(self, batched, n_queries: int, local, k: int,
                            interner) -> Iterator["WindowResult"]:
        """Bulk twin of the kNN multi loops: per-window (Q, k) results with
        ids resolved through the parse-time ``interner``."""
        import jax.numpy as jnp

        def eval_batch(payload, ts_base):
            _idx, batch = payload
            res, evals = self._knn_multi_result(batch, local, k)
            return self._defer_knn_multi(res, jnp.sum(evals),
                                         interner=interner)

        for result in self._drive_batched(batched, eval_batch,
                                          count=lambda p: len(p[0])):
            result.extras["k"] = k
            result.extras["queries"] = n_queries
            yield result

    def _run_dynamic_filter(self, stream: Iterable, registry, radius: float,
                            multi_mask_builder, batch_builder,
                            leaf_union_builder=None
                            ) -> Iterator["WindowResult"]:
        """Dynamic standing-query driver for FILTER-shaped operators
        (range): the Q-axis fleet comes from a live
        :class:`~spatialflink_tpu.runtime.queryplane.QueryRegistry`
        instead of a frozen query list. Per window:

        1. ``registry.apply()`` lands any staged admissions/updates/
           retirements (and drains the control topic) — windows are the
           fleet-change granularity, so a window is never evaluated
           against a half-applied fleet and checkpoint barriers (also
           between windows) always snapshot a consistent one;
        2. on a ``fleet_version`` bump the padded query arrays, the gated
           multi-mask closure, and the union leaf-mask cache are rebuilt
           (the same invalidation contract grid-version bumps drive);
           within a size bucket the rebuild REPADS to identical shapes,
           so the jitted kernels are cache hits — zero XLA recompiles;
        3. the (B, N) kernel masks and per-query pruning counters are
           ANDed/scaled with the (B,) valid-slot gate, forcing padded
           slots empty, and only the LIVE slots demultiplex into the
           result — each window carries ``extras['query_ids']`` naming
           its fleet at dispatch time.

        Pane mode deliberately does not engage here: pane partials are
        fleet-shaped, and reusing a partial across a fleet change would
        serve stale queries — full-window evaluation keeps admissions
        exact."""
        import jax.numpy as jnp

        from spatialflink_tpu.utils import telemetry as _telemetry

        label = self.telemetry_label or type(self).__name__
        state: dict = {"v": -1, "entries": [], "live": 0, "fn": None,
                       "mask_cache": None}

        def ensure() -> None:
            if state["v"] == registry.fleet_version:
                return
            entries, qpts, valid = registry.padded_fleet(self.grid)
            fn = mask_cache = None
            if entries:
                base_fn = multi_mask_builder(qpts, radius)
                jvalid = jnp.asarray(valid)

                def fn(b, _base=base_fn, _v=jvalid):
                    masks, gn_c, evals = _base(b)
                    # padded slots forced empty: masks AND the valid gate,
                    # pruning counters scaled by it (a pad slot must not
                    # inflate gn-bypassed/distance-computations)
                    return masks & _v[:, None], gn_c * _v, evals * _v

                if leaf_union_builder is not None:
                    live_pts = qpts[:len(entries)]
                    mask_cache = self._leaf_mask_cache(
                        lambda: leaf_union_builder(live_pts))
            state.update(v=registry.fleet_version, entries=entries,
                         live=len(entries), fn=fn, mask_cache=mask_cache)

        window_ids: dict = {}

        def eval_batch(records, ts_base):
            registry.apply()
            ensure()
            live = state["live"]
            window_ids[ts_base] = [e.id for e in state["entries"]]
            if not live:
                return []
            if not records:
                return [[] for _ in range(live)]
            keep = None
            pre = self._prefilter(records, state["mask_cache"], ts_base)
            if pre is not None:
                keep, batch = pre
                if batch is None:
                    return [[] for _ in range(live)]
            else:
                batch = batch_builder(records, ts_base)
            masks, gn_c, evals = self._multi_filter_stream(batch, state["fn"])
            take = getattr(records, "take", None)
            limit = keep.size if keep is not None else len(records)
            tel = _telemetry.active()
            acct = tel.tenants if tel is not None else None
            # (id, tenant) per live slot, captured NOW: a later apply()
            # may repad before the deferred demux runs
            slots = ([(e.id, e.spec.tenant) for e in state["entries"]]
                     if acct is not None else None)

            def rows(m):
                m = np.asarray(m)  # ONE (B, N) device->host transfer
                if acct is not None:
                    # resolve the parked dispatch span across the live
                    # slots proportional to mask-true candidate work —
                    # padded slots (rows >= live) and padded record
                    # columns (>= limit) never weigh in; host-side sums
                    # on the already-transferred masks, no device ops
                    weights = m[:live, :limit].sum(axis=1)
                    acct.resolve(label, ts_base, [
                        (qid, tenant, int(c))
                        for (qid, tenant), c in zip(slots, weights)])
                out = []
                for q in range(live):
                    idx = np.nonzero(m[q])[0]
                    idx = idx[idx < limit]
                    if keep is not None:
                        idx = keep[idx]
                    out.append(take(idx) if take is not None
                               else [records[int(i)] for i in idx])
                return out

            return self._defer_with_stats(
                masks, (jnp.sum(gn_c), jnp.sum(evals)), rows)

        for result in self._drive(stream, eval_batch):
            ids = window_ids.pop(result.window_start, [])
            result.extras["query_ids"] = ids
            result.extras["queries"] = len(ids)
            yield result

    def _multi_results(self, stream: Iterable, eval_batch, *, pane_merge=None,
                       pane_device_merge=None) -> Iterator["WindowResult"]:
        """_drive for multi-query evaluators, whose per-window result is a
        list of Q per-query lists — always truthy, so _drive_batched's
        realtime no-empty-emission gate cannot see an all-empty micro-batch;
        re-apply it on the per-query contents (the reference's
        fire-per-element trigger never emits empties)."""
        realtime = self.conf.query_type is QueryType.RealTime
        for result in self._drive(stream, eval_batch, pane_merge=pane_merge,
                                  pane_device_merge=pane_device_merge):
            if realtime and not any(result.records):
                continue
            yield result

    def _knn_strategy(self) -> str:
        """Top-k selection strategy: approximate mode rides the TPU
        partial-reduce fast path (``lax.approx_min_k``), exact mode
        auto-selects.

        Documented deviation from the reference: its approximate kNN only
        substitutes cheaper bbox distances and still runs an *exact* top-k
        (``knn/PointPolygonKNNQuery.java:124-139``), so every true neighbor
        appears, just possibly mis-ranked. Here approximate mode trades
        *recall* instead (``approx_min_k`` recall < 1 — some true neighbors
        may be dropped entirely) because on TPU the distance computation is
        effectively free next to the selection; the selection itself is the
        cost worth approximating. Set ``approximate=False`` (default) for
        exact results.
        """
        return "approx" if self.conf.approximate else "auto"

    def _drive_bulk(self, parsed, eval_batch, *, pad: Optional[int] = None,
                    pane_merge=None,
                    pane_device_merge=None) -> Iterator["WindowResult"]:
        """Bulk-replay driver: vectorized window batches
        (``streams.bulk.bulk_window_batches``) through the pipelined
        evaluator. eval_batch((idx, PointBatch), ts_base) as in _drive.
        With ``pane_merge`` and pane mode active, per-pane batches are built
        ONCE (``bulk_pane_window_batches``), the same eval_batch runs once
        per pane, and windows merge cached partials."""
        from spatialflink_tpu.streams.bulk import (bulk_pane_window_batches,
                                                   bulk_window_batches)

        if pane_merge is not None and self._panes_active():
            pane_windows = bulk_pane_window_batches(
                parsed, self.conf.window_spec(), self.grid, pad=pad)
            return self._drive_batched(
                pane_windows,
                self._pane_eval(eval_batch, pane_merge,
                                device_merge=pane_device_merge),
                count=lambda panes: sum(len(p[1][0]) for p in panes))
        batched = (
            (start, end, (idx, batch))
            for start, end, idx, batch in bulk_window_batches(
                parsed, self.conf.window_spec(), self.grid, pad=pad)
        )
        return self._drive_batched(batched, eval_batch,
                                   count=lambda p: len(p[0]))

    def _drive(self, stream: Iterable, eval_batch, *, pane_merge=None,
               pane_device_merge=None) -> Iterator["WindowResult"]:
        """Shared window/realtime driver.

        eval_batch(records, ts_base) returns either the final record list or
        a :class:`Deferred`; deferred results are pipelined — up to
        ``conf.pipeline_depth`` windows stay in flight on device while the
        host assembles the next batch — and emitted in window order.

        ``pane_merge(parts) -> records`` opts the operator into the
        pane-incremental mode (``conf.panes``): eval_batch then runs once
        per sealed PANE and each window's result is the merge of its cached
        pane partials. None = family has no mergeable partial; pane mode
        silently falls back to full-window evaluation (identical results).
        """
        realtime = self.conf.query_type is QueryType.RealTime
        if realtime:
            # realtime as a degenerate case of the batched path: tumbling
            # COUNT micro-windows cut by the vectorized MicroBatcher (SoA
            # slices straight off the decode chunks), driven through the
            # same pipelined loop as windowed queries — so realtime
            # inherits the checkpoint barrier, the latency plane, and the
            # chunk governor. Batch boundaries are count-strict in arrival
            # order, so results are identical to the old scalar
            # ``_micro_batches`` path (kept as the trajectory-family
            # helper and the identity oracle in tests/test_control.py).
            from spatialflink_tpu.runtime.windows import MicroBatcher

            mb = MicroBatcher(max(1, self.conf.realtime_batch_size))
            # the open micro-batch checkpoints like a window buffer:
            # records noted past the source position but not yet fired
            # restore from the manifest instead of being lost (the old
            # path relied on decode-chunk/batch-size alignment, which the
            # governor deliberately breaks)
            self._register_ckpt_windows("realtime-batcher", mb)
            if not self.columnar_windows:
                stream = iter(stream)  # flatten any chunked decode stream
            batched = mb.batches(stream)
        elif pane_merge is not None and self._panes_active():
            return self._drive_batched(
                self._pane_windows(stream),
                self._pane_eval(eval_batch, pane_merge,
                                device_merge=pane_device_merge),
                count=self._pane_count)
        else:
            batched = self._windows(stream)
        return self._drive_batched(batched, eval_batch, realtime=realtime)

    def _drive_batched(self, batched: Iterable, eval_batch, *,
                       realtime: bool = False, count=len
                       ) -> Iterator["WindowResult"]:
        """Pipelined evaluation over pre-assembled (start, end, payload)
        triples (record lists from _drive, or index/batch payloads from the
        bulk path). ``count(payload)`` feeds the records-evaluated metric."""
        from spatialflink_tpu.utils import telemetry as _telemetry
        from spatialflink_tpu.utils.metrics import REGISTRY, trace

        batches = REGISTRY.counter("batches-evaluated")
        records_c = REGISTRY.counter("records-evaluated")
        depth = max(1, self.conf.pipeline_depth)
        # fast lane: while interactive queries are in the fleet, the chunk
        # governor caps how many deferred windows may queue here (depth is
        # throughput headroom; every queued window is emit latency for the
        # interactive class). Checked per batch — a plain bool read — so
        # the lane engages/disengages live with fleet changes.
        from spatialflink_tpu.runtime.control import active_governor
        gov = active_governor()
        pending: deque = deque()  # (start, end, Deferred)
        # named per-operator trace annotations (≙ the reference's named
        # operators in the Flink web UI, StreamingJob.java:70-72): visible
        # in a jax.profiler capture (--profile / utils.metrics.profile_to),
        # no-ops otherwise. With a telemetry session active they upgrade to
        # stage SPANS (window/kernel/merge under the family label) which
        # still carry the trace annotation inside; checked ONCE here so a
        # disabled run drives the exact pre-telemetry loop.
        op_name = type(self).__name__
        tel = _telemetry.active()
        label = self.telemetry_label or op_name
        book = tel.traces if tel is not None else None
        costs = tel.costs if tel is not None else None
        lat = tel.latency if tel is not None else None
        acct = tel.tenants if tel is not None else None
        if tel is not None:
            backlog = tel.gauge("window-backlog")
            # per-window dispatch→ready overlap: 1 − blocked/round-trip —
            # the fraction of the device round-trip hidden behind host
            # work (pipeline_depth's payoff; ~0 when the drain blocks the
            # whole time, →1 when readback returns instantly)
            overlap_hist = tel.histogram("dispatch-overlap-ratio")
            batched = self._spanned_batches(batched, tel, label)

        def emit(start, end, sel) -> Iterator[WindowResult]:
            # realtime mode only fires on non-empty selections (the
            # reference's fire-per-element trigger never emits empties);
            # windowed mode reports every window, selected-or-not
            if sel or not realtime:
                if book is not None:
                    book.seal(label, start, end)
                yield WindowResult(start, end, sel)

        def note_budget(start, end, meta, m0, m1) -> None:
            # the window's stage-residency budget: consecutive wall-clock
            # intervals from first-record ingest to emission, so the
            # stages SUM to record→emit by construction (the invariant
            # tests assert; ARCHITECTURE.md § Latency decomposition).
            # meta = (first_ingest_ms, t_seal, t_kernel0, t_kernel1);
            # m0/m1 bound the merge (equal for non-deferred results).
            if lat is None:  # every caller gates on tel, but the latency
                return       # contract must hold locally on every path
            fi, li, t_seal, k0, k1 = meta
            t_emit = time.time()
            if fi is not None and fi > t_seal * 1e3:
                # a seal note from a coarser clock (the int-ms ingest
                # stamp) must not yield a negative buffer stage
                fi = None
            lat.window_complete(label, start, end, fi, {
                "buffer": (t_seal * 1e3 - fi) if fi is not None else 0.0,
                "queue": (k0 - t_seal) * 1e3,
                "dispatch": (k1 - k0) * 1e3,
                "inflight": (m0 - k1) * 1e3,
                "merge": (m1 - m0) * 1e3,
                "emit": (t_emit - m1) * 1e3,
            }, t_emit, last_ingest_ms=li)

        def drain(n: int) -> Iterator[WindowResult]:
            while len(pending) > n:
                start, end, dfd, t_disp, meta = pending.popleft()
                if tel is not None:
                    w0 = time.time()
                    with tel.span("merge", query=label):
                        sel = dfd.finish()
                    w1 = time.time()
                    if book is not None:
                        book.note(label, start, "merge", w0, w1)
                    if costs is not None:
                        costs.attribute_merge(label, w1 - w0)
                    total = w1 - t_disp
                    if total > 0:
                        overlap_hist.record(
                            max(0.0, 1.0 - (w1 - w0) / total))
                    backlog.set(len(pending))
                    if not realtime or sel:
                        note_budget(start, end, meta, w0, w1)
                else:
                    with trace(f"{op_name}.readback"):
                        sel = dfd.finish()
                yield from emit(start, end, sel)

        coord = self.conf.checkpointer
        for start, end, payload in batched:
            batches.inc()
            records_c.inc(count(payload))
            if tel is not None:
                w0 = time.time()
                # the chain's seal point: the assembler's sweep noted the
                # true seal wall clock for every ready window before the
                # first yielded, so windows pulled later carry their wait
                # behind earlier windows' eval/drain as "queue"; paths
                # without a sweeping assembler fall back to the pull time
                # (queue honestly 0)
                t_seal = lat.pop_seal(start, w0)
                fi = self._first_ingest_ms(payload)
                li = self._last_ingest_ms(payload) if fi is not None \
                    else None
                with tel.span("kernel", query=label):
                    sel = eval_batch(payload, start)
                w1 = time.time()
                if book is not None:
                    book.note(label, start, "kernel", w0, w1)
                if costs is not None:
                    nb = self._payload_nbytes(payload)
                    costs.attribute_kernel(
                        label, w1 - w0, records=count(payload), nbytes=nb)
                    # park the measured span on the tenant ledger; the
                    # dynamic demux (rows()) resolves it across the live
                    # slots, static paths age into the default tenant
                    acct.note_dispatch(label, start, w1 - w0,
                                       count(payload), nb)
                meta = (fi, li, min(t_seal, w0), w0, w1)
            else:
                meta = None
                with trace(f"{op_name}.dispatch"):
                    sel = eval_batch(payload, start)
            if isinstance(sel, Deferred):
                if tel is not None:
                    pending.append((start, end, sel, w1, meta))
                    lat.note_dispatch(start, w1)
                    backlog.set(len(pending))
                else:
                    pending.append((start, end, sel, 0.0, None))
                eff = depth if gov is None else gov.drain_depth(depth)
                yield from drain(eff - 1)
            else:
                yield from drain(0)  # keep window order
                if tel is not None and (sel or not realtime):
                    note_budget(start, end, meta, w1, w1)
                yield from emit(start, end, sel)
            if coord is not None:
                # coordinated-checkpoint barrier: when a checkpoint is due,
                # drain every in-flight window first (each drained yield
                # returns only after the consumer sank it), so the manifest
                # never captures an assembler missing a sealed-but-unsunk
                # window's records. Off the critical path otherwise — one
                # int compare per batch.
                coord.note_batch()
                if coord.due():
                    yield from drain(0)
                    coord.commit()
        yield from drain(0)

    @classmethod
    def _spanned_batches(cls, batched: Iterable, tel, label: str) -> Iterator:
        """Wrap a (start, end, payload) source so each pull is timed as the
        ``window`` stage (assembly/buffering time — the host-side half the
        kernel spans don't see). The span is class-based, so the final
        StopIteration passes through it without being miscounted. With
        tracing on, each pull also opens the window's trace record: the
        assembly slice plus the first record's ingest wall clock."""
        it = iter(batched)
        book = tel.traces
        while True:
            try:
                t0 = time.time()
                with tel.span("window", query=label):
                    item = next(it)
            except StopIteration:
                return
            if book is not None:
                book.note(label, item[0], "window", t0, time.time())
                ing = cls._first_ingest_ms(item[2])
                if ing is not None:
                    book.first_record(label, item[0], ing)
            yield item

    @staticmethod
    def _first_ingest_ms(payload):
        """Best-effort first-record ingest wall clock for trace lineage:
        record lists carry Points with an ``ingestion_time`` stamped at
        parse; pane payloads hold ``(pane_start, records)`` pairs; bulk
        (idx, batch) payloads have no per-record host objects — None."""
        return SpatialOperator._ingest_ms(payload, -1)

    @staticmethod
    def _last_ingest_ms(payload):
        """The LAST record's ingest stamp — with the first-record stamp it
        bounds the window's buffer-residency spread (a window whose first
        record waited 9 s and whose last waited 10 ms is normal sliding-
        window fill; both old means the pipeline sat on a sealed-ready
        window)."""
        return SpatialOperator._ingest_ms(payload, +1)

    @staticmethod
    def _ingest_ms(payload, end: int):
        """Shared first/last ingest-stamp reader (``end`` = -1 first,
        +1 last); one record materializes per call, never the window."""
        from spatialflink_tpu.streams.bulk import LazyRecords

        try:
            recs = payload
            pos = 0 if end < 0 else -1
            if isinstance(recs, LazyRecords):
                # columnar window: materialize ONE record (its
                # ingestion_time is the chunk's decode stamp)
                return int(recs[pos].ingestion_time) if len(recs) else None
            if not isinstance(recs, list) or not recs:
                return None
            if (isinstance(recs[0], tuple) and len(recs[0]) == 2
                    and isinstance(recs[0][1], (list, LazyRecords))):
                recs = recs[pos][1]  # pane payload: first/last pane
                if not len(recs):
                    return None
            ing = getattr(recs[pos], "ingestion_time", None)
            if isinstance(ing, (int, float)) and ing > 0:
                return int(ing)
        except Exception:
            pass
        return None

    @staticmethod
    def _payload_nbytes(payload) -> int:
        """Approximate host->device bytes for one window payload: summed
        array ``nbytes`` where the payload carries arrays (bulk
        (idx, batch) tuples), a flat 32-bytes-per-record estimate for host
        record lists (x/y/ts/id as packed fields) — a cost-profile
        ESTIMATE of data motion, not a transfer measurement."""
        from spatialflink_tpu.streams.bulk import LazyRecords

        try:
            if isinstance(payload, LazyRecords):
                return 32 * len(payload)
            if isinstance(payload, tuple) and len(payload) == 2:
                idx, batch = payload
                total = int(getattr(idx, "nbytes", 0))
                parts = (batch if isinstance(batch, tuple)
                         else [getattr(batch, f, None)
                               for f in getattr(batch,
                                                "__dataclass_fields__", ())])
                for a in parts:
                    total += int(getattr(a, "nbytes", 0) or 0)
                return total
            if isinstance(payload, list):
                if (payload and isinstance(payload[0], tuple)
                        and len(payload[0]) == 2):
                    inner = payload[0][1]
                    if isinstance(inner, (list, LazyRecords)):
                        # record-path pane payload
                        return 32 * sum(len(rs) for _, rs in payload)
                    if isinstance(inner, tuple):  # bulk pane payload
                        return sum(
                            SpatialOperator._payload_nbytes(p)
                            for _, p in payload)
                return 32 * len(payload)
        except Exception:
            pass
        return 0


class GeomQueryMixin:
    """Query-side precomputation shared by all operators: dense GN/CN/NB cell
    masks (union over the query geometry's cells — ``UniformGrid.java:193-222``)
    and padded query edge arrays."""

    def _query_cells(self, query) -> list:
        if isinstance(query, Point):
            return [query.cell] if query.cell >= 0 else []
        return sorted(query.cells)

    def _query_masks(self, query, radius: float):
        import jax.numpy as jnp

        cells = self._query_cells(query)
        gn = self.grid.guaranteed_cells_mask(radius, cells)
        cn = self.grid.candidate_cells_mask(radius, cells, gn)
        nb = self.grid.neighboring_cells_mask(radius, cells)
        return jnp.asarray(gn), jnp.asarray(cn), jnp.asarray(nb)

    def _query_nb(self, query, radius: float):
        """Dense neighboring-cells (GN ∪ CN) mask for a query geometry —
        radius 0 selects all cells (UniformGrid.java:264-266)."""
        import jax.numpy as jnp

        return jnp.asarray(
            self.grid.neighboring_cells_mask(radius, self._query_cells(query))
        )

    def _stack_query_nb(self, queries, radius: float):
        """(Q, n*n) dense neighboring-cells masks, one per query object —
        the multi-query form of :meth:`_query_nb`."""
        return self._stack_query_masks(queries, radius, which=("nb",))[0]

    def _stack_query_masks(self, queries, radius: float,
                           which=("gn", "cn", "nb")):
        """Selected dense-mask stacks, each (Q, n*n), in ``which`` order —
        the multi-query form of :meth:`_query_masks`. Builds straight from
        the grid's host-side masks (no per-query device round-trip) and
        only the masks the caller asked for (cn derives from gn, so
        requesting cn computes gn internally without stacking it)."""
        import jax.numpy as jnp

        rows = {k: [] for k in which}
        for q in queries:
            cells = self._query_cells(q)
            gn = (self.grid.guaranteed_cells_mask(radius, cells)
                  if ("gn" in which or "cn" in which) else None)
            if "gn" in which:
                rows["gn"].append(np.asarray(gn))
            if "cn" in which:
                rows["cn"].append(np.asarray(
                    self.grid.candidate_cells_mask(radius, cells, gn)))
            if "nb" in which:
                rows["nb"].append(np.asarray(
                    self.grid.neighboring_cells_mask(radius, cells)))
        return tuple(jnp.asarray(np.stack(rows[k])) for k in which)

    def _query_geom_batch(self, queries):
        """The Q query geometries as ONE exact-capacity padded edge batch
        (no bucket padding: built once per run_multi, and the G axis must
        match the (Q,) per-query mask stacks)."""
        from spatialflink_tpu.models.batches import EdgeGeomBatch

        return EdgeGeomBatch.from_objects(queries, self.grid,
                                          pad=len(queries))

    def _query_edges(self, query):
        from spatialflink_tpu.models.batches import single_query_edges
        import jax.numpy as jnp

        e, m = single_query_edges(query)
        from spatialflink_tpu.models.objects import Polygon as _P, MultiPolygon as _MP

        areal = isinstance(query, (_P, _MP))
        return jnp.asarray(e), jnp.asarray(m), areal

    def _query_bbox(self, query):
        import jax.numpy as jnp
        import numpy as np

        return jnp.asarray(np.asarray(query.bbox, np.float32))


