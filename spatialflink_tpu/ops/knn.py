"""k-nearest-neighbor window kernels with object-id dedup.

Reference semantics (``knn/PointPointKNNQuery.java:138-191`` +
``knn/KNNQuery.java:204-300``): the radius r selects the neighboring-cell set
(GN ∪ CN) but the exact distance is NOT radius-filtered in windowed mode; the
per-cell windows keep a k-element max-heap, and the global ``windowAll`` merge
deduplicates by objID keeping the *minimum* distance per object.

TPU re-design: instead of per-cell heaps + a parallelism-1 merge, we compute
all masked distances in one shot, deduplicate by objID with a lexicographic
sort (sort by (objID, dist); the first row of each objID run carries its min
distance), then take a single ``lax.top_k``. The same kernel runs per shard
under shard_map, with partial top-k results merged by all-gather + re-top-k
(see spatialflink_tpu.parallel) — that kills the reference's windowAll
bottleneck.

The trajectory variant (tKnn) *does* enforce the exact radius
(``tKnn/PointPointTKNNQuery.java:95-111``); pass ``enforce_radius=True``.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from spatialflink_tpu.models.batches import PointBatch
from spatialflink_tpu.ops import distances as D
from spatialflink_tpu.ops.range import cheb_layers

_BIG = jnp.float32(3.4e38)
_OID_SENTINEL = jnp.int32(2**31 - 1)


class KnnResult(NamedTuple):
    obj_id: jnp.ndarray  # (k,) i32; sentinel 2^31-1 in empty slots
    dist: jnp.ndarray    # (k,) f32; +BIG in empty slots
    valid: jnp.ndarray   # (k,) bool


def dedup_min_by_id(obj_id, dist, eligible):
    """Per-object minimum distance via one lexicographic sort.

    Returns (obj_id_sorted, dist_sorted, keep) where ``keep`` marks the first
    occurrence of each object id (which, after an ascending (id, dist) sort,
    carries that object's min distance). Ineligible rows get a sentinel id so
    they sort to the back and are never kept.
    """
    oid = jnp.where(eligible, obj_id, _OID_SENTINEL)
    d = jnp.where(eligible, dist, _BIG)
    oid_s, d_s = jax.lax.sort((oid, d), num_keys=2)
    prev = jnp.concatenate([jnp.full((1,), -1, oid_s.dtype), oid_s[:-1]])
    keep = (oid_s != prev) & (oid_s != _OID_SENTINEL)
    return oid_s, d_s, keep


def topk_by_distance(obj_id, dist, eligible, k: int) -> KnnResult:
    """Dedup by object id (keep min dist) then top-k smallest distances."""
    oid_s, d_s, keep = dedup_min_by_id(obj_id, dist, eligible)
    d_masked = jnp.where(keep, d_s, _BIG)
    neg_top, idx = jax.lax.top_k(-d_masked, k)
    top_d = -neg_top
    top_oid = jnp.where(top_d < _BIG, oid_s[idx], _OID_SENTINEL)
    return KnnResult(obj_id=top_oid, dist=top_d, valid=top_d < _BIG)


@partial(jax.jit, static_argnames=("n", "k", "enforce_radius"))
def knn_point(
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    nb_layers,
    *,
    n: int,
    k: int,
    enforce_radius: bool = False,
) -> KnnResult:
    """kNN of a query point over a window batch.

    nb_layers: candidate layer count (``UniformGrid.candidate_layers``);
    pass ``n`` (the grid size) to disable cell pruning (radius 0 semantics:
    all cells are neighbors, ``UniformGrid.java:264-266``).
    """
    layers = cheb_layers(points.cell, q_cell, n)
    eligible = points.valid & (layers <= nb_layers)
    d = D.pp_dist(points.x, points.y, qx, qy)
    if enforce_radius:
        eligible = eligible & (d <= radius)
    return topk_by_distance(points.obj_id, d, eligible, k)


@partial(jax.jit, static_argnames=("k", "enforce_radius"))
def knn_with_dists(
    obj_id,
    dists,
    nb_mask,
    cell,
    valid,
    radius,
    *,
    k: int,
    enforce_radius: bool = False,
) -> KnnResult:
    """Generic kNN: caller supplies distances (e.g. point->polygon) and a
    dense neighboring-cells mask for the query geometry."""
    eligible = point_stream_eligibility(cell, valid, nb_mask)
    if enforce_radius:
        eligible = eligible & (dists <= radius)
    return topk_by_distance(obj_id, dists, eligible, k)


def merge_knn(results, k: int) -> KnnResult:
    """Merge per-shard/per-window partial KnnResults (the reference's
    ``kNNWinAllEvaluationPointStream`` dedup+merge, without the
    parallelism-1 bottleneck: concatenate, dedup, re-top-k)."""
    obj_id = jnp.concatenate([r.obj_id for r in results])
    dist = jnp.concatenate([r.dist for r in results])
    valid = jnp.concatenate([r.valid for r in results])
    return topk_by_distance(obj_id, dist, valid, k)


@partial(jax.jit, static_argnames=("k",))
def knn_eligible(obj_id, dists, eligible, *, k: int) -> KnnResult:
    """Jitted dedup+top-k over caller-computed eligibility and distances —
    the generic entry for polygon/linestring streams and geometry queries."""
    return topk_by_distance(obj_id, dists, eligible, k)


def point_stream_eligibility(cell, valid, nb_mask):
    """Shared point-stream eligibility rule: valid, in-grid, and in a
    neighboring cell of the query (dense mask form). Single source of truth
    for knn_with_dists and the operator layer."""
    return valid & (cell >= 0) & nb_mask[jnp.maximum(cell, 0)]
