"""Bench regression sentinel: diff two bench JSON files row by row.

CI-shaped guard for the ledgers this repo already produces
(``benchmarks/RESULTS_e2e_cpu.json``, ``RESULTS_panes_*.json``, ...): pair
rows between a BASELINE file and a CURRENT file by their identity fields
(option / path / overlap / queries / ...), compare one metric per row
(default ``records_per_sec``, higher-is-better), and exit nonzero when any
row regressed past its threshold — so a perf regression fails the pipeline
instead of quietly rewriting the ledger.

Usage:
    python benchmarks/bench_diff.py BASELINE.json CURRENT.json \
        [--metric records_per_sec] [--threshold 0.10] \
        [--rule path=bulk:0.05] [--rule option=51,path=record:0.25] \
        [--lower-is-better] [--require-all]

- ``--threshold`` is the default allowed fractional regression (0.10 =
  current may be up to 10% worse than baseline).
- ``--rule k=v[,k=v...]:threshold`` overrides the threshold for rows whose
  identity fields match every listed pair (first matching rule wins, in
  argument order) — per-row thresholds for noisy rows (e.g. the scalar
  record path) next to tight ones (the vectorized bulk path).
- ``--lower-is-better`` flips the comparison (wall_s / latency-style
  metrics). Worked example — gate a record→emit p99 latency ledger where
  the baseline rows carry ceilings::

      # baseline.json: {"rows": [{"path": "latency_record_emit",
      #                           "p99_ms": 61.0}]}
      # current.json:  {"rows": [{"path": "latency_record_emit",
      #                           "p99_ms": 20.3}]}
      python benchmarks/bench_diff.py baseline.json current.json \
          --metric p99_ms --lower-is-better --threshold 0.25

  20.3 ms against a 61.0 ms ceiling is a +66.7% improvement (change =
  (base - current) / base, so positive is always better); the run only
  fails once current p99 exceeds 61.0 x 1.25 = 76.25 ms. This is exactly
  how ``bench_guard --check`` gates its ``latency_rows`` next to the
  higher-is-better speedup floors.
- Rows present in only one file are reported (``missing`` / ``new``) and
  are non-fatal unless ``--require-all`` (a silently dropped bench row is
  how coverage rots).

Exit codes: 0 = no regression, 1 = regression(s), 2 = usage / missing
rows under ``--require-all``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

#: fields that IDENTIFY a row (never compared as metrics); a row's key is
#: the subset of these it actually carries, in this order
ID_FIELDS = ("option", "path", "overlap", "queries", "checkpoint_every",
             "records", "backend")


def load_rows(path: str) -> List[dict]:
    """Rows from a bench JSON file: either ``{"rows": [...]}`` (the
    RESULTS_* shape) or a bare JSON list / JSONL of row objects."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = [json.loads(line) for line in text.splitlines() if line.strip()]
    if isinstance(doc, dict):
        doc = doc.get("rows", [])
    if not isinstance(doc, list):
        raise ValueError(f"{path}: expected a rows list or {{'rows': [...]}}")
    return [r for r in doc if isinstance(r, dict)]


def row_key(row: dict) -> Tuple:
    return tuple((f, str(row[f])) for f in ID_FIELDS if f in row)


def _key_sans_backend(key: Tuple) -> Tuple:
    return tuple((f, v) for f, v in key if f != "backend")


def _backend_of(key: Tuple) -> Optional[str]:
    for f, v in key:
        if f == "backend":
            return v
    return None


def parse_rule(spec: str) -> Tuple[Dict[str, str], float]:
    """``k=v[,k=v...]:threshold`` -> (match dict, threshold)."""
    match_part, sep, thr_part = spec.rpartition(":")
    if not sep:
        raise ValueError(f"--rule {spec!r} is not 'k=v[,k=v]:threshold'")
    try:
        thr = float(thr_part)
    except ValueError:
        raise ValueError(f"--rule {spec!r}: threshold {thr_part!r} "
                         "is not numeric")
    match: Dict[str, str] = {}
    for pair in match_part.split(","):
        key, eq, val = pair.partition("=")
        if not eq:
            raise ValueError(f"--rule {spec!r}: {pair!r} is not key=value")
        match[key.strip()] = val.strip()
    return match, thr


def rule_threshold(row: dict, rules: List[Tuple[Dict[str, str], float]],
                   default: float) -> float:
    for match, thr in rules:
        if all(str(row.get(k)) == v for k, v in match.items()):
            return thr
    return default


def diff_rows(base_rows: List[dict], cur_rows: List[dict], metric: str,
              threshold: float,
              rules: Optional[List[Tuple[Dict[str, str], float]]] = None,
              lower_is_better: bool = False) -> List[dict]:
    """Pairwise comparison; one result dict per row key, statuses:
    ``ok`` / ``regression`` / ``missing`` (in baseline only) / ``new``
    (in current only) / ``unmeasured`` (metric absent on either side) /
    ``backend_mismatch`` (identical identity except ``backend`` — the
    rows refuse to pair; fatal in :func:`main`)."""
    rules = rules or []
    base = {row_key(r): r for r in base_rows}
    cur = {row_key(r): r for r in cur_rows}
    # rows that pair on every identity field EXCEPT backend were measured
    # on different hardware: the comparison is meaningless whichever way it
    # points, so the diff REFUSES them (fatal in main) instead of letting a
    # TPU baseline silently "regress" against a CPU-fallback current
    cur_sans = {_key_sans_backend(k): k for k in cur}
    mismatched_cur: set = set()
    out: List[dict] = []
    for key, b in base.items():
        label = ",".join(f"{k}={v}" for k, v in key)
        c = cur.get(key)
        if c is None:
            twin = cur_sans.get(_key_sans_backend(key))
            if twin is not None and _backend_of(twin) != _backend_of(key):
                mismatched_cur.add(twin)
                out.append({
                    "key": label, "status": "backend_mismatch",
                    "base_backend": _backend_of(key),
                    "current_backend": _backend_of(twin)})
                continue
            out.append({"key": label, "status": "missing",
                        "base": b.get(metric)})
            continue
        bv, cv = b.get(metric), c.get(metric)
        if not isinstance(bv, (int, float)) or not isinstance(cv,
                                                              (int, float)):
            out.append({"key": label, "status": "unmeasured",
                        "base": bv, "current": cv})
            continue
        thr = rule_threshold(b, rules, threshold)
        # change > 0 is always an improvement, whichever way the metric
        # points; regression when it exceeds the row's allowance
        change = ((cv - bv) if not lower_is_better else (bv - cv)) / bv \
            if bv else 0.0
        out.append({
            "key": label, "base": bv, "current": cv,
            "change": round(change, 4), "threshold": thr,
            "status": "regression" if change < -thr else "ok",
        })
    for key, c in cur.items():
        if key not in base and key not in mismatched_cur:
            out.append({"key": ",".join(f"{k}={v}" for k, v in key),
                        "status": "new", "current": c.get(metric)})
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_diff",
        description="diff two bench JSON files; nonzero exit on regression")
    ap.add_argument("baseline", help="baseline bench JSON (the ledger)")
    ap.add_argument("current", help="current bench JSON (the fresh run)")
    ap.add_argument("--metric", default="records_per_sec",
                    help="row field to compare (default records_per_sec)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="default allowed fractional regression "
                         "(default 0.10)")
    ap.add_argument("--rule", action="append", default=[],
                    metavar="K=V[,K=V]:THR",
                    help="per-row threshold override for rows matching "
                         "every K=V identity pair; first match wins")
    ap.add_argument("--lower-is-better", action="store_true",
                    help="the metric improves downward (wall_s)")
    ap.add_argument("--require-all", action="store_true",
                    help="baseline rows missing from current are fatal "
                         "(exit 2)")
    args = ap.parse_args(argv)

    try:
        rules = [parse_rule(s) for s in args.rule]
        base_rows = load_rows(args.baseline)
        cur_rows = load_rows(args.current)
    except (ValueError, OSError, json.JSONDecodeError) as e:
        print(f"bench_diff: {e}", file=sys.stderr)
        return 2

    results = diff_rows(base_rows, cur_rows, args.metric, args.threshold,
                        rules, args.lower_is_better)
    regressions = missing = mismatches = 0
    for r in results:
        if r["status"] == "backend_mismatch":
            mismatches += 1
            print(f"BACKEND MISMATCH {r['key']}: baseline measured on "
                  f"{r['base_backend']!r}, current on "
                  f"{r['current_backend']!r} — rows refuse to pair "
                  "(re-measure on the same backend, or use "
                  "--require-backend on the harness)")
        elif r["status"] == "regression":
            regressions += 1
            print(f"REGRESSION {r['key']}: {args.metric} "
                  f"{r['base']} -> {r['current']} "
                  f"({r['change'] * 100:+.1f}%, allowed "
                  f"-{r['threshold'] * 100:.0f}%)")
        elif r["status"] == "ok":
            print(f"ok         {r['key']}: {args.metric} "
                  f"{r['base']} -> {r['current']} "
                  f"({r['change'] * 100:+.1f}%)")
        elif r["status"] == "missing":
            missing += 1
            print(f"MISSING    {r['key']}: in baseline only")
        elif r["status"] == "new":
            print(f"new        {r['key']}: in current only")
        else:
            print(f"unmeasured {r['key']}: {args.metric} absent "
                  f"({r.get('base')!r} -> {r.get('current')!r})")
    compared = sum(r["status"] in ("ok", "regression") for r in results)
    print(f"# {compared} row(s) compared, {regressions} regression(s), "
          f"{missing} missing, {mismatches} backend mismatch(es)",
          file=sys.stderr)
    if mismatches:
        return 2
    if missing and args.require_all:
        return 2
    return 1 if regressions else 0


if __name__ == "__main__":
    raise SystemExit(main())
