"""bench_diff regression sentinel on synthetic rows: row pairing by
identity fields, default + per-row thresholds, both metric directions,
missing/new row handling, and the CLI exit codes CI keys on."""

import json

import pytest

from benchmarks.bench_diff import (diff_rows, load_rows, main, parse_rule,
                                   row_key)


def _row(option=1, path="record", rps=1000, **kw):
    return dict(option=option, path=path, records=100_000,
                records_per_sec=rps, **kw)


def _by_key(results):
    return {r["key"]: r for r in results}


class TestDiffRows:
    def test_identity_pairing_and_ok(self):
        base = [_row(1, "record", 1000), _row(1, "bulk", 9000),
                _row(51, "record", 800)]
        cur = [_row(51, "record", 820), _row(1, "bulk", 8950),
               _row(1, "record", 990)]  # order must not matter
        res = diff_rows(base, cur, "records_per_sec", 0.10)
        assert all(r["status"] == "ok" for r in res)
        assert len(res) == 3

    def test_regression_past_threshold_flags(self):
        base = [_row(1, "record", 1000), _row(1, "bulk", 9000)]
        cur = [_row(1, "record", 850), _row(1, "bulk", 8500)]
        res = _by_key(diff_rows(base, cur, "records_per_sec", 0.10))
        assert res["option=1,path=record,records=100000"]["status"] == \
            "regression"
        assert res["option=1,path=bulk,records=100000"]["status"] == "ok"

    def test_improvement_never_flags(self):
        res = diff_rows([_row(rps=1000)], [_row(rps=5000)],
                        "records_per_sec", 0.0)
        assert res[0]["status"] == "ok" and res[0]["change"] == 4.0

    def test_per_row_rule_overrides_default(self):
        base = [_row(1, "record", 1000), _row(1, "bulk", 9000)]
        cur = [_row(1, "record", 920), _row(1, "bulk", 8300)]
        # default 10% passes both; a tight bulk-only rule fails bulk
        rules = [parse_rule("path=bulk:0.05")]
        res = _by_key(diff_rows(base, cur, "records_per_sec", 0.10, rules))
        assert res["option=1,path=bulk,records=100000"]["status"] == \
            "regression"
        assert res["option=1,path=record,records=100000"]["status"] == "ok"

    def test_lower_is_better_direction(self):
        base = [_row(wall_s=10.0)]
        worse = [_row(wall_s=12.0)]
        better = [_row(wall_s=8.0)]
        assert diff_rows(base, worse, "wall_s", 0.10,
                         lower_is_better=True)[0]["status"] == "regression"
        assert diff_rows(base, better, "wall_s", 0.10,
                         lower_is_better=True)[0]["status"] == "ok"

    def test_missing_new_and_unmeasured(self):
        base = [_row(1, "record"), _row(1, "bulk"),
                dict(option=9, path="x", records_per_sec=None)]
        cur = [_row(1, "record"), _row(2, "record"),
               dict(option=9, path="x", records_per_sec=None)]
        statuses = {r["key"]: r["status"]
                    for r in diff_rows(base, cur, "records_per_sec", 0.1)}
        assert statuses["option=1,path=bulk,records=100000"] == "missing"
        assert statuses["option=2,path=record,records=100000"] == "new"
        assert statuses["option=9,path=x"] == "unmeasured"

    def test_row_key_ignores_metrics(self):
        assert row_key(_row(rps=1)) == row_key(_row(rps=99999))

    def test_parse_rule_rejects_malformed(self):
        with pytest.raises(ValueError, match="threshold"):
            parse_rule("path=bulk")
        with pytest.raises(ValueError, match="not numeric"):
            parse_rule("path=bulk:fast")
        with pytest.raises(ValueError, match="key=value"):
            parse_rule("bulk:0.1")


class TestCli:
    def _write(self, tmp_path, name, rows, wrapped=True):
        p = tmp_path / name
        p.write_text(json.dumps({"rows": rows} if wrapped else rows))
        return str(p)

    def test_exit_codes(self, tmp_path, capsys):
        base = self._write(tmp_path, "base.json",
                           [_row(1, "record", 1000), _row(1, "bulk", 9000)])
        ok = self._write(tmp_path, "ok.json",
                         [_row(1, "record", 980), _row(1, "bulk", 9100)],
                         wrapped=False)  # bare-list shape also loads
        bad = self._write(tmp_path, "bad.json",
                          [_row(1, "record", 400), _row(1, "bulk", 9100)])
        assert main([base, ok]) == 0
        assert main([base, bad]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION" in out and "path=record" in out
        # missing rows: visible but non-fatal ...
        part = self._write(tmp_path, "part.json", [_row(1, "bulk", 9000)])
        assert main([base, part]) == 0
        assert "MISSING" in capsys.readouterr().out
        # ... unless CI demands full coverage
        assert main([base, part, "--require-all"]) == 2

    def test_usage_errors_exit_2(self, tmp_path):
        good = self._write(tmp_path, "g.json", [_row()])
        assert main([str(tmp_path / "absent.json"), good]) == 2
        assert main([good, good, "--rule", "nonsense"]) == 2

    def test_cli_rules_and_metric_flags(self, tmp_path):
        base = self._write(tmp_path, "b.json", [_row(1, "bulk", 9000)])
        cur = self._write(tmp_path, "c.json", [_row(1, "bulk", 8400)])
        assert main([base, cur]) == 0  # -6.7% inside the default 10%
        assert main([base, cur, "--rule", "path=bulk:0.05"]) == 1

    def test_load_rows_real_ledger_shape(self):
        # the in-repo ledger parses and pairs with itself (zero diff)
        rows = load_rows("benchmarks/RESULTS_e2e_cpu.json")
        assert rows and all(isinstance(r, dict) for r in rows)
        res = diff_rows(rows, rows, "records_per_sec", 0.0)
        assert all(r["status"] in ("ok", "unmeasured") for r in res)
