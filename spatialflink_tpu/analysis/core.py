"""Invariant-lint framework: a small rule engine over ``ast``.

The engine's correctness contracts — zero post-warmup recompiles, zero
hot-path telemetry without a session, accounted host↔device syncs,
checkpoint coverage of mutable streaming state, lock discipline on
cross-thread state — are each enforced at runtime by a sentinel or a spy,
but only on the code paths a test happens to execute. This package
promotes them to *static* invariants: every tier-1 run parses the whole
``spatialflink_tpu`` tree and proves the contracts at the AST level, on
every path, including ones no benchmark has ever taken.

Pieces:

- :class:`Finding` — one violation: rule id, file/line/col, severity,
  message, and the enclosing dotted ``symbol`` (``Class.method``) so
  allowlist entries can anchor to code instead of line numbers.
- :class:`Rule` — subclass per invariant; ``scope`` globs pick the
  modules a contract covers, ``check(mod)`` yields findings. Rules
  self-register via :func:`register`.
- :class:`ModuleSource` — parsed module plus the parent map / enclosing-
  scope helpers every rule needs.
- :class:`Allowlist` — reviewed exceptions loaded from
  ``analysis/ALLOWLIST.toml``. Every entry needs a ``reason``; an entry
  that matches no current finding is *stale* and fails ``--check``, so
  the list can only shrink (ratchet), never accrete dead weight.
- :class:`Pragma` — the line-anchored twin of an allowlist entry:
  ``# analysis: allow(<rule-id>): <reason>`` on the offending line
  suppresses that rule there, under the same shrink-only ratchet (a
  pragma whose line no longer triggers the rule is stale and fails
  ``--check``).
- :func:`run_analysis` — scan a tree, run the rules over the shared
  project call graph (:mod:`spatialflink_tpu.analysis.callgraph`),
  apply pragmas then the allowlist, report stale entries of both kinds.
  Per-module findings are cached under the source content hash
  (:mod:`spatialflink_tpu.analysis.cache`) so the repeated tier-1
  passes reparse nothing on an unchanged tree.

The CLI lives in :mod:`spatialflink_tpu.analysis.cli` and the rule
implementations in :mod:`spatialflink_tpu.analysis.rules`.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import hashlib
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

#: repo root (the directory holding the ``spatialflink_tpu`` package).
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
#: the committed allowlist for the real tree.
ALLOWLIST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "ALLOWLIST.toml")

SEVERITIES = ("error", "warning")


class AllowlistError(ValueError):
    """Malformed allowlist file (syntax, missing reason, unknown rule) —
    a configuration error, distinct from findings (exit 2, not 1)."""


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    severity: str
    message: str
    symbol: str = ""  # dotted enclosing scope, e.g. "PaneCache.get"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(**d)

    def render(self) -> str:
        where = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"{self.severity}: {self.message}{where}")


class ModuleSource:
    """A parsed module plus the structural indexes rules share: a
    child→parent map, enclosing-function/class lookup, and dotted
    qualnames for findings and symbol-anchored allowlist entries."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    @classmethod
    def from_source(cls, source: str,
                    relpath: str = "spatialflink_tpu/snippet.py"
                    ) -> "ModuleSource":
        """Build from a source string — the fixture-test entry point."""
        return cls(relpath, relpath, source)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        """Innermost-first chain of ancestors up to the module node."""
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        """Enclosing FunctionDef/AsyncFunctionDef/Lambda nodes, innermost
        first."""
        return [a for a in self.ancestors(node)
                if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))]

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a
        return None

    def qualname(self, node: ast.AST) -> str:
        """Dotted scope name for ``node`` (classes and named functions on
        the ancestor chain, outermost first; lambdas render as
        ``<lambda>``)."""
        parts: List[str] = []
        for a in self.ancestors(node):
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                parts.append(a.name)
            elif isinstance(a, ast.Lambda):
                parts.append("<lambda>")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            parts.insert(0, node.name)
        return ".".join(reversed(parts))


class Rule:
    """One static invariant. Subclasses set ``id``/``contract``/``scope``
    and implement :meth:`check`; ``runtime_twin`` names the runtime
    enforcement (sentinel/spy/test) the rule complements — the docs table
    renders it. ``depth`` documents how far the rule reasons ("lexical"
    or "interprocedural"); ``interprocedural`` additionally marks rules
    whose findings depend on OTHER modules (cross-module call-graph
    resolution), which widens their cache key to the whole-tree hash."""

    id: str = ""
    contract: str = ""
    runtime_twin: str = ""
    severity: str = "error"
    #: "lexical" or "interprocedural" — the docs-table depth column.
    depth: str = "lexical"
    #: findings depend on modules beyond the one being checked.
    interprocedural: bool = False
    #: fnmatch globs over repo-relative paths this contract covers.
    scope: Tuple[str, ...] = ("spatialflink_tpu/**",)

    def applies_to(self, relpath: str) -> bool:
        rel = relpath.replace(os.sep, "/")
        return any(fnmatch.fnmatch(rel, pat) for pat in self.scope)

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:  # pragma: no cover
        """Yield findings for ``mod``. ``project`` is the shared
        :class:`~spatialflink_tpu.analysis.callgraph.Project` (never None
        when invoked through the runner; rules needing it should fall
        back to a single-module project for direct calls)."""
        raise NotImplementedError

    def finding(self, mod: ModuleSource, node: ast.AST, message: str,
                severity: Optional[str] = None) -> Finding:
        return Finding(rule=self.id, path=mod.relpath,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       severity=severity or self.severity,
                       message=message, symbol=mod.qualname(node))


#: global rule registry, id → instance (populated by the rule modules).
RULES: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate and register a rule by id."""
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"{rule_cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    RULES[rule.id] = rule
    return rule_cls


def all_rules() -> List[Rule]:
    """Every registered rule, importing the rule modules on first use."""
    from spatialflink_tpu.analysis import rules as _rules  # noqa: F401

    return [RULES[k] for k in sorted(RULES)]


def resolve_rules(rule_ids: Optional[Sequence[str]] = None) -> List[Rule]:
    rules = all_rules()
    if not rule_ids:
        return rules
    unknown = sorted(set(rule_ids) - set(RULES))
    if unknown:
        raise AllowlistError(
            f"unknown rule id(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(RULES))})")
    return [RULES[r] for r in sorted(set(rule_ids))]


# --------------------------------------------------------------------- #
# allowlist


@dataclasses.dataclass
class AllowEntry:
    """One reviewed exception. Matches a finding when rule+path agree and
    the anchor (symbol, line, or neither = whole file) matches. ``count``
    tracks how many findings the entry absorbed — zero after a full run
    means the exception is stale and must be removed."""

    rule: str
    path: str
    reason: str
    symbol: Optional[str] = None
    line: Optional[int] = None
    count: int = 0

    def matches(self, f: Finding) -> bool:
        if f.rule != self.rule or f.path != self.path:
            return False
        if self.symbol is not None and f.symbol != self.symbol \
                and not f.symbol.startswith(self.symbol + "."):
            return False
        if self.line is not None and f.line != self.line:
            return False
        return True

    def render(self) -> str:
        anchor = (f" symbol={self.symbol}" if self.symbol else "") + \
            (f" line={self.line}" if self.line is not None else "")
        return f"{self.rule} @ {self.path}{anchor} ({self.reason})"


def _parse_toml(path: str) -> dict:
    try:
        import tomllib  # Python ≥3.11
    except ImportError:  # pragma: no cover - environment-dependent
        import tomli as tomllib
    with open(path, "rb") as f:
        try:
            return tomllib.load(f)
        except tomllib.TOMLDecodeError as e:
            raise AllowlistError(f"{path}: invalid TOML: {e}")


class Allowlist:
    """Reviewed exceptions; see the module docstring for the ratchet."""

    def __init__(self, entries: Optional[List[AllowEntry]] = None):
        self.entries = entries or []

    @classmethod
    def load(cls, path: str) -> "Allowlist":
        if not os.path.exists(path):
            return cls([])
        doc = _parse_toml(path)
        entries: List[AllowEntry] = []
        for i, raw in enumerate(doc.get("allow", []) or []):
            if not isinstance(raw, dict):
                raise AllowlistError(f"{path}: [[allow]] #{i + 1} is not "
                                     "a table")
            unknown = set(raw) - {"rule", "path", "reason", "symbol",
                                  "line"}
            if unknown:
                raise AllowlistError(
                    f"{path}: [[allow]] #{i + 1} has unknown key(s) "
                    f"{sorted(unknown)}")
            for key in ("rule", "path", "reason"):
                if not isinstance(raw.get(key), str) or not raw[key].strip():
                    raise AllowlistError(
                        f"{path}: [[allow]] #{i + 1} needs a non-empty "
                        f"{key!r} string — every exception carries its "
                        "review reason")
            entries.append(AllowEntry(
                rule=raw["rule"], path=raw["path"],
                reason=raw["reason"].strip(),
                symbol=raw.get("symbol"), line=raw.get("line")))
        return cls(entries)

    def apply(self, findings: Iterable[Finding],
              ran_rules: Iterable[str]) -> Tuple[
                  List[Finding], List[Tuple[Finding, AllowEntry]],
                  List[AllowEntry]]:
        """Split findings into (active, suppressed) and report stale
        entries. Staleness only considers entries whose rule actually ran
        — a ``--rule`` subset run must not condemn the others' entries."""
        ran = set(ran_rules)
        for e in self.entries:
            e.count = 0
        active: List[Finding] = []
        suppressed: List[Tuple[Finding, AllowEntry]] = []
        for f in findings:
            hit = next((e for e in self.entries if e.matches(f)), None)
            if hit is not None:
                hit.count += 1
                suppressed.append((f, hit))
            else:
                active.append(f)
        stale = [e for e in self.entries if e.count == 0 and e.rule in ran]
        return active, suppressed, stale


# --------------------------------------------------------------------- #
# inline suppression pragmas

#: a full, well-formed pragma (the ``allow(<id>): <reason>`` comment
#: form documented in ARCHITECTURE.md).
PRAGMA_RE = re.compile(
    r"#\s*analysis:\s*allow\(\s*([A-Za-z0-9_-]+)\s*\)\s*:\s*(\S.*)$")
#: anything that LOOKS like it wants to be a pragma — a malformed one
#: must fail loudly, not silently suppress nothing.
PRAGMA_HINT_RE = re.compile(r"#\s*analysis:")


def _comment_tokens(source: str) -> Iterator[Tuple[int, str]]:
    """(line, comment text) for every real COMMENT token — a pragma in a
    docstring or string literal is prose, not suppression."""
    import io
    import tokenize

    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


@dataclasses.dataclass
class Pragma:
    """One line-anchored reviewed exception, living in the source itself.
    Same ratchet as :class:`AllowEntry`: a pragma whose line no longer
    triggers its rule is stale and fails ``--check``."""

    rule: str
    path: str
    line: int
    reason: str
    count: int = 0

    def matches(self, f: Finding) -> bool:
        return (f.rule == self.rule and f.path == self.path
                and f.line == self.line)

    def render(self) -> str:
        return f"{self.rule} @ {self.path}:{self.line} ({self.reason})"


def extract_pragmas(source: str, relpath: str,
                    known_rules: Iterable[str]
                    ) -> Tuple[List[Pragma], List[Finding]]:
    """(pragmas, pragma-error findings) for one module's source. A
    comment matching ``# analysis:`` that is not a well-formed
    ``allow(<known-rule>): <reason>`` is an error finding — a typo'd
    pragma that silently suppressed nothing would be worse than none."""
    known = set(known_rules)
    pragmas: List[Pragma] = []
    errors: List[Finding] = []
    rel = relpath.replace(os.sep, "/")
    for lineno, text in _comment_tokens(source):
        if not PRAGMA_HINT_RE.search(text):
            continue
        m = PRAGMA_RE.search(text)
        if m is None:
            errors.append(Finding(
                rule="pragma-error", path=rel, line=lineno, col=0,
                severity="error",
                message="malformed analysis pragma — the form is "
                        "`# analysis: allow(<rule-id>): <reason>` "
                        "(the reason is mandatory)"))
            continue
        rule, reason = m.group(1), m.group(2).strip()
        if rule not in known:
            errors.append(Finding(
                rule="pragma-error", path=rel, line=lineno, col=0,
                severity="error",
                message=f"pragma names unknown rule {rule!r} "
                        f"(known: {', '.join(sorted(known))})"))
            continue
        pragmas.append(Pragma(rule=rule, path=rel, line=lineno,
                              reason=reason))
    return pragmas, errors


def apply_pragmas(findings: Iterable[Finding], pragmas: List[Pragma],
                  ran_rules: Iterable[str]) -> Tuple[
                      List[Finding], List[Tuple[Finding, Pragma]],
                      List[Pragma]]:
    """Split findings into (active, pragma-suppressed) and report stale
    pragmas — mirror of :meth:`Allowlist.apply`, line-anchored."""
    ran = set(ran_rules)
    for p in pragmas:
        p.count = 0
    active: List[Finding] = []
    suppressed: List[Tuple[Finding, Pragma]] = []
    for f in findings:
        hit = next((p for p in pragmas if p.matches(f)), None)
        if hit is not None:
            hit.count += 1
            suppressed.append((f, hit))
        else:
            active.append(f)
    stale = [p for p in pragmas if p.count == 0 and p.rule in ran]
    return active, suppressed, stale


# --------------------------------------------------------------------- #
# runner


@dataclasses.dataclass
class Report:
    """One full pass over a tree."""

    findings: List[Finding]          # active (non-suppressed)
    suppressed: List[Tuple[Finding, AllowEntry]]
    stale: List[AllowEntry]
    rules: List[str]
    files: int
    parse_errors: List[Finding]
    pragma_suppressed: List[Tuple[Finding, Pragma]] = \
        dataclasses.field(default_factory=list)
    stale_pragmas: List[Pragma] = dataclasses.field(default_factory=list)
    cache_hits: int = 0
    cache_misses: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale \
            and not self.stale_pragmas

    def findings_by_rule(self) -> Dict[str, int]:
        """Active-finding count per rule that ran (zeros included), plus
        any pseudo-rules (parse-error / pragma-error) that fired — the
        per-rule breakdown ``doctor --preflight`` reports."""
        out = {r: 0 for r in self.rules}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files": self.files,
            "rules": self.rules,
            "findings": [f.to_dict() for f in self.findings],
            "findings_by_rule": self.findings_by_rule(),
            "allowlisted": [{**f.to_dict(), "reason": e.reason}
                            for f, e in self.suppressed],
            "pragma_allowlisted": [{**f.to_dict(), "reason": p.reason}
                                   for f, p in self.pragma_suppressed],
            "stale_allowlist_entries": [
                {"rule": e.rule, "path": e.path, "symbol": e.symbol,
                 "line": e.line, "reason": e.reason}
                for e in self.stale],
            "stale_pragmas": [
                {"rule": p.rule, "path": p.path, "line": p.line,
                 "reason": p.reason}
                for p in self.stale_pragmas],
            "cache": {"hits": self.cache_hits,
                      "misses": self.cache_misses},
        }


def iter_sources(root: str = REPO_ROOT) -> Iterator[Tuple[str, str]]:
    """(abspath, relpath) for every ``.py`` under ``root``'s
    ``spatialflink_tpu`` package — the contracts govern the engine, not
    tests/benchmarks/examples."""
    pkg = os.path.join(root, "spatialflink_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                yield path, os.path.relpath(path, root)


def check_module(mod: ModuleSource,
                 rules: Optional[Sequence[Rule]] = None,
                 project=None) -> List[Finding]:
    """Run ``rules`` (default: all registered) over one parsed module.
    Without an explicit ``project`` the module is analyzed as a
    single-module project (the fixture-test mode)."""
    if project is None:
        from spatialflink_tpu.analysis.callgraph import Project

        project = Project.of_module(mod)
    out: List[Finding] = []
    for rule in (rules if rules is not None else all_rules()):
        if rule.applies_to(mod.relpath):
            out.extend(rule.check(mod, project))
    return out


def check_source(source: str, relpath: str = "spatialflink_tpu/snippet.py",
                 rules: Optional[Sequence[Rule]] = None) -> List[Finding]:
    """Fixture-test helper: run rules over a source snippet as if it
    lived at ``relpath``."""
    return check_module(ModuleSource.from_source(source, relpath), rules)


def _sha(data: str) -> str:
    return hashlib.sha256(data.encode("utf-8")).hexdigest()


def run_analysis(root: str = REPO_ROOT,
                 rule_ids: Optional[Sequence[str]] = None,
                 allowlist: Optional[str] = ALLOWLIST_PATH,
                 cache: Optional[str] = "auto") -> Report:
    """The full pass: parse every engine module under ``root``, run the
    selected rules over the shared project call graph, apply inline
    pragmas then the allowlist. ``allowlist=None`` disables file-based
    suppression (raw findings; pragmas still apply — they live in the
    sources being judged). ``cache`` is ``"auto"`` (a per-root file under
    the system temp dir), an explicit path, or None to disable."""
    from spatialflink_tpu.analysis.cache import AnalysisCache, package_hash
    from spatialflink_tpu.analysis.callgraph import Project

    rules = resolve_rules(rule_ids)
    ran_ids = [r.id for r in rules]
    raw: List[Tuple[str, str, str, str]] = []  # path, rel, source, hash
    for path, relpath in iter_sources(root):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        raw.append((path, relpath.replace(os.sep, "/"), source,
                    _sha(source)))
    files = len(raw)
    tree_hash = _sha("\n".join(f"{rel}:{h}" for _, rel, _, h in raw))
    pkg_hash = package_hash()
    cache_obj = AnalysisCache.open(root, cache)

    findings_map: Dict[Tuple[str, str], List[Finding]] = {}
    parse_map: Dict[str, List[Finding]] = {}
    needed: List[Tuple[str, Optional[Rule], str]] = []
    hits = 0
    for _, rel, _, h in raw:
        # parse status rides the cache as a pseudo-rule so a --rule
        # subset run still reports syntax errors in out-of-scope modules
        pkey = f"{h}:{pkg_hash}"
        got = cache_obj.get(rel, "__parse__", pkey) if cache_obj else None
        if got is None:
            needed.append((rel, None, pkey))
        else:
            hits += 1
            parse_map[rel] = [Finding.from_dict(d) for d in got]
        for rule in rules:
            if not rule.applies_to(rel):
                continue
            key = pkey if not rule.interprocedural \
                else f"{pkey}:{tree_hash}"
            got = cache_obj.get(rel, rule.id, key) if cache_obj else None
            if got is None:
                needed.append((rel, rule, key))
            else:
                hits += 1
                findings_map[(rel, rule.id)] = [Finding.from_dict(d)
                                                for d in got]

    if needed:
        mods: Dict[str, ModuleSource] = {}
        for path, rel, source, _ in raw:
            try:
                mods[rel] = ModuleSource(path, rel, source)
            except SyntaxError as e:
                parse_map[rel] = [Finding(
                    rule="parse-error", path=rel,
                    line=e.lineno or 0, col=e.offset or 0,
                    severity="error", message=f"syntax error: {e.msg}")]
            else:
                parse_map.setdefault(rel, [])
        project = Project(list(mods.values()))
        for rel, rule, key in needed:
            if rule is None:
                if cache_obj is not None:
                    cache_obj.put(rel, "__parse__", key,
                                  [f.to_dict()
                                   for f in parse_map.get(rel, [])])
                continue
            mod = mods.get(rel)
            if mod is None:  # unparseable: the parse-error finding gates
                continue
            fs = list(rule.check(mod, project))
            findings_map[(rel, rule.id)] = fs
            if cache_obj is not None:
                cache_obj.put(rel, rule.id, key,
                              [f.to_dict() for f in fs])
        if cache_obj is not None:
            cache_obj.save()
    parse_errors = [f for fs in parse_map.values() for f in fs]
    parse_errors.sort(key=lambda f: (f.path, f.line))

    findings = [f for fs in findings_map.values() for f in fs]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    pragmas: List[Pragma] = []
    pragma_errors: List[Finding] = []
    for _, rel, source, _ in raw:
        ps, errs = extract_pragmas(source, rel, RULES)
        pragmas.extend(ps)
        pragma_errors.extend(errs)
    findings, pragma_suppressed, stale_pragmas = apply_pragmas(
        findings, pragmas, ran_ids)

    al = Allowlist.load(allowlist) if allowlist else Allowlist([])
    active, suppressed, stale = al.apply(findings, ran_ids)
    active = parse_errors + pragma_errors + active
    return Report(findings=active, suppressed=suppressed, stale=stale,
                  rules=ran_ids, files=files, parse_errors=parse_errors,
                  pragma_suppressed=pragma_suppressed,
                  stale_pragmas=stale_pragmas,
                  cache_hits=hits, cache_misses=len(needed))
