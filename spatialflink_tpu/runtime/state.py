"""Keyed operator state with explicit snapshot/restore.

The reference leans on Flink managed state (``ValueState``/``MapState``/
``ListState``) and would get checkpointing from Flink if it were configured
(SURVEY §5: it never is). Here host-side operator state is explicit and
snapshot-able: device state pytrees hop to host numpy for serialization, and
:meth:`CheckpointableState.save` / :meth:`load` round-trip through a single
``.npz`` file — the rebuild's checkpoint/resume story.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np


class CheckpointableState:
    """A named bag of numpy/jax arrays + JSON-able metadata."""

    def __init__(self):
        self.arrays: Dict[str, Any] = {}
        self.meta: Dict[str, Any] = {}

    def save(self, path: str) -> None:
        """Atomic write: a crash mid-save never corrupts the previous
        checkpoint (tmp file + rename)."""
        host = {k: np.asarray(v) for k, v in self.arrays.items()}
        tmp = f"{path}.tmp"
        with open(tmp, "wb") as f:
            np.savez(f, __meta__=json.dumps(self.meta), **host)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        # make the rename itself durable across power loss
        dir_fd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    @classmethod
    def load(cls, path: str) -> "CheckpointableState":
        out = cls()
        with np.load(path, allow_pickle=False) as z:
            for k in z.files:
                if k == "__meta__":
                    out.meta = json.loads(str(z[k]))
                else:
                    out.arrays[k] = z[k]
        return out


def checkpoint_consumed(path: str) -> int:
    """Resume offset recorded in a checkpoint (0 if none/absent) — the number
    of source records already reflected in the saved state. Reads only the
    meta entry (np.load on an npz is lazy per-array), not the state arrays."""
    if not os.path.exists(path):
        return 0
    with np.load(path, allow_pickle=False) as z:
        if "__meta__" not in z.files:
            return 0
        meta = json.loads(str(z["__meta__"]))
    return int(meta.get("consumed", 0))


class TrajStateStore:
    """Host wrapper around a device :class:`TrajStatsState` that grows with
    the interner and snapshots to disk."""

    def __init__(self, capacity: int = 256):
        from spatialflink_tpu.ops.trajectory import TrajStatsState

        self.capacity = capacity
        self.state = TrajStatsState.zeros(capacity)

    def ensure(self, min_capacity: int) -> None:
        """Grow (power-of-two) so new interned object ids fit."""
        if min_capacity <= self.capacity:
            return
        from spatialflink_tpu.ops.trajectory import TrajStatsState
        from spatialflink_tpu.utils import bucket_size

        new_cap = bucket_size(min_capacity, self.capacity * 2)
        old = self.state
        grown = TrajStatsState.zeros(new_cap)
        import jax.numpy as jnp

        self.state = TrajStatsState(
            *(g.at[: self.capacity].set(o) for g, o in zip(grown, old))
        )
        self.capacity = new_cap

    def rebase_ts(self, delta_ms: int) -> None:
        """Shift carried ``last_ts`` offsets when the caller moves the batch
        ``ts_base`` forward by ``delta_ms`` — keeps int32 offsets small over
        an unbounded realtime run instead of wrapping after ~24.8 days.
        Entries dormant beyond ~12.4 days clamp to a "very old" floor (any
        new timestamp still compares newer; the next gap's temporal
        contribution saturates at the floor); the uninitialized sentinel is
        kept. The floor is -(2^30) rather than the int32 min so downstream
        subtraction cannot wrap."""
        if delta_ms == 0:
            return
        import jax.numpy as jnp

        from spatialflink_tpu.ops.trajectory import INT32_MIN

        # int32-safe saturating subtraction (int64 is unavailable without
        # jax_enable_x64): thresholds are computed host-side so the device
        # subtraction provably cannot wrap.
        # floor at -(2^30)+1: together with the operators' 2^30 batch-span
        # cap, |ts - last_ts| stays < 2^31 so the kernel's int32 delta is
        # exact (see ops.trajectory.tstats_update)
        floor, imax = -(2**30) + 1, 2**31 - 1
        lt = self.state.last_ts
        if delta_ms >= 2**31:
            shifted = jnp.full_like(lt, floor)
        elif delta_ms <= -(2**31):
            shifted = jnp.full_like(lt, imax)
        elif delta_ms > 0:
            thr = jnp.int32(floor + delta_ms)
            shifted = jnp.where(lt < thr, jnp.int32(floor),
                                lt - jnp.int32(delta_ms))
        else:
            thr = jnp.int32(imax + delta_ms)
            shifted = jnp.where(lt > thr, jnp.int32(imax),
                                lt - jnp.int32(delta_ms))
        self.state = self.state._replace(
            last_ts=jnp.where(lt != INT32_MIN, shifted, lt)
        )

    def snapshot(self) -> CheckpointableState:
        cp = CheckpointableState()
        cp.meta["capacity"] = self.capacity
        for name, arr in self.state._asdict().items():
            cp.arrays[name] = arr
        return cp

    @classmethod
    def restore(cls, cp: CheckpointableState) -> "TrajStateStore":
        from spatialflink_tpu.ops.trajectory import TrajStatsState
        import jax.numpy as jnp

        store = cls(capacity=int(cp.meta["capacity"]))
        # jnp.array (copy) rather than jnp.asarray: the restored state is
        # DONATED on the first tstats_update, and asarray may zero-copy
        # alias the checkpoint's numpy buffers on CPU — donation would then
        # free memory numpy still owns (observed as nondeterministic heap
        # corruption/aborts on the first post-restore update)
        store.state = TrajStatsState(
            **{k: jnp.array(v) for k, v in cp.arrays.items()}
        )
        return store
