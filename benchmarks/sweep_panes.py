"""Window-size scaling sweep for the pane-incremental engine (VERDICT #4):
kNN and join throughput at growing stream sizes and sliding overlaps
(window = overlap * slide), panes on vs off, window-table identity asserted
per configuration.

- kNN rides the bulk windowed pipeline (parse once per size, outside the
  timed region — the stage panes optimize is window assembly + kernels).
- join rides the record-path windowed pipeline (pane-pair blocks are a
  record-path feature); its stream sizes default to 1/16 of the kNN sizes
  because the O(Na x Nb) pair lattice, not the pane engine, dominates
  large CPU joins.

Usage:
    python benchmarks/sweep_panes.py [--sizes 1000000,4000000,16000000]
        [--overlaps 1,4,8] [--families knn,join] [--join-divisor 16]
        [--out PATH]

Emits one JSON line per (family, size, overlap, panes) and writes the
table to ``benchmarks/RESULTS_panes_<backend>.json`` — the BASELINE.md
pane-scaling ledger's source. Overlap 1 is the tumbling control: the pane
cache bypasses (overlap 1 shares nothing), so on/off rows there should
measure noise, not speedup.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.bench_e2e import SLIDE_S, _params, _window_table, _write_stream


def _canon_pairs(results) -> list:
    return [(r.window_start, r.window_end,
             sorted(((a.obj_id, a.timestamp), (b.obj_id, b.timestamp))
                    for a, b in r.records))
            for r in results]


def sweep_knn(path: str, n: int, overlaps, rows: list, backend: str) -> None:
    from spatialflink_tpu import driver

    p = _params(51)
    parsed = driver._bulk_parse_stream(p.input1, path,
                                       p.query.allowed_lateness_s)
    u_grid, _ = p.grids()
    spec = driver.CASES[51]
    q = driver._query_object(p, u_grid, "Point")

    for overlap in overlaps:
        p.window.interval_s = SLIDE_S * overlap
        p.window.step_s = SLIDE_S

        def run(panes: bool):
            p.query.panes = panes
            conf = driver._query_conf(p, spec)
            op = driver._operator_class(spec)(conf, u_grid)
            t0 = time.perf_counter()
            table = _window_table(
                op.run_bulk(parsed, q, p.query.radius, p.query.k), 51)
            return table, time.perf_counter() - t0

        run(False)  # warm BOTH modes' jit shapes outside the timed rows
        run(True)   # (full-window buckets differ per overlap; pane shapes too)
        t_off, dt_off = run(False)
        t_on, dt_on = run(True)
        assert t_on == t_off, f"knn n={n} overlap={overlap}: table diverged"
        for panes, dt in (("off", dt_off), ("on", dt_on)):
            row = dict(family="knn", records=n, overlap=overlap, panes=panes,
                       windows=len(t_off), wall_s=round(dt, 3),
                       records_per_sec=round(n / dt), identical=True,
                       backend=backend)
            if panes == "on":
                row["speedup_vs_panes_off"] = round(dt_off / dt_on, 2)
            print(json.dumps(row), flush=True)
            rows.append(row)


def sweep_join(path: str, path2: str, n: int, overlaps, rows: list,
               backend: str) -> None:
    from spatialflink_tpu import driver
    from spatialflink_tpu.operators import PointPointJoinQuery
    from spatialflink_tpu.streams.bulk import bulk_parse_csv

    p = _params(101)
    # sparse-join radius: at bench_e2e's r=0.5 over this extent ~23% of all
    # pairs survive, so O(survivor) host pair materialization — identical in
    # both modes — swamps the lattice kernels the pane blocks reuse. 0.05
    # is the realistic-selectivity regime where the lattice dominates.
    p.query.radius = 0.05
    u_grid, _ = p.grids()
    schema = driver._schema4(p.input1)
    with open(path, "rb") as f:
        pts_a = bulk_parse_csv(f.read(), schema=schema,
                               date_format=None).to_points(u_grid)
    with open(path2, "rb") as f:
        pts_b = bulk_parse_csv(f.read(), schema=schema,
                               date_format=None).to_points(u_grid)

    for overlap in overlaps:
        p.window.interval_s = SLIDE_S * overlap
        p.window.step_s = SLIDE_S

        def run(panes: bool):
            p.query.panes = panes
            conf = driver._query_conf(p, driver.CASES[101])
            op = PointPointJoinQuery(conf, u_grid, u_grid)
            t0 = time.perf_counter()
            table = _canon_pairs(op.run(iter(pts_a), iter(pts_b),
                                        p.query.radius))
            return table, time.perf_counter() - t0

        run(False)  # warm both modes outside the timed rows
        run(True)
        t_off, dt_off = run(False)
        t_on, dt_on = run(True)
        assert t_on == t_off, f"join n={n} overlap={overlap}: table diverged"
        for panes, dt in (("off", dt_off), ("on", dt_on)):
            row = dict(family="join", records=n, overlap=overlap,
                       panes=panes, windows=len(t_off), wall_s=round(dt, 3),
                       records_per_sec=round(n / dt), identical=True,
                       backend=backend)
            if panes == "on":
                row["speedup_vs_panes_off"] = round(dt_off / dt_on, 2)
            print(json.dumps(row), flush=True)
            rows.append(row)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="1000000,4000000,16000000",
                    help="comma-separated stream sizes (kNN; join divides "
                         "by --join-divisor)")
    ap.add_argument("--overlaps", default="1,4,8")
    ap.add_argument("--families", default="knn,join")
    ap.add_argument("--join-divisor", type=int, default=16,
                    help="join stream size = size // divisor (the pair "
                         "lattice, not the pane engine, dominates large "
                         "CPU joins)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from benchmarks._common import settle_backend

    settle_backend()
    import jax

    backend = jax.default_backend()
    sizes = [int(x) for x in args.sizes.split(",")]
    overlaps = [int(x) for x in args.overlaps.split(",")]
    families = args.families.split(",")

    rows: list = []
    with tempfile.TemporaryDirectory() as td:
        for n in sizes:
            path = os.path.join(td, f"s{n}.csv")
            _write_stream(path, n, seed=0)
            if "knn" in families:
                sweep_knn(path, n, overlaps, rows, backend)
            if "join" in families:
                nj = max(n // args.join_divisor, 1)
                pj = os.path.join(td, f"j{nj}.csv")
                pj2 = os.path.join(td, f"j2{nj}.csv")
                _write_stream(pj, nj, seed=0)
                _write_stream(pj2, max(nj // 64, 1), seed=1)
                sweep_join(pj, pj2, nj, overlaps, rows, backend)
            os.unlink(path)

    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"RESULTS_panes_{backend}.json")
    with open(out, "w") as f:
        json.dump({"backend": backend, "rows": rows}, f, indent=1)
    print(f"# wrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
