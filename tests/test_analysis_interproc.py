"""Interprocedural dataflow layer of the invariant linter (PR 15).

Four families of tests, each proving a *depth* upgrade over the PR 12
lexical rules — every "bad" fixture here is one the lexical version
provably missed (or wrongly flagged), asserted as regression fixtures:

- **call graph** — the three resolution rules (module-level names with
  shadowing, self-methods, by-name callbacks) and cross-module import
  resolution;
- **deep rules** — lockset thread-shared-state, field-level
  checkpoint-coverage, taint-through-helpers host-sync, and the new
  recompile-surface proof;
- **suppression** — the inline ``# analysis: allow(rule): reason``
  pragma lifecycle (suppress + stale ratchet + malformed errors) next
  to the ALLOWLIST.toml one;
- **infrastructure** — SARIF output, the per-module findings cache
  (byte-identical + measurably faster warm pass), per-rule counts in
  ``doctor --preflight``.
"""

import ast
import json
import textwrap
import time

import pytest

from spatialflink_tpu.analysis import check_source, run_analysis
from spatialflink_tpu.analysis.callgraph import ModuleGraph, Project
from spatialflink_tpu.analysis.core import REPO_ROOT, ModuleSource
from spatialflink_tpu.analysis import dataflow

pytestmark = pytest.mark.analysis


def _ids(findings):
    return [f.rule for f in findings]


def _mod(source, relpath="spatialflink_tpu/utils/x.py"):
    return ModuleSource.from_source(textwrap.dedent(source), relpath)


def _calls_of(graph, name):
    return [s for s in graph.calls if s.callee.name == name]


# --------------------------------------------------------------------- #
# call-graph resolution rules


class TestCallGraphResolution:
    def test_module_level_name_resolves(self):
        g = ModuleGraph(_mod("""
            def helper():
                return 1

            def main():
                return helper()
            """))
        sites = _calls_of(g, "helper")
        assert len(sites) == 1
        assert sites[0].kind == "direct"
        assert sites[0].caller.name == "main"

    def test_import_after_def_shadows(self):
        """Last top-level binding wins: an import below the def re-binds
        the name, so the call must NOT resolve to the local def."""
        g = ModuleGraph(_mod("""
            def helper():
                return 1

            from os.path import join as helper

            def main():
                return helper()
            """))
        assert not _calls_of(g, "helper")

    def test_def_after_import_shadows_import(self):
        g = ModuleGraph(_mod("""
            from os.path import join as helper

            def helper():
                return 1

            def main():
                return helper()
            """))
        assert len(_calls_of(g, "helper")) == 1

    def test_local_rebinding_shadows(self):
        """A function-local assignment of the name hides the module
        function for calls inside that function."""
        g = ModuleGraph(_mod("""
            def helper():
                return 1

            def main(helper):
                return helper()
            """))
        assert not _calls_of(g, "helper")

    def test_self_method_edge(self):
        g = ModuleGraph(_mod("""
            class C:
                def a(self):
                    return self.b()

                def b(self):
                    return 1
            """))
        sites = _calls_of(g, "b")
        assert len(sites) == 1
        assert sites[0].kind == "self"
        assert sites[0].callee.qualname == "C.b"
        assert sites[0].caller.qualname == "C.a"

    def test_by_name_callback_edge_is_deferred(self):
        g = ModuleGraph(_mod("""
            import threading

            class C:
                def _loop(self):
                    return 1

                def start(self):
                    return threading.Thread(target=self._loop)
            """))
        sites = _calls_of(g, "_loop")
        assert len(sites) == 1
        assert sites[0].kind == "by-name" and sites[0].deferred

    def test_cross_module_from_import(self, tmp_path):
        pkg = tmp_path / "spatialflink_tpu"
        (pkg / "ops").mkdir(parents=True)
        (pkg / "ops" / "k.py").write_text(
            "from spatialflink_tpu.utils.deviceplane import "
            "instrumented_jit\n\n"
            "@instrumented_jit\ndef kernel(x):\n    return x\n")
        (pkg / "ops" / "u.py").write_text(
            "from spatialflink_tpu.ops.k import kernel\n\n"
            "def use(b):\n    return kernel(b)\n")
        mods = [ModuleSource(str(pkg / "ops" / n),
                             f"spatialflink_tpu/ops/{n}",
                             (pkg / "ops" / n).read_text())
                for n in ("k.py", "u.py")]
        proj = Project(mods)
        use_mod = mods[1]
        call = next(n for n in ast.walk(use_mod.tree)
                    if isinstance(n, ast.Call)
                    and getattr(n.func, "id", "") == "kernel")
        info = proj.resolve_call(use_mod, call)
        assert info is not None and info.is_kernel
        assert info.module == "spatialflink_tpu/ops/k.py"

    def test_module_alias_attribute_call(self):
        mod = _mod("""
            def helper():
                return 1
            """, "spatialflink_tpu/ops/a.py")
        user = _mod("""
            import spatialflink_tpu.ops.a as A

            def main():
                return A.helper()
            """, "spatialflink_tpu/ops/b.py")
        proj = Project([mod, user])
        call = next(n for n in ast.walk(user.tree)
                    if isinstance(n, ast.Call))
        info = proj.resolve_call(user, call)
        assert info is not None and info.name == "helper"


# --------------------------------------------------------------------- #
# deep rule 1: lockset thread-shared-state


LOCKED_CLASS = """
    import threading

    class Ring:
        def __init__(self):
            self._lock = threading.Lock()
            self.total = 0

        def append(self, ev):
            with self._lock:
                self._bump()

        def _bump(self):
            self.total += 1
    """


class TestLocksetRule:
    SCOPE = "spatialflink_tpu/utils/x.py"

    def _check(self, src):
        return [f for f in check_source(textwrap.dedent(src), self.SCOPE)
                if f.rule == "thread-shared-state"]

    def test_helper_reached_only_under_lock_is_clean(self):
        """PR 12 flagged this (write not lexically under `with`); the
        lockset proves every call site holds the lock."""
        assert not self._check(LOCKED_CLASS)

    def test_helper_with_one_unlocked_site_is_flagged(self):
        fs = self._check(LOCKED_CLASS + """
        def poke(self):
            self._bump()
    """)
        assert fs and "unlocked path" in fs[0].message

    def test_two_hop_lock_inference(self):
        """_outer is locked at its only site; _bump is called only from
        _outer — the fixpoint proves both."""
        assert not self._check("""
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()

                def append(self, ev):
                    with self._lock:
                        self._outer()

                def _outer(self):
                    self._bump()

                def _bump(self):
                    self.total = 1
            """)

    def test_public_method_never_inferred(self):
        """A public method's writes need the lexical lock even if every
        intra-class call site holds it — external callers exist."""
        fs = self._check("""
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()

                def drive(self):
                    with self._lock:
                        self.bump()

                def bump(self):
                    self.total = 1
            """)
        assert fs and "self.total" in fs[0].message

    def test_locked_suffix_called_from_unlocked_path(self):
        """THE bug PR 12 provably missed: _locked methods were exempt
        from the write check AND nobody audited their call sites."""
        fs = self._check("""
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()

                def _flush_locked(self):
                    self.total = 0

                def flush(self):
                    self._flush_locked()
            """)
        assert fs and "caller-locked" in fs[0].message
        # regression half: the lexical write-check alone sees nothing
        # here (the only write sits in an exempt _locked method)
        assert all("caller-locked" in f.message for f in fs)

    def test_locked_suffix_called_under_lock_is_clean(self):
        assert not self._check("""
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()

                def _flush_locked(self):
                    self.total = 0

                def flush(self):
                    with self._lock:
                        self._flush_locked()
            """)

    def test_by_name_reference_never_counts_as_locked(self):
        """Passing self._loop by name (a thread target) runs it later
        without the with-block — the helper stays unlocked even though
        the reference site holds the lock."""
        fs = self._check("""
            import threading

            class Ring:
                def __init__(self):
                    self._lock = threading.Lock()

                def _loop(self):
                    self.total = 1

                def start(self):
                    with self._lock:
                        self.t = threading.Thread(target=self._loop)
            """)
        assert any("self.total" in f.message for f in fs)


# --------------------------------------------------------------------- #
# deep rule 2: field-level checkpoint coverage


PAIRED = """
    class Assembler:
        def __init__(self):
            self.windows = {}
            self.pane_ring = []

        def add(self, rec):
            self.windows[rec.key] = rec
            self.pane_ring.append(rec)

        def snapshot(self, encode):
            return {"windows": dict(self.windows),
                    "panes": list(self.pane_ring)}

        def restore(self, state, decode):
            self.windows = dict(state["windows"])
            self.pane_ring = list(state["panes"])
    """


class TestFieldCoverage:
    SCOPE = "spatialflink_tpu/runtime/x.py"

    def _check(self, src):
        return [f for f in check_source(textwrap.dedent(src), self.SCOPE)
                if f.rule == "checkpoint-coverage"]

    def test_covered_pair_is_clean(self):
        assert not self._check(PAIRED)

    def test_forgotten_pane_ring_in_snapshot(self):
        """THE bug PR 12 provably missed: the pair exists, but the new
        pane ring never made it into snapshot() — the lexical rule only
        checked method presence."""
        src = PAIRED.replace(',\n                    "panes": '
                             'list(self.pane_ring)', '')
        fs = self._check(src)
        assert fs and "pane_ring" in fs[0].message
        assert "never read in snapshot()" in fs[0].message

    def test_forgotten_field_in_restore(self):
        src = PAIRED.replace(
            "            self.pane_ring = list(state[\"panes\"])\n", "")
        fs = self._check(src)
        assert fs and "never assigned in restore()" in fs[0].message

    def test_container_mutation_counts_as_state_write(self):
        """`self.windows[k] = v` / `.append` made the class look
        stateless to PR 12's attr-assign detector."""
        fs = self._check("""
            class Grower:
                def __init__(self):
                    self.windows = {}

                def add(self, rec):
                    self.windows[rec.key] = rec
            """)
        assert fs and "lacks snapshot and restore" in fs[0].message

    def test_snapshot_via_helper_counts(self):
        """snapshot() delegating to a self-method still covers the field
        (call-graph reach, depth 3)."""
        assert not self._check("""
            class Assembler:
                def __init__(self):
                    self.windows = {}

                def add(self, rec):
                    self.windows[rec.key] = rec

                def _encode_windows(self):
                    return dict(self.windows)

                def snapshot(self, encode):
                    return {"windows": self._encode_windows()}

                def restore(self, state, decode):
                    self.windows = dict(state["windows"])
            """)

    def test_classmethod_restore_is_exempt(self):
        """Constructor-style restore (TrajStateStore idiom) builds a
        fresh instance — field flow through cls(...) is a documented
        blind spot, not a finding."""
        assert not self._check("""
            class Store:
                def __init__(self):
                    self.offsets = {}

                def add(self, rec):
                    self.offsets[rec.p] = rec.o

                def snapshot(self):
                    return {"offsets": dict(self.offsets)}

                @classmethod
                def restore(cls, state):
                    st = cls()
                    return st
            """)

    def test_queryplane_registry_state_in_scope(self):
        """The _STATE_PAT fix: fleet/entries/specs/staged attrs (mutated
        via container ops) now require the pair — PR 12 grandfathered
        the whole query plane."""
        fs = self._check("""
            class Registry:
                def __init__(self):
                    self._fleet = []
                    self._entries = {}

                def admit(self, q):
                    self._entries[q.id] = q
                    self._fleet.append(q.id)
            """)
        assert fs
        msg = fs[0].message
        assert "_fleet" in msg and "_entries" in msg

    def test_dict_update_restore_covers_everything(self):
        assert not self._check("""
            class Assembler:
                def __init__(self):
                    self.windows = {}

                def add(self, rec):
                    self.windows[rec.key] = rec

                def snapshot(self, encode):
                    return dict(self.__dict__)

                def restore(self, state, decode):
                    self.__dict__.update(state)
            """)


# --------------------------------------------------------------------- #
# deep rule 3: host-sync taint through helpers


class TestHostSyncTaint:
    SCOPE = "spatialflink_tpu/ops/x.py"

    def _check(self, src):
        return [f for f in check_source(textwrap.dedent(src), self.SCOPE)
                if f.rule == "host-sync"]

    def test_float_of_jax_returning_helper(self):
        """THE flow PR 12 provably missed: float()'s argument is
        lexically a plain call, but _total returns jnp.sum(x)."""
        fs = self._check("""
            import jax.numpy as jnp

            def _total(x):
                return jnp.sum(x)

            def dispatch(x):
                return float(_total(x))
            """)
        assert fs and "float()" in fs[0].message

    def test_two_level_helper_chain(self):
        fs = self._check("""
            import jax.numpy as jnp

            def _inner(x):
                return jnp.sum(x)

            def _outer(x):
                return _inner(x)

            def dispatch(x):
                return float(_outer(x))
            """)
        assert fs and "float()" in fs[0].message

    def test_jax_value_into_helper_sink_param(self):
        """The other direction: the float() hides inside the helper; the
        call site feeding it a jax value is the finding."""
        fs = self._check("""
            import jax.numpy as jnp

            def _log(v, out):
                out.append(float(v))

            def dispatch(x, out):
                _log(jnp.sum(x), out)
            """)
        assert fs and "_log" in fs[0].message and "parameter 'v'" \
            in fs[0].message

    def test_host_helper_return_is_clean(self):
        assert not self._check("""
            def _total(xs):
                return sum(xs)

            def dispatch(xs):
                return float(_total(xs))
            """)

    def test_seam_helper_sink_param_is_clean(self):
        """A collect*/_defer*/*_host helper IS the accounted readback
        seam — feeding it jax values is the design, not a leak."""
        assert not self._check("""
            import jax.numpy as jnp

            def collect_total(v):
                return float(v)

            def finish(x):
                return collect_total(jnp.sum(x))
            """)

    def test_sink_call_inside_seam_function_is_clean(self):
        assert not self._check("""
            import jax.numpy as jnp

            def _total(x):
                return jnp.sum(x)

            def merge_host(x):
                return float(_total(x))
            """)


# --------------------------------------------------------------------- #
# deep rule 4 (new): recompile-surface


KERNEL_PREAMBLE = """
    from functools import partial
    from spatialflink_tpu.utils.deviceplane import instrumented_jit
    from spatialflink_tpu.utils.padding import bucket_size

    @partial(instrumented_jit, static_argnames=("n",))
    def kernel(x, n):
        return x[:n]
    """


class TestRecompileSurface:
    SCOPE = "spatialflink_tpu/ops/x.py"

    def _check(self, body, scope=None):
        src = textwrap.dedent(KERNEL_PREAMBLE) + textwrap.dedent(body)
        return [f for f in check_source(src, scope or self.SCOPE)
                if f.rule == "recompile-surface"]

    def test_raw_len_static_is_flagged(self):
        """The deliberately unbucketed kernel call of the acceptance
        bar: n follows the record count, so every distinct chunk size
        compiles a fresh XLA program. Invisible to every PR 12 rule."""
        fs = self._check("""
            def dispatch(records, batch):
                return kernel(batch, n=len(records))
            """)
        assert fs and "data-dependent (len(...))" in fs[0].message

    def test_bucketed_len_is_clean(self):
        assert not self._check("""
            def dispatch(records, batch):
                return kernel(batch, n=bucket_size(len(records)))
            """)

    def test_shape_read_static_is_flagged(self):
        fs = self._check("""
            def dispatch(records, batch):
                return kernel(batch, n=batch.shape[0])
            """)
        assert fs and ".shape" in fs[0].message

    def test_taint_through_local_name(self):
        fs = self._check("""
            def dispatch(records, batch):
                m = len(records)
                return kernel(batch, n=m)
            """)
        assert fs

    def test_bucketed_local_name_is_clean(self):
        assert not self._check("""
            def dispatch(records, batch):
                m = bucket_size(len(records))
                return kernel(batch, n=m)
            """)

    def test_caller_param_is_contract(self):
        """A static fed from the enclosing function's parameter hoists
        the obligation to the caller (the repo's `k=k` idiom)."""
        assert not self._check("""
            def dispatch(batch, n):
                return kernel(batch, n=n)
            """)

    def test_run_constant_attribute_is_clean(self):
        assert not self._check("""
            def dispatch(self_like, batch):
                return kernel(batch, n=self_like.grid.n)
            """)

    def test_mode_flag_statics_are_not_shape(self):
        """strategy/approximate-style statics take a few fixed values —
        only size-like names are churn surface."""
        src = """
            from functools import partial
            from spatialflink_tpu.utils.deviceplane import instrumented_jit

            @partial(instrumented_jit, static_argnames=("strategy",))
            def kernel2(x, strategy):
                return x

            def dispatch(batch, conf):
                return kernel2(batch, strategy=conf.pick())
            """
        fs = [f for f in check_source(textwrap.dedent(src), self.SCOPE)
              if f.rule == "recompile-surface"]
        assert not fs

    def test_cross_module_call_site(self, tmp_path):
        """Kernel in ops/, unbucketed call in operators/ — only the
        project-wide graph can see it; injected via run_analysis."""
        pkg = tmp_path / "spatialflink_tpu"
        (pkg / "ops").mkdir(parents=True)
        (pkg / "operators").mkdir(parents=True)
        (pkg / "ops" / "k.py").write_text(textwrap.dedent("""
            from functools import partial
            from spatialflink_tpu.utils.deviceplane import instrumented_jit

            @partial(instrumented_jit, static_argnames=("n",))
            def kernel(x, n):
                return x[:n]
            """))
        (pkg / "operators" / "u.py").write_text(textwrap.dedent("""
            from spatialflink_tpu.ops.k import kernel

            def evaluate(records, batch):
                return kernel(batch, n=len(records))
            """))
        report = run_analysis(root=str(tmp_path), allowlist=None,
                              cache=None,
                              rule_ids=["recompile-surface"])
        assert [f.rule for f in report.findings] == ["recompile-surface"]
        assert report.findings[0].path == "spatialflink_tpu/operators/u.py"

    def test_real_tree_is_clean_for_recompile_surface(self):
        report = run_analysis(rule_ids=["recompile-surface"],
                              allowlist=None, cache=None)
        assert not report.findings, \
            "\n".join(f.render() for f in report.findings)


# --------------------------------------------------------------------- #
# dataflow unit coverage


class TestDataflowCores:
    def test_jax_returning_depth(self):
        g = ModuleGraph(_mod("""
            import jax.numpy as jnp

            def a(x):
                return jnp.sum(x)

            def b(x):
                return a(x)

            def c(xs):
                return sum(xs)
            """))
        fns = dataflow.jax_returning(g)
        assert {"a", "b"} <= fns and "c" not in fns

    def test_sink_params_transitive(self):
        g = ModuleGraph(_mod("""
            def inner(v):
                return float(v)

            def outer(w):
                return inner(w)
            """))
        sinks = dataflow.sink_params(g)
        assert sinks["inner"] == {"v"} and sinks["outer"] == {"w"}


# --------------------------------------------------------------------- #
# inline pragma lifecycle (the line-anchored ratchet)


def _tree(tmp_path, source, name="streams/bad.py"):
    target = tmp_path / "spatialflink_tpu" / name
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(source))
    return str(tmp_path)


UNGATED = """
    from spatialflink_tpu.utils import telemetry as _t


    def drive(stream):
        tel = _t.active()
        tel.observe('ingest', 1.0){pragma}
    """


class TestPragmaLifecycle:
    def test_pragma_suppresses_on_its_line(self, tmp_path):
        root = _tree(tmp_path, UNGATED.format(
            pragma="  # analysis: allow(telemetry-gating): fixture —"
                   " reviewed, gate lives one frame up"))
        report = run_analysis(root=root, allowlist=None, cache=None)
        assert report.ok
        assert len(report.pragma_suppressed) == 1
        f, p = report.pragma_suppressed[0]
        assert f.rule == "telemetry-gating"
        assert "reviewed" in p.reason

    def test_pragma_on_wrong_line_does_not_suppress(self, tmp_path):
        root = _tree(tmp_path, UNGATED.format(pragma="") +
                     "# analysis: allow(telemetry-gating): wrong line\n")
        report = run_analysis(root=root, allowlist=None, cache=None)
        assert not report.ok
        rules = {f.rule for f in report.findings}
        assert "telemetry-gating" in rules
        assert report.stale_pragmas  # and the pragma itself is stale

    def test_stale_pragma_fails_check(self, tmp_path):
        """The ratchet: fix the finding, the pragma must go too."""
        root = _tree(tmp_path,
                     "X = 1  # analysis: allow(telemetry-gating): "
                     "obsolete exception\n")
        report = run_analysis(root=root, allowlist=None, cache=None)
        assert not report.ok and len(report.stale_pragmas) == 1

        from spatialflink_tpu.analysis.cli import main
        import io

        out = io.StringIO()
        rc = main(["--root", root, "--allowlist", "none", "--no-cache",
                   "--check"], out=out)
        assert rc == 1
        assert "remove stale pragma" in out.getvalue()

    def test_stale_only_judged_for_rules_that_ran(self, tmp_path):
        root = _tree(tmp_path,
                     "X = 1  # analysis: allow(telemetry-gating): "
                     "entry for a rule not in this run\n")
        report = run_analysis(root=root, rule_ids=["host-sync"],
                              allowlist=None, cache=None)
        assert report.ok

    def test_malformed_pragma_is_an_error(self, tmp_path):
        root = _tree(tmp_path,
                     "X = 1  # analysis: allow(telemetry-gating)\n")
        report = run_analysis(root=root, allowlist=None, cache=None)
        assert any(f.rule == "pragma-error"
                   and "malformed" in f.message
                   for f in report.findings)

    def test_unknown_rule_pragma_is_an_error(self, tmp_path):
        root = _tree(tmp_path,
                     "X = 1  # analysis: allow(no-such-rule): why\n")
        report = run_analysis(root=root, allowlist=None, cache=None)
        assert any(f.rule == "pragma-error"
                   and "unknown rule" in f.message
                   for f in report.findings)

    def test_pragma_text_in_docstring_is_prose(self, tmp_path):
        root = _tree(tmp_path, '''
            """Docs may say `# analysis: allow(telemetry-gating): x`
            without creating a suppression."""

            X = 1
            ''')
        report = run_analysis(root=root, allowlist=None, cache=None)
        assert report.ok and not report.stale_pragmas


# --------------------------------------------------------------------- #
# SARIF output


class TestSarif:
    def _run(self, *args):
        from spatialflink_tpu.analysis.cli import main
        import io

        out = io.StringIO()
        rc = main(list(args), out=out)
        return rc, out.getvalue()

    def test_sarif_schema_on_real_tree(self):
        rc, out = self._run("--format", "sarif")
        assert rc == 0
        doc = json.loads(out)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        driver = run["tool"]["driver"]
        assert driver["name"] == "spatialflink-analysis"
        rule_ids = {r["id"] for r in driver["rules"]}
        assert "recompile-surface" in rule_ids
        # the clean tree still ships its allowlisted findings, marked
        # suppressed, so CI viewers can render the reviewed exceptions
        assert all("suppressions" in r for r in run["results"])
        assert any(s["kind"] == "external"
                   for r in run["results"] for s in r["suppressions"])

    def test_sarif_results_carry_locations(self, tmp_path):
        root = _tree(tmp_path, UNGATED.format(pragma=""))
        rc, out = self._run("--root", root, "--allowlist", "none",
                            "--no-cache", "--format", "sarif")
        doc = json.loads(out)
        results = doc["runs"][0]["results"]
        assert results
        loc = results[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] \
            == "spatialflink_tpu/streams/bad.py"
        assert loc["region"]["startLine"] >= 1
        assert results[0]["level"] in ("error", "warning")


# --------------------------------------------------------------------- #
# per-module findings cache


class TestAnalysisCache:
    def test_warm_pass_is_identical_and_faster(self, tmp_path):
        cache = str(tmp_path / "cache.json")
        t0 = time.perf_counter()
        cold = run_analysis(root=REPO_ROOT, allowlist=None, cache=cache)
        t_cold = time.perf_counter() - t0
        assert cold.cache_misses > 0
        t0 = time.perf_counter()
        warm = run_analysis(root=REPO_ROOT, allowlist=None, cache=cache)
        t_warm = time.perf_counter() - t0
        assert warm.cache_misses == 0
        assert warm.cache_hits == cold.cache_hits + cold.cache_misses
        cold_doc, warm_doc = cold.to_dict(), warm.to_dict()
        cold_doc.pop("cache"), warm_doc.pop("cache")
        assert json.dumps(cold_doc, sort_keys=True) \
            == json.dumps(warm_doc, sort_keys=True)
        # "measurably faster": the warm pass skips parsing + every rule
        assert t_warm * 1.5 < t_cold, (t_warm, t_cold)

    def test_module_edit_invalidates_that_module(self, tmp_path):
        cache = str(tmp_path / "cache.json")
        root = _tree(tmp_path / "tree", "X = 1\n")
        first = run_analysis(root=root, allowlist=None, cache=cache)
        assert first.ok
        _tree(tmp_path / "tree", UNGATED.format(pragma=""))
        second = run_analysis(root=root, allowlist=None, cache=cache)
        assert not second.ok
        assert any(f.rule == "telemetry-gating" for f in second.findings)

    def test_parse_errors_survive_subset_runs_and_cache(self, tmp_path):
        """Syntax errors gate even when the only ran rule does not scope
        the broken module, warm or cold (parse status is a cached
        pseudo-rule)."""
        cache = str(tmp_path / "cache.json")
        root = _tree(tmp_path, "def f(:\n", name="runtime/broken.py")
        for _ in range(2):
            report = run_analysis(root=root, rule_ids=["host-sync"],
                                  allowlist=None, cache=cache)
            assert [f.rule for f in report.findings] == ["parse-error"]
        assert report.cache_misses == 0

    def test_interprocedural_key_widens_to_tree(self, tmp_path):
        """Changing ONE module re-judges recompile-surface everywhere:
        its cache key embeds the tree hash."""
        cache = str(tmp_path / "cache.json")
        pkg = tmp_path / "t" / "spatialflink_tpu"
        (pkg / "ops").mkdir(parents=True)
        (pkg / "ops" / "k.py").write_text(textwrap.dedent("""
            from functools import partial
            from spatialflink_tpu.utils.deviceplane import instrumented_jit

            @partial(instrumented_jit, static_argnames=("n",))
            def kernel(x, n):
                return x[:n]
            """))
        (pkg / "ops" / "u.py").write_text(textwrap.dedent("""
            from spatialflink_tpu.ops.k import kernel

            def use(records, batch):
                return kernel(batch, n=len(records))
            """))
        root = str(tmp_path / "t")
        first = run_analysis(root=root, allowlist=None, cache=cache,
                             rule_ids=["recompile-surface"])
        assert len(first.findings) == 1
        # un-jit the kernel WITHOUT touching u.py: the call site there
        # must be re-judged (and come back clean)
        (pkg / "ops" / "k.py").write_text(
            "def kernel(x, n):\n    return x[:n]\n")
        second = run_analysis(root=root, allowlist=None, cache=cache,
                              rule_ids=["recompile-surface"])
        assert not second.findings
