"""UniformGrid parity tests against a direct reading of UniformGrid.java."""

import math

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.index.uniform_grid import cells_within_layers

# Canonical Beijing / T-Drive config (conf/geoflink-conf.yml:20-21)
BBOX = dict(min_x=115.50, max_x=117.60, min_y=39.60, max_y=41.10)


def make_grid(n=100):
    return UniformGrid(BBOX["min_x"], BBOX["max_x"], BBOX["min_y"], BBOX["max_y"],
                       num_grid_partitions=n)


class TestConstruction:
    def test_cell_count_ctor(self):
        g = make_grid(100)
        assert g.n == 100
        assert g.cell_length == pytest.approx((117.60 - 115.50) / 100)

    def test_cell_length_ctor_squares_bbox(self):
        # UniformGrid.java:47-72 + adjustCoordinatesForSquareGrid :114-134
        g = UniformGrid(0.0, 10.0, 0.0, 4.0, cell_length=1.0)
        # x span 10 > y span 4 -> y expanded symmetrically to 10
        assert (g.min_y, g.max_y) == (-3.0, 7.0)
        assert g.n == 10
        assert g.cell_length == pytest.approx(1.0)

    def test_cell_length_ctor_non_integer(self):
        g = UniformGrid(0.0, 10.0, 0.0, 10.0, cell_length=3.0)
        assert g.n == math.ceil(10 / 3)  # 4
        assert g.cell_length == pytest.approx(10 / 4)


class TestCellAssignment:
    def test_floor_division(self):
        g = make_grid(100)
        cell, valid = g.assign_cell(115.50, 39.60)
        assert valid and cell == 0
        # interior point
        cell, _ = g.assign_cell(116.55, 40.35)
        cx = math.floor((116.55 - g.min_x) / g.cell_length)
        cy = math.floor((40.35 - g.min_y) / g.cell_length)
        assert cell == cx * 100 + cy

    def test_out_of_bbox_invalid(self):
        g = make_grid(100)
        cell, valid = g.assign_cell(110.0, 39.9)
        assert not valid and cell == -1
        cell, valid = g.assign_cell(117.61, 39.9)
        assert not valid

    def test_vectorized_matches_scalar(self):
        g = make_grid(100)
        rng = np.random.default_rng(0)
        xs = rng.uniform(115.0, 118.0, 500)
        ys = rng.uniform(39.0, 41.5, 500)
        cells, valid = g.assign_cell(xs, ys)
        for i in range(0, 500, 37):
            c, v = g.assign_cell(xs[i], ys[i])
            assert cells[i] == c and valid[i] == v

    def test_cell_key_roundtrip(self):
        g = make_grid(100)
        key = g.cell_key(g.cell_id(7, 42))
        assert key == "0000700042"  # 5-digit zero padding, UniformGrid.java:92
        assert g.cell_from_key(key) == g.cell_id(7, 42)

    def test_cell_bounds(self):
        g = make_grid(100)
        x1, y1, x2, y2 = g.cell_bounds(g.cell_id(3, 5))
        assert x1 == pytest.approx(g.min_x + 3 * g.cell_length)
        assert y2 == pytest.approx(g.min_y + 6 * g.cell_length)


class TestLayerMath:
    def test_guaranteed_layers_formula(self):
        g = make_grid(100)
        diag = g.cell_length * math.sqrt(2)
        for r in (0.005, 0.01, 0.05, 0.1, 0.5, 1.0):
            assert g.guaranteed_layers(r) == int(math.floor(r / diag - 1))

    def test_candidate_layers_formula(self):
        g = make_grid(100)
        for r in (0.005, 0.01, 0.05, 0.1, 0.5):
            assert g.candidate_layers(r) == int(math.ceil(r / g.cell_length))

    def test_small_radius_no_guaranteed(self):
        g = make_grid(100)
        # r much smaller than a cell diagonal => guaranteed layers == -1
        assert g.guaranteed_layers(0.005) == -1
        mask = g.guaranteed_cells_mask(0.005, g.cell_id(50, 50))
        assert not mask.any()

    def test_gn_zero_layers_only_query_cell(self):
        g = make_grid(100)
        diag = g.cell_length * math.sqrt(2)
        r = 1.5 * diag  # floor(1.5 - 1) = 0 layers
        assert g.guaranteed_layers(r) == 0
        mask = g.guaranteed_cells_mask(r, g.cell_id(50, 50))
        assert mask.sum() == 1 and mask[g.cell_id(50, 50)]


class TestNeighborMasks:
    def test_gn_cn_mutually_exclusive(self):
        g = make_grid(100)
        c = g.cell_id(50, 50)
        for r in (0.05, 0.1, 0.3, 0.5):
            gn = g.guaranteed_cells_mask(r, c)
            cn = g.candidate_cells_mask(r, c, gn)
            assert not (gn & cn).any()
            # union == all cells within candidate layers
            assert ((gn | cn) == g.neighboring_cells_mask(r, c)).all()

    def test_candidate_count_exact(self):
        g = make_grid(100)
        c = g.cell_id(50, 50)
        r = 0.5
        L = g.candidate_layers(r)
        nb = g.neighboring_cells_mask(r, c)
        assert nb.sum() == (2 * L + 1) ** 2  # interior cell, no clipping

    def test_border_clipping(self):
        g = make_grid(100)
        c = g.cell_id(0, 0)
        r = 0.5
        L = g.candidate_layers(r)
        nb = g.neighboring_cells_mask(r, c)
        assert nb.sum() == (L + 1) ** 2  # corner cell keeps one quadrant

    def test_radius_zero_all_cells(self):
        g = make_grid(100)
        nb = g.neighboring_cells_mask(0.0, g.cell_id(10, 10))
        assert nb.all()  # UniformGrid.java:264-266

    def test_polygon_union_semantics(self):
        g = make_grid(100)
        seeds = [g.cell_id(10, 10), g.cell_id(12, 10)]
        gn = g.guaranteed_cells_mask(0.2, seeds)
        per_seed = [g.guaranteed_cells_mask(0.2, s) for s in seeds]
        assert (gn == (per_seed[0] | per_seed[1])).all()

    def test_layer_rings(self):
        g = make_grid(100)
        c = g.cell_id(50, 50)
        ring0 = g.neighboring_layer_cells_mask(c, 0)
        ring2 = g.neighboring_layer_cells_mask(c, 2)
        assert ring0.sum() == 1
        assert ring2.sum() == 5 * 5 - 3 * 3
        layers = g.all_neighboring_layers(c)
        assert layers[0].sum() == 1 and len(layers) >= 50

    def test_cell_layer_wrt(self):
        g = make_grid(100)
        q = g.cell_id(50, 50)
        assert g.cell_layer_wrt(q, q) == 0
        assert g.cell_layer_wrt(q, g.cell_id(53, 48)) == 3


class TestDevicePredicate:
    def test_cells_within_layers_matches_mask(self):
        g = make_grid(100)
        q = g.cell_id(50, 50)
        r = 0.3
        L = g.candidate_layers(r)
        mask = g.neighboring_cells_mask(r, q)
        cells = np.arange(g.num_cells, dtype=np.int32)
        got = np.asarray(cells_within_layers(cells, np.int32(q), L, g.n))
        assert (got == mask).all()

    def test_invalid_cells_never_match(self):
        g = make_grid(100)
        got = cells_within_layers(np.array([-1], np.int32), np.int32(0), 100, g.n)
        assert not np.asarray(got).any()
