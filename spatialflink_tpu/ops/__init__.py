"""Device-side geometry kernels (jax.jit / vmap / Pallas).

This package is the TPU replacement for the work the reference delegates to
the JTS library and per-tuple Flink operators (``utils/DistanceFunctions.java``
and the hot loops in ``spatialOperators/{range,knn,join}``): everything here
operates on padded, masked, fixed-shape arrays.
"""

from spatialflink_tpu.ops import distances, geom, join, knn, range  # noqa: F401
