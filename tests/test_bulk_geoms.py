"""Bulk WKT geometry ingestion: native parse, SoA assembly parity with the
object path, window batching, and the driver fast path."""

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models.batches import EdgeGeomBatch
from spatialflink_tpu.operators import QueryConfiguration
from spatialflink_tpu.streams.bulk import (
    ParsedGeoms,
    bulk_parse_wkt,
    bulk_geom_window_batches,
    geoms_to_edge_batch,
)
from spatialflink_tpu.streams.formats import parse_spatial
from spatialflink_tpu.utils import IdInterner

GRID = UniformGrid(0.0, 10.0, 0.0, 10.0, num_grid_partitions=10)
T0 = 1_700_000_000_000


def _lines(n=40, seed=1, t_step=1):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        w = float(rng.uniform(0.1, 1.5))
        t = T0 + i * t_step
        if i % 3 == 0:
            out.append(f"l{i}, {t}, LINESTRING ({cx} {cy}, {cx+w} {cy+w}, {cx+w} {cy})")
        elif i % 7 == 0:  # native-rejected: reparsed + flattened in Python
            out.append(f"m{i}, {t}, MULTIPOLYGON ((({cx} {cy}, {cx+w} {cy}, {cx+w} {cy+w}, {cx} {cy})))")
        else:
            out.append(f"p{i}, {t}, POLYGON (({cx} {cy}, {cx+w} {cy}, {cx+w} {cy+w}, {cx} {cy+w}), "
                       f"({cx+w/4} {cy+w/4}, {cx+w/2} {cy+w/4}, {cx+w/2} {cy+w/2}))")
    return out


class TestParsedGeomsParity:
    def _check_against_objects(self, lines):
        parsed = bulk_parse_wkt(("\n".join(lines)).encode())
        batch = geoms_to_edge_batch(parsed, GRID, ts_base=T0)
        i2 = IdInterner()
        objs = [parse_spatial(ln, "WKT", GRID) for ln in lines]
        want = EdgeGeomBatch.from_objects(objs, GRID, i2, ts_base=T0)
        n = len(lines)
        assert (batch.valid == want.valid).all()
        np.testing.assert_array_equal(batch.ts[:n], want.ts[:n])
        np.testing.assert_allclose(batch.bbox[:n], want.bbox[:n], atol=1e-6)
        np.testing.assert_array_equal(batch.is_areal[:n], want.is_areal[:n])
        np.testing.assert_array_equal(batch.cell[:n], want.cell[:n])
        for g in range(n):
            # cells and edge SETS equal (object path sorts polygon rings by
            # area; the edge set is identical and kernels are edge-order
            # invariant)
            assert set(batch.cells[g][batch.cells_mask[g]].tolist()) == \
                set(want.cells[g][want.cells_mask[g]].tolist()), g
            a = {tuple(e) for e in batch.edges[g][batch.edge_mask[g]].tolist()}
            b = {tuple(e) for e in want.edges[g][want.edge_mask[g]].tolist()}
            assert a == b, g
            assert parsed.interner.lookup(int(batch.obj_id[g])) == \
                i2.lookup(int(want.obj_id[g])), g

    def test_native_path_matches_object_path(self):
        self._check_against_objects(_lines(40))

    def test_python_fallback_matches_object_path(self, monkeypatch):
        monkeypatch.setenv("SPATIALFLINK_NATIVE", "0")
        self._check_against_objects(_lines(25, seed=2))

    def test_unclosed_rings_get_closure_edges(self):
        # raw ring not closed -> closure edge must appear (auto-close parity)
        parsed = bulk_parse_wkt(b"p, 1, POLYGON ((1 1, 3 1, 3 3, 1 3))")
        batch = geoms_to_edge_batch(parsed, GRID)
        edges = batch.edges[0][batch.edge_mask[0]]
        assert edges.shape[0] == 4  # 3 base + closure
        assert (edges[-1] == np.float32([1, 3, 1, 1])).all()

    def test_geometrycollection_line_raises(self):
        with pytest.raises(ValueError):
            bulk_parse_wkt(b"GEOMETRYCOLLECTION (POINT (1 2))")

    def test_subset_rebases_offsets(self):
        parsed = bulk_parse_wkt(("\n".join(_lines(30, seed=3))).encode())
        idx = np.array([4, 7, 20, 21])
        sub = parsed.subset(idx)
        full = geoms_to_edge_batch(parsed, GRID, ts_base=T0)
        part = geoms_to_edge_batch(sub, GRID, ts_base=T0)
        for k, g in enumerate(idx):
            a = {tuple(e) for e in part.edges[k][part.edge_mask[k]].tolist()}
            b = {tuple(e) for e in full.edges[g][full.edge_mask[g]].tolist()}
            assert a == b
            assert part.ts[k] == full.ts[g]


class TestGeomBulkWindows:
    def test_run_bulk_matches_record_path(self):
        from spatialflink_tpu.models import Polygon
        from spatialflink_tpu.operators import PolygonPolygonRangeQuery

        lines = _lines(60, seed=4, t_step=400)
        parsed = bulk_parse_wkt(("\n".join(lines)).encode())
        q = Polygon.create([[(3, 3), (7, 3), (7, 7), (3, 7)]], GRID)
        conf = QueryConfiguration(window_size_ms=10_000, slide_ms=5_000)
        objs = [parse_spatial(ln, "WKT", GRID) for ln in lines]
        rec = list(PolygonPolygonRangeQuery(conf, GRID).run(iter(objs), q, 1.0))
        bulk = list(PolygonPolygonRangeQuery(conf, GRID).run_bulk(parsed, q, 1.0))
        assert any(w.records for w in rec)
        assert [(w.window_start,
                 sorted(g.obj_id for g in w.records)) for w in rec] == \
               [(w.window_start,
                 sorted(parsed.interner.lookup(int(parsed.obj_id[i]))
                        for i in w.records)) for w in bulk]

    def test_run_bulk_distributed_matches(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PolygonPointRangeQuery

        lines = _lines(60, seed=5, t_step=400)
        parsed = bulk_parse_wkt(("\n".join(lines)).encode())
        q = Point.create(5.0, 5.0, GRID)
        r1 = list(PolygonPointRangeQuery(
            QueryConfiguration(window_size_ms=10_000, slide_ms=5_000),
            GRID).run_bulk(parsed, q, 2.0))
        r8 = list(PolygonPointRangeQuery(
            QueryConfiguration(window_size_ms=10_000, slide_ms=5_000,
                               devices=8), GRID).run_bulk(parsed, q, 2.0))
        assert any(w.records for w in r1)
        assert [(w.window_start, w.records) for w in r1] == \
               [(w.window_start, w.records) for w in r8]

    def test_window_assembly_groups_by_ts(self):
        lines = _lines(30, seed=6, t_step=1000)
        parsed = bulk_parse_wkt(("\n".join(lines)).encode())
        from spatialflink_tpu.runtime import WindowSpec

        wins = list(bulk_geom_window_batches(
            parsed, WindowSpec.sliding(10_000, 5_000), GRID))
        assert wins
        for start, end, idx, batch in wins:
            assert (parsed.ts[idx] >= start - 5_000).all()  # sanity
            assert int(batch.valid.sum()) == len(idx)


class TestDriverGeomBulk:
    def test_driver_bulk_option21(self, tmp_path, capsys):
        from spatialflink_tpu.driver import main

        lines = _lines(50, seed=7, t_step=400)
        f = tmp_path / "polys.wkt"
        f.write_text("\n".join(lines))
        import yaml

        with open("conf/spatialflink-conf.yml") as fh:
            y = yaml.safe_load(fh)
        y["inputStream1"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["inputStream2"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["query"]["option"] = 21
        y["query"]["radius"] = 1.0
        y["query"]["queryPolygons"] = [[[3, 3], [7, 3], [7, 7], [3, 7]]]
        y["inputStream1"]["format"] = "WKT"
        y["inputStream1"]["dateFormat"] = None
        cfgf = tmp_path / "conf.yml"
        cfgf.write_text(yaml.safe_dump(y))
        rc = main(["--config", str(cfgf), "--input1", str(f), "--bulk"])
        assert rc == 0
        out = capsys.readouterr()
        assert "not applicable" not in out.err
        assert out.out.strip()

    def test_driver_bulk_mixed_geometry_falls_back_to_record_path(
            self, tmp_path, capsys):
        # a stray POINT row in a polygon WKT stream is not bulk-ingestible;
        # run_option_bulk's contract is fall-back-to-record-path, not an
        # uncaught ValueError mid-ingest
        from spatialflink_tpu.driver import main

        lines = _lines(20, seed=7, t_step=400)
        lines.insert(3, f"p99, {T0 + 2}, POINT (5 5)")
        f = tmp_path / "mixed.wkt"
        f.write_text("\n".join(lines))
        import yaml

        with open("conf/spatialflink-conf.yml") as fh:
            y = yaml.safe_load(fh)
        y["inputStream1"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["inputStream2"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["query"]["option"] = 21
        y["query"]["radius"] = 1.0
        y["query"]["queryPolygons"] = [[[3, 3], [7, 3], [7, 7], [3, 7]]]
        y["inputStream1"]["format"] = "WKT"
        y["inputStream1"]["dateFormat"] = None
        cfgf = tmp_path / "conf.yml"
        cfgf.write_text(yaml.safe_dump(y))
        rc = main(["--config", str(cfgf), "--input1", str(f), "--bulk"])
        assert rc == 0
        out = capsys.readouterr()
        assert "not bulk-ingestible" in out.err
        assert out.out.strip()


class TestGeomKnnBulk:
    def test_geom_knn_run_bulk_matches_record_path(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PolygonPointKNNQuery

        lines = _lines(60, seed=8, t_step=400)
        parsed = bulk_parse_wkt(("\n".join(lines)).encode())
        q = Point.create(5.0, 5.0, GRID)
        conf = QueryConfiguration(window_size_ms=10_000, slide_ms=5_000)
        objs = [parse_spatial(ln, "WKT", GRID) for ln in lines]
        rec = list(PolygonPointKNNQuery(conf, GRID).run(iter(objs), q, 0.0, 7))
        bulk = list(PolygonPointKNNQuery(conf, GRID).run_bulk(parsed, q, 0.0, 7))
        assert any(w.records for w in rec)
        # equal-distance ties may order differently (interner id order
        # differs between parse paths); compare tie-insensitively
        assert [(w.window_start, sorted(w.records)) for w in rec] == \
               [(w.window_start, sorted(w.records)) for w in bulk]

    def test_point_geom_knn_run_bulk_matches_record_path(self):
        from spatialflink_tpu.models import Point, Polygon
        from spatialflink_tpu.operators import PointPolygonKNNQuery
        from spatialflink_tpu.streams.bulk import bulk_parse_csv

        rng = np.random.default_rng(9)
        rows = [f"o{i % 30},{T0 + i * 400},{rng.uniform(0.5, 9.5):.6f},"
                f"{rng.uniform(0.5, 9.5):.6f}" for i in range(400)]
        parsed = bulk_parse_csv(("\n".join(rows)).encode(), date_format=None)
        q = Polygon.create([[(4, 4), (6, 4), (6, 6), (4, 6)]], GRID)
        conf = QueryConfiguration(window_size_ms=10_000, slide_ms=5_000)
        pts = [Point.create(float(x), float(y), GRID, o, int(t))
               for o, t, x, y in (r.split(",") for r in rows)]
        rec = list(PointPolygonKNNQuery(conf, GRID).run(iter(pts), q, 0.0, 9))
        bulk = list(PointPolygonKNNQuery(conf, GRID).run_bulk(parsed, q, 0.0, 9))
        assert any(w.records for w in rec)
        assert [(w.window_start, sorted(w.records)) for w in rec] == \
               [(w.window_start, sorted(w.records)) for w in bulk]

    def test_driver_bulk_geom_knn_option(self, tmp_path, capsys):
        # option 71 = kNN, (Polygon, Point) stream/query pair
        from spatialflink_tpu.driver import CASES, main

        assert CASES[71].family == "knn" and CASES[71].stream == "Polygon"
        lines = _lines(50, seed=10, t_step=400)
        f = tmp_path / "polys.wkt"
        f.write_text("\n".join(lines))
        import yaml

        with open("conf/spatialflink-conf.yml") as fh:
            y = yaml.safe_load(fh)
        y["inputStream1"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["inputStream2"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["query"]["option"] = 71
        y["query"]["radius"] = 0.0
        y["query"]["k"] = 5
        y["query"]["queryPoints"] = [[5.0, 5.0]]
        y["inputStream1"]["format"] = "WKT"
        y["inputStream1"]["dateFormat"] = None
        cfgf = tmp_path / "conf.yml"
        cfgf.write_text(yaml.safe_dump(y))
        rc = main(["--config", str(cfgf), "--input1", str(f), "--bulk"])
        assert rc == 0
        out = capsys.readouterr()
        assert "not applicable" not in out.err
        assert out.out.strip()


class TestPointGeomRangeBulkDriver:
    def test_driver_bulk_point_polygon_range_option6(self, tmp_path, capsys):
        from spatialflink_tpu.driver import CASES, main

        assert CASES[6].family == "range" and \
            (CASES[6].stream, CASES[6].query) == ("Point", "Polygon")
        rng = np.random.default_rng(11)
        rows = [f"o{i % 30},{T0 + i * 400},{rng.uniform(0.5, 9.5):.6f},"
                f"{rng.uniform(0.5, 9.5):.6f}" for i in range(300)]
        f = tmp_path / "pts.csv"
        f.write_text("\n".join(rows))
        import yaml

        with open("conf/spatialflink-conf.yml") as fh:
            y = yaml.safe_load(fh)
        y["inputStream1"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["inputStream2"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["query"]["option"] = 6
        y["query"]["radius"] = 1.0
        y["query"]["queryPolygons"] = [[[4, 4], [6, 4], [6, 6], [4, 6]]]
        y["inputStream1"]["format"] = "CSV"
        y["inputStream1"]["dateFormat"] = None
        cfgf = tmp_path / "conf.yml"
        cfgf.write_text(yaml.safe_dump(y))
        rc = main(["--config", str(cfgf), "--input1", str(f), "--bulk"])
        assert rc == 0
        out = capsys.readouterr()
        assert "not applicable" not in out.err
        assert out.out.strip()


def _geojson_lines(n=30, seed=1, t_step=1):
    import json as _json

    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        cx, cy = rng.uniform(1, 9), rng.uniform(1, 9)
        w = float(rng.uniform(0.1, 1.5))
        t = T0 + i * t_step
        props = {"oID": f"g{i}", "timestamp": t}
        if i % 3 == 0:
            geom = {"type": "LineString",
                    "coordinates": [[cx, cy], [cx + w, cy + w], [cx + w, cy]]}
        elif i % 7 == 0:  # native-rejected: reparsed + flattened in Python
            geom = {"type": "MultiPolygon", "coordinates": [
                [[[cx, cy], [cx + w, cy], [cx + w, cy + w], [cx, cy]]]]}
        else:  # polygon with a hole
            geom = {"type": "Polygon", "coordinates": [
                [[cx, cy], [cx + w, cy], [cx + w, cy + w], [cx, cy + w],
                 [cx, cy]],
                [[cx + w / 4, cy + w / 4], [cx + w / 2, cy + w / 4],
                 [cx + w / 2, cy + w / 2], [cx + w / 4, cy + w / 4]]]}
        rec = {"type": "Feature", "geometry": geom, "properties": props}
        if i % 5 == 0:  # Kafka envelope form
            rec = {"topic": "polys", "timestamp": 0, "value": rec}
        out.append(_json.dumps(rec))
    return out


class TestGeoJsonGeomsParity:
    """bulk_parse_geojson_geoms must equal the per-record GeoJSON object
    path — including native-rejected features (Multi*, envelope oddities)
    flattened through the Python reparser."""

    def _check_against_objects(self, lines):
        from spatialflink_tpu.streams.bulk import bulk_parse_geojson_geoms

        parsed = bulk_parse_geojson_geoms(("\n".join(lines)).encode())
        batch = geoms_to_edge_batch(parsed, GRID, ts_base=T0)
        i2 = IdInterner()
        objs = [parse_spatial(ln, "GeoJSON", GRID) for ln in lines]
        want = EdgeGeomBatch.from_objects(objs, GRID, i2, ts_base=T0)
        n = len(lines)
        assert (batch.valid == want.valid).all()
        np.testing.assert_array_equal(batch.ts[:n], want.ts[:n])
        np.testing.assert_allclose(batch.bbox[:n], want.bbox[:n], atol=1e-6)
        np.testing.assert_array_equal(batch.is_areal[:n], want.is_areal[:n])
        np.testing.assert_array_equal(batch.cell[:n], want.cell[:n])
        for g in range(n):
            assert set(batch.cells[g][batch.cells_mask[g]].tolist()) == \
                set(want.cells[g][want.cells_mask[g]].tolist()), g
            a = {tuple(e) for e in batch.edges[g][batch.edge_mask[g]].tolist()}
            b = {tuple(e) for e in want.edges[g][want.edge_mask[g]].tolist()}
            assert a == b, g
            assert parsed.interner.lookup(int(batch.obj_id[g])) == \
                i2.lookup(int(want.obj_id[g])), g

    def test_native_path_matches_object_path(self):
        self._check_against_objects(_geojson_lines(30))

    def test_python_fallback_matches_object_path(self, monkeypatch):
        monkeypatch.setenv("SPATIALFLINK_NATIVE", "0")
        self._check_against_objects(_geojson_lines(20, seed=4))

    def test_point_feature_raises(self):
        from spatialflink_tpu.streams.bulk import bulk_parse_geojson_geoms

        with pytest.raises(ValueError):
            bulk_parse_geojson_geoms(
                b'{"type": "Feature", "geometry": {"type": "Point", '
                b'"coordinates": [1, 2]}, "properties": {"oID": "p"}}')


class TestDriverGeoJsonGeomBulk:
    def test_driver_bulk_option21_geojson(self, tmp_path, capsys):
        from spatialflink_tpu.driver import main

        lines = _geojson_lines(40, seed=9, t_step=400)
        f = tmp_path / "polys.geojson"
        f.write_text("\n".join(lines))
        import yaml

        with open("conf/spatialflink-conf.yml") as fh:
            y = yaml.safe_load(fh)
        y["inputStream1"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["inputStream2"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["query"]["option"] = 21
        y["query"]["radius"] = 1.0
        y["query"]["queryPolygons"] = [[[3, 3], [7, 3], [7, 7], [3, 7]]]
        y["inputStream1"]["format"] = "GeoJSON"
        y["inputStream1"]["dateFormat"] = None
        cfgf = tmp_path / "conf.yml"
        cfgf.write_text(yaml.safe_dump(y))
        rc = main(["--config", str(cfgf), "--input1", str(f), "--bulk"])
        assert rc == 0
        out = capsys.readouterr()
        assert "not applicable" not in out.err
        assert "not bulk-ingestible" not in out.err
        assert out.out.strip()

    def test_bulk_output_matches_record_path(self, tmp_path, capsys):
        from spatialflink_tpu.driver import main

        lines = _geojson_lines(40, seed=9, t_step=400)
        f = tmp_path / "polys.geojson"
        f.write_text("\n".join(lines))
        import yaml

        with open("conf/spatialflink-conf.yml") as fh:
            y = yaml.safe_load(fh)
        y["inputStream1"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["inputStream2"]["gridBBox"] = [0.0, 0.0, 10.0, 10.0]
        y["query"]["option"] = 21
        y["query"]["radius"] = 1.0
        y["query"]["queryPolygons"] = [[[3, 3], [7, 3], [7, 7], [3, 7]]]
        y["inputStream1"]["format"] = "GeoJSON"
        y["inputStream1"]["dateFormat"] = None
        cfgf = tmp_path / "conf.yml"
        cfgf.write_text(yaml.safe_dump(y))
        assert main(["--config", str(cfgf), "--input1", str(f), "--bulk"]) == 0
        bulk_out = capsys.readouterr().out
        assert main(["--config", str(cfgf), "--input1", str(f)]) == 0
        rec_out = capsys.readouterr().out
        assert bulk_out == rec_out


class TestMalformedConsistency:
    """Bulk ingest accepts exactly what the record path accepts — and FAILS
    exactly where it fails: a malformed line must raise the same exception
    type from both, never silently produce a record."""

    CASES = [
        ("GeoJSON", '{"type": "Feature", "geometry": {"type": "Polygon", '
                    '"coordinates": [[[1, 2], [3'),
        ("GeoJSON", '{"type": "Feature", "geometry": {"type": "Polygon"}, '
                    '"properties": {}}'),
        ("GeoJSON", "garbage line"),
        ("GeoJSON", '{"type": "Feature", "geometry": null, '
                    '"properties": {"oID": "x"}}'),
        ("WKT", "POLYGON ((1 1, 2 2"),
        ("WKT", "POLYGONE ((1 1, 2 2, 3 3))"),
    ]

    @pytest.mark.parametrize("fmt,line", CASES)
    def test_same_exception_type(self, fmt, line):
        from spatialflink_tpu.streams.bulk import (
            bulk_parse_geojson_geoms,
            bulk_parse_wkt,
        )

        bulk_fn = (bulk_parse_geojson_geoms if fmt == "GeoJSON"
                   else bulk_parse_wkt)
        with pytest.raises(Exception) as bulk_err:
            bulk_fn(line.encode())
        with pytest.raises(Exception) as rec_err:
            parse_spatial(line, fmt, GRID)
        assert type(bulk_err.value) is type(rec_err.value), \
            (bulk_err.value, rec_err.value)
