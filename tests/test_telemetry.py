"""Telemetry layer tests: streaming histograms vs numpy, span nesting and
exception propagation, reporter snapshot schema, registry scoping, grid
occupancy, and the driver acceptance runs (file + live kafka-follow) —
including the telemetry-OFF contract: no span/histogram calls on the record
loop when no session is active."""

import json
import os
import threading
import time

import numpy as np
import pytest
import yaml

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.streams.formats import serialize_spatial
from spatialflink_tpu.utils import telemetry as telemetry_mod
from spatialflink_tpu.utils.metrics import MetricsRegistry, scoped_registry
from spatialflink_tpu.utils.telemetry import (
    StreamingHistogram,
    Telemetry,
    TelemetryReporter,
    active,
    prometheus_text,
    telemetry_session,
)

GRID = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)

SNAPSHOT_KEYS = {"ts_ms", "uptime_s", "spans", "histograms", "gauges",
                 "counters", "degradation", "grid"}


def _write_points(path, n=50, t0=1_700_000_000_000, step_ms=500):
    with open(path, "w") as f:
        for i in range(n):
            p = Point.create(116.5 + 0.001 * i, 40.5, GRID, obj_id=f"o{i}",
                             timestamp=t0 + i * step_ms)
            f.write(serialize_spatial(p, "GeoJSON") + "\n")
    return str(path)


def _snapshots(tdir):
    with open(os.path.join(str(tdir), "telemetry.jsonl")) as f:
        return [json.loads(line) for line in f]


class TestStreamingHistogram:
    def test_percentiles_match_numpy_on_random_samples(self):
        # log-bucket resolution bound: geometric-midpoint error <=
        # sqrt(growth) ~ 4.4% at the default growth; allow headroom for
        # rank-vs-interpolation differences at the tails
        rng = np.random.default_rng(7)
        for dist in (rng.lognormal(2.0, 1.5, 4000),
                     rng.uniform(0.5, 500.0, 4000),
                     rng.exponential(50.0, 4000) + 0.01):
            h = StreamingHistogram("t")
            for v in dist:
                h.record(float(v))
            for p in (50, 90, 95, 99):
                est = h.percentile(p)
                ref = float(np.percentile(dist, p))
                assert est == pytest.approx(ref, rel=0.08), (p, est, ref)
        assert h.count == 4000
        assert h.max == pytest.approx(float(dist.max()))

    def test_constant_memory(self):
        h = StreamingHistogram("t")
        buckets = len(h.counts)
        for i in range(100_000):
            h.record(0.001 * (i + 1))
        assert len(h.counts) == buckets  # O(1) per record, no growth
        assert h.count == 100_000

    def test_empty_and_edge_values(self):
        h = StreamingHistogram("t")
        assert h.percentile(50) == 0.0
        assert h.to_dict() == {"count": 0}
        h.record(0.0)      # at/below lo -> underflow bucket, not a crash
        h.record(-5.0)
        h.record(1e12)     # overflow clamps to the last bucket
        assert h.count == 3
        assert h.percentile(100) == pytest.approx(1e12)

    def test_single_value(self):
        h = StreamingHistogram("t")
        h.record(42.0)
        for p in (1, 50, 99):
            assert h.percentile(p) == pytest.approx(42.0, rel=0.05)


class TestSpans:
    def test_nesting_records_both_and_self_time(self):
        tel = Telemetry()
        with tel.span("outer", query="q"):
            with tel.span("inner", query="q"):
                time.sleep(0.02)
        outer, inner = tel.spans["q.outer"], tel.spans["q.inner"]
        assert outer.count == 1 and inner.count == 1
        assert outer.total_s >= inner.total_s
        # nesting-aware: the child's time is excluded from the parent's self
        assert outer.self_s <= outer.total_s - inner.total_s + 0.005

    def test_exception_propagates_and_is_counted(self):
        tel = Telemetry()
        with pytest.raises(ValueError):
            with tel.span("boom"):
                raise ValueError("x")
        st = tel.spans["boom"]
        assert st.count == 1 and st.errors == 1
        assert st.total_s >= 0.0

    def test_observe_accumulates(self):
        tel = Telemetry()
        tel.observe("ingest", 0.01)
        tel.observe("ingest", 0.03)
        st = tel.spans["ingest"]
        assert st.count == 2
        assert st.total_s == pytest.approx(0.04)
        assert st.max_s == pytest.approx(0.03)

    def test_query_scoping_separates_families(self):
        tel = Telemetry()
        with tel.span("kernel", query="knn"):
            pass
        with tel.span("kernel", query="range"):
            pass
        assert {"knn.kernel", "range.kernel"} <= set(tel.spans)


class TestGaugesAndOccupancy:
    def test_gauge_set_and_callable(self):
        tel = Telemetry()
        tel.gauge("a").set(3.5)
        tel.gauge("b", fn=lambda: 7.0)
        snap = tel.snapshot()
        assert snap["gauges"]["a"] == 3.5
        assert snap["gauges"]["b"] == 7.0

    def test_cell_occupancy_topk_and_skew(self):
        tel = Telemetry()
        # 3 records in one cell, 1 in another -> skew = 3 / 2
        tel.record_cells(np.array([11, 11, 11, 55, -1], dtype=np.int32))
        g = tel.snapshot()["grid"]
        assert g["occupied_cells"] == 2
        assert g["top_cells"][0] == [11, 3] or g["top_cells"][0] == (11, 3)
        assert g["skew"] == pytest.approx(1.5)

    def test_cell_occupancy_scalar_fast_path(self):
        # per-record ingest assigns one cell at a time (0-d arrays from
        # assign_cell on scalars); the scalar path must count identically
        # to the vectorized one, including dropping invalid cells
        tel = Telemetry()
        for c in (np.int32(7), np.array(7, dtype=np.int32), 7, -1):
            tel.record_cells(c)
        g = tel.snapshot()["grid"]
        assert g["occupied_cells"] == 1
        assert list(g["top_cells"][0]) == [7, 3]

    def test_session_hooks_grid_assignment(self):
        with telemetry_session() as tel:
            GRID.assign_cell(np.array([116.5, 116.5]), np.array([40.5, 40.5]))
            assert tel.snapshot()["grid"]["occupied_cells"] >= 1
        # hook restored: assignments outside the session are not observed
        from spatialflink_tpu.index import uniform_grid
        assert uniform_grid._CELL_OBSERVER is None


class TestReporter:
    def test_snapshot_schema_and_min_two_snapshots(self, tmp_path):
        with telemetry_session(str(tmp_path), interval_s=0.05) as tel:
            with tel.span("stage", query="q"):
                time.sleep(0.12)
            tel.histogram("lat").record(5.0)
            tel.gauge("g").set(1.0)
        snaps = _snapshots(tmp_path)
        assert len(snaps) >= 2  # immediate + periodic(s) + final
        for s in snaps:
            assert SNAPSHOT_KEYS <= set(s)
        last = snaps[-1]
        assert last["spans"]["q.stage"]["count"] == 1
        for k in ("count", "total_ms", "max_ms", "self_ms", "errors"):
            assert k in last["spans"]["q.stage"]
        for k in ("count", "sum", "min", "max", "p50", "p95", "p99"):
            assert k in last["histograms"]["lat"]
        assert last["gauges"]["g"] == 1.0

    def test_snapshot_carries_status_digest_and_health(self, tmp_path):
        """Every JSONL line embeds the shared operator digest — incl. the
        PR 3 pane-cache counters and PR 4 checkpoint gauges an operator
        reads first — and, with an SLO evaluator attached, the health
        verdict."""
        from spatialflink_tpu.runtime.health import HealthEvaluator

        with scoped_registry() as reg, telemetry_session(
                str(tmp_path), interval_s=5.0,
                health=HealthEvaluator.from_spec("dlq_depth=100")) as tel:
            reg.counter("pane-cache-hits").inc(3)
            reg.counter("pane-cache-misses").inc(1)
            reg.counter("checkpoints-written").inc(1)
            tel.gauge("checkpoint.seq").set(1.0)
        snaps = _snapshots(tmp_path)
        for s in snaps:
            assert "status" in s and "health" in s
        st = snaps[-1]["status"]
        assert st["pane_cache"] == {"hits": 3, "misses": 1, "hit_rate": 0.75}
        assert st["checkpoint"]["written"] == 1
        assert st["checkpoint"]["seq"] == 1.0
        assert "watermark_lag_ms" in st and "breaker_state" in st
        assert snaps[-1]["health"]["status"] == "ok"

    def test_prometheus_dump(self, tmp_path):
        with telemetry_session(str(tmp_path), interval_s=5.0) as tel:
            with tel.span("s"):
                pass
            tel.histogram("h").record(2.0)
            tel.gauge("g").set(4.0)
        prom = open(os.path.join(str(tmp_path), "metrics.prom")).read()
        for family in ("spatialflink_span_count", "spatialflink_span_seconds_total",
                       "spatialflink_histogram_quantile", "spatialflink_gauge",
                       "spatialflink_counter"):
            assert family in prom
        assert 'stage="s"' in prom and 'name="h"' in prom

    def test_crash_still_writes_final_snapshot(self, tmp_path):
        with pytest.raises(RuntimeError):
            with telemetry_session(str(tmp_path), interval_s=5.0) as tel:
                with pytest.raises(RuntimeError):
                    with tel.span("dead"):
                        raise RuntimeError("boom")
                raise RuntimeError("run crashed")
        snaps = _snapshots(tmp_path)
        assert len(snaps) >= 2
        assert snaps[-1]["spans"]["dead"]["errors"] == 1


class TestScopedRegistry:
    def test_counters_do_not_bleed_through(self):
        from spatialflink_tpu.utils import metrics as m

        outer = m.REGISTRY
        outer_before = outer.counter("scoped-test").count
        with scoped_registry() as reg:
            assert m.REGISTRY is reg
            m.REGISTRY.counter("scoped-test").inc(5)
            assert reg.counter("scoped-test").count == 5
        assert m.REGISTRY is outer
        assert outer.counter("scoped-test").count == outer_before

    def test_registry_reset(self):
        r = MetricsRegistry()
        r.counter("a").inc(3)
        r.meter("b").mark()
        r.reset()
        assert r.snapshot() == {}

    def test_telemetry_snapshot_reads_scoped_registry(self):
        with scoped_registry() as reg:
            reg.counter("retry-attempts").inc(2)
            tel = Telemetry()
            snap = tel.snapshot()
        assert snap["counters"]["retry-attempts"] == 2
        assert snap["degradation"] == {"retry-attempts": 2}


class TestLatencySink:
    def test_histogram_backed_percentile_and_bounded_memory(self):
        from spatialflink_tpu.streams.sinks import LatencySink

        sink = LatencySink()
        for i in range(5000):
            p = Point.create(116.5, 40.5, GRID, obj_id="a",
                             timestamp=int(time.time() * 1000))
            # stamp RIGHT before emit so the latency is ~10ms regardless of
            # how long the loop itself takes
            p.ingestion_time = time.time() * 1000 - 10.0
            sink.emit(p)
        assert sink.count == 5000
        assert sink.percentile(50) == pytest.approx(10.0, rel=0.3)
        # no unbounded per-record sample list anywhere on the sink
        assert not hasattr(sink, "latencies_ms")
        assert len(sink.hist.counts) < 1000


class _CallCounter:
    """Counts every Telemetry.span/observe, StreamingHistogram.record,
    CostProfiles feed, WindowTraceBook note, FlightRecorder note, and
    device-memory probe process-wide — the telemetry-off hot-path
    assertion (the PR 6 cost/trace plane AND the ISSUE 12 device plane
    must obey the same contract as the PR 2 spans: zero calls without a
    session; memory probes happen per snapshot/request only, and no
    snapshot is built during an unqueried run)."""

    def __init__(self, monkeypatch):
        from spatialflink_tpu.utils import deviceplane as deviceplane_mod
        from spatialflink_tpu.utils.accounting import TenantLedger
        from spatialflink_tpu.utils.deviceplane import FlightRecorder
        from spatialflink_tpu.utils.latencyplane import LatencyPlane
        from spatialflink_tpu.utils.telemetry import (CostProfiles,
                                                      WindowTraceBook)

        self.calls = 0
        counter = self

        def wrap(cls, name):
            orig = getattr(cls, name)

            def spy(self, *a, **k):
                counter.calls += 1
                return orig(self, *a, **k)

            monkeypatch.setattr(cls, name, spy)

        for cls, name in ((Telemetry, "span"), (Telemetry, "observe"),
                          (StreamingHistogram, "record"),
                          (CostProfiles, "record_cells"),
                          (CostProfiles, "record_scalar"),
                          (CostProfiles, "record_counts"),
                          (CostProfiles, "attribute_kernel"),
                          (CostProfiles, "attribute_merge"),
                          (WindowTraceBook, "note"),
                          (WindowTraceBook, "note_any"),
                          (WindowTraceBook, "seal"),
                          (FlightRecorder, "note"),
                          # the latency-decomposition plane obeys the same
                          # contract: zero touches without a session
                          (LatencyPlane, "note_seal"),
                          (LatencyPlane, "note_dispatch"),
                          (LatencyPlane, "window_complete"),
                          (LatencyPlane, "note_downstream"),
                          (LatencyPlane, "query_emit"),
                          (LatencyPlane, "tick"),
                          # the tenant ledger rides the same gate: zero
                          # feeds without a session
                          (TenantLedger, "note_dispatch"),
                          (TenantLedger, "resolve"),
                          (TenantLedger, "note_window"),
                          (TenantLedger, "note_shed"),
                          (TenantLedger, "note_breach"),
                          (TenantLedger, "note_quota_rejection"),
                          (TenantLedger, "maybe_tick")):
            wrap(cls, name)

        orig_mem = deviceplane_mod.device_memory

        def mem_spy(*a, **k):
            counter.calls += 1
            return orig_mem(*a, **k)

        monkeypatch.setattr(deviceplane_mod, "device_memory", mem_spy)


class TestDriverTelemetry:
    def test_off_by_default_no_calls_on_record_loop(self, tmp_path,
                                                    monkeypatch, capsys):
        from spatialflink_tpu.driver import main

        spy = _CallCounter(monkeypatch)
        inp = _write_points(tmp_path / "pts.geojson")
        assert active() is None
        assert main(["--config", "conf/spatialflink-conf.yml",
                     "--input1", inp, "--option", "1"]) == 0
        assert spy.calls == 0, \
            "telemetry disabled must leave the record loop uninstrumented"

    def test_status_server_idle_keeps_record_loop_identical(
            self, tmp_path, monkeypatch):
        """The live-plane hot-path guarantee: --status-port with no
        telemetry session leaves the record loop byte-identical to the
        uninstrumented run — zero span/observe/histogram calls — and with
        the server UNQUERIED, zero snapshot constructions (snapshots are
        built on request/interval only, never per record)."""
        from spatialflink_tpu.driver import main
        from spatialflink_tpu.runtime import opserver as opserver_mod

        spy = _CallCounter(monkeypatch)
        snap_calls = []
        orig_status = telemetry_mod.status_snapshot
        monkeypatch.setattr(
            telemetry_mod, "status_snapshot",
            lambda *a, **k: (snap_calls.append(1), orig_status(*a, **k))[1])
        inp = _write_points(tmp_path / "pts.geojson")
        assert active() is None
        assert main(["--config", "conf/spatialflink-conf.yml",
                     "--input1", inp, "--option", "1",
                     "--status-port", "0"]) == 0
        assert spy.calls == 0, \
            "an idle status server must not instrument the record loop"
        assert snap_calls == [], \
            "snapshot construction must happen on request only"
        # the plane died with the pipeline
        assert opserver_mod.active_server() is None

    def test_file_run_covers_ingest_to_sink(self, tmp_path, capsys):
        from spatialflink_tpu.driver import main

        inp = _write_points(tmp_path / "pts.geojson")
        tdir = tmp_path / "tel"
        assert main(["--config", "conf/spatialflink-conf.yml",
                     "--input1", inp, "--option", "1",
                     "--telemetry-dir", str(tdir),
                     "--telemetry-interval", "0.05", "--metrics"]) == 0
        snaps = _snapshots(tdir)
        assert len(snaps) >= 2
        last = snaps[-1]
        # the span taxonomy covers the pipeline end to end
        assert {"ingest", "range.window", "range.kernel", "range.merge",
                "sink"} <= set(last["spans"])
        assert last["histograms"]["window-latency-ms"]["count"] >= 1
        assert last["grid"]["occupied_cells"] >= 1
        assert os.path.exists(os.path.join(str(tdir), "metrics.prom"))
        # --metrics now emits sorted JSON with the degradation digest
        err = capsys.readouterr().err
        metrics_lines = [ln for ln in err.splitlines()
                         if ln.startswith("{")]
        assert metrics_lines, f"no JSON metrics line in stderr: {err!r}"
        payload = json.loads(metrics_lines[-1])
        assert "metrics" in payload and "degradation" in payload
        assert payload["metrics"]["batches-evaluated"] >= 1

    def test_session_leaves_no_active_telemetry(self, tmp_path, capsys):
        from spatialflink_tpu.driver import main

        inp = _write_points(tmp_path / "pts.geojson", n=10)
        assert main(["--config", "conf/spatialflink-conf.yml",
                     "--input1", inp, "--option", "1",
                     "--telemetry-dir", str(tmp_path / "t")]) == 0
        assert active() is None


class TestKafkaFollowAcceptance:
    """The ISSUE acceptance run: a live --kafka-follow driver run with
    --telemetry-dir emits >= 2 JSONL snapshots containing stage spans,
    latency-histogram percentiles, the watermark-lag gauge, and the PR 1
    degradation counters — correlated in one stream."""

    CONTROL = json.dumps({"geometry": {"type": "control", "coordinates": []}})

    def _conf(self, tmp_path, name):
        with open("conf/spatialflink-conf.yml") as f:
            d = yaml.safe_load(f)
        d["kafkaBootStrapServers"] = f"memory://{name}"
        d["window"].update(interval=1, step=1)
        # zero allowed lateness so 1s windows seal ~1s after they fill (the
        # default 1s out-of-orderness would need a 2s+ feed per window)
        d["query"]["thresholds"]["outOfOrderTuples"] = 0
        p = tmp_path / "conf.yml"
        p.write_text(yaml.safe_dump(d))
        return str(p), f"memory://{name}"

    def test_follow_run_snapshots(self, tmp_path, capsys):
        from spatialflink_tpu.driver import main
        from spatialflink_tpu.streams.kafka import (reset_memory_brokers,
                                                    resolve_broker)

        reset_memory_brokers()
        try:
            cfg, url = self._conf(tmp_path, "tel-follow")
            broker = resolve_broker(url)

            def produce():
                for i in range(250):
                    p = Point.create(116.5 + 0.001 * (i % 40), 40.5, GRID,
                                     obj_id=f"veh{i % 7}",
                                     timestamp=int(time.time() * 1000))
                    broker.produce("points.geojson",
                                   serialize_spatial(p, "GeoJSON"))
                    time.sleep(0.01)
                broker.produce("points.geojson", self.CONTROL)

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            tdir = tmp_path / "tel"
            rc = main(["--config", cfg, "--kafka", "--kafka-follow",
                       "--option", "1",
                       # PR 1 machinery engaged so degradation counters are
                       # non-empty in the same snapshot stream
                       "--chaos", "seed=3,fail_next_fetches=2",
                       "--retry", "attempts=8,base_ms=1",
                       "--telemetry-dir", str(tdir),
                       "--telemetry-interval", "0.1"])
            t.join(timeout=30)
            assert rc == 0
            snaps = _snapshots(tdir)
            assert len(snaps) >= 2
            for s in snaps:
                assert SNAPSHOT_KEYS <= set(s)
            last = snaps[-1]
            # stage spans across the pipeline (+ transport)
            assert {"ingest", "range.window", "range.kernel", "range.merge",
                    "kafka.fetch", "kafka.sink", "sink"} <= set(last["spans"])
            # latency histogram percentiles
            wl = last["histograms"]["window-latency-ms"]
            assert wl["count"] >= 1 and "p50" in wl and "p99" in wl
            # watermark-lag gauge (live run: small but present)
            assert "kafka.watermark-lag-ms" in last["gauges"]
            # PR 1 degradation counters in the SAME snapshot stream
            assert last["degradation"].get("chaos-fetch-fail", 0) >= 1
            assert last["degradation"].get("retry-attempts", 0) >= 1
        finally:
            reset_memory_brokers()
