"""Live operations plane tests: the status server's endpoint schemas and
lifecycle, SLO/health evaluation (incl. the 200 -> 503 /healthz flip and
the breach counter/events), the shared status-snapshot digest, the live
Prometheus rewrite, and the driver acceptance runs — a live --kafka-follow
run under --chaos with mid-run endpoint fetches, periodic stderr digests,
and health-stamped JSONL snapshots."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest
import yaml

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.runtime.health import KNOWN_CHECKS, HealthEvaluator
from spatialflink_tpu.runtime.opserver import (LiveStats, OpServer,
                                               active_server, format_digest)
from spatialflink_tpu.streams.formats import serialize_spatial
from spatialflink_tpu.utils import telemetry as telemetry_mod
from spatialflink_tpu.utils.metrics import scoped_registry
from spatialflink_tpu.utils.telemetry import (EventRing, emit_event,
                                              registry_snapshot,
                                              status_snapshot,
                                              telemetry_session)

pytestmark = pytest.mark.liveops

GRID = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)

RAW_KEYS = {"ts_ms", "uptime_s", "spans", "histograms", "gauges",
            "counters", "degradation", "grid", "costs", "traces"}
STATUS_KEYS = {"records_in", "throughput_rps", "windows_evaluated",
               "record_latency_ms", "window_latency_ms", "watermark_lag_ms",
               "commit_backlog", "window_backlog", "pane_cache",
               "checkpoint", "breaker_state", "dlq_depth",
               "mesh_degradations", "slo_breaches", "top_cells",
               "skew", "top_cost_cells", "device", "dispatch_overlap",
               "latency", "controller", "tenants"}


def _get(url, timeout=5):
    """(status_code, parsed-or-text body) for one GET, 4xx/5xx included."""
    try:
        resp = urllib.request.urlopen(url, timeout=timeout)
        code, body = resp.status, resp.read()
        ctype = resp.headers.get("Content-Type", "")
    except urllib.error.HTTPError as e:
        code, body = e.code, e.read()
        ctype = e.headers.get("Content-Type", "")
    if "json" in ctype:
        return code, json.loads(body)
    return code, body.decode()


class TestHealthEvaluator:
    def test_spec_parsing(self):
        h = HealthEvaluator.from_spec(
            "watermark_lag_ms=5000, p99_window_ms=250,commit_backlog=1e4")
        assert h.thresholds == {"watermark_lag_ms": 5000.0,
                                "p99_window_ms": 250.0,
                                "commit_backlog": 10000.0}

    def test_unknown_check_names_the_known_set(self):
        with pytest.raises(ValueError, match="watermark_lag_ms"):
            HealthEvaluator.from_spec("wobble=3")
        with pytest.raises(ValueError, match="key=value"):
            HealthEvaluator.from_spec("watermark_lag_ms")
        with pytest.raises(ValueError, match="not numeric"):
            HealthEvaluator.from_spec("watermark_lag_ms=fast")
        with pytest.raises(ValueError, match="at least one"):
            HealthEvaluator.from_spec("")

    def test_missing_data_is_healthy_not_breached(self):
        # a pipeline that has not produced a window/gauge yet is starting
        # up, not breaching — every check must tolerate absent instruments
        # (scoped registry: earlier suites' global dlq/degradation counters
        # must not masquerade as this fresh pipeline's state)
        h = HealthEvaluator({k: 1.0 for k in KNOWN_CHECKS})
        with scoped_registry():
            verdict = h.evaluate(registry_snapshot())
        assert verdict["healthy"] and verdict["status"] == "ok"
        assert set(verdict["checks"]) == set(KNOWN_CHECKS)
        # gauge/histogram-backed checks read None (never set); the
        # counter-backed ones (dlq_depth, ...) legitimately read 0
        assert all(c["ok"] and c["value"] in (None, 0)
                   for c in verdict["checks"].values())

    def test_breach_transition_counts_once_and_recovers(self):
        with scoped_registry() as reg, telemetry_session() as tel:
            h = HealthEvaluator.from_spec("watermark_lag_ms=10")
            tel.gauge("kafka.watermark-lag-ms").set(50.0)
            for _ in range(3):  # sustained breach = ONE transition
                verdict = h.evaluate(tel.snapshot())
                assert not verdict["healthy"]
                assert verdict["status"] == "breach"
                assert verdict["checks"]["watermark_lag_ms"] == {
                    "value": 50.0, "threshold": 10.0, "ok": False}
            assert reg.counter("slo-breaches").count == 1
            kinds = [e["kind"] for e in tel.events.list()]
            assert kinds == ["slo-breach", "watermark-stall"]
            tel.gauge("kafka.watermark-lag-ms").set(2.0)
            assert h.evaluate(tel.snapshot())["healthy"]
            assert tel.events.list()[-1]["kind"] == "slo-recovered"
            # re-breach is a NEW transition
            tel.gauge("kafka.watermark-lag-ms").set(99.0)
            h.evaluate(tel.snapshot())
            assert reg.counter("slo-breaches").count == 2

    def test_min_throughput_breaches_low_not_high(self):
        with scoped_registry() as reg:
            h = HealthEvaluator.from_spec("min_throughput_rps=100")
            # no records yet -> unknown -> healthy (startup, not a stall)
            assert h.evaluate(registry_snapshot())["healthy"]
            reg.meter("ingest-throughput").mark(5)  # ~5 rec total, low rate
            verdict = h.evaluate(registry_snapshot())
            assert not verdict["healthy"]


class TestStatusSnapshot:
    def test_digest_surfaces_operator_fields(self):
        with scoped_registry() as reg, telemetry_session() as tel:
            reg.counter("pane-cache-hits").inc(30)
            reg.counter("pane-cache-misses").inc(10)
            reg.counter("checkpoints-written").inc(2)
            reg.counter("dlq-records").inc(1)
            reg.counter("batches-evaluated").inc(7)
            reg.meter("ingest-throughput").mark(100)
            tel.gauge("checkpoint.seq").set(2.0)
            tel.gauge("checkpoint.age-s").set(1.25)
            tel.gauge("broker.breaker-state").set(0.5)
            tel.gauge("kafka.watermark-lag-ms").set(42.0)
            tel.histogram("window-latency-ms").record(8.0)
            tel.record_cells(__import__("numpy").array([3, 3, 9]))
            snap = status_snapshot(tel)
        assert RAW_KEYS <= set(snap)
        st = snap["status"]
        assert set(st) == STATUS_KEYS
        assert st["pane_cache"] == {"hits": 30, "misses": 10,
                                    "hit_rate": 0.75}
        assert st["checkpoint"]["seq"] == 2.0
        assert st["checkpoint"]["age_s"] == 1.25
        assert st["checkpoint"]["written"] == 2
        assert st["breaker_state"] == 0.5
        assert st["dlq_depth"] == 1
        assert st["records_in"] == 100
        assert st["windows_evaluated"] == 7
        assert st["watermark_lag_ms"] == 42.0
        assert st["window_latency_ms"]["count"] == 1
        assert st["top_cells"][0][0] == 3
        # skew-concentration gauges (top-cell share / Gini) ride the same
        # digest — the observable form of the --adaptive-grid trigger
        assert st["skew"]["top_share"] == pytest.approx(2 / 3, abs=1e-3)
        assert 0.0 <= st["skew"]["gini"] <= 1.0
        assert st["skew"]["factor"] == pytest.approx(4 / 3, abs=1e-3)
        # the whole document is JSON-serializable as-is
        json.dumps(snap)

    def test_registry_only_fallback_without_session(self):
        assert telemetry_mod.active() is None
        with scoped_registry() as reg:
            reg.counter("batches-evaluated").inc(3)
            snap = status_snapshot()
        assert RAW_KEYS <= set(snap)
        assert snap["uptime_s"] is None and snap["spans"] == {}
        assert snap["counters"]["batches-evaluated"] == 3
        assert snap["status"]["windows_evaluated"] == 3
        assert snap["status"]["watermark_lag_ms"] is None

    def test_health_stamped_from_session(self):
        h = HealthEvaluator.from_spec("dlq_depth=0")
        with scoped_registry() as reg, telemetry_session(health=h) as tel:
            assert status_snapshot(tel)["health"]["healthy"]
            reg.counter("dlq-records").inc()
            assert status_snapshot(tel)["health"]["status"] == "breach"

    def test_format_digest_one_line(self):
        with scoped_registry(), telemetry_session() as tel:
            tel.gauge("kafka.watermark-lag-ms").set(130.0)
            line = format_digest(status_snapshot(tel))
        assert line.startswith("# live: ") and "\n" not in line
        assert "wm lag 130ms" in line


class TestEventRing:
    def test_capacity_eviction_and_total(self):
        ring = EventRing(capacity=4)
        for i in range(10):
            ring.append("e", i=i)
        evs = ring.list()
        assert len(evs) == 4 and ring.total == 10
        assert [e["i"] for e in evs] == [6, 7, 8, 9]
        assert all("ts_ms" in e and e["kind"] == "e" for e in evs)

    def test_emit_event_noop_without_session(self):
        assert telemetry_mod.active() is None
        emit_event("orphan", x=1)  # must not raise, must not record
        with telemetry_session() as tel:
            emit_event("kept", x=2)
            assert [e["kind"] for e in tel.events.list()] == ["kept"]


class TestOpServer:
    def test_endpoints_schemas_ephemeral_port_and_shutdown(self):
        with scoped_registry() as reg, telemetry_session() as tel:
            reg.counter("batches-evaluated").inc(5)
            tel.event("checkpoint-committed", seq=1)
            srv = OpServer(port=0).start()
            try:
                assert srv.port and srv.port > 0  # ephemeral bind
                assert active_server() is srv
                code, health = _get(srv.url + "/healthz")
                assert code == 200 and health == {
                    "healthy": True, "status": "ok", "checks": {}}
                code, status = _get(srv.url + "/status")
                assert code == 200
                assert RAW_KEYS | {"status"} <= set(status)
                assert status["status"]["windows_evaluated"] == 5
                code, prom = _get(srv.url + "/metrics")
                assert code == 200
                assert 'spatialflink_counter{name="batches-evaluated"} 5' \
                    in prom
                code, events = _get(srv.url + "/events")
                assert code == 200 and events["total"] == 1
                assert events["events"][0]["kind"] == "checkpoint-committed"
                code, missing = _get(srv.url + "/nope")
                assert code == 404 and "/status" in missing["endpoints"]
                assert srv.requests_served == 5
            finally:
                port = srv.port
                srv.close()
        assert active_server() is None
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=1)

    def test_method_handling_405_with_allow_and_404_elsewhere(self):
        """Satellite fix: non-GET on a known route must be a proper JSON
        405 carrying ``Allow:`` (NOT http.server's default bare 501), an
        unknown path is 404 whatever the method, and the mutating
        /queries verbs answer for real."""

        def req(url, method, body=None):
            r = urllib.request.Request(
                url, method=method,
                data=None if body is None else json.dumps(body).encode())
            try:
                resp = urllib.request.urlopen(r, timeout=3)
                code, raw, hdrs = resp.status, resp.read(), resp.headers
            except urllib.error.HTTPError as e:
                code, raw, hdrs = e.code, e.read(), e.headers
            payload = (json.loads(raw)
                       if raw and "json" in hdrs.get("Content-Type", "")
                       else None)  # HEAD responses carry headers only
            return code, payload, hdrs

        srv = OpServer(port=0).start()
        try:
            u = srv.url
            # known GET-only routes: JSON 405 + Allow for every other verb
            for path in ("/status", "/healthz", "/metrics", "/events",
                         "/partition", "/trace/recent", "/trace/some-id",
                         "/profile/cells"):
                for method in ("POST", "DELETE", "PUT", "PATCH", "HEAD"):
                    code, payload, hdrs = req(u + path, method, body={})
                    assert code == 405, (path, method, code)
                    assert hdrs.get("Allow") == "GET", (path, method)
                    if method != "HEAD":  # HEAD: headers only
                        assert payload["allow"] == ["GET"]
                        assert path in payload["error"]
            # unknown paths: 404 for ANY method, with the endpoint list
            for method in ("GET", "POST", "DELETE", "PUT", "PATCH"):
                code, payload, _ = req(u + "/wat", method, body={})
                assert code == 404 and "/queries" in payload["endpoints"]
            # /queries knows GET+POST; DELETE lives on /queries/<id>
            code, _, hdrs = req(u + "/queries", "DELETE")
            assert code == 405 and hdrs.get("Allow") == "GET, POST"
            code, _, hdrs = req(u + "/queries/some-id", "POST", body={})
            assert code == 405 and hdrs.get("Allow") == "GET, DELETE"
            # without a registry the query surface answers, not crashes
            assert req(u + "/queries", "GET")[0] == 200
            assert req(u + "/queries", "POST", body={"id": "x"})[0] == 409
            assert req(u + "/queries/x", "DELETE")[0] == 409
            # a POST body that is not JSON is a 400, not a traceback
            r = urllib.request.Request(u + "/queries", method="POST",
                                       data=b"{nope")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(r, timeout=3)
            assert ei.value.code == 400
        finally:
            srv.close()

    def test_queries_surface_with_live_registry(self):
        """POST /queries admits (then updates), GET lists, DELETE drains —
        the HTTP admission surface against an installed registry."""
        from spatialflink_tpu.runtime.queryplane import QueryRegistry

        def req(url, method="GET", body=None):
            r = urllib.request.Request(
                url, method=method,
                data=None if body is None else json.dumps(body).encode())
            try:
                resp = urllib.request.urlopen(r, timeout=3)
                return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        srv = OpServer(port=0).start()
        reg = QueryRegistry("range", radius=0.5).install()
        try:
            u = srv.url
            code, body = req(u + "/queries", "POST",
                             {"id": "q1", "x": 116.5, "y": 40.3})
            assert code == 200 and body["query"]["state"] == "pending"
            assert body["applies"] == "at the next window boundary"
            assert req(u + "/queries", "POST", {"id": "q1"})[0] == 400
            reg.apply()
            code, body = req(u + "/queries")
            assert code == 200 and body["fleet"] == ["q1"]
            assert body["live"] == 1 and body["bucket"] == 1
            code, body = req(u + "/queries/q1")
            assert code == 200 and body["state"] == "active"
            assert req(u + "/queries/ghost")[0] == 404
            code, body = req(u + "/queries/q1", "DELETE")
            assert code == 200 and body["query"]["state"] == "draining"
            reg.apply()
            assert req(u + "/queries/q1", "DELETE")[0] == 404
        finally:
            reg.uninstall()
            srv.close()

    def test_healthz_flips_200_to_503_on_injected_breach(self):
        h = HealthEvaluator.from_spec("watermark_lag_ms=10")
        with scoped_registry() as reg, telemetry_session(health=h) as tel:
            srv = OpServer(port=0).start()
            try:
                tel.gauge("kafka.watermark-lag-ms").set(3.0)
                code, verdict = _get(srv.url + "/healthz")
                assert code == 200 and verdict["healthy"]
                tel.gauge("kafka.watermark-lag-ms").set(5000.0)  # breach
                code, verdict = _get(srv.url + "/healthz")
                assert code == 503 and not verdict["healthy"]
                assert verdict["checks"]["watermark_lag_ms"]["ok"] is False
                assert reg.counter("slo-breaches").count == 1
                # the /status document agrees (same evaluator instance)
                _, status = _get(srv.url + "/status")
                assert status["health"]["status"] == "breach"
                assert status["status"]["slo_breaches"] == 1
                tel.gauge("kafka.watermark-lag-ms").set(3.0)  # recover
                code, _ = _get(srv.url + "/healthz")
                assert code == 200
            finally:
                srv.close()

    def test_no_session_serves_registry_counters(self):
        assert telemetry_mod.active() is None
        with scoped_registry() as reg:
            reg.counter("records-evaluated").inc(11)
            srv = OpServer(port=0,
                           health=HealthEvaluator.from_spec(
                               "commit_backlog=5")).start()
            try:
                code, status = _get(srv.url + "/status")
                assert code == 200
                assert status["counters"]["records-evaluated"] == 11
                assert status["spans"] == {}  # no session, no spans
                code, verdict = _get(srv.url + "/healthz")
                assert code == 200  # backlog gauge absent -> unknown -> ok
                code, events = _get(srv.url + "/events")
                assert events["events"] == [] and "note" in events
            finally:
                srv.close()


class TestLivePromRewrite:
    def test_metrics_prom_rewritten_per_snapshot(self, tmp_path):
        """Satellite: metrics.prom is live, not close-only — a scraper
        pointed at the file mid-run sees values that keep moving."""
        with scoped_registry() as reg, \
                telemetry_session(str(tmp_path), interval_s=0.05):
            reg.counter("batches-evaluated").inc(1)
            deadline = time.monotonic() + 5.0
            prom_path = os.path.join(str(tmp_path), "metrics.prom")
            while time.monotonic() < deadline:
                if os.path.exists(prom_path) and \
                        'name="batches-evaluated"} 1' in open(prom_path).read():
                    break
                time.sleep(0.02)
            else:
                pytest.fail("live metrics.prom never showed the counter")
            reg.counter("batches-evaluated").inc(41)
            while time.monotonic() < deadline:
                if 'name="batches-evaluated"} 42' in open(prom_path).read():
                    break
                time.sleep(0.02)
            else:
                pytest.fail("metrics.prom was not rewritten mid-run")
        # the close-time dump still lands (and reflects the final state)
        assert 'name="batches-evaluated"} 42' in open(prom_path).read()


class TestLiveStats:
    def test_periodic_digest_lines(self, capsys):
        with scoped_registry() as reg, telemetry_session():
            reg.meter("ingest-throughput").mark(10)
            live = LiveStats(interval_s=0.05).start()
            time.sleep(0.2)
            live.close()
        lines = [ln for ln in capsys.readouterr().err.splitlines()
                 if ln.startswith("# live: ")]
        assert len(lines) >= 2  # immediate + periodic(s) + final
        assert live.emitted == len(lines)
        assert any("in 10 rec" in ln for ln in lines)


def _follow_conf(tmp_path, name):
    with open("conf/spatialflink-conf.yml") as f:
        d = yaml.safe_load(f)
    d["kafkaBootStrapServers"] = f"memory://{name}"
    d["window"].update(interval=1, step=1)
    d["query"]["thresholds"]["outOfOrderTuples"] = 0
    p = tmp_path / "conf.yml"
    p.write_text(yaml.safe_dump(d))
    return str(p), f"memory://{name}"


CONTROL = json.dumps({"geometry": {"type": "control", "coordinates": []}})


class _Poller(threading.Thread):
    """Fetches the plane's endpoints MID-RUN: waits for the driver's
    ephemeral server, then polls /status until live (non-initial) numbers
    appear, then grabs every endpoint."""

    def __init__(self, min_records=1):
        super().__init__(daemon=True)
        self.min_records = min_records
        self.result: dict = {}

    def run(self):
        deadline = time.monotonic() + 25.0
        srv = None
        while time.monotonic() < deadline and srv is None:
            srv = active_server()
            if srv is None or srv.port is None:
                srv = None
                time.sleep(0.01)
        if srv is None:
            self.result["error"] = "status server never came up"
            return
        while time.monotonic() < deadline:
            try:
                code, status = _get(srv.url + "/status", timeout=2)
            except Exception:
                time.sleep(0.05)
                continue
            st = status.get("status", {})
            if (code == 200 and st.get("records_in", 0) >= self.min_records
                    and st.get("watermark_lag_ms") is not None
                    and status.get("degradation")):
                self.result["status"] = status
                break
            time.sleep(0.05)
        else:
            self.result["error"] = "live /status never matured"
            return
        try:
            self.result["healthz"] = _get(srv.url + "/healthz", timeout=2)
            self.result["metrics"] = _get(srv.url + "/metrics", timeout=2)
            self.result["events"] = _get(srv.url + "/events", timeout=2)
            # a later /status so breach counters had a chance to land
            time.sleep(0.3)
            self.result["status2"] = _get(srv.url + "/status", timeout=2)[1]
            self.result["port"] = srv.port
        except Exception as e:  # pragma: no cover - diagnostic only
            self.result["error"] = repr(e)


class TestLiveFollowAcceptance:
    """The ISSUE acceptance run: a live --kafka-follow --status-port 0
    --telemetry-dir run under --chaos, with a mid-run client asserting
    well-formed live endpoint payloads, the SLO breach flipping /healthz
    to 503, nonzero retry/breaker counters in /status correlated with the
    degradation digest, >= 2 periodic stderr digests and JSONL snapshots
    before the stream ends, and server shutdown on pipeline exit."""

    def test_follow_chaos_live_plane(self, tmp_path, capsys):
        from spatialflink_tpu.driver import main
        from spatialflink_tpu.streams.kafka import (reset_memory_brokers,
                                                    resolve_broker)

        reset_memory_brokers()
        try:
            cfg, url = _follow_conf(tmp_path, "liveops-follow")
            broker = resolve_broker(url)

            def produce():
                for i in range(250):
                    p = Point.create(116.5 + 0.001 * (i % 40), 40.5, GRID,
                                     obj_id=f"veh{i % 7}",
                                     timestamp=int(time.time() * 1000))
                    broker.produce("points.geojson",
                                   serialize_spatial(p, "GeoJSON"))
                    time.sleep(0.01)
                broker.produce("points.geojson", CONTROL)

            t = threading.Thread(target=produce, daemon=True)
            poller = _Poller()
            t.start()
            poller.start()
            tdir = tmp_path / "tel"
            rc = main(["--config", cfg, "--kafka", "--kafka-follow",
                       "--option", "1", "--status-port", "0",
                       "--chaos", "seed=3,fail_next_fetches=2",
                       "--retry", "attempts=8,base_ms=1",
                       # any real lag breaches: the injected-SLO-breach shape
                       "--slo", "watermark_lag_ms=0.0001",
                       "--telemetry-dir", str(tdir),
                       "--telemetry-interval", "0.1"])
            t.join(timeout=30)
            poller.join(timeout=30)
            assert rc == 0
            res = poller.result
            assert "error" not in res, res
            # --- live /status mid-run: non-initial values, full schema ---
            status = res["status"]
            assert RAW_KEYS | {"status", "health"} <= set(status)
            st = status["status"]
            assert set(st) == STATUS_KEYS
            assert st["records_in"] >= 1
            assert st["watermark_lag_ms"] is not None
            # --- chaos counters in /status, correlated with the summary ---
            assert status["degradation"].get("chaos-fetch-fail", 0) >= 1
            assert status["degradation"].get("retry-attempts", 0) >= 1
            # --- injected SLO breach: /healthz 503 + breach counter ---
            code, verdict = res["healthz"]
            assert code == 503 and not verdict["healthy"]
            assert verdict["checks"]["watermark_lag_ms"]["ok"] is False
            assert res["status2"]["status"]["slo_breaches"] >= 1
            # --- live /metrics: prometheus families present mid-run ---
            code, prom = res["metrics"]
            assert code == 200
            assert "spatialflink_counter" in prom
            assert 'name="ingest-throughput.count"' in prom
            # --- events ring reachable mid-run (chaos run may or may not
            # trip the breaker; the SLO breach events are deterministic) ---
            code, events = res["events"]
            assert code == 200
            kinds = {e["kind"] for e in events["events"]}
            assert "slo-breach" in kinds and "watermark-stall" in kinds
            # --- >= 2 periodic digests/snapshots BEFORE the run ended ---
            err = capsys.readouterr().err
            digests = [ln for ln in err.splitlines()
                       if ln.startswith("# live: ")]
            assert len(digests) >= 2, err
            assert "degraded" in err  # kafka summary digest correlation
            with open(os.path.join(str(tdir), "telemetry.jsonl")) as f:
                snaps = [json.loads(line) for line in f]
            assert len(snaps) >= 3  # start + >=1 periodic mid-run + final
            for s in snaps:
                assert "status" in s and "health" in s
            assert snaps[-1]["health"]["status"] == "breach"
            assert snaps[-1]["status"]["slo_breaches"] >= 1
            # --- the plane died with the pipeline ---
            assert active_server() is None
            with pytest.raises(urllib.error.URLError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{res['port']}/status", timeout=1)
        finally:
            reset_memory_brokers()

    @pytest.mark.slow
    def test_follow_panes_checkpoint_soak(self, tmp_path):
        """Longer follow soak: --panes + --checkpoint-dir under the plane;
        /status surfaces the pane-cache hit rate and checkpoint seq/age
        (the PR 3/PR 4 gauges an operator reads first) and /events carries
        checkpoint-committed entries, all mid-run."""
        from spatialflink_tpu.driver import main
        from spatialflink_tpu.streams.kafka import (reset_memory_brokers,
                                                    resolve_broker)

        reset_memory_brokers()
        try:
            with open("conf/spatialflink-conf.yml") as f:
                d = yaml.safe_load(f)
            d["kafkaBootStrapServers"] = "memory://liveops-soak"
            d["window"].update(interval=4, step=1)  # overlap 4 -> pane reuse
            d["query"]["thresholds"]["outOfOrderTuples"] = 0
            cfg = tmp_path / "conf.yml"
            cfg.write_text(yaml.safe_dump(d))
            broker = resolve_broker("memory://liveops-soak")

            def produce():
                # ~8s of wall-time event data: windows (4s, slide 1s) seal
                # from ~4s on, so the mid-run poll has a multi-second span
                # in which pane hits AND a checkpoint both already happened
                for i in range(800):
                    p = Point.create(116.5 + 0.001 * (i % 40), 40.5, GRID,
                                     obj_id=f"veh{i % 7}",
                                     timestamp=int(time.time() * 1000))
                    broker.produce("points.geojson",
                                   serialize_spatial(p, "GeoJSON"))
                    time.sleep(0.01)
                broker.produce("points.geojson", CONTROL)

            got: dict = {}

            def poll():
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    srv = active_server()
                    if srv is None or srv.port is None:
                        time.sleep(0.02)
                        continue
                    try:
                        _, status = _get(srv.url + "/status", timeout=2)
                    except Exception:
                        time.sleep(0.05)
                        continue
                    st = status.get("status", {})
                    if (st.get("pane_cache", {}).get("hits", 0) >= 1
                            and (st.get("checkpoint", {}).get("seq") or 0)
                            >= 1):
                        got["status"] = status
                        got["events"] = _get(srv.url + "/events",
                                             timeout=2)[1]
                        return
                    time.sleep(0.05)

            t = threading.Thread(target=produce, daemon=True)
            pt = threading.Thread(target=poll, daemon=True)
            t.start()
            pt.start()
            rc = main(["--config", str(cfg), "--kafka", "--kafka-follow",
                       "--option", "1", "--panes",
                       "--checkpoint-dir", str(tmp_path / "ckpt"),
                       "--checkpoint-every", "2",
                       "--status-port", "0",
                       "--telemetry-dir", str(tmp_path / "tel"),
                       "--telemetry-interval", "0.1"])
            t.join(timeout=30)
            pt.join(timeout=30)
            assert rc == 0
            assert "status" in got, "live /status never showed pane " \
                                    "hits + checkpoint seq mid-run"
            st = got["status"]["status"]
            assert st["pane_cache"]["hit_rate"] > 0
            assert st["checkpoint"]["seq"] >= 1
            assert st["checkpoint"]["age_s"] is not None
            assert st["checkpoint"]["write_ms"]["count"] >= 1
            kinds = [e["kind"] for e in got["events"]["events"]]
            assert "checkpoint-committed" in kinds
        finally:
            reset_memory_brokers()
