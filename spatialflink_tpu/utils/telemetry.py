"""Structured telemetry: spans, streaming histograms, gauges, reporter.

The reference exposes its pipeline through Flink's web UI and Dropwizard
meters (SURVEY §5); the rebuild's counters (:mod:`.metrics`) say *how much*
work happened but not *where the time went*. This layer adds the missing
dimensions, all host-side and all O(1) per observation:

- :meth:`Telemetry.span` — a context manager recording count / total / max /
  self (minus-children) wall-clock per named stage, nesting-aware via a
  thread-local stack, composing with :func:`~.metrics.trace` so every span
  is also a jax.profiler annotation when a ``--profile`` capture is running.
  Stage names are query-scoped (``knn.kernel`` vs one flat namespace) so
  ``--multi-query`` and multi-family runs stay separable.
- :class:`StreamingHistogram` — fixed log-bucket histogram (geometric
  buckets, O(1) record, constant memory) exposing p50/p95/p99/max; the
  per-record and per-window latency distributions ride it instead of an
  unbounded sample list.
- :class:`Gauge` — last-value (or callable) gauges: watermark lag, window
  backlog, breaker state.
- :class:`CellOccupancy` — grid-cell assignment counts from
  :meth:`~spatialflink_tpu.index.uniform_grid.UniformGrid.assign_cell`
  (installed as the grid module's observer hook only while a session is
  active): top-k hottest cells and a max/mean skew factor — the keyBy(grid)
  hot-spot signal the reference reads off Flink's backpressure UI.
- :class:`TelemetryReporter` — a daemon thread emitting one JSONL snapshot
  to ``--telemetry-dir`` immediately, every ``--telemetry-interval``
  seconds, and at close (so even a short run yields >= 2 snapshots), and
  REWRITING the Prometheus text dump (``metrics.prom``) on every snapshot
  so a file-pointed scraper sees live values, not only the final state.
  Snapshots embed the ambient registry's counters AND
  :func:`~.metrics.degradation_snapshot`, so PR 1's retry/breaker/DLQ
  events correlate with stage timings by timestamp in one stream.
- :class:`EventRing` / :func:`emit_event` — a bounded ring of structured
  lifecycle events (checkpoint committed/fallback, breaker transitions,
  DLQ quarantine, mesh degradation, SLO breach/recovery) served by the
  status server's ``/events`` endpoint and dropped for free when no
  session is active.
- :class:`WindowTraceBook` — per-window TRACE LINEAGE: every emitted
  window carries a trace record (stable id derived from
  ``(query, window_start)``) whose events walk the window's life —
  first-record ingest, assembly, pane seals, kernel dispatch, merge/
  readback, emit, driver sink, Kafka sink commit — with wall-clock
  timestamps and durations, buffered in a bounded ring and exportable as
  Chrome trace-event JSON (Perfetto-loadable; the driver's
  ``--trace-dir``). Opt-in per session (``trace=True`` /
  ``trace_dir=``): a plain telemetry session records no traces, so the
  PR 2/5 session cost is unchanged unless tracing is asked for.
- :class:`CostProfiles` — WHO PAYS: per-grid-cell and per-query-family
  cost accumulators (records in, attributed kernel/merge wall-clock,
  pane-cache hits/misses, approximate bytes moved) fed from the existing
  ``record_cells`` observer hook and the family-labeled spans in
  ``operators/base.py``, plus a bounded windowed time series (one bucket
  per snapshot interval, closed by the reporter or the
  ``/profile/cells`` scrape) so skew COST — not just occupancy — is visible
  and ratcheting. Kernel time is attributed to cells proportionally to
  the records that arrived since the previous dispatch (the new slide of
  data at steady state); documented as attribution, not measurement.
- :func:`status_snapshot` / :func:`status_digest` — THE definition of
  "current pipeline state": the raw snapshot plus a derived operator
  digest (throughput, latency percentiles, watermark lag, backlogs,
  pane-cache hit rate, checkpoint age/seq, breaker/DLQ/mesh state, top
  cells) shared verbatim by the reporter's JSONL lines, the status
  server's ``/status``, and the ``--live-stats`` stderr digest — one
  schema, three consumers. With no active session it degrades to a
  registry-only view (the always-on counters/meters), so a bare
  ``--status-port`` run serves real numbers while the record loop stays
  byte-identical to the uninstrumented path.

OFF BY DEFAULT: :func:`active` returns None until a
:func:`telemetry_session` is entered, and every instrumented hot path
checks that once per stream/loop (not per record) — a disabled run executes
the exact pre-telemetry code.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import math
import os
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from spatialflink_tpu.utils import metrics as _metrics
from spatialflink_tpu.utils.metrics import trace


class SpanStats:
    """Aggregate wall-clock stats for one named stage."""

    __slots__ = ("name", "count", "total_s", "max_s", "self_s", "errors")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        #: total minus time spent in CHILD spans (the nesting-aware part:
        #: an outer "window" span wrapping a "kernel" span reports how much
        #: of the window was NOT kernel)
        self.self_s = 0.0
        self.errors = 0

    def to_dict(self) -> dict:
        return {
            "count": self.count,
            "total_ms": round(self.total_s * 1e3, 3),
            "max_ms": round(self.max_s * 1e3, 3),
            "self_ms": round(self.self_s * 1e3, 3),
            "errors": self.errors,
        }


class _Span:
    """One span activation. Class-based (not a generator contextmanager) so
    a StopIteration raised INSIDE the block propagates normally — spans wrap
    ``next()`` calls on the window assembly path."""

    __slots__ = ("tel", "name", "t0", "child_s", "_trace")

    def __init__(self, tel: "Telemetry", name: str):
        self.tel = tel
        self.name = name
        self.child_s = 0.0

    def __enter__(self) -> "_Span":
        self._trace = trace(self.name)
        self._trace.__enter__()
        self.tel._stack().append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        dt = time.perf_counter() - self.t0
        stack = self.tel._stack()
        if stack and stack[-1] is self:
            stack.pop()
        if stack:
            stack[-1].child_s += dt
        st = self.tel._span_stats(self.name)
        st.count += 1
        st.total_s += dt
        st.self_s += max(0.0, dt - self.child_s)
        if dt > st.max_s:
            st.max_s = dt
        # StopIteration through a span is normal control flow (the span
        # times the pull from an exhausted iterator), not a stage failure
        if et is not None and et is not StopIteration:
            st.errors += 1
        self._trace.__exit__(et, ev, tb)
        return False


class StreamingHistogram:
    """Fixed log-bucket streaming histogram: O(1) per record, constant
    memory, percentiles by cumulative bucket walk.

    Bucket ``i >= 1`` covers ``[lo * growth**(i-1), lo * growth**i)``;
    bucket 0 is the underflow bucket (values <= lo, including zeros and
    negatives); the last bucket absorbs overflow. A percentile returns the
    geometric midpoint of its bucket clamped to the observed [min, max], so
    the relative error is bounded by ``sqrt(growth)`` (~4.4% at the default
    8-buckets-per-octave growth) — the Dropwizard-reservoir answer without
    sampling jitter or per-record allocation.
    """

    __slots__ = ("name", "lo", "growth", "_log_lo", "_log_g", "_nb",
                 "counts", "count", "total", "min", "max")

    def __init__(self, name: str = "", lo: float = 1e-3, hi: float = 1e7,
                 growth: float = 2.0 ** 0.125):
        if not (lo > 0 and hi > lo and growth > 1.0):
            raise ValueError("need 0 < lo < hi and growth > 1")
        self.name = name
        self.lo = lo
        self.growth = growth
        self._log_lo = math.log(lo)
        self._log_g = math.log(growth)
        self._nb = int(math.ceil((math.log(hi) - self._log_lo) / self._log_g))
        self.counts: List[int] = [0] * (self._nb + 2)
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, value: float) -> None:
        self.count += 1
        self.total += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        if value <= self.lo:
            idx = 0
        else:
            idx = int((math.log(value) - self._log_lo) / self._log_g) + 1
            if idx > self._nb + 1:
                idx = self._nb + 1
        self.counts[idx] += 1

    def _bucket_value(self, idx: int) -> float:
        if idx == 0:
            return self.min if self.min < math.inf else self.lo
        if idx == self._nb + 1:
            # overflow bucket: the midpoint would lie about anything past
            # hi; the observed max is the honest representative
            return self.max
        # geometric midpoint of the bucket
        return math.exp(self._log_lo + (idx - 0.5) * self._log_g)

    def percentile(self, p: float) -> float:
        if not self.count:
            return 0.0
        target = max(1, math.ceil(self.count * min(max(p, 0.0), 100.0) / 100.0))
        cum = 0
        for idx, n in enumerate(self.counts):
            cum += n
            if cum >= target:
                v = self._bucket_value(idx)
                return float(min(max(v, self.min), self.max))
        return float(self.max)  # pragma: no cover - cum always reaches count

    def to_dict(self) -> dict:
        if not self.count:
            return {"count": 0}
        return {
            "count": self.count,
            "sum": round(self.total, 3),
            "min": round(self.min, 3),
            "max": round(self.max, 3),
            "p50": round(self.percentile(50), 3),
            "p95": round(self.percentile(95), 3),
            "p99": round(self.percentile(99), 3),
        }


class Gauge:
    """Last-value gauge; construct with ``fn`` for pull-style gauges that
    are read at snapshot time."""

    __slots__ = ("name", "fn", "_value")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.fn = fn
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    def get(self) -> float:
        if self.fn is not None:
            try:
                return float(self.fn())
            except Exception:
                return float("nan")
        return self._value


class CellOccupancy:
    """Grid-cell assignment counts: top-k hottest cells + skew (max/mean
    over occupied cells). Fed int arrays (or scalars) of cell ids; invalid
    cells (-1) are dropped. Vectorized bincount accumulation — cheap even
    on the 1M-point bulk ingest paths."""

    def __init__(self):
        import numpy as np

        self._np = np
        self._counts = np.zeros(0, dtype=np.int64)

    def _ensure(self, hi: int) -> None:
        if hi > self._counts.size:
            np = self._np
            grown = np.zeros(max(hi, 2 * self._counts.size), dtype=np.int64)
            grown[: self._counts.size] = self._counts
            self._counts = grown

    def record_scalar(self, ci: int) -> None:
        """One pre-validated cell id (>= 0): a bounds check + increment."""
        self._ensure(ci + 1)
        self._counts[ci] += 1

    def record_counts(self, hi: int, counts) -> None:
        """A pre-normalized bincount (valid cells only, length ``hi``)."""
        self._ensure(hi)
        self._counts[:hi] += counts

    def record(self, cells) -> None:
        # scalar fast path: the per-record streaming ingest assigns one
        # cell at a time — a single bounds check + increment, O(1), no
        # array construction (the vectorized branch below would cost
        # O(num_cells) per record and dwarf the parse it observes).
        # Telemetry.record_cells normalizes ONCE and calls the
        # record_scalar/record_counts halves directly so the cost-profile
        # twin shares the same pass; this entry serves direct callers.
        norm = normalize_cells(cells, self._np)
        if norm is None:
            return
        kind, a, b = norm
        if kind == "scalar":
            self.record_scalar(a)
        else:
            self.record_counts(a, b)

    def top_k(self, k: int = 8) -> List[Tuple[int, int]]:
        np = self._np
        nz = np.nonzero(self._counts)[0]
        if nz.size == 0:
            return []
        order = nz[np.argsort(self._counts[nz])[::-1][:k]]
        return [(int(c), int(self._counts[c])) for c in order]

    def skew(self) -> float:
        """max/mean over occupied cells; 1.0 = perfectly uniform."""
        np = self._np
        nz = self._counts[self._counts > 0]
        if nz.size == 0:
            return 0.0
        return float(nz.max() / nz.mean())

    def top_share(self) -> float:
        """The hottest cell's share of ALL recorded assignments — the
        skew-concentration number the repartition split threshold is
        compared against (``--adaptive-grid`` splits when an epoch share
        crosses ``split_share``), surfaced so the trigger is observable
        before it fires."""
        total = int(self._counts.sum())
        if total == 0:
            return 0.0
        return float(self._counts.max()) / total

    def gini(self) -> float:
        """Gini coefficient of the per-cell record distribution over
        OCCUPIED cells: 0 = perfectly uniform, ->1 = everything in one
        cell. Companion concentration gauge to :meth:`top_share` (top
        share sees only the single hottest cell; Gini sees the whole
        tail)."""
        np = self._np
        nz = np.sort(self._counts[self._counts > 0].astype(np.float64))
        m = nz.size
        if m == 0:
            return 0.0
        total = float(nz.sum())
        if total <= 0 or m == 1:
            return 0.0
        # standard mean-difference form over the sorted counts
        idx = np.arange(1, m + 1)
        return float((2.0 * (idx * nz).sum() / (m * total)) - (m + 1) / m)

    def to_dict(self, k: int = 8) -> dict:
        occ = int((self._counts > 0).sum())
        return {"occupied_cells": occ, "skew": round(self.skew(), 3),
                "top_share": round(self.top_share(), 4),
                "gini": round(self.gini(), 4),
                "top_cells": self.top_k(k)}


def normalize_cells(cells, np):
    """ONE normalization pass shared by the occupancy and cost-profile
    accumulators (both are fed by the same observer hook — doing the
    scalar check / ravel / negative filter / bincount twice would double
    the hot ingest path's observation cost): returns
    ``("scalar", cell_id, None)`` for a single valid cell,
    ``("counts", hi, bincount)`` for an array, or None when nothing valid
    remains."""
    if isinstance(cells, (int, np.integer)) or (
            isinstance(cells, np.ndarray) and cells.ndim == 0):
        ci = int(cells)
        return None if ci < 0 else ("scalar", ci, None)
    c = np.asarray(cells).ravel()
    c = c[c >= 0]
    if c.size == 0:
        return None
    hi = int(c.max()) + 1
    return ("counts", hi, np.bincount(c, minlength=hi).astype(np.int64))


class EventRing:
    """Bounded ring buffer of structured lifecycle events. Appends are
    O(1) and lock-guarded (emitters live on pipeline, reporter, and HTTP
    threads); ``list()`` copies so readers never hold the lock while
    serializing. ``total`` counts every event ever appended, including
    those the ring has since evicted.

    Every event carries a monotonic ``seq`` (1-based, assigned under the
    lock — ``total`` IS the last assigned seq) plus BOTH a wall-clock
    ``ts_ms`` and a steady ``mono_ms`` (``time.monotonic``) timestamp, so
    a wall-clock step (NTP, DST) cannot reorder the stream a poller
    reconstructs. ``list(since=seq)`` returns only events newer than
    ``seq`` — the ``/events?since=`` cursor that lets pollers stop
    re-reading (and re-alerting on) the whole ring every fetch."""

    def __init__(self, capacity: int = 256):
        from collections import deque

        self._ring = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self.total = 0

    def append(self, kind: str, **fields) -> dict:
        ev = {"ts_ms": int(time.time() * 1000),
              "mono_ms": round(time.monotonic() * 1e3, 3), "kind": kind}
        ev.update(fields)
        with self._lock:
            self.total += 1
            ev["seq"] = self.total
            self._ring.append(ev)
        return ev

    def list(self, since: Optional[int] = None) -> List[dict]:
        with self._lock:
            evs = list(self._ring)
        if since is not None:
            evs = [e for e in evs if e.get("seq", 0) > since]
        return evs


class WindowTraceBook:
    """Per-window trace lineage: one record per window, keyed by a STABLE
    trace id derived from ``(query, window_start)`` (re-deliveries and
    resumed runs land on the same id). Each record accumulates timestamped
    events as the window moves through the pipeline — ``ingest`` (the
    first record's ingestion wall clock), ``window`` (assembly pull),
    ``pane-seal`` (one per fresh pane kernel, pane mode), ``kernel``
    (dispatch), ``merge`` (readback), ``emit``, then the downstream
    ``sink`` / ``sink-commit`` stages (appended by window_start — the
    driver and Kafka sink don't know the family).

    Bounded: at most ``capacity`` traces are retained (oldest-started
    evicted first); ``total`` counts every trace ever started. All methods
    are lock-guarded and called at WINDOW granularity, never per record.
    :meth:`chrome_trace` renders the ring as Chrome trace-event JSON
    (the ``{"traceEvents": [...]}`` form), loadable in Perfetto /
    ``chrome://tracing`` — durations become ``"ph": "X"`` slices, instants
    ``"ph": "i"`` marks, one named track (tid) per query family."""

    def __init__(self, capacity: int = 256):
        from collections import OrderedDict

        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()
        self.capacity = max(1, int(capacity))
        self.total = 0
        #: traces dropped by the capacity ring — overflow used to be
        #: silent, leaving "where did my lineage go?" unanswerable; the
        #: ``trace-evictions`` counter and /trace/recent's ``evicted``
        #: field now say exactly how much history fell off
        self.evicted = 0

    @staticmethod
    def trace_id(query: str, window_start) -> str:
        return f"{query}:{int(window_start)}"

    def _trace(self, query: str, window_start) -> dict:
        """Get-or-start (caller holds the lock)."""
        tid = self.trace_id(query, window_start)
        tr = self._traces.get(tid)
        if tr is None:
            tr = {"trace_id": tid, "query": query,
                  "window_start": int(window_start), "window_end": None,
                  "first_record_ms": None, "emitted_ms": None, "events": []}
            self._traces[tid] = tr
            self.total += 1
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)
                self.evicted += 1
                _metrics.REGISTRY.counter("trace-evictions").inc()
        return tr

    def note(self, query: str, window_start, stage: str, t0_s: float,
             t1_s: Optional[float] = None, **fields) -> None:
        """Append one event; ``t0_s``/``t1_s`` are ``time.time()`` seconds
        (wall clock, so slices line up across threads and processes)."""
        ev = {"stage": stage, "ts_ms": round(t0_s * 1e3, 3)}
        if t1_s is not None:
            ev["dur_ms"] = round((t1_s - t0_s) * 1e3, 3)
        ev.update(fields)
        with self._lock:
            self._trace(query, window_start)["events"].append(ev)

    def first_record(self, query: str, window_start, ingest_ms) -> None:
        """Record the window's first-record ingest wall clock (once)."""
        with self._lock:
            tr = self._trace(query, window_start)
            if tr["first_record_ms"] is None:
                tr["first_record_ms"] = int(ingest_ms)
                tr["events"].insert(
                    0, {"stage": "ingest", "ts_ms": int(ingest_ms)})

    def seal(self, query: str, window_start, window_end) -> None:
        """The window was emitted by its operator: stamp bounds + an
        ``emit`` instant (later sink stages still append — the trace stays
        in the ring until evicted by capacity)."""
        now_ms = round(time.time() * 1e3, 3)
        with self._lock:
            tr = self._trace(query, window_start)
            tr["window_end"] = int(window_end)
            tr["emitted_ms"] = now_ms
            tr["events"].append({"stage": "emit", "ts_ms": now_ms})

    def note_any(self, window_start, stage: str, t0_s: float,
                 t1_s: Optional[float] = None, **fields) -> None:
        """Append an event to EVERY trace with this ``window_start`` — the
        downstream sink stages see a WindowResult, not a family label.
        O(ring) per emitted window, never per record."""
        ws = int(window_start)
        ev = {"stage": stage, "ts_ms": round(t0_s * 1e3, 3)}
        if t1_s is not None:
            ev["dur_ms"] = round((t1_s - t0_s) * 1e3, 3)
        ev.update(fields)
        with self._lock:
            for tr in self._traces.values():
                if tr["window_start"] == ws:
                    tr["events"].append(dict(ev))

    # ------------------------------ readers --------------------------- #

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                return None
            return {**tr, "events": [dict(e) for e in tr["events"]]}

    def recent(self, k: int = 32) -> List[dict]:
        """Newest-started ``k`` trace summaries (id, window, event count,
        emitted) — the ``/trace/recent`` index."""
        with self._lock:
            traces = list(self._traces.values())[-max(0, int(k)):]
            return [{"trace_id": t["trace_id"], "query": t["query"],
                     "window_start": t["window_start"],
                     "window_end": t["window_end"],
                     "emitted_ms": t["emitted_ms"],
                     "events": len(t["events"])} for t in reversed(traces)]

    def chrome_trace(self) -> dict:
        """The ring as a Chrome trace-event document (Perfetto-loadable)."""
        events: List[dict] = []
        tids: Dict[str, int] = {}
        with self._lock:
            traces = [
                {**t, "events": [dict(e) for e in t["events"]]}
                for t in self._traces.values()
            ]
        for tr in traces:
            tid = tids.setdefault(tr["query"], len(tids) + 1)
            for ev in tr["events"]:
                args = {k: v for k, v in ev.items()
                        if k not in ("stage", "ts_ms", "dur_ms")}
                args["trace_id"] = tr["trace_id"]
                base = {"name": ev["stage"], "cat": tr["query"],
                        "ts": round(ev["ts_ms"] * 1e3, 1), "pid": 1,
                        "tid": tid, "args": args}
                if "dur_ms" in ev:
                    events.append({**base, "ph": "X",
                                   "dur": max(1.0, round(ev["dur_ms"] * 1e3,
                                                         1))})
                else:
                    events.append({**base, "ph": "i", "s": "t"})
        for query, tid in tids.items():
            events.append({"name": "thread_name", "ph": "M", "pid": 1,
                           "tid": tid, "args": {"name": query}})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome(self, path: str) -> str:
        """Write :meth:`chrome_trace` to ``path`` (atomic tmp+rename, like
        the Prometheus dump — a viewer must never load a torn file)."""
        doc = self.chrome_trace()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


class CostProfiles:
    """Per-grid-cell and per-query-family COST accumulators — the
    where-does-the-time-go / who-pays complement to :class:`CellOccupancy`
    (which only counts). Fed at two grains:

    - per record (via :meth:`Telemetry.record_cells`, i.e. the existing
      ``UniformGrid.assign_cell`` observer hook): per-cell records-in,
      plus a PENDING bucket of cells seen since the last kernel dispatch;
    - per window (from the family-labeled spans in ``operators/base.py``):
      kernel/merge wall-clock, records, approximate bytes moved, and
      pane-cache hits/misses per family — and the pending cell bucket is
      folded into per-cell ``cost_ms`` proportionally (at steady state the
      records that arrived since the previous dispatch are the new slide
      of data, so each cell's share of fresh records is its share of the
      kernel it triggered). This is ATTRIBUTION, not measurement — the
      kernel runs on the whole window — but it is exactly the signal
      skew-aware balancing needs: a hot cell's records make every window
      containing them expensive, and its attributed cost ratchets
      accordingly.

    :meth:`tick` (called by the reporter once per interval) appends a
    delta bucket to a bounded ``series`` deque, so ``/profile/cells``
    serves a windowed time series of skew cost, not just a cumulative
    total."""

    def __init__(self, series_capacity: int = 128,
                 tick_interval_s: float = 5.0):
        import numpy as np

        self._np = np
        self._records = np.zeros(0, dtype=np.int64)
        self._cost_ms = np.zeros(0, dtype=np.float64)
        self._pending = np.zeros(0, dtype=np.int64)
        self._pending_total = 0
        self._cost_at_tick = np.zeros(0, dtype=np.float64)
        self.families: Dict[str, dict] = {}
        from collections import deque

        self.series = deque(maxlen=max(1, int(series_capacity)))
        #: minimum spacing between :meth:`maybe_tick` buckets — the
        #: session's snapshot interval (telemetry_session sets it)
        self.tick_interval_s = max(0.01, float(tick_interval_s))
        self._last_tick_s = time.time()
        self._lock = threading.Lock()

    def _ensure(self, hi: int) -> None:
        if hi > self._records.size:
            np = self._np
            size = max(hi, 2 * self._records.size)
            for name in ("_records", "_cost_ms", "_pending"):
                old = getattr(self, name)
                grown = np.zeros(size, dtype=old.dtype)
                grown[: old.size] = old
                setattr(self, name, grown)

    def record_scalar(self, ci: int) -> None:
        """One pre-validated cell id — the per-record ingest twin of
        :meth:`CellOccupancy.record_scalar`.

        Deliberately LOCK-FREE (allowlisted in analysis/ALLOWLIST.toml):
        the ingest feeds are single-writer — only the pipeline thread
        records cells — and the snapshot readers tolerate a torn read of
        one in-flight bucket by design. Taking the instance lock here
        measurably starves the drive loop against the reporter/opserver
        tick cadence (~3x on the follow acceptance run)."""
        self._ensure(ci + 1)
        self._records[ci] += 1
        self._pending[ci] += 1
        self._pending_total += 1

    def record_counts(self, hi: int, counts, n: int) -> None:
        """A pre-normalized bincount (``n`` = total valid records).
        Lock-free for the same single-writer reason as
        :meth:`record_scalar`."""
        self._ensure(hi)
        self._records[:hi] += counts
        self._pending[:hi] += counts
        self._pending_total += n

    def record_cells(self, cells) -> None:
        """Normalizing entry for direct callers; the session observer
        (:meth:`Telemetry.record_cells`) normalizes ONCE and feeds the
        scalar/counts halves of both accumulators instead."""
        norm = normalize_cells(cells, self._np)
        if norm is None:
            return
        kind, a, b = norm
        if kind == "scalar":
            self.record_scalar(a)
        else:
            self.record_counts(a, b, int(b.sum()))

    def family(self, label: str) -> dict:
        f = self.families.get(label)
        if f is None:
            with self._lock:
                f = self.families.setdefault(label, {
                    "records_in": 0, "windows": 0, "kernel_ms": 0.0,
                    "merge_ms": 0.0, "pane_hits": 0, "pane_misses": 0,
                    "bytes_moved": 0})
        return f

    def attribute_kernel(self, label: str, dt_s: float, records: int = 0,
                         nbytes: int = 0) -> None:
        """One window's kernel dispatch: bump the family profile and fold
        the pending cell bucket into per-cell cost (proportional split of
        ``dt_s`` over the cells of records that arrived since the last
        dispatch; an all-cached window — no fresh records — attributes
        nothing, which is honest: it cost no new kernel work per cell)."""
        dt_ms = dt_s * 1e3
        f = self.family(label)
        with self._lock:
            f["windows"] += 1
            f["records_in"] += int(records)
            f["kernel_ms"] += dt_ms
            f["bytes_moved"] += int(nbytes)
            if self._pending_total:
                n = self._pending.size
                self._cost_ms[:n] += self._pending * (
                    dt_ms / self._pending_total)
                self._pending[:] = 0
                self._pending_total = 0

    def attribute_merge(self, label: str, dt_s: float) -> None:
        f = self.family(label)
        with self._lock:
            f["merge_ms"] += dt_s * 1e3

    def note_readback(self, label: str, nbytes: int) -> None:
        """Device→host bytes actually read back for one window's pane merge
        (host-merged: the partials resolved this window; device-merged: the
        merged result only) — folded into the family's ``bytes_moved`` so
        the cost profile reflects real data motion on the pane path."""
        f = self.family(label)
        with self._lock:
            f["bytes_moved"] += int(nbytes)

    def note_pane(self, label: str, hits: int, misses: int) -> None:
        f = self.family(label)
        with self._lock:
            f["pane_hits"] += int(hits)
            f["pane_misses"] += int(misses)

    def cell_costs(self, size: int):
        """Per-cell cumulative attributed kernel cost (ms), zero-padded /
        truncated to ``size`` — the repartition controller's cost signal
        (``runtime.repartition``). A copy; callers may normalize freely."""
        np = self._np
        out = np.zeros(size, np.float64)
        n = min(size, self._cost_ms.size)
        out[:n] = self._cost_ms[:n]
        return out

    def top_cost_cells(self, k: int = 8, cost=None) -> List[list]:
        """``[cell, cost_ms, records]`` rows, costliest first."""
        np = self._np
        cost = cost if cost is not None else self._cost_ms
        nz = np.nonzero(cost > 0)[0]
        if nz.size == 0:
            return []
        order = nz[np.argsort(cost[nz])[::-1][:k]]
        return [[int(c), round(float(cost[c]), 3),
                 int(self._records[c]) if c < self._records.size else 0]
                for c in order]

    def maybe_tick(self) -> None:
        """Close a bucket only when ``tick_interval_s`` elapsed since the
        last one — safe to call from every periodic/read path (reporter
        snapshot, ``/profile/cells`` scrape) without double-bucketing."""
        if time.time() - self._last_tick_s >= self.tick_interval_s:
            self.tick()

    def tick(self) -> dict:
        """Close one time-series bucket: per-cell cost DELTA since the
        previous tick (top-k) plus the delta's total. Bounded by the
        series deque."""
        np = self._np
        with self._lock:
            self._last_tick_s = time.time()
            cur = self._cost_ms
            prev = self._cost_at_tick
            if prev.size < cur.size:
                grown = np.zeros(cur.size, dtype=np.float64)
                grown[: prev.size] = prev
                prev = grown
            delta = cur - prev[: cur.size]
            self._cost_at_tick = cur.copy()
        bucket = {"ts_ms": int(time.time() * 1000),
                  "kernel_ms": round(float(delta.sum()), 3),
                  "top_cells": self.top_cost_cells(8, cost=delta)}
        self.series.append(bucket)
        return bucket

    def _families_dict(self) -> dict:
        with self._lock:
            return {
                label: {k: (round(v, 3) if isinstance(v, float) else v)
                        for k, v in f.items()}
                for label, f in self.families.items()
            }

    def to_dict(self, k: int = 8) -> dict:
        """The compact form embedded in every snapshot."""
        return {
            "top_cost_cells": self.top_cost_cells(k),
            "total_kernel_ms": round(
                float(self._cost_ms.sum()), 3),
            "families": self._families_dict(),
            "series_len": len(self.series),
        }

    def cells_payload(self, k: int = 64) -> dict:
        """The full ``/profile/cells`` document: top-k per-cell rows with
        cost shares, the per-family table, and the windowed time series.
        Scrape-driven ticking (Prometheus-style): in a reporterless
        session (``--trace-dir``/``--status-port`` without
        ``--telemetry-dir``) the series still advances, one bucket per
        ``tick_interval_s`` of being read."""
        self.maybe_tick()
        total = float(self._cost_ms.sum())
        cells = [{"cell": c, "records": n, "cost_ms": cost,
                  "cost_share": round(cost / total, 4) if total else 0.0}
                 for c, cost, n in self.top_cost_cells(k)]
        return {"ts_ms": int(time.time() * 1000), "cells": cells,
                "total_kernel_ms": round(total, 3),
                "occupied_cells": int((self._records > 0).sum()),
                "families": self._families_dict(),
                "series": list(self.series)}


class Telemetry:
    """One session's span/histogram/gauge/occupancy state.

    ``registry`` pins the metrics registry whose counters ride the
    snapshots; None reads the ambient :data:`~.metrics.REGISTRY` at
    snapshot time (so :func:`~.metrics.scoped_registry` composes).
    Mutations on the hot path are single attribute bumps under the GIL;
    only entry creation and snapshotting take the lock, so a reporter
    thread reading mid-window sees a consistent-enough view (telemetry,
    not accounting).
    """

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None,
                 trace: bool = False):
        self.registry = registry
        self.spans: Dict[str, SpanStats] = {}
        self.histograms: Dict[str, StreamingHistogram] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.cells = CellOccupancy()
        self.costs = CostProfiles()
        #: latency-decomposition plane (stage-residency budgets, record→
        #: emit histograms, backpressure timeline — utils.latencyplane);
        #: fed at WINDOW/tick granularity only, so it rides every session
        #: like the cost profiles do
        from spatialflink_tpu.utils.latencyplane import LatencyPlane

        self.latency = LatencyPlane()
        #: per-query/per-tenant cost ledger (utils.accounting): the
        #: shared padded-fleet dispatch attributed to who asked for it;
        #: fed at dispatch/window granularity only, so it rides every
        #: session like the cost profiles do
        from spatialflink_tpu.utils.accounting import TenantLedger

        self.tenants = TenantLedger()
        #: per-window trace lineage — OPT-IN (``trace=True`` /
        #: ``--trace-dir``): None keeps the plain session's hot-path cost
        #: exactly what PRs 2/5 measured; instrumented sites check this
        #: once per stream/loop like everything else
        self.traces: Optional[WindowTraceBook] = (
            WindowTraceBook() if trace else None)
        self.events = EventRing()
        #: optional runtime.health.HealthEvaluator attached by the driver
        #: (--slo): status_snapshot() stamps its verdict into every
        #: snapshot this session emits
        self.health = None
        self.started_at = time.time()
        self._lock = threading.Lock()
        self._tls = threading.local()

    def event(self, kind: str, **fields) -> None:
        """Record one structured lifecycle event (see :class:`EventRing`).
        Emitters are stage boundaries (checkpoint commits, breaker
        transitions, quarantines), never per-record paths."""
        self.events.append(kind, **fields)

    # ------------------------------ spans ---------------------------- #

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _span_stats(self, name: str) -> SpanStats:
        st = self.spans.get(name)
        if st is None:
            with self._lock:
                st = self.spans.setdefault(name, SpanStats(name))
        return st

    def span(self, stage: str, query: Optional[str] = None) -> _Span:
        """Context manager timing one activation of ``stage``; ``query``
        scopes the stage name (``knn.kernel``) so families/queries stay
        separable. Exceptions propagate (and bump ``errors``)."""
        return _Span(self, f"{query}.{stage}" if query else stage)

    def observe(self, stage: str, dt_s: float,
                query: Optional[str] = None) -> None:
        """Record one pre-timed observation — the per-record loops use this
        instead of a context manager (no object churn on the ingest path)."""
        st = self._span_stats(f"{query}.{stage}" if query else stage)
        st.count += 1
        st.total_s += dt_s
        st.self_s += dt_s
        if dt_s > st.max_s:
            st.max_s = dt_s

    # --------------------------- histograms/gauges -------------------- #

    def histogram(self, name: str, **kw) -> StreamingHistogram:
        h = self.histograms.get(name)
        if h is None:
            with self._lock:
                h = self.histograms.setdefault(
                    name, StreamingHistogram(name, **kw))
        return h

    def gauge(self, name: str,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self.gauges.get(name)
        if g is None:
            with self._lock:
                g = self.gauges.setdefault(name, Gauge(name, fn))
        elif fn is not None and g.fn is None:
            g.fn = fn
        return g

    def record_cells(self, cells) -> None:
        # ONE normalization (scalar check / filter / bincount) feeding
        # both accumulators — this is the per-record observer hook, so the
        # pass must not be paid twice
        norm = normalize_cells(cells, self.cells._np)
        if norm is None:
            return
        kind, a, b = norm
        if kind == "scalar":
            self.cells.record_scalar(a)
            self.costs.record_scalar(a)
        else:
            self.cells.record_counts(a, b)
            self.costs.record_counts(a, b, int(b.sum()))

    # ------------------------------ snapshot -------------------------- #

    def _registry(self) -> _metrics.MetricsRegistry:
        return self.registry if self.registry is not None else _metrics.REGISTRY

    def snapshot(self) -> dict:
        """One JSON-safe snapshot: stage spans, histogram percentiles,
        gauges, the registry's counters/meters, the degradation digest
        (PR 1's retry/breaker/DLQ/chaos counters — same stream, same
        timestamp, correlation for free), grid occupancy, and the device
        block (backend provenance, compile/recompile counters, memory
        gauges — ``utils.deviceplane``; the probe runs once per snapshot,
        never per record)."""
        from spatialflink_tpu.utils import deviceplane as _deviceplane

        reg = self._registry()
        # close a backpressure bucket at most once per tick interval —
        # whoever snapshots first (reporter, /status, digest) drives it
        self.latency.maybe_tick(self)
        with self._lock:
            spans = {n: s.to_dict() for n, s in self.spans.items()}
            hists = {n: h.to_dict() for n, h in self.histograms.items()}
            gauges = {n: g.get() for n, g in self.gauges.items()}
        return {
            "ts_ms": int(time.time() * 1000),
            "uptime_s": round(time.time() - self.started_at, 3),
            "spans": spans,
            "histograms": hists,
            "gauges": gauges,
            "counters": reg.snapshot(),
            "degradation": _metrics.degradation_snapshot(reg),
            "grid": self.cells.to_dict(),
            "costs": self.costs.to_dict(),
            "latency": self.latency.to_dict(),
            "tenants": self.tenants.to_dict(),
            "device": _deviceplane.status_block(self, self._registry()),
            "traces": {
                "enabled": self.traces is not None,
                "total": self.traces.total if self.traces is not None else 0,
                "evicted": (self.traces.evicted
                            if self.traces is not None else 0),
            },
        }


# --------------------------------------------------------------------- #
# the active session (module-global, like metrics.REGISTRY)

_ACTIVE: Optional[Telemetry] = None
_NULL_CM = contextlib.nullcontext()

#: this process incarnation's identity + the monotonic snapshot counter
#: — stamped into every status_snapshot() so federated collectors can
#: order and dedupe worker snapshots (a restarted worker gets a fresh
#: run_id, so its seq restart reads as "new incarnation", never "stale")
_RUN_ID = uuid.uuid4().hex[:12]
_SNAP_SEQ = itertools.count(1)


def active() -> Optional[Telemetry]:
    """The active session's :class:`Telemetry`, or None when telemetry is
    off. Hot paths call this ONCE per stream/loop and branch to the
    uninstrumented code when it is None."""
    return _ACTIVE


def set_active(tel: Optional[Telemetry]) -> Optional[Telemetry]:
    global _ACTIVE
    old = _ACTIVE
    _ACTIVE = tel
    return old


def span(stage: str, query: Optional[str] = None):
    """Module-level convenience for call-once sites (stage boundaries, CLI
    plumbing): a real span when a session is active, a shared nullcontext
    otherwise. Per-record loops should capture :func:`active` instead."""
    tel = _ACTIVE
    return tel.span(stage, query) if tel is not None else _NULL_CM


def emit_event(kind: str, **fields) -> None:
    """Append a lifecycle event to the active session's ring; a no-op when
    telemetry is off (one attribute read — safe at stage boundaries even
    in uninstrumented runs)."""
    tel = _ACTIVE
    if tel is not None:
        tel.event(kind, **fields)


# --------------------------------------------------------------------- #
# the shared "current pipeline state" snapshot (reporter JSONL lines, the
# status server's /status, and the --live-stats stderr digest all render
# exactly this — one schema definition)

#: chain-stage membership for the dominant-stage digest (downstream sink
#: stages run after emit and must not win the "where did record→emit go"
#: headline)
CHAIN_STAGES_SET = frozenset(
    ("buffer", "queue", "dispatch", "inflight", "merge", "emit"))


def _hist_digest(hists: dict, name: str) -> dict:
    h = hists.get(name)
    if not h or not h.get("count"):
        return {"count": 0}
    return {k: h.get(k) for k in ("count", "p50", "p95", "p99", "max")}


def status_digest(snap: dict) -> dict:
    """Derive the compact operator view from a raw snapshot dict: the
    numbers an operator reads FIRST, by name, instead of fishing them out
    of the spans/histograms/gauges/counters maps. Keys are stable schema
    (ARCHITECTURE.md § Live operations); absent instruments render as
    None / zero-count, never as missing keys."""
    counters = snap.get("counters") or {}
    gauges = snap.get("gauges") or {}
    hists = snap.get("histograms") or {}
    grid = snap.get("grid") or {}
    hits = int(counters.get("pane-cache-hits", 0))
    misses = int(counters.get("pane-cache-misses", 0))
    return {
        "records_in": int(counters.get("ingest-throughput.count", 0)),
        "throughput_rps": round(
            float(counters.get("ingest-throughput.rate", 0.0)), 3),
        "windows_evaluated": int(counters.get("batches-evaluated", 0)),
        "record_latency_ms": _hist_digest(hists, "record-latency-ms"),
        "window_latency_ms": _hist_digest(hists, "window-latency-ms"),
        "watermark_lag_ms": gauges.get("kafka.watermark-lag-ms"),
        "commit_backlog": gauges.get("kafka.commit-backlog"),
        "window_backlog": gauges.get("window-backlog"),
        "pane_cache": {
            "hits": hits, "misses": misses,
            "hit_rate": (round(hits / (hits + misses), 4)
                         if hits + misses else None),
        },
        "checkpoint": {
            "seq": gauges.get("checkpoint.seq"),
            "age_s": (round(gauges["checkpoint.age-s"], 3)
                      if "checkpoint.age-s" in gauges else None),
            "written": int(counters.get("checkpoints-written", 0)),
            "replay_depth": gauges.get("recovery.replay-depth"),
            "write_ms": _hist_digest(hists, "checkpoint-write-ms"),
            "size_bytes": _hist_digest(hists, "checkpoint-size-bytes"),
        },
        "breaker_state": gauges.get("broker.breaker-state"),
        "dlq_depth": int(counters.get("dlq-records", 0)),
        "mesh_degradations": int(counters.get("mesh-degradations", 0)),
        "slo_breaches": int(counters.get("slo-breaches", 0)),
        "top_cells": grid.get("top_cells", []),
        # skew-concentration gauges (CellOccupancy): top-cell record share
        # and Gini over occupied cells — what the --adaptive-grid
        # repartition trigger compares its split threshold against, so the
        # threshold is observable BEFORE it fires
        "skew": {
            "factor": grid.get("skew"),
            "top_share": grid.get("top_share"),
            "gini": grid.get("gini"),
        },
        # [[cell, attributed_kernel_ms, records], ...] — skew COST, the
        # companion to top_cells' occupancy counts (CostProfiles)
        "top_cost_cells": (snap.get("costs") or {}).get(
            "top_cost_cells", []),
        # device truth (utils.deviceplane): backend provenance, compile/
        # recompile counters, memory gauges — the --slo recompiles=/
        # device_mem_bytes= checks and the stderr digest read these
        "device": snap.get("device") or {},
        # per-window dispatch→ready vs wall-clock overlap: 1.0 = the whole
        # device round-trip was hidden behind host work (the
        # pipeline_depth payoff metric the MULTICHIP ledger wants)
        "dispatch_overlap": _hist_digest(hists, "dispatch-overlap-ratio"),
        # latency decomposition (utils.latencyplane): record→emit
        # percentiles, the stage whose residency dominates, and the
        # freshest backpressure annotations — the full table lives at
        # GET /latency
        "latency": _latency_digest(snap.get("latency") or {}),
        # closed-loop chunk governor (runtime.control): the live actuator
        # value + step/shed totals, derived from the exported gauges/
        # counters so federated cross-process digests carry it too; the
        # full decision tail is the controller block on GET /latency.
        # chunk=None = no governor installed in this run.
        "controller": {
            "chunk": (int(gauges["decode.chunk"])
                      if gauges.get("decode.chunk") is not None else None),
            "fast_lane": bool(gauges.get("decode.fast-lane")),
            "shedding": bool(gauges.get("controller.shedding")),
            "grows": int(counters.get("chunk-grow", 0)),
            "shrinks": int(counters.get("chunk-shrink", 0)),
            "sheds": int(counters.get("shed", 0)),
        },
        # tenant accounting (utils.accounting): who pays for the shared
        # dispatch — tenant count, top payer by attributed kernel-ms,
        # the fairness shares + Gini, and the attribution residual;
        # the full per-tenant table lives at GET /tenants
        "tenants": _tenants_digest(snap.get("tenants") or {}),
    }


def _tenants_digest(ten: dict) -> dict:
    """The compact operator view of the tenant ledger's snapshot block.
    Absent plane (no session) renders zero-count, never missing keys."""
    fairness = ten.get("fairness") or {}
    return {
        "n": int(ten.get("n") or 0),
        "top": fairness.get("top"),
        "top_share": fairness.get("top_share", 0.0),
        "max_share": fairness.get("max_share", 0.0),
        "min_share": fairness.get("min_share", 0.0),
        "gini": fairness.get("gini", 0.0),
        "quota_rejections": sum(
            int((r or {}).get("quota_rejections") or 0)
            for r in (ten.get("tenants") or {}).values()),
        "max_residual_ms": ten.get("max_residual_ms", 0.0),
    }


def _latency_digest(lat: dict) -> dict:
    """The compact operator view of the latency plane's snapshot block:
    record→emit percentiles, the dominant stage by total residency, and
    the last backpressure bucket's stall/residency signals. Absent plane
    (no session) renders zero-count, never missing keys."""
    re_h = lat.get("record_emit") or {}
    stages = lat.get("stages") or {}
    dominant = None
    if stages:
        totals = {s: (h.get("sum") or 0.0) for s, h in stages.items()
                  if s in CHAIN_STAGES_SET}
        if any(totals.values()):
            dominant = max(totals, key=totals.get)
    bp = (lat.get("backpressure") or {}).get("last") or {}
    return {
        "record_emit_ms": ({k: re_h.get(k) for k in
                            ("count", "p50", "p95", "p99", "max")}
                           if re_h.get("count") else {"count": 0}),
        "dominant_stage": dominant,
        "stall": bp.get("stall"),
        "backlog_residency_ms": bp.get("backlog_residency_ms"),
    }


def registry_snapshot(registry: Optional[_metrics.MetricsRegistry] = None
                      ) -> dict:
    """A snapshot with the raw-snapshot SHAPE built from the always-on
    metrics registry alone — what a bare ``--status-port`` run (no
    telemetry session) serves. Spans/histograms/gauges are empty by
    construction: populating them needs the per-record instrumentation a
    session activates, and the no-session contract is a byte-identical
    record loop. The device block IS present — backend provenance and the
    compile registry are process truth, not session instrumentation, and
    this snapshot is only ever built on demand (per request), never per
    record."""
    from spatialflink_tpu.utils import deviceplane as _deviceplane

    reg = registry if registry is not None else _metrics.REGISTRY
    return {
        "ts_ms": int(time.time() * 1000),
        "uptime_s": None,
        "spans": {},
        "histograms": {},
        "gauges": {},
        "counters": reg.snapshot(),
        "degradation": _metrics.degradation_snapshot(reg),
        "grid": {},
        "costs": {},
        "latency": {},
        "tenants": {},
        "device": _deviceplane.status_block(None, reg),
        "traces": {"enabled": False, "total": 0, "evicted": 0},
    }


def status_snapshot(tel: Optional[Telemetry] = None, health=None,
                    registry: Optional[_metrics.MetricsRegistry] = None
                    ) -> dict:
    """One full "current pipeline state" document: the raw snapshot (or
    the registry-only fallback), the derived ``status`` digest, and —
    when an SLO evaluator is attached (explicitly or on the session) —
    the ``health`` verdict. Built ON DEMAND only: per HTTP request, per
    reporter interval, per digest line; never per record."""
    tel = tel if tel is not None else _ACTIVE
    snap = tel.snapshot() if tel is not None else registry_snapshot(registry)
    # provenance + ordering stamp for federated collectors: run_id pins
    # the emitting process incarnation, snapshot_seq orders snapshots
    # WITHIN it — a poller (FleetMonitor, /fleet/tenants harvesting)
    # drops any snapshot whose (run_id, seq) it has already seen, and a
    # changed run_id (restart) resets the ordering instead of wedging it
    snap["run_id"] = _RUN_ID
    snap["snapshot_seq"] = next(_SNAP_SEQ)
    snap["status"] = status_digest(snap)
    if health is None and tel is not None:
        health = tel.health
    if health is not None:
        # evaluated AFTER the digest so checks read the same numbers the
        # operator sees; breach transitions count in the SAME registry the
        # snapshot was built from (a pinned/scoped registry must see its
        # own slo-breaches), landing in the NEXT snapshot's status
        reg = (tel._registry() if tel is not None
               else registry if registry is not None else _metrics.REGISTRY)
        snap["health"] = health.evaluate(snap, registry=reg)
    return snap


def fleet_snapshot(workers: list, *, epoch: int = 0, routed: int = 0,
                   restart_log: Optional[list] = None) -> dict:
    """The fleet supervisor's aggregated snapshot schema (``fleet-v1``,
    served at ``GET /fleet``): one row per worker (liveness, restarts,
    heartbeat age, leaf share, last polled per-worker ops payloads) plus
    the fleet-level totals the doctor and the rebalance policy read. A
    schema builder, not a poller — the supervisor supplies the rows so
    this stays testable without processes."""
    alive = sum(1 for w in workers if w.get("alive"))
    restarts = sum(int(w.get("restarts") or 0) for w in workers)
    return {
        "schema": "fleet-v1",
        "ts_ms": int(time.time() * 1000),
        "workers": workers,
        "n_workers": len(workers),
        "alive": alive,
        "epoch": int(epoch),
        "routed": int(routed),
        "restarts_total": restarts,
        "restart_log": list(restart_log or [])[-50:],
    }


# --------------------------------------------------------------------- #
# reporter

def prometheus_text(tel: Optional[Telemetry] = None,
                    registry: Optional[_metrics.MetricsRegistry] = None
                    ) -> str:
    """Prometheus text exposition of a session: spans as count/total/max
    seconds, histograms as count/sum plus p50/p95/p99 quantile gauges,
    gauges and registry counters as-is. Metric names are fixed; the
    span/histogram/counter name rides a label (dots and dashes are legal
    in label VALUES, so the query-scoped names survive unmangled).
    Query-family-scoped spans and histograms (``knn.kernel``) split into
    PROPER labels — ``stage="kernel",family="knn"`` — instead of a
    flattened combined value, so live scrapes can aggregate a stage
    across families (``sum by (stage)``) or a family across stages
    without regex label surgery; unscoped names render as ``stage="..."``
    / ``name="..."`` with no family label.
    ``tel=None`` renders the registry-only view (counter families only) —
    the no-session ``/metrics`` endpoint. Rendered live by both the
    reporter (every snapshot rewrites ``metrics.prom``) and the status
    server's ``/metrics`` — one renderer, two transports."""
    lines: List[str] = []

    def emit(metric: str, mtype: str, rows: List[Tuple[str, float]]):
        lines.append(f"# TYPE {metric} {mtype}")
        for labels, v in rows:
            lines.append(f"{metric}{{{labels}}} {v}")

    def span_labels(name: str) -> str:
        family, sep, stage = name.rpartition(".")
        if sep:
            return f'stage="{stage}",family="{family}"'
        return f'stage="{name}"'

    def hist_labels(name: str, extra: str = "") -> str:
        # per-query instruments ride a '<base>@<query-id>' naming
        # convention (the standing-query plane's counters/histograms):
        # split into a PROPER query="<id>" label — the same treatment the
        # family-scoped '<family>.<base>' names get — so scrapes can
        # aggregate across the fleet (sum by (name)) or follow one query
        base, qsep, qid = name.partition("@")
        family, sep, leaf = base.rpartition(".")
        lab = (f'name="{leaf}",family="{family}"' if sep
               else f'name="{base}"')
        if qsep:
            lab += f',query="{qid}"'
        return lab + extra

    def counter_labels(name: str) -> str:
        base, qsep, qid = name.partition("@")
        if qsep:
            return f'name="{base}",query="{qid}"'
        return f'name="{name}"'

    if tel is None:
        reg = registry if registry is not None else _metrics.REGISTRY
        emit("spatialflink_counter", "counter",
             [(counter_labels(n), v)
              for n, v in sorted(reg.snapshot().items())])
        return "\n".join(lines) + "\n"

    snap_reg = tel._registry()
    with tel._lock:
        spans = dict(tel.spans)
        hists = dict(tel.histograms)
        gauges = dict(tel.gauges)
    emit("spatialflink_span_count", "counter",
         [(span_labels(n), s.count) for n, s in sorted(spans.items())])
    emit("spatialflink_span_seconds_total", "counter",
         [(span_labels(n), round(s.total_s, 6))
          for n, s in sorted(spans.items())])
    emit("spatialflink_span_seconds_max", "gauge",
         [(span_labels(n), round(s.max_s, 6))
          for n, s in sorted(spans.items())])
    emit("spatialflink_histogram_count", "counter",
         [(hist_labels(n), h.count) for n, h in sorted(hists.items())])
    emit("spatialflink_histogram_sum", "counter",
         [(hist_labels(n), round(h.total, 6))
          for n, h in sorted(hists.items())])
    qrows = []
    for n, h in sorted(hists.items()):
        for q in (50, 95, 99):
            qrows.append((hist_labels(n, f',quantile="0.{q}"'),
                          round(h.percentile(q), 6)))
    emit("spatialflink_histogram_quantile", "gauge", qrows)
    emit("spatialflink_gauge", "gauge",
         [(counter_labels(n), g.get()) for n, g in sorted(gauges.items())])
    emit("spatialflink_counter", "counter",
         [(counter_labels(n), v)
          for n, v in sorted(snap_reg.snapshot().items())])
    # tenant accounting families (utils.accounting): the attributed-cost
    # ledger under PROPER tenant="T" labels — the same label discipline
    # as stage/family/query, so /fleet/metrics relabeling federates them
    ten = tel.tenants.to_dict()
    trows = sorted((ten.get("tenants") or {}).items())
    emit("spatialflink_tenant_kernel_ms_total", "counter",
         [(f'tenant="{t}"', r.get("kernel_ms", 0.0)) for t, r in trows])
    emit("spatialflink_tenant_bytes_moved_total", "counter",
         [(f'tenant="{t}"', r.get("bytes_moved", 0)) for t, r in trows])
    emit("spatialflink_tenant_records_in_total", "counter",
         [(f'tenant="{t}"', r.get("records_in", 0)) for t, r in trows])
    emit("spatialflink_tenant_records_out_total", "counter",
         [(f'tenant="{t}"', r.get("records_out", 0)) for t, r in trows])
    emit("spatialflink_tenant_windows_total", "counter",
         [(f'tenant="{t}"', r.get("windows", 0)) for t, r in trows])
    emit("spatialflink_tenant_slo_breaches_total", "counter",
         [(f'tenant="{t}"', r.get("slo_breaches", 0)) for t, r in trows])
    emit("spatialflink_tenant_quota_rejections_total", "counter",
         [(f'tenant="{t}"', r.get("quota_rejections", 0))
          for t, r in trows])
    fairness = ten.get("fairness") or {}
    emit("spatialflink_tenant_fairness_gini", "gauge",
         [("", fairness.get("gini", 0.0))] if trows else [])
    return "\n".join(lines) + "\n"


def relabel_prometheus_lines(text: str, label: str, value: str) -> str:
    """Prepend ``label="value"`` to every sample line of a Prometheus
    text exposition; ``#`` comment/TYPE lines and blanks pass through
    unchanged. The fleet supervisor's ``/fleet/metrics`` federation uses
    this to pin ``worker="wN"`` onto each worker's scraped ``/metrics``
    body — the same proper-label discipline :func:`prometheus_text`
    applies to stage/family/query names, so one fleet scrape point can
    still ``sum by (stage)`` across workers."""
    pin = f'{label}="{value}"'
    out: List[str] = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        name, brace, rest = line.partition("{")
        if brace:
            # `metric{a="b",...} v` -> `metric{worker="wN",a="b",...} v`
            out.append(f"{name}{{{pin},{rest}" if not rest.startswith("}")
                       else f"{name}{{{pin}{rest}")
        else:
            metric, sp, val = line.partition(" ")
            out.append(f"{metric}{{{pin}}} {val}" if sp else line)
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")


class TelemetryReporter:
    """Daemon thread writing shared-schema :func:`status_snapshot` JSONL
    lines to ``<out_dir>/telemetry.jsonl`` — one immediately at
    :meth:`start`, one per ``interval_s``, one final at :meth:`close` (so
    every run yields >= 2) — and REWRITING the Prometheus text dump
    ``<out_dir>/metrics.prom`` on every snapshot (atomic tmp+rename, so a
    scraper tailing the file never reads a torn exposition). Each line
    embeds the derived ``status`` digest and, when the session carries an
    SLO evaluator, the ``health`` verdict."""

    def __init__(self, telemetry: Telemetry, out_dir: str,
                 interval_s: float = 5.0):
        os.makedirs(out_dir, exist_ok=True)
        self.telemetry = telemetry
        self.interval_s = max(0.01, float(interval_s))
        self.jsonl_path = os.path.join(out_dir, "telemetry.jsonl")
        self.prom_path = os.path.join(out_dir, "metrics.prom")
        self.snapshots_written = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _emit(self) -> None:
        # close a cost-profile time-series bucket at most once per tick
        # interval (maybe_tick: the /profile/cells scrape path ticks too,
        # and the two must not double-bucket)
        self.telemetry.costs.maybe_tick()
        self.telemetry.tenants.maybe_tick()
        snap = status_snapshot(self.telemetry)
        with open(self.jsonl_path, "a") as f:
            f.write(json.dumps(snap, sort_keys=True) + "\n")
        self.snapshots_written += 1
        tmp = self.prom_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(prometheus_text(self.telemetry))
        os.replace(tmp, self.prom_path)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._emit()

    def start(self) -> "TelemetryReporter":
        self._emit()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-reporter")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5.0)
            self._thread = None
        self._emit()


@contextlib.contextmanager
def telemetry_session(out_dir: Optional[str] = None, interval_s: float = 5.0,
                      registry: Optional[_metrics.MetricsRegistry] = None,
                      health=None, trace: bool = False,
                      trace_dir: Optional[str] = None):
    """Activate telemetry for the enclosed block: installs the
    :class:`Telemetry` as the active session, hooks the grid's cell-
    assignment observer, and (when ``out_dir`` is given) runs a
    :class:`TelemetryReporter`. ``health`` attaches an SLO evaluator
    (``runtime.health.HealthEvaluator``) so every snapshot carries its
    verdict. ``trace=True`` (implied by ``trace_dir``) records per-window
    trace lineage in a :class:`WindowTraceBook`; ``trace_dir`` exports it
    as Chrome trace-event JSON (``trace.json``, Perfetto-loadable) at
    close. Everything is restored on exit — including after an
    exception — so a crashed run still gets its final snapshot (and its
    trace: a crash is exactly when the timeline matters)."""
    from spatialflink_tpu.index import uniform_grid as _ug

    tel = Telemetry(registry, trace=trace or bool(trace_dir))
    tel.health = health
    # the cost-profile series buckets at the session's snapshot cadence,
    # whoever drives it (reporter snapshot or /profile/cells scrape)
    tel.costs.tick_interval_s = max(0.01, float(interval_s))
    # the tenant ledger's delta buckets ride the same cadence (reporter
    # snapshot or /tenants scrape — maybe_tick dedupes the drivers)
    tel.tenants.tick_interval_s = max(0.01, float(interval_s))
    old = set_active(tel)
    old_obs = _ug._CELL_OBSERVER
    _ug._CELL_OBSERVER = tel.record_cells
    reporter = None
    if out_dir:
        reporter = TelemetryReporter(tel, out_dir, interval_s).start()
    try:
        yield tel
    finally:
        try:
            if reporter is not None:
                reporter.close()
        finally:
            try:
                if trace_dir and tel.traces is not None:
                    os.makedirs(trace_dir, exist_ok=True)
                    tel.traces.export_chrome(
                        os.path.join(trace_dir, "trace.json"))
            except Exception:
                pass  # export is best-effort; never mask the run's error
            finally:
                # restore the globals even when the final snapshot/prom
                # write fails (disk full, dir deleted mid-run): a dead
                # session left active would instrument every later run in
                # the process
                _ug._CELL_OBSERVER = old_obs
                set_active(old)
