"""Hand-computed geometry fixtures for containment/intersection edge cases.

Unlike tests/oracles.py (which re-derives semantics in NumPy and could share
a misreading with the kernels), every expected value here is a literal
computed by hand from the definition of JTS ``Geometry.distance`` semantics:
0 iff the geometries intersect (boundary crossing OR containment), else the
minimum boundary-boundary Euclidean distance. Exercises
``ops/geom.py`` — in particular the vertex-based containment resolution of
``geoms_to_single_geom_dist``.
"""

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import LineString, Point, Polygon
from spatialflink_tpu.models.batches import EdgeGeomBatch, PointBatch, single_query_edges
from spatialflink_tpu.ops.geom import (
    geoms_to_single_geom_dist,
    points_in_geoms,
    points_to_geoms_dist,
)

GRID = UniformGrid(0.0, 20.0, 0.0, 20.0, num_grid_partitions=20)


def square(x0, y0, x1, y1):
    return [(x0, y0), (x1, y0), (x1, y1), (x0, y1), (x0, y0)]


def poly(*rings):
    return Polygon.create([list(r) for r in rings], GRID)


def batch(geoms):
    return EdgeGeomBatch.from_objects(list(geoms), GRID)


def dist_to_query(geoms, query):
    gb = batch(geoms)
    q_edges, q_mask = single_query_edges(query)
    q_areal = isinstance(query, Polygon)
    d = np.asarray(geoms_to_single_geom_dist(gb, q_edges, q_mask, q_areal))
    return d[: len(geoms)]


class TestPolygonPolygonFixtures:
    def test_disjoint_axis_gap(self):
        # [0,1]^2 vs [3,4]x[0,1]: closest edges x=1 and x=3 -> gap exactly 2
        d = dist_to_query([poly(square(0, 0, 1, 1))], poly(square(3, 0, 4, 1)))
        np.testing.assert_allclose(d, [2.0], atol=1e-6)

    def test_disjoint_diagonal_gap(self):
        # corners (1,1) and (2,2): gap sqrt(2)
        d = dist_to_query([poly(square(0, 0, 1, 1))], poly(square(2, 2, 3, 3)))
        np.testing.assert_allclose(d, [np.sqrt(2.0)], atol=1e-6)

    def test_corner_touch_is_zero(self):
        d = dist_to_query([poly(square(0, 0, 1, 1))], poly(square(1, 1, 2, 2)))
        np.testing.assert_allclose(d, [0.0], atol=1e-7)

    def test_edge_touch_is_zero(self):
        d = dist_to_query([poly(square(0, 0, 1, 1))], poly(square(1, 0, 2, 1)))
        np.testing.assert_allclose(d, [0.0], atol=1e-7)

    def test_boundary_crossing_is_zero(self):
        # plus-shape: A = [0,3]x[1,2], B = [1,2]x[0,3]; boundaries cross but
        # NO vertex of either lies inside the other — the seg-seg kernel must
        # see the crossing, not the vertex tests
        d = dist_to_query([poly(square(0, 1, 3, 2))], poly(square(1, 0, 2, 3)))
        np.testing.assert_allclose(d, [0.0], atol=1e-7)

    def test_containment_disjoint_boundaries_both_ways(self):
        # containment with no boundary contact: distance 0 both directions
        inner, outer = poly(square(4, 4, 5, 5)), poly(square(3, 3, 6, 6))
        np.testing.assert_allclose(dist_to_query([inner], outer), [0.0], atol=1e-7)
        np.testing.assert_allclose(dist_to_query([outer], inner), [0.0], atol=1e-7)

    def test_query_in_hole_is_positive(self):
        # outer [0,10]^2 with hole [4,6]^2; query [4.5,5.5]^2 sits inside the
        # hole -> NOT contained; nearest boundaries are the hole ring and the
        # query ring, 0.5 apart on every side
        holed = poly(square(0, 0, 10, 10), square(4, 4, 6, 6))
        d = dist_to_query([holed], poly(square(4.5, 4.5, 5.5, 5.5)))
        np.testing.assert_allclose(d, [0.5], atol=1e-6)

    def test_query_overlapping_hole_boundary_is_zero(self):
        holed = poly(square(0, 0, 10, 10), square(4, 4, 6, 6))
        # query crosses the hole ring: intersects the solid part -> 0
        d = dist_to_query([holed], poly(square(5, 5, 7, 7)))
        np.testing.assert_allclose(d, [0.0], atol=1e-7)

    def test_concave_notch_distance(self):
        # C-shape open to the left; query square in the notch, 0.5 from the
        # inner arms: [0,4]^2 minus notch [0,3]x[1,3] => ring below, right
        # arm, ring above. Query [0.5,1.5]x[1.5,2.5] inside the notch:
        # nearest inner edges y=1 (0.5 below), y=3 (0.5 above), x=3 (1.5
        # right) -> 0.5
        c_shape = poly([(0, 0), (4, 0), (4, 4), (0, 4), (0, 3), (3, 3),
                        (3, 1), (0, 1), (0, 0)])
        d = dist_to_query([c_shape], poly(square(0.5, 1.5, 1.5, 2.5)))
        np.testing.assert_allclose(d, [0.5], atol=1e-6)

    def test_multi_component_batch(self):
        # one contained, one 2 away, one crossing — all in one batch call
        geoms = [poly(square(4, 4, 5, 5)),      # inside query
                 poly(square(13, 3, 14, 6)),    # 3 right of query x=10... gap 3
                 poly(square(9, 9, 12, 12))]    # crosses query corner
        d = dist_to_query(geoms, poly(square(3, 3, 10, 10)))
        np.testing.assert_allclose(d, [0.0, 3.0, 0.0], atol=1e-6)


class TestLineStringPolygonFixtures:
    def test_linestring_inside_polygon_is_zero(self):
        ls = LineString.create([(1, 1), (2, 2)], GRID)
        d = dist_to_query([ls], poly(square(0, 0, 3, 3)))
        np.testing.assert_allclose(d, [0.0], atol=1e-7)

    def test_linestring_crossing_is_zero(self):
        ls = LineString.create([(-1, 1.5), (4, 1.5)], GRID)
        d = dist_to_query([ls], poly(square(0, 0, 3, 3)))
        np.testing.assert_allclose(d, [0.0], atol=1e-7)

    def test_linestring_outside_gap(self):
        # vertical segment x=5, y in [0,3] vs square [0,3]^2: gap 2
        ls = LineString.create([(5, 0), (5, 3)], GRID)
        d = dist_to_query([ls], poly(square(0, 0, 3, 3)))
        np.testing.assert_allclose(d, [2.0], atol=1e-6)

    def test_polygon_not_inside_linestring_query(self):
        # a linestring query is NOT areal: a polygon "containing" it scores
        # 0 only because the polygon is areal and the ls vertices are inside
        ls_query = LineString.create([(1, 1), (2, 2)], GRID)
        d = dist_to_query([poly(square(0, 0, 3, 3))], ls_query)
        np.testing.assert_allclose(d, [0.0], atol=1e-7)
        # and a DISJOINT polygon keeps its boundary gap: ls (5,0)-(5,3)
        ls_far = LineString.create([(5, 0), (5, 3)], GRID)
        d = dist_to_query([poly(square(0, 0, 3, 3))], ls_far)
        np.testing.assert_allclose(d, [2.0], atol=1e-6)


class TestPointPolygonFixtures:
    def _pts(self, coords):
        xs = np.array([c[0] for c in coords], float)
        ys = np.array([c[1] for c in coords], float)
        return PointBatch.from_arrays(xs, ys, grid=GRID)

    def test_point_fixture_matrix(self):
        holed = poly(square(0, 0, 10, 10), square(4, 4, 6, 6))
        gb = batch([holed])
        pts = self._pts([
            (2, 2),      # solid part -> inside, distance 0
            (5, 5),      # in the hole -> outside, 1 from hole ring
            (12, 5),     # right of outer ring -> 2
            (5, 10),     # exactly on outer boundary -> 0
        ])
        d = np.asarray(points_to_geoms_dist(pts, gb))[:4, 0]
        np.testing.assert_allclose(d, [0.0, 1.0, 2.0, 0.0], atol=1e-6)
        inside = np.asarray(points_in_geoms(pts.x, pts.y, gb.edges,
                                            gb.edge_mask))[:4, 0]
        assert inside[0] and not inside[1] and not inside[2]

    def test_concave_notch_point(self):
        c_shape = poly([(0, 0), (4, 0), (4, 4), (0, 4), (0, 3), (3, 3),
                        (3, 1), (0, 1), (0, 0)])
        gb = batch([c_shape])
        pts = self._pts([(1, 2),    # in the notch: outside, min(1, sqrt 2)=1
                         (3.5, 2)])  # in the right arm: inside
        d = np.asarray(points_to_geoms_dist(pts, gb))[:2, 0]
        np.testing.assert_allclose(d, [1.0, 0.0], atol=1e-6)
        inside = np.asarray(points_in_geoms(pts.x, pts.y, gb.edges,
                                            gb.edge_mask))[:2, 0]
        assert not inside[0] and inside[1]
