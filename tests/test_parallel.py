"""Distributed kernels on the 8-device virtual CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import PointBatch
from spatialflink_tpu.ops.knn import knn_point
from spatialflink_tpu.parallel import (
    distributed_join_counts,
    distributed_knn,
    distributed_range_count,
    make_mesh,
    shard_batch,
)
from spatialflink_tpu.parallel.mesh import cell_hash_order

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
QX, QY = 116.5, 40.5


def make_batch(n, seed=0):
    rng = np.random.default_rng(seed)
    return PointBatch.from_arrays(
        rng.uniform(115.5, 117.6, n),
        rng.uniform(39.6, 41.1, n),
        grid=GRID,
        obj_id=rng.integers(0, 200, n).astype(np.int32),
    )


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return make_mesh(8)


class TestDistributedKnn:
    def test_matches_single_device(self, mesh):
        b = make_batch(2048)
        r = 0.3
        q_cell, _ = GRID.assign_cell(QX, QY)
        L = GRID.candidate_layers(r)
        single = knn_point(b, QX, QY, jnp.int32(q_cell), r, L, n=GRID.n, k=20)
        sharded = shard_batch(b, mesh)
        dist = distributed_knn(
            mesh, sharded, QX, QY, jnp.int32(int(q_cell)), r, L, n=GRID.n, k=20
        )
        np.testing.assert_allclose(
            np.asarray(dist.dist)[np.asarray(dist.valid)],
            np.asarray(single.dist)[np.asarray(single.valid)],
            atol=1e-5,
        )
        assert (np.asarray(dist.obj_id) == np.asarray(single.obj_id)).all()

    def test_strategy_threads_to_shards(self, mesh):
        """conf.approximate must behave the same at any parallelism: the
        per-shard strategy kwarg reaches knn_point (ADVICE round-2
        knn_query.py:58). On CPU approx_min_k is exact, so the distributed
        approx result must match the single-device approx result."""
        b = make_batch(2048)
        r = 0.3
        q_cell, _ = GRID.assign_cell(QX, QY)
        L = GRID.candidate_layers(r)
        single = knn_point(b, QX, QY, jnp.int32(q_cell), r, L,
                           n=GRID.n, k=20, strategy="approx")
        dist = distributed_knn(
            mesh, shard_batch(b, mesh), QX, QY, jnp.int32(int(q_cell)), r, L,
            n=GRID.n, k=20, strategy="approx",
        )
        assert np.asarray(dist.valid).sum() == np.asarray(single.valid).sum()
        np.testing.assert_allclose(
            np.sort(np.asarray(dist.dist)[np.asarray(dist.valid)]),
            np.sort(np.asarray(single.dist)[np.asarray(single.valid)]),
            atol=1e-5,
        )

    def test_cell_hash_order_preserves_results(self, mesh):
        b = make_batch(1024)
        idx = cell_hash_order(np.asarray(b.cell), 8)
        b_perm = jax.tree.map(lambda a: a[idx], b)
        q_cell, _ = GRID.assign_cell(QX, QY)
        r = 0.3
        L = GRID.candidate_layers(r)
        a1 = distributed_knn(mesh, shard_batch(b, mesh), QX, QY,
                             jnp.int32(int(q_cell)), r, L, n=GRID.n, k=10)
        a2 = distributed_knn(mesh, shard_batch(b_perm, mesh), QX, QY,
                             jnp.int32(int(q_cell)), r, L, n=GRID.n, k=10)
        np.testing.assert_allclose(np.asarray(a1.dist), np.asarray(a2.dist), atol=1e-5)


class TestDistributedRange:
    def test_count_matches_single_device(self, mesh):
        from spatialflink_tpu.ops.range import range_filter_point

        b = make_batch(2048, seed=5)
        r = 0.4
        q_cell, _ = GRID.assign_cell(QX, QY)
        mask, _ = range_filter_point(
            b, QX, QY, jnp.int32(q_cell), r,
            GRID.guaranteed_layers(r), GRID.candidate_layers(r), n=GRID.n,
        )
        count, dmask = distributed_range_count(
            mesh, shard_batch(b, mesh), QX, QY, jnp.int32(int(q_cell)), r,
            GRID.guaranteed_layers(r), GRID.candidate_layers(r), n=GRID.n,
        )
        assert int(count) == int(mask.sum())
        assert (np.asarray(dmask) == np.asarray(mask)).all()


class TestDistributedJoin:
    def test_total_matches_single_device(self, mesh):
        from spatialflink_tpu.ops.join import join_mask

        a = make_batch(1024, seed=7)
        b = make_batch(256, seed=8)
        r = 0.1
        L = GRID.candidate_layers(r)
        cx, cy = (GRID.min_x + GRID.max_x) / 2, (GRID.min_y + GRID.max_y) / 2
        m = np.asarray(join_mask(a, b, r, L, cx, cy, n=GRID.n))
        per_a, total = distributed_join_counts(
            mesh, shard_batch(a, mesh), b, r, L, cx, cy, n=GRID.n
        )
        assert int(total) == m.sum()
        assert (np.asarray(per_a) == m.sum(axis=1)).all()


class TestGraftEntry:
    def test_entry_compiles(self):
        import sys, os
        sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert int(out.valid.sum()) > 0

    def test_dryrun_multichip(self, capsys):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)
        assert "ok" in capsys.readouterr().out


def test_hierarchical_knn_matches_single_device():
    """2-D (hosts, cells) mesh; two-level ICI->DCN merge must equal the
    single-device kernel."""
    from spatialflink_tpu.parallel import (
        distributed_knn_hierarchical,
        make_mesh_2d,
        shard_batch,
    )

    mesh = make_mesh_2d(2, 4)
    b = make_batch(512)
    sharded = shard_batch(b, mesh, axis=mesh.axis_names)
    qx, qy = 116.5, 40.5
    got = distributed_knn_hierarchical(
        mesh, sharded, qx, qy, jnp.int32(0), 0.0, GRID.n, n=GRID.n, k=10)
    want = knn_point(b, qx, qy, jnp.int32(0), 0.0, GRID.n, n=GRID.n, k=10)
    np.testing.assert_array_equal(np.asarray(got.valid), np.asarray(want.valid))
    np.testing.assert_allclose(
        np.asarray(got.dist)[np.asarray(got.valid)],
        np.asarray(want.dist)[np.asarray(want.valid)], atol=0)


def test_make_mesh_2d_shape_and_axes():
    from spatialflink_tpu.parallel import make_mesh_2d

    mesh = make_mesh_2d(4, 2)
    assert mesh.axis_names == ("hosts", "cells")
    assert mesh.devices.shape == (4, 2)


def test_make_mesh_2d_rejects_oversubscription():
    from spatialflink_tpu.parallel import make_mesh_2d

    with pytest.raises(ValueError):
        make_mesh_2d(16)  # 16 hosts on an 8-device pool -> inner axis would be 0
    with pytest.raises(ValueError):
        make_mesh_2d(4, 4)


def test_init_distributed_noop_single_process():
    from spatialflink_tpu.parallel import init_distributed

    init_distributed()  # no coordinator configured -> must be a silent no-op


class TestOperatorDistributedDispatch:
    """Mesh-aware operator mode (conf.devices): the driver-reachable path
    must match the single-device path bit-for-bit on the 8-device mesh."""

    def _points(self, n, seed):
        from spatialflink_tpu.models import Point

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        return [
            Point.create(float(rng.uniform(115.6, 117.5)),
                         float(rng.uniform(39.7, 41.0)), GRID,
                         obj_id=f"o{i % 97}", timestamp=t0 + i * 10)
            for i in range(n)
        ]

    def _conf(self, devices=None):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(QueryType.WindowBased, window_size_ms=10_000,
                                  slide_ms=5_000, devices=devices)

    def test_range_matches_single_device(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointRangeQuery

        pts = self._points(3000, 31)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointRangeQuery(self._conf(), GRID).run(
            iter(pts), q, 0.4))
        r8 = list(PointPointRangeQuery(self._conf(8), GRID).run(
            iter(pts), q, 0.4))
        assert [w.window_start for w in r1] == [w.window_start for w in r8]
        for a, b in zip(r1, r8):
            assert [(p.obj_id, p.timestamp) for p in a.records] == \
                   [(p.obj_id, p.timestamp) for p in b.records]

    def test_knn_matches_single_device(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointKNNQuery

        pts = self._points(3000, 32)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointKNNQuery(self._conf(), GRID).run(
            iter(pts), q, 0.5, 15))
        r8 = list(PointPointKNNQuery(self._conf(8), GRID).run(
            iter(pts), q, 0.5, 15))
        assert len(r1) == len(r8)
        for a, b in zip(r1, r8):
            assert [o for o, _ in a.records] == [o for o, _ in b.records]
            np.testing.assert_array_equal(
                np.array([d for _, d in a.records]),
                np.array([d for _, d in b.records]))

    def test_join_matches_single_device(self):
        from spatialflink_tpu.operators import PointPointJoinQuery

        a = self._points(1500, 33)
        b = self._points(300, 34)
        r1 = list(PointPointJoinQuery(self._conf(), GRID).run(
            iter(a), iter(b), 0.2))
        r8 = list(PointPointJoinQuery(self._conf(8), GRID).run(
            iter(a), iter(b), 0.2))
        assert len(r1) == len(r8)
        for wa, wb in zip(r1, r8):
            pa = sorted((x.obj_id, x.timestamp, y.obj_id, y.timestamp)
                        for x, y in wa.records)
            pb = sorted((x.obj_id, x.timestamp, y.obj_id, y.timestamp)
                        for x, y in wb.records)
            assert pa == pb

    def test_driver_parallelism_dispatches_distributed(self, tmp_path):
        """End-to-end: query.parallelism in the YAML drives the mesh path
        through run_option and matches the single-device driver run."""
        import yaml

        from spatialflink_tpu.config import Params
        from spatialflink_tpu.driver import run_option

        with open("conf/spatialflink-conf.yml") as f:
            y = yaml.safe_load(f)
        y["query"]["option"] = 1
        y["query"]["radius"] = 0.4
        y["inputStream1"]["format"] = "CSV"
        y["inputStream1"]["csvTsvSchemaAttr"] = [0, 1, 2, 3]
        y["inputStream1"]["dateFormat"] = None
        pts = self._points(2000, 35)
        lines = [f"{p.obj_id},{p.timestamp},{p.x},{p.y}" for p in pts]
        single = list(run_option(Params.from_dict(y), iter(lines)))
        y["query"]["parallelism"] = 8
        dist = list(run_option(Params.from_dict(y), iter(lines)))
        assert [w.window_start for w in single] == [w.window_start for w in dist]
        for a, b in zip(single, dist):
            assert [(p.obj_id, p.timestamp) for p in a.records] == \
                   [(p.obj_id, p.timestamp) for p in b.records]

    def test_non_power_of_two_devices_rejected(self):
        from spatialflink_tpu.operators import PointPointRangeQuery

        with pytest.raises(ValueError):
            PointPointRangeQuery(self._conf(3), GRID)

    def test_config_rejects_bad_parallelism(self):
        from spatialflink_tpu.config import ConfigError, QueryConfig

        with pytest.raises(ConfigError):
            QueryConfig.from_dict({"option": 1, "parallelism": 3})


class TestGeomStreamDistributedDispatch:
    """VERDICT r3 #4: geometry-stream operators must dispatch through the
    mesh like PointPoint — 8-dev results must equal 1-dev bit-for-bit."""

    def _polys(self, n, seed):
        from spatialflink_tpu.models import Polygon

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        out = []
        for i in range(n):
            cx = float(rng.uniform(115.7, 117.4))
            cy = float(rng.uniform(39.8, 40.9))
            w = float(rng.uniform(0.01, 0.08))
            out.append(Polygon.create(
                [[(cx - w, cy - w), (cx + w, cy - w), (cx + w, cy + w),
                  (cx - w, cy + w)]], GRID, obj_id=f"g{i % 61}",
                timestamp=t0 + i * 10))
        return out

    def _pts(self, n, seed):
        from spatialflink_tpu.models import Point

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        return [
            Point.create(float(rng.uniform(115.6, 117.5)),
                         float(rng.uniform(39.7, 41.0)), GRID,
                         obj_id=f"o{i % 97}", timestamp=t0 + i * 10)
            for i in range(n)
        ]

    def _conf(self, devices=None):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(QueryType.WindowBased, window_size_ms=10_000,
                                  slide_ms=5_000, devices=devices)

    def _qpoly(self):
        from spatialflink_tpu.models import Polygon

        return Polygon.create(
            [[(116.3, 40.3), (116.7, 40.3), (116.7, 40.7), (116.3, 40.7)]],
            GRID)

    def test_geomgeom_range_matches_single_device(self):
        from spatialflink_tpu.operators import PolygonPolygonRangeQuery

        polys = self._polys(700, 41)
        q = self._qpoly()
        r1 = list(PolygonPolygonRangeQuery(self._conf(), GRID).run(
            iter(polys), q, 0.3))
        r8 = list(PolygonPolygonRangeQuery(self._conf(8), GRID).run(
            iter(polys), q, 0.3))
        assert [w.window_start for w in r1] == [w.window_start for w in r8]
        assert any(w.records for w in r1)
        for a, b in zip(r1, r8):
            assert [(g.obj_id, g.timestamp) for g in a.records] == \
                   [(g.obj_id, g.timestamp) for g in b.records]

    def test_geompoint_range_matches_single_device(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PolygonPointRangeQuery

        polys = self._polys(500, 42)
        q = Point.create(QX, QY, GRID)
        r1 = list(PolygonPointRangeQuery(self._conf(), GRID).run(
            iter(polys), q, 0.4))
        r8 = list(PolygonPointRangeQuery(self._conf(8), GRID).run(
            iter(polys), q, 0.4))
        assert any(w.records for w in r1)
        for a, b in zip(r1, r8):
            assert [(g.obj_id, g.timestamp) for g in a.records] == \
                   [(g.obj_id, g.timestamp) for g in b.records]

    def test_pointgeom_knn_matches_single_device(self):
        from spatialflink_tpu.operators import PointPolygonKNNQuery

        pts = self._pts(3000, 43)
        q = self._qpoly()
        r1 = list(PointPolygonKNNQuery(self._conf(), GRID).run(
            iter(pts), q, 0.5, 12))
        r8 = list(PointPolygonKNNQuery(self._conf(8), GRID).run(
            iter(pts), q, 0.5, 12))
        assert any(w.records for w in r1)
        for a, b in zip(r1, r8):
            assert [o for o, _ in a.records] == [o for o, _ in b.records]
            np.testing.assert_array_equal(
                np.array([d for _, d in a.records]),
                np.array([d for _, d in b.records]))

    def test_geomgeom_knn_matches_single_device(self):
        from spatialflink_tpu.operators import PolygonPolygonKNNQuery

        polys = self._polys(400, 44)
        q = self._qpoly()
        r1 = list(PolygonPolygonKNNQuery(self._conf(), GRID).run(
            iter(polys), q, 0.8, 9))
        r8 = list(PolygonPolygonKNNQuery(self._conf(8), GRID).run(
            iter(polys), q, 0.8, 9))
        assert any(w.records for w in r1)
        for a, b in zip(r1, r8):
            assert [o for o, _ in a.records] == [o for o, _ in b.records]
            np.testing.assert_array_equal(
                np.array([d for _, d in a.records]),
                np.array([d for _, d in b.records]))

    def test_pointgeom_join_matches_single_device(self):
        from spatialflink_tpu.operators import PointPolygonJoinQuery

        pts = self._pts(1200, 45)
        polys = self._polys(150, 46)
        r1 = list(PointPolygonJoinQuery(self._conf(), GRID).run(
            iter(pts), iter(polys), 0.15))
        r8 = list(PointPolygonJoinQuery(self._conf(8), GRID).run(
            iter(pts), iter(polys), 0.15))
        assert len(r1) == len(r8)
        assert any(w.records for w in r1)
        for wa, wb in zip(r1, r8):
            pa = sorted((x.obj_id, x.timestamp, y.obj_id, y.timestamp)
                        for x, y in wa.records)
            pb = sorted((x.obj_id, x.timestamp, y.obj_id, y.timestamp)
                        for x, y in wb.records)
            assert pa == pb

    def test_geomgeom_join_matches_single_device(self):
        from spatialflink_tpu.operators import PolygonPolygonJoinQuery

        a = self._polys(250, 47)
        b = self._polys(60, 48)
        r1 = list(PolygonPolygonJoinQuery(self._conf(), GRID).run(
            iter(a), iter(b), 0.1))
        r8 = list(PolygonPolygonJoinQuery(self._conf(8), GRID).run(
            iter(a), iter(b), 0.1))
        assert any(w.records for w in r1)
        for wa, wb in zip(r1, r8):
            pa = sorted((x.obj_id, x.timestamp, y.obj_id, y.timestamp)
                        for x, y in wa.records)
            pb = sorted((x.obj_id, x.timestamp, y.obj_id, y.timestamp)
                        for x, y in wb.records)
            assert pa == pb

    def test_config5_reachable_via_run_option_21(self):
        """BASELINE config 5 (polygon-polygon range on a mesh) through the
        driver: run_option(option=21, parallelism=8) — not bespoke bench
        code (VERDICT r3 missing #3)."""
        import yaml

        from spatialflink_tpu.config import Params
        from spatialflink_tpu.driver import run_option
        from spatialflink_tpu.streams.formats import serialize_spatial

        with open("conf/spatialflink-conf.yml") as f:
            y = yaml.safe_load(f)
        y["query"]["option"] = 21
        y["query"]["radius"] = 0.3
        y["query"]["queryPolygons"] = [
            [[116.3, 40.3], [116.7, 40.3], [116.7, 40.7], [116.3, 40.7]]]
        y["inputStream1"]["format"] = "WKT"
        y["inputStream1"]["dateFormat"] = None
        polys = self._polys(400, 49)
        lines = [f"{p.obj_id}, {p.timestamp}, {serialize_spatial(p, 'WKT')}"
                 for p in polys]
        single = list(run_option(Params.from_dict(y), iter(lines)))
        y["query"]["parallelism"] = 8
        dist = list(run_option(Params.from_dict(y), iter(lines)))
        assert any(w.records for w in single)
        assert [w.window_start for w in single] == [w.window_start for w in dist]
        for a, b in zip(single, dist):
            assert [(g.obj_id, g.timestamp) for g in a.records] == \
                   [(g.obj_id, g.timestamp) for g in b.records]

    def test_knn_small_window_shards_smaller_than_k(self):
        """Shard capacity < k must clamp+pad, not crash at trace time:
        20 polygons over 8 devices (pad 32, shard 4) with k=10."""
        from spatialflink_tpu.operators import PolygonPolygonKNNQuery

        polys = self._polys(20, 51)
        q = self._qpoly()
        r1 = list(PolygonPolygonKNNQuery(self._conf(), GRID).run(
            iter(polys), q, 5.0, 10))
        r8 = list(PolygonPolygonKNNQuery(self._conf(8), GRID).run(
            iter(polys), q, 5.0, 10))
        assert any(w.records for w in r1)
        for a, b in zip(r1, r8):
            assert [o for o, _ in a.records] == [o for o, _ in b.records]
            np.testing.assert_array_equal(
                np.array([d for _, d in a.records]),
                np.array([d for _, d in b.records]))


class TestTrajectoryDistributedDispatch:
    """Kernel-backed trajectory ops ride the mesh too (tJoin already goes
    through the distributed join): tRange containment and tKnn top-k must
    match single-device bit-for-bit at parallelism 8."""

    def _traj_pts(self, n, seed):
        from spatialflink_tpu.models import Point

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        return [
            Point.create(float(rng.uniform(115.6, 117.5)),
                         float(rng.uniform(39.7, 41.0)), GRID,
                         obj_id=f"t{i % 37}", timestamp=t0 + i * 10)
            for i in range(n)
        ]

    def _conf(self, devices=None, realtime=False):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(
            QueryType.RealTime if realtime else QueryType.WindowBased,
            window_size_ms=10_000, slide_ms=5_000, devices=devices)

    def test_trange_matches_single_device(self):
        from spatialflink_tpu.models import Polygon
        from spatialflink_tpu.operators import PointPolygonTRangeQuery

        pts = self._traj_pts(2000, 61)
        polys = [Polygon.create(
            [[(116.2, 40.2), (116.9, 40.2), (116.9, 40.8), (116.2, 40.8)]],
            GRID)]
        r1 = list(PointPolygonTRangeQuery(self._conf(), GRID).run(
            iter(pts), polys))
        r8 = list(PointPolygonTRangeQuery(self._conf(8), GRID).run(
            iter(pts), polys))
        assert any(w.records for w in r1)
        assert [w.extras.get("matched_ids") for w in r1] == \
               [w.extras.get("matched_ids") for w in r8]

    def test_trange_realtime_matches_single_device(self):
        from spatialflink_tpu.models import Polygon
        from spatialflink_tpu.operators import PointPolygonTRangeQuery

        pts = self._traj_pts(1500, 62)
        polys = [Polygon.create(
            [[(116.2, 40.2), (116.9, 40.2), (116.9, 40.8), (116.2, 40.8)]],
            GRID)]
        r1 = list(PointPolygonTRangeQuery(self._conf(realtime=True), GRID).run(
            iter(pts), polys))
        r8 = list(PointPolygonTRangeQuery(self._conf(8, realtime=True), GRID)
                  .run(iter(pts), polys))
        assert any(w.records for w in r1)
        assert [[(p.obj_id, p.timestamp) for p in w.records] for w in r1] == \
               [[(p.obj_id, p.timestamp) for p in w.records] for w in r8]

    def test_tknn_matches_single_device(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointTKNNQuery

        pts = self._traj_pts(2000, 63)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointTKNNQuery(self._conf(), GRID).run(
            iter(pts), q, 0.5, 8))
        r8 = list(PointPointTKNNQuery(self._conf(8), GRID).run(
            iter(pts), q, 0.5, 8))
        assert any(w.records for w in r1)
        assert len(r1) == len(r8)
        for a, b in zip(r1, r8):
            assert [(o, d) for o, d, _ in a.records] == \
                   [(o, d) for o, d, _ in b.records]

    def _assert_tstats_parity(self, r1, r8):
        assert any(w.records for w in r1)
        assert len(r1) == len(r8)
        for a, b in zip(r1, r8):
            assert (a.window_start, a.window_end) == \
                   (b.window_start, b.window_end)
            # trajectory ids + integer temporal lengths: exact; spatial
            # sums/speeds: f32 summation order differs between the sharded
            # stitch and the single-device cumsum — last-ulp tolerance
            assert [t[0] for t in a.records] == [t[0] for t in b.records]
            assert [t[2] for t in a.records] == [t[2] for t in b.records]
            # observed ~5e-6 relative over ~10^2 f32 pair additions
            np.testing.assert_allclose([t[1] for t in a.records],
                                       [t[1] for t in b.records], rtol=2e-5)
            np.testing.assert_allclose([t[3] for t in a.records],
                                       [t[3] for t in b.records], rtol=2e-5)

    def test_tstats_windowed_matches_single_device(self):
        from spatialflink_tpu.operators import PointTStatsQuery

        pts = self._traj_pts(2000, 64)
        r1 = list(PointTStatsQuery(self._conf(), GRID).run(iter(pts)))
        r8 = list(PointTStatsQuery(self._conf(8), GRID).run(iter(pts)))
        self._assert_tstats_parity(r1, r8)

    def test_tstats_windowed_out_of_order_and_duplicates(self):
        """Shuffled arrival and exact (objID, ts) duplicates — including
        same-ts different-coords pairs — must not break the sharded
        stitch's global-sort precondition (host pre-sort + dedup)."""
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointTStatsQuery

        pts = self._traj_pts(1200, 65)
        rng = np.random.default_rng(9)
        extra = []
        for i in range(0, len(pts), 10):
            p = pts[i]
            extra.append(Point.create(p.x, p.y, GRID, obj_id=p.obj_id,
                                      timestamp=p.timestamp))
            extra.append(Point.create(p.x + 0.01, p.y, GRID, obj_id=p.obj_id,
                                      timestamp=p.timestamp))
        pts = pts + extra
        # mild shuffle (bounded displacement keeps the window assembly
        # identical concern-free: both runs see the SAME stream)
        for i in range(0, len(pts) - 8, 8):
            j = i + int(rng.integers(0, 8))
            pts[i], pts[j] = pts[j], pts[i]
        from spatialflink_tpu.operators import PointTStatsQuery as Q

        r1 = list(Q(self._conf(), GRID).run(iter(pts)))
        r8 = list(Q(self._conf(8), GRID).run(iter(pts)))
        self._assert_tstats_parity(r1, r8)

    @pytest.mark.parametrize("agg", ["SUM", "COUNT", "MIN", "MAX", "AVG"])
    def test_taggregate_windowed_heatmap_matches_single_device(self, agg):
        from spatialflink_tpu.operators import PointTAggregateQuery

        pts = self._traj_pts(2000, 66)
        r1 = list(PointTAggregateQuery(self._conf(), GRID).run(
            iter(pts), agg))
        r8 = list(PointTAggregateQuery(self._conf(8), GRID).run(
            iter(pts), agg))
        assert len(r1) == len(r8) > 0
        assert any(w.extras["heatmap"].any() for w in r1)
        for a, b in zip(r1, r8):
            assert (a.window_start, a.window_end) == \
                   (b.window_start, b.window_end)
            # group lengths are exact ints; per-cell reductions of them in
            # f32 are exact at window scale -> bit-for-bit
            np.testing.assert_array_equal(a.extras["heatmap"],
                                          b.extras["heatmap"])

    def test_taggregate_windowed_all_matches_single_device(self):
        from spatialflink_tpu.operators import PointTAggregateQuery

        pts = self._traj_pts(1500, 67)
        r1 = list(PointTAggregateQuery(self._conf(), GRID).run(
            iter(pts), "ALL"))
        r8 = list(PointTAggregateQuery(self._conf(8), GRID).run(
            iter(pts), "ALL"))
        assert len(r1) == len(r8) > 0
        assert any(w.records for w in r1)
        for a, b in zip(r1, r8):
            assert a.records == b.records


class TestRealtimeDistributedDispatch:
    """Realtime (micro-batch) mode through the mesh: identical output to the
    single-device realtime run for range and kNN."""

    def _pts(self, n, seed):
        from spatialflink_tpu.models import Point

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        return [
            Point.create(float(rng.uniform(115.6, 117.5)),
                         float(rng.uniform(39.7, 41.0)), GRID,
                         obj_id=f"o{i % 53}", timestamp=t0 + i * 10)
            for i in range(n)
        ]

    def _conf(self, devices=None):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(QueryType.RealTime, window_size_ms=10_000,
                                  slide_ms=5_000, realtime_batch_size=256,
                                  devices=devices)

    def test_realtime_range_matches_single_device(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointRangeQuery

        pts = self._pts(1200, 71)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointRangeQuery(self._conf(), GRID).run(
            iter(pts), q, 0.4))
        r8 = list(PointPointRangeQuery(self._conf(8), GRID).run(
            iter(pts), q, 0.4))
        assert any(w.records for w in r1)
        assert [[(p.obj_id, p.timestamp) for p in w.records] for w in r1] == \
               [[(p.obj_id, p.timestamp) for p in w.records] for w in r8]

    def test_realtime_knn_matches_single_device(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointKNNQuery

        pts = self._pts(1200, 72)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointKNNQuery(self._conf(), GRID).run(
            iter(pts), q, 0.5, 10))
        r8 = list(PointPointKNNQuery(self._conf(8), GRID).run(
            iter(pts), q, 0.5, 10))
        assert len(r1) == len(r8) and any(w.records for w in r1)
        for a, b in zip(r1, r8):
            assert a.records == b.records


class TestElasticDegradedMode:
    """SURVEY §7 phase 7's elastic/degraded-mode story: a device failure
    during a distributed window halves the mesh and re-dispatches; at one
    device the single-device path takes over. Output must stay identical to
    an undisturbed single-device run; host state is untouched."""

    def _points(self, n, seed):
        from spatialflink_tpu.models import Point

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        return [
            Point.create(float(rng.uniform(115.6, 117.5)),
                         float(rng.uniform(39.7, 41.0)), GRID,
                         obj_id=f"o{i % 53}", timestamp=t0 + i * 10)
            for i in range(n)
        ]

    def _conf(self, devices=None):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(QueryType.WindowBased, window_size_ms=10_000,
                                  slide_ms=5_000, devices=devices)

    def test_range_degrades_and_matches(self, monkeypatch):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointRangeQuery
        from spatialflink_tpu.parallel import ops as pops
        from spatialflink_tpu.utils.metrics import REGISTRY

        pts = self._points(2000, 61)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointRangeQuery(self._conf(), GRID).run(
            iter(pts), q, 0.4))

        real = pops.distributed_stream_filter
        failures = {"left": 2}

        def flaky(mesh, batch, fn):
            if failures["left"] > 0:
                failures["left"] -= 1
                raise RuntimeError("injected device loss (test)")
            return real(mesh, batch, fn)

        monkeypatch.setattr(pops, "distributed_stream_filter", flaky)
        before = REGISTRY.counter("mesh-degradations").count
        op = PointPointRangeQuery(self._conf(8), GRID)
        r8 = list(op.run(iter(pts), q, 0.4))
        assert REGISTRY.counter("mesh-degradations").count == before + 2
        assert op.conf.devices == 2  # 8 -> 4 -> 2, success at 2
        assert [w.window_start for w in r1] == [w.window_start for w in r8]
        for a, b in zip(r1, r8):
            assert [(p.obj_id, p.timestamp) for p in a.records] == \
                   [(p.obj_id, p.timestamp) for p in b.records]

    def test_knn_persistent_failure_raises_after_bounded_degradations(
            self, monkeypatch):
        """A PERSISTENT distributed failure must trip a loud error after the
        elastic halvings run out (8 -> 4 -> 2, then refuse the final halving
        to 1) — never a permanent silent single-device run (the VERDICT r4
        tradeoff, now bounded)."""
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointKNNQuery
        from spatialflink_tpu.parallel import ops as pops

        pts = self._points(2000, 62)
        q = Point.create(QX, QY, GRID)

        def always_fail(*a, **kw):
            raise RuntimeError("injected device loss (test)")

        monkeypatch.setattr(pops, "distributed_stream_knn", always_fail)
        op = PointPointKNNQuery(self._conf(8), GRID)
        with pytest.raises(RuntimeError, match="refusing to silently"):
            list(op.run(iter(pts), q, 0.5, 15))
        assert op.conf.devices == 2 and op._degradations == 2
        # the loud error carries the underlying failure
        try:
            list(op.run(iter(pts), q, 0.5, 15))
        except RuntimeError as e:
            assert "injected device loss" in str(e.__cause__)

    def test_max_degradations_bound_is_configurable(self, monkeypatch):
        """conf.max_degradations=1 allows ONE elastic halving; the second
        failure raises instead of narrowing further."""
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import (PointPointRangeQuery,
                                                QueryConfiguration, QueryType)
        from spatialflink_tpu.parallel import ops as pops

        def always_fail(*a, **kw):
            raise RuntimeError("injected device loss (test)")

        monkeypatch.setattr(pops, "distributed_stream_filter", always_fail)
        pts = self._points(600, 64)
        q = Point.create(QX, QY, GRID)
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000,
                                  devices=8, max_degradations=1)
        op = PointPointRangeQuery(conf, GRID)
        with pytest.raises(RuntimeError, match="refusing to silently"):
            list(op.run(iter(pts), q, 0.4))
        assert op.conf.devices == 4 and op._degradations == 1

    def test_two_device_mesh_failure_is_loud(self, monkeypatch):
        """At devices=2 there is no narrower multi-device width: the first
        failure raises (silent 2 -> 1 fallback would be the exact hidden
        state the bound exists to prevent)."""
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointRangeQuery
        from spatialflink_tpu.parallel import ops as pops

        def always_fail(*a, **kw):
            raise RuntimeError("injected device loss (test)")

        monkeypatch.setattr(pops, "distributed_stream_filter", always_fail)
        pts = self._points(600, 65)
        q = Point.create(QX, QY, GRID)
        op = PointPointRangeQuery(self._conf(2), GRID)
        with pytest.raises(RuntimeError, match="refusing to silently"):
            list(op.run(iter(pts), q, 0.4))
        assert op.conf.devices == 2 and op._degradations == 0

    def test_non_device_errors_propagate(self, monkeypatch):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointRangeQuery
        from spatialflink_tpu.parallel import ops as pops

        def type_bug(*a, **kw):
            raise TypeError("shape bug (test)")

        monkeypatch.setattr(pops, "distributed_stream_filter", type_bug)
        pts = self._points(600, 63)
        q = Point.create(QX, QY, GRID)
        op = PointPointRangeQuery(self._conf(8), GRID)
        with pytest.raises(TypeError):
            list(op.run(iter(pts), q, 0.4))


class TestTwoDMeshOperators:
    """conf.hosts > 1 builds the 2-D (hosts x chips) mesh through the SAME
    operator paths: output must match single-device bit-for-bit, with kNN
    merged in two levels (ICI within a slice, then k-sized partials per
    slice over DCN)."""

    def _points(self, n, seed):
        from spatialflink_tpu.models import Point

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        return [
            Point.create(float(rng.uniform(115.6, 117.5)),
                         float(rng.uniform(39.7, 41.0)), GRID,
                         obj_id=f"o{i % 61}", timestamp=t0 + i * 10)
            for i in range(n)
        ]

    def _conf(self, devices=None, hosts=None):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(QueryType.WindowBased, window_size_ms=10_000,
                                  slide_ms=5_000, devices=devices, hosts=hosts)

    def test_range_2d_matches_single(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointRangeQuery

        pts = self._points(3000, 71)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointRangeQuery(self._conf(), GRID).run(
            iter(pts), q, 0.4))
        r2d = list(PointPointRangeQuery(self._conf(8, hosts=2), GRID).run(
            iter(pts), q, 0.4))
        assert [w.window_start for w in r1] == [w.window_start for w in r2d]
        for a, b in zip(r1, r2d):
            assert [(p.obj_id, p.timestamp) for p in a.records] == \
                   [(p.obj_id, p.timestamp) for p in b.records]

    def test_knn_2d_matches_single(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointKNNQuery

        pts = self._points(3000, 72)
        q = Point.create(QX, QY, GRID)
        r1 = list(PointPointKNNQuery(self._conf(), GRID).run(
            iter(pts), q, 0.5, 15))
        r2d = list(PointPointKNNQuery(self._conf(8, hosts=4), GRID).run(
            iter(pts), q, 0.5, 15))
        assert len(r1) == len(r2d) and any(w.records for w in r1)
        for a, b in zip(r1, r2d):
            assert a.records == b.records

    def test_join_2d_matches_single(self):
        from spatialflink_tpu.operators import PointPointJoinQuery

        a = self._points(1500, 73)
        b = self._points(400, 74)
        r1 = list(PointPointJoinQuery(self._conf(), GRID, GRID).run(
            iter(a), iter(b), 0.1))
        r2d = list(PointPointJoinQuery(self._conf(8, hosts=2), GRID, GRID).run(
            iter(a), iter(b), 0.1))
        assert len(r1) == len(r2d) and any(w.records for w in r1)
        for x, y in zip(r1, r2d):
            key = lambda prs: sorted((p.obj_id, p.timestamp, q.obj_id,
                                      q.timestamp) for p, q in prs)
            assert key(x.records) == key(y.records)

    def test_2d_degrades_to_flat_mesh(self, monkeypatch):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PointPointRangeQuery
        from spatialflink_tpu.parallel import ops as pops

        real = pops.distributed_stream_filter
        failures = {"left": 1}

        def flaky(mesh, batch, fn):
            if failures["left"] > 0:
                failures["left"] -= 1
                raise RuntimeError("injected device loss (test)")
            return real(mesh, batch, fn)

        monkeypatch.setattr(pops, "distributed_stream_filter", flaky)
        pts = self._points(1200, 75)
        q = Point.create(QX, QY, GRID)
        op = PointPointRangeQuery(self._conf(8, hosts=2), GRID)
        r = list(op.run(iter(pts), q, 0.4))
        assert op.conf.devices == 4 and op.conf.hosts is None
        r1 = list(PointPointRangeQuery(self._conf(), GRID).run(
            iter(pts), q, 0.4))
        for a, b in zip(r1, r):
            assert [(p.obj_id, p.timestamp) for p in a.records] == \
                   [(p.obj_id, p.timestamp) for p in b.records]

    def test_hosts_must_divide_devices(self):
        from spatialflink_tpu.operators import PointPointRangeQuery

        with pytest.raises(ValueError):  # power-of-two but > devices
            PointPointRangeQuery(self._conf(4, hosts=8), GRID)
        with pytest.raises(ValueError):  # not a power of two
            PointPointRangeQuery(self._conf(8, hosts=3), GRID)


class TestGeomStream2DMesh:
    """Geometry streams through the 2-D (hosts x chips) mesh: the generic
    stream funnels (filter / kNN / join lattice) must produce single-device
    output bit-for-bit on the hosts>1 shape too."""

    def _polys(self, n, seed):
        from spatialflink_tpu.models import Polygon

        rng = np.random.default_rng(seed)
        t0 = 1_700_000_000_000
        out = []
        for i in range(n):
            cx = float(rng.uniform(115.7, 117.4))
            cy = float(rng.uniform(39.8, 40.9))
            w = float(rng.uniform(0.01, 0.08))
            out.append(Polygon.create(
                [[(cx - w, cy - w), (cx + w, cy - w), (cx + w, cy + w),
                  (cx - w, cy + w)]], GRID, obj_id=f"g{i % 61}",
                timestamp=t0 + i * 10))
        return out

    def _conf(self, devices=None, hosts=None):
        from spatialflink_tpu.operators import QueryConfiguration, QueryType

        return QueryConfiguration(QueryType.WindowBased, window_size_ms=10_000,
                                  slide_ms=5_000, devices=devices, hosts=hosts)

    def _qpoly(self):
        from spatialflink_tpu.models import Polygon

        return Polygon.create([[(116.2, 40.2), (116.9, 40.2), (116.9, 40.8),
                                (116.2, 40.8)]], GRID)

    def test_polygon_range_2d_matches_single(self):
        from spatialflink_tpu.operators import PolygonPolygonRangeQuery

        polys = self._polys(600, 81)
        r1 = list(PolygonPolygonRangeQuery(self._conf(), GRID).run(
            iter(polys), self._qpoly(), 0.3))
        r2d = list(PolygonPolygonRangeQuery(self._conf(8, hosts=2), GRID).run(
            iter(polys), self._qpoly(), 0.3))
        assert any(w.records for w in r1)
        assert [(w.window_start,
                 sorted(g.obj_id for g in w.records)) for w in r1] == \
               [(w.window_start,
                 sorted(g.obj_id for g in w.records)) for w in r2d]

    def test_polygon_knn_2d_matches_single(self):
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import PolygonPointKNNQuery

        polys = self._polys(600, 82)
        q = Point.create(QX, QY, GRID)
        r1 = list(PolygonPointKNNQuery(self._conf(), GRID).run(
            iter(polys), q, 0.5, 9))
        r2d = list(PolygonPointKNNQuery(self._conf(8, hosts=2), GRID).run(
            iter(polys), q, 0.5, 9))
        assert len(r1) == len(r2d) and any(w.records for w in r1)
        for a, b in zip(r1, r2d):
            assert a.records == b.records
