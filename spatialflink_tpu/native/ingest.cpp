// Native ingest hot path: bulk CSV/TSV + GeoJSON point parsing.
//
// TPU-native equivalent of the reference's per-tuple JVM deserializer
// (spatialStreams/Deserialization.java:288-330 CSV schema parse, :167-207
// GeoJSON trajectory parse). There the parser runs inside Flink map tasks;
// here the host must keep a TPU fed, so the line -> arrays conversion is a
// single C++ pass producing the structure-of-arrays a PointBatch wraps.
//
// Contract (shared with streams/bulk.py):
// - Input is a '\0'-terminated buffer of '\n'-separated records.
// - Outputs are preallocated arrays of capacity >= number of lines.
// - Object ids are returned as FNV-1a 64 hashes plus (start, len) spans into
//   the input buffer; Python interns one representative string per unique
//   hash (collisions at 64-bit are negligible for stream cardinalities).
// - Records the parser cannot handle exactly (ISO timestamps, non-point
//   GeoJSON, malformed lines) are NOT errors: their line indices go to
//   `rejects` and Python re-parses just those with the full-fidelity parser.
//
// Build: g++ -O3 -shared -fPIC (see native/__init__.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

inline uint64_t fnv1a(const char* s, long n) {
    uint64_t h = 1469598103934665603ULL;
    for (long i = 0; i < n; i++) {
        h ^= (unsigned char)s[i];
        h *= 1099511628211ULL;
    }
    return h;
}

inline const char* skip_ws(const char* p, const char* end) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
    return p;
}

inline const char* rskip_ws(const char* begin, const char* p) {
    while (p > begin && (p[-1] == ' ' || p[-1] == '\t' || p[-1] == '\r')) p--;
    return p;
}

// Parse an integer timestamp field. Digits-only, mirroring
// formats.parse_timestamp (which passes `s.isdigit()` strings through as
// ints and sends everything else — ISO dates, signs, floats — down the
// strptime path); any other shape is rejected to Python.
inline bool parse_int_field(const char* s, const char* end, int64_t* out) {
    if (s >= end) return false;
    for (const char* p = s; p < end; p++)
        if (*p < '0' || *p > '9') return false;
    *out = (int64_t)strtoll(s, nullptr, 10);
    return true;
}

inline bool parse_double_field(const char* s, const char* end, double* out) {
    char* stop = nullptr;
    double v = strtod(s, &stop);
    if (stop == s) return false;
    const char* rest = skip_ws(stop, end);
    if (rest != end) return false;
    *out = v;
    return true;
}

struct Span {
    const char* start;
    const char* end;
};

// Trim whitespace and one layer of double quotes (parse_csv strips '"').
inline Span trim_field(const char* s, const char* e) {
    s = skip_ws(s, e);
    e = rskip_ws(s, e);
    if (e - s >= 2 && *s == '"' && e[-1] == '"') {
        s++;
        e--;
    }
    return {s, e};
}

}  // namespace

extern "C" {

// Returns number of accepted records. Lines that need the Python parser are
// appended to rejects (their 0-based line index); blank lines are skipped
// entirely. Schema indices: oi (objID), ti (timestamp), xi, yi; oi/ti may be
// -1 (absent). Capacity of all output arrays must be >= the line count.
long sf_parse_points_csv(const char* buf, long len, char delim,
                         int oi, int ti, int xi, int yi,
                         double* xs, double* ys, int64_t* ts,
                         uint64_t* oid_hash, int64_t* oid_start,
                         int32_t* oid_len,
                         int64_t* rejects, long* n_rejects) {
    long count = 0;
    long nrej = 0;
    long line_idx = -1;
    const char* end = buf + len;
    const char* p = buf;
    int max_field = xi > yi ? xi : yi;
    if (oi > max_field) max_field = oi;
    if (ti > max_field) max_field = ti;

    while (p < end) {
        line_idx++;
        const char* line_end = (const char*)memchr(p, '\n', end - p);
        if (!line_end) line_end = end;
        const char* ls = p;
        p = line_end + 1;

        // skip blank lines without consuming a record slot
        {
            const char* t = skip_ws(ls, line_end);
            if (t == rskip_ws(t, line_end)) {
                line_idx--;
                continue;
            }
        }

        // split into fields up to the max index we need
        Span fields[64];
        int nf = 0;
        const char* fs = ls;
        const char* q = ls;
        bool overflow = false;
        while (q <= line_end && nf <= max_field) {
            if (q == line_end || *q == delim) {
                if (nf >= 64) {
                    overflow = true;
                    break;
                }
                fields[nf++] = trim_field(fs, q);
                fs = q + 1;
            }
            q++;
        }
        if (overflow || nf <= max_field) {
            rejects[nrej++] = line_idx;
            continue;
        }

        double x, y;
        if (!parse_double_field(fields[xi].start, fields[xi].end, &x) ||
            !parse_double_field(fields[yi].start, fields[yi].end, &y)) {
            rejects[nrej++] = line_idx;
            continue;
        }
        int64_t t = 0;
        if (ti >= 0 &&
            !parse_int_field(fields[ti].start, fields[ti].end, &t)) {
            rejects[nrej++] = line_idx;  // ISO date etc. -> Python
            continue;
        }
        if (oi >= 0) {
            // Normalize the id exactly like the Python parser: remove every
            // '"' (parse_csv does line.replace('"', '')), then trim
            // whitespace. The hash is over the normalized bytes; the Python
            // side applies the same normalization when materializing the
            // span. Oversized ids take the Python path.
            const Span& f = fields[oi];
            char tmp[256];
            long m = 0;
            bool toolong = false;
            for (const char* q2 = f.start; q2 < f.end; q2++) {
                if (*q2 == '"') continue;
                if (m >= (long)sizeof(tmp)) {
                    toolong = true;
                    break;
                }
                tmp[m++] = *q2;
            }
            if (toolong) {
                rejects[nrej++] = line_idx;
                continue;
            }
            long b = 0;
            while (b < m && (tmp[b] == ' ' || tmp[b] == '\t' || tmp[b] == '\r'))
                b++;
            while (m > b &&
                   (tmp[m - 1] == ' ' || tmp[m - 1] == '\t' || tmp[m - 1] == '\r'))
                m--;
            oid_hash[count] = fnv1a(tmp + b, m - b);
            oid_start[count] = f.start - buf;
            oid_len[count] = (int32_t)(f.end - f.start);
        } else {
            oid_hash[count] = fnv1a(nullptr, 0);
            oid_start[count] = 0;
            oid_len[count] = 0;
        }
        xs[count] = x;
        ys[count] = y;
        ts[count] = t;
        count++;
    }
    *n_rejects = nrej;
    return count;
}

namespace {

// One past the matching close of the JSON object/array starting at p
// (which must point at '{' or '['), quote-aware; nullptr if unbalanced.
inline const char* match_close(const char* p, const char* end) {
    char open = *p;
    char close = (open == '{') ? '}' : ']';
    int depth = 0;
    bool instr = false;
    for (const char* q = p; q < end; q++) {
        char c = *q;
        if (instr) {
            if (c == '\\')
                q++;
            else if (c == '"')
                instr = false;
        } else if (c == '"') {
            instr = true;
        } else if (c == open) {
            depth++;
        } else if (c == close) {
            if (--depth == 0) return q + 1;
        }
    }
    return nullptr;
}

// Find `"key"` within [s, end) and return a pointer to its value (first
// non-ws char after the colon). Flat scan — callers narrow [s, end) to the
// owning JSON object first; a miss sends the line to Python.
inline const char* find_key(const char* s, const char* end, const char* key,
                            long key_len) {
    const char* p = s;
    while (p + key_len + 2 <= end) {
        const char* hit =
            (const char*)memchr(p, '"', end - p - key_len - 1);
        if (!hit) return nullptr;
        if (memcmp(hit + 1, key, key_len) == 0 && hit[key_len + 1] == '"') {
            const char* after = skip_ws(hit + key_len + 2, end);
            if (after < end && *after == ':') return skip_ws(after + 1, end);
        }
        p = hit + 1;
    }
    return nullptr;
}

// Kafka-envelope unwrap ({"...": ..., "value": {...}}) followed by
// geometry-object narrowing for one GeoJSON record line [ls, le):
// *rs/*re get the record region (the properties scope), *cs/*ce the
// coordinates scope ("geometry" object when present, else the record).
// Returns false -> reject the line to Python. Shared by the point and
// geometry parsers.
inline bool narrow_geojson_record(const char* ls, const char* le,
                                  const char** rs_out, const char** re_out,
                                  const char** cs_out, const char** ce_out) {
    const char* rs = ls;
    const char* re = le;
    {
        const char* v = find_key(rs, re, "value", 5);
        if (v && *v == '{') {
            const char* ve = match_close(v, re);
            if (!ve) return false;
            rs = v;
            re = ve;
        }
    }
    const char* cs = rs;
    const char* ce = re;
    {
        const char* gkey = find_key(rs, re, "geometry", 8);
        if (gkey) {
            if (*gkey != '{') return false;
            ce = match_close(gkey, re);
            if (!ce) return false;
            cs = gkey;
        }
    }
    *rs_out = rs;
    *re_out = re;
    *cs_out = cs;
    *ce_out = ce;
    return true;
}

// properties[oid_key] / properties[ts_key] from the record region [rs, re).
// Mirrors formats.parse_geojson: absent/null properties -> empty id / ts 0;
// escaped strings, bool ids and non-integer timestamps are not representable
// here. Returns false -> send the line to Python. Shared by the GeoJSON
// point and geometry parsers.
inline bool parse_props_oid_ts(const char* buf, const char* rs, const char* re,
                               const char* oid_key, long oid_key_len,
                               const char* ts_key, long ts_key_len,
                               uint64_t* oh_out, int64_t* os_out,
                               int32_t* ol_out, int64_t* ts_out) {
    const char* ps = nullptr;
    const char* pe = nullptr;
    {
        const char* pkey = find_key(rs, re, "properties", 10);
        if (pkey && *pkey == '{') {
            pe = match_close(pkey, re);
            if (!pe) return false;
            ps = pkey;
        }
    }
    uint64_t oh = fnv1a(nullptr, 0);
    int64_t os = 0;
    int32_t ol = 0;
    if (oid_key_len && ps) {
        const char* v = find_key(ps, pe, oid_key, oid_key_len);
        if (v) {
            const char* vs;
            const char* ve;
            if (*v == '"') {
                vs = v + 1;
                ve = (const char*)memchr(vs, '"', pe - vs);
                // escapes need real JSON decoding -> Python
                if (!ve || memchr(vs, '\\', ve - vs)) return false;
            } else {  // bare number / literal: up to , } ]
                vs = v;
                ve = v;
                while (ve < pe && *ve != ',' && *ve != '}' && *ve != ']') ve++;
                ve = rskip_ws(vs, ve);
                long n_tok = ve - vs;
                if (n_tok == 4 && memcmp(vs, "null", 4) == 0) {
                    vs = ve;  // bare JSON null => empty id
                } else if ((n_tok == 4 && memcmp(vs, "true", 4) == 0) ||
                           (n_tok == 5 && memcmp(vs, "false", 5) == 0)) {
                    return false;  // str(True) capitalizes -> Python
                }
            }
            oh = fnv1a(vs, ve - vs);
            os = vs - buf;
            ol = (int32_t)(ve - vs);
        }
    }
    int64_t t = 0;
    if (ts_key_len && ps) {
        const char* v = find_key(ps, pe, ts_key, ts_key_len);
        if (v) {
            const char* vs = v;
            const char* ve;
            if (*v == '"') {  // quoted: integer ok, ISO date -> Python
                vs = v + 1;
                ve = (const char*)memchr(vs, '"', pe - vs);
            } else {
                ve = v;
                while (ve < pe && *ve != ',' && *ve != '}') ve++;
                ve = rskip_ws(vs, ve);
            }
            if (!ve || !parse_int_field(vs, ve, &t)) return false;
        }
    }
    *oh_out = oh;
    *os_out = os;
    *ol_out = ol;
    *ts_out = t;
    return true;
}

}  // namespace

// GeoJSON fast path: extracts Point coordinates plus the oID / timestamp
// properties (reference: Deserialization.java:167-207 pulls
// properties[oID] / properties[timestamp]). Non-Point geometries, quoted
// non-integer timestamps and anything surprising goes to `rejects`.
long sf_parse_points_geojson(const char* buf, long len,
                             const char* oid_key, const char* ts_key,
                             double* xs, double* ys, int64_t* ts,
                             uint64_t* oid_hash, int64_t* oid_start,
                             int32_t* oid_len,
                             int64_t* rejects, long* n_rejects) {
    long count = 0;
    long nrej = 0;
    long line_idx = -1;
    long oid_key_len = oid_key ? (long)strlen(oid_key) : 0;
    long ts_key_len = ts_key ? (long)strlen(ts_key) : 0;
    const char* end = buf + len;
    const char* p = buf;

    while (p < end) {
        line_idx++;
        const char* line_end = (const char*)memchr(p, '\n', end - p);
        if (!line_end) line_end = end;
        const char* ls = p;
        p = line_end + 1;

        {
            const char* t = skip_ws(ls, line_end);
            if (t == rskip_ws(t, line_end)) {
                line_idx--;
                continue;
            }
        }

        // envelope unwrap ("value" object, so envelope-level keys like the
        // broker "timestamp" are never picked up) + geometry narrowing
        // (bare-geometry records are scanned whole; "geometry": null etc.
        // goes to Python) — shared helper with the geometry parser
        const char* rs;
        const char* re;
        const char* cs;
        const char* ce;
        if (!narrow_geojson_record(ls, line_end, &rs, &re, &cs, &ce)) {
            rejects[nrej++] = line_idx;
            continue;
        }
        const char* c = find_key(cs, ce, "coordinates", 11);
        if (!c || *c != '[') {
            rejects[nrej++] = line_idx;
            continue;
        }
        const char* q = skip_ws(c + 1, ce);
        if (q < ce && *q == '[') {  // nested => not a Point
            rejects[nrej++] = line_idx;
            continue;
        }
        char* stop = nullptr;
        double x = strtod(q, &stop);
        if (stop == q) {
            rejects[nrej++] = line_idx;
            continue;
        }
        q = skip_ws(stop, ce);
        if (q >= ce || *q != ',') {
            rejects[nrej++] = line_idx;
            continue;
        }
        double y = strtod(q + 1, &stop);
        if (stop == q + 1) {
            rejects[nrej++] = line_idx;
            continue;
        }

        // oID / timestamp from the "properties" object (shared helper with
        // the geometry parser below)
        uint64_t oh;
        int64_t os, t;
        int32_t ol;
        if (!parse_props_oid_ts(buf, rs, re, oid_key, oid_key_len,
                                ts_key, ts_key_len, &oh, &os, &ol, &t)) {
            rejects[nrej++] = line_idx;
            continue;
        }

        xs[count] = x;
        ys[count] = y;
        ts[count] = t;
        oid_hash[count] = oh;
        oid_start[count] = os;
        oid_len[count] = ol;
        count++;
    }
    *n_rejects = nrej;
    return count;
}

}  // extern "C"

// ------------------------------------------------------------------------- //
// Bulk WKT geometry parsing: POLYGON / LINESTRING lines with optional
// "oid<delim>ts<delim>" prefix fields -> flattened ring/vertex arrays.
//
// TPU-native equivalent of the reference's per-tuple WKT polygon/linestring
// deserializers (spatialStreams/Deserialization.java:516-628 WKTToSpatial
// Polygon/LineString and the convertCoordinates family :1367-1565): one C++
// pass emits the structure the EdgeGeomBatch assembler vectorizes over.
// MULTI*/GEOMETRYCOLLECTION/POINT lines reject to the Python parser (full
// fidelity), exactly like the point parsers' reject contract.

namespace {

inline bool is_word(char c) {
    return (c >= 'A' && c <= 'Z') || (c >= 'a' && c <= 'z') || c == '_';
}

// Find the first boundary-respecting occurrence of kw in [s, e).
inline const char* find_kw(const char* s, const char* e, const char* kw,
                           long kwlen) {
    for (const char* p = s; p + kwlen <= e; p++) {
        if ((p == s || !is_word(p[-1])) && memcmp(p, kw, kwlen) == 0 &&
            (p + kwlen == e || !is_word(p[kwlen])))
            return p;
    }
    return nullptr;
}

}  // namespace

extern "C" {

// Returns number of accepted records; per-record arrays sized >= line count,
// ring arrays >= count('('), vertex arrays >= count(',') + count('(') + 2.
// bbox is (cap, 4) row-major [minx, miny, maxx, maxy].
long sf_parse_wkt_geoms(const char* buf, long len, char delim,
                        int64_t* ts, uint64_t* oid_hash, int64_t* oid_start,
                        int32_t* oid_len, int8_t* is_poly,
                        int64_t* ring_off, int32_t* ring_cnt, double* bbox,
                        int64_t* ring_voff, int32_t* ring_size,
                        double* vx, double* vy,
                        int64_t* rejects, long* n_rejects) {
    long count = 0, nrej = 0, line_idx = -1;
    long n_rings = 0, n_verts = 0;
    const char* end = buf + len;
    const char* p = buf;

    while (p < end) {
        line_idx++;
        const char* line_end = (const char*)memchr(p, '\n', end - p);
        if (!line_end) line_end = end;
        const char* ls = p;
        p = line_end + 1;
        {
            const char* t = skip_ws(ls, line_end);
            if (t == rskip_ws(t, line_end)) {
                line_idx--;
                continue;
            }
        }

        const char* kp = find_kw(ls, line_end, "POLYGON", 7);
        const char* kl = find_kw(ls, line_end, "LINESTRING", 10);
        const char* kw = kp && (!kl || kp < kl) ? kp : kl;
        bool poly = (kw == kp && kp != nullptr);
        long kwlen = poly ? 7 : 10;
        if (!kw) {  // POINT / MULTI* / GEOMETRYCOLLECTION / junk -> Python
            rejects[nrej++] = line_idx;
            continue;
        }
        // the keyword must not sit inside an outer structure's parens
        // (a POLYGON inside a GEOMETRYCOLLECTION body): prefix paren
        // balance must be zero, like formats.parse_wkt's guard
        long bal = 0;
        for (const char* q = ls; q < kw; q++) {
            if (*q == '(') bal++;
            else if (*q == ')') bal--;
        }
        if (bal != 0) {
            rejects[nrej++] = line_idx;
            continue;
        }

        // prefix fields before the keyword: [oid][delim][ts][delim]
        uint64_t oh = fnv1a(nullptr, 0);
        int64_t osp = 0;
        int32_t oln = 0;
        int64_t tval = 0;
        {
            const char* pe = rskip_ws(ls, kw);
            // drop one trailing delimiter separating the fields from the
            // geometry, then split what remains
            if (pe > ls && pe[-1] == delim) pe--;
            if (pe > ls) {
                Span fields[8];
                int nf = 0;
                const char* fs = ls;
                bool overflow = false;
                for (const char* q = ls; q <= pe; q++) {
                    if (q == pe || *q == delim) {
                        if (nf >= 8) { overflow = true; break; }
                        Span f = trim_field(fs, q);
                        // drop empty fields ANYWHERE, like the Python WKT
                        // branch's `if f.strip()` filter — keeping an
                        // interior empty would shift the timestamp slot
                        if (f.start != f.end)
                            fields[nf++] = f;
                        fs = q + 1;
                    }
                }
                if (overflow) {
                    rejects[nrej++] = line_idx;
                    continue;
                }
                if (nf >= 1 && fields[0].start != fields[0].end) {
                    // normalize like the Python WKT branch: strip quotes
                    char tmp[256];
                    long m = 0;
                    bool toolong = false;
                    for (const char* q2 = fields[0].start;
                         q2 < fields[0].end; q2++) {
                        if (*q2 == '"') continue;
                        if (m >= (long)sizeof(tmp)) { toolong = true; break; }
                        tmp[m++] = *q2;
                    }
                    if (toolong) {
                        rejects[nrej++] = line_idx;
                        continue;
                    }
                    oh = fnv1a(tmp, m);
                    osp = fields[0].start - buf;
                    oln = (int32_t)(fields[0].end - fields[0].start);
                }
                if (nf >= 2 && fields[1].start != fields[1].end &&
                    !parse_int_field(fields[1].start, fields[1].end, &tval)) {
                    rejects[nrej++] = line_idx;  // date-formatted ts -> Python
                    continue;
                }
            }
        }

        // geometry body
        const char* q = skip_ws(kw + kwlen, line_end);
        if (q >= line_end || *q != '(') {
            rejects[nrej++] = line_idx;
            continue;
        }
        q++;
        long rstart = n_rings, vstart_total = n_verts;
        bool bad = false;
        double minx = 0, miny = 0, maxx = 0, maxy = 0;
        bool first_v = true;
        int rings_here = 0;

        auto parse_ring = [&](const char*& q, const char* term) -> bool {
            // vertices "x y" separated by ','; stops at the char in `term`
            long vstart = n_verts;
            while (true) {
                q = skip_ws(q, line_end);
                char* stop = nullptr;
                double x = strtod(q, &stop);
                if (stop == q) return false;
                q = skip_ws(stop, line_end);
                double y = strtod(q, &stop);
                if (stop == q) return false;
                q = skip_ws(stop, line_end);
                vx[n_verts] = x;
                vy[n_verts] = y;
                n_verts++;
                if (first_v) {
                    minx = maxx = x;
                    miny = maxy = y;
                    first_v = false;
                } else {
                    if (x < minx) minx = x;
                    if (x > maxx) maxx = x;
                    if (y < miny) miny = y;
                    if (y > maxy) maxy = y;
                }
                if (q < line_end && *q == ',') {
                    q++;
                    continue;
                }
                if (q < line_end && *q == *term) {
                    ring_voff[n_rings] = vstart;
                    ring_size[n_rings] = (int32_t)(n_verts - vstart);
                    n_rings++;
                    rings_here++;
                    return true;
                }
                return false;  // z coordinate / junk -> Python
            }
        };

        if (poly) {
            while (true) {
                q = skip_ws(q, line_end);
                if (q >= line_end || *q != '(') { bad = true; break; }
                q++;
                if (!parse_ring(q, ")")) { bad = true; break; }
                q++;  // consume ')'
                q = skip_ws(q, line_end);
                if (q < line_end && *q == ',') { q++; continue; }
                if (q < line_end && *q == ')') { q++; break; }
                bad = true;
                break;
            }
            if (!bad) {
                // every raw ring needs >= 3 vertices (Polygon.create drops
                // smaller ones / raises; let Python own that semantics)
                for (long r = rstart; r < n_rings; r++)
                    if (ring_size[r] < 3) { bad = true; break; }
            }
        } else {
            if (!parse_ring(q, ")")) bad = true;
            else {
                q++;  // consume ')'
                if (ring_size[n_rings - 1] < 2) bad = true;
            }
        }
        if (!bad) {
            q = skip_ws(q, line_end);
            if (q != rskip_ws(ls, line_end)) bad = true;  // trailing junk
        }
        if (bad) {
            n_rings = rstart;  // roll back this line's ring/vertex output
            n_verts = vstart_total;
            rejects[nrej++] = line_idx;
            continue;
        }

        ts[count] = tval;
        oid_hash[count] = oh;
        oid_start[count] = osp;
        oid_len[count] = oln;
        is_poly[count] = poly ? 1 : 0;
        ring_off[count] = rstart;
        ring_cnt[count] = rings_here;
        bbox[count * 4 + 0] = minx;
        bbox[count * 4 + 1] = miny;
        bbox[count * 4 + 2] = maxx;
        bbox[count * 4 + 3] = maxy;
        count++;
    }
    *n_rejects = nrej;
    return count;
}

}  // extern "C" (wkt geometry parser)

// ------------------------------------------------------------------------- //
// Bulk GeoJSON geometry parsing: Polygon / LineString features -> the same
// flattened ring/vertex layout as sf_parse_wkt_geoms.
//
// TPU-native equivalent of the reference's per-tuple GeoJSON polygon/
// linestring deserializers (spatialStreams/Deserialization.java:236-334
// GeoJSONToSpatialPolygon/LineString; properties[oID]/properties[timestamp]
// extraction as in :167-207). Point / Multi* / GeometryCollection features,
// escaped strings and date-formatted timestamps reject to the Python parser
// (full fidelity), exactly like the point parser's reject contract.

extern "C" {

// Output contract identical to sf_parse_wkt_geoms; ring arrays must be
// sized >= count('['), vertex arrays >= count('[') + 2.
long sf_parse_geojson_geoms(const char* buf, long len,
                            const char* oid_key, const char* ts_key,
                            int64_t* ts, uint64_t* oid_hash,
                            int64_t* oid_start, int32_t* oid_len,
                            int8_t* is_poly,
                            int64_t* ring_off, int32_t* ring_cnt, double* bbox,
                            int64_t* ring_voff, int32_t* ring_size,
                            double* vx, double* vy,
                            int64_t* rejects, long* n_rejects) {
    long count = 0, nrej = 0, line_idx = -1;
    long n_rings = 0, n_verts = 0;
    long oid_key_len = oid_key ? (long)strlen(oid_key) : 0;
    long ts_key_len = ts_key ? (long)strlen(ts_key) : 0;
    const char* end = buf + len;
    const char* p = buf;

    while (p < end) {
        line_idx++;
        const char* line_end = (const char*)memchr(p, '\n', end - p);
        if (!line_end) line_end = end;
        const char* ls = p;
        p = line_end + 1;
        {
            const char* t0 = skip_ws(ls, line_end);
            if (t0 == rskip_ws(t0, line_end)) {
                line_idx--;
                continue;
            }
        }

        // envelope unwrap + geometry-object narrowing (shared helper with
        // the point parser)
        const char* rs;
        const char* re;
        const char* cs;
        const char* ce;
        if (!narrow_geojson_record(ls, line_end, &rs, &re, &cs, &ce)) {
            rejects[nrej++] = line_idx;
            continue;
        }
        // geometry type must be exactly Polygon or LineString
        bool poly;
        {
            const char* tv = find_key(cs, ce, "type", 4);
            if (!tv || *tv != '"') { rejects[nrej++] = line_idx; continue; }
            const char* tvs = tv + 1;
            const char* tve = (const char*)memchr(tvs, '"', ce - tvs);
            if (!tve) { rejects[nrej++] = line_idx; continue; }
            long tn = tve - tvs;
            if (tn == 7 && memcmp(tvs, "Polygon", 7) == 0)
                poly = true;
            else if (tn == 10 && memcmp(tvs, "LineString", 10) == 0)
                poly = false;
            else { rejects[nrej++] = line_idx; continue; }
        }
        const char* c = find_key(cs, ce, "coordinates", 11);
        if (!c || *c != '[') { rejects[nrej++] = line_idx; continue; }
        const char* cend = match_close(c, ce);
        if (!cend) { rejects[nrej++] = line_idx; continue; }

        uint64_t oh;
        int64_t os_v, tval;
        int32_t ol_v;
        if (!parse_props_oid_ts(buf, rs, re, oid_key, oid_key_len,
                                ts_key, ts_key_len,
                                &oh, &os_v, &ol_v, &tval)) {
            rejects[nrej++] = line_idx;
            continue;
        }

        // walk the coordinate nest: Polygon [[[x,y],..],..] (points at
        // depth 3, each depth-2 '[' opens a ring); LineString [[x,y],..]
        // (points at depth 2, the depth-1 '[' IS the single ring). A
        // trailing z in a point array is skipped; deeper nesting is
        // malformed for these types and rejects.
        const int pt_depth = poly ? 3 : 2;
        const int ring_depth = poly ? 2 : 1;
        long rec_rings = 0;
        const long saved_rings = n_rings, saved_verts = n_verts;
        double minx = 1e308, miny = 1e308, maxx = -1e308, maxy = -1e308;
        bool bad = false;
        int depth = 0;
        const char* q = c;
        while (q < cend) {
            char ch = *q;
            if (ch == '[') {
                depth++;
                if (depth > pt_depth) { bad = true; break; }
                if (depth == ring_depth) {
                    ring_voff[n_rings] = n_verts;
                    ring_size[n_rings] = 0;
                    n_rings++;
                    rec_rings++;
                }
                if (depth == pt_depth) {
                    const char* s2 = skip_ws(q + 1, cend);
                    char* stop = nullptr;
                    double x = strtod(s2, &stop);
                    if (stop == s2) { bad = true; break; }
                    s2 = skip_ws(stop, cend);
                    if (s2 >= cend || *s2 != ',') { bad = true; break; }
                    double y = strtod(s2 + 1, &stop);
                    if (stop == s2 + 1) { bad = true; break; }
                    const char* pc =
                        (const char*)memchr(stop, ']', cend - stop);
                    if (!pc) { bad = true; break; }
                    vx[n_verts] = x;
                    vy[n_verts] = y;
                    ring_size[n_rings - 1]++;
                    n_verts++;
                    if (x < minx) minx = x;
                    if (x > maxx) maxx = x;
                    if (y < miny) miny = y;
                    if (y > maxy) maxy = y;
                    depth--;
                    q = pc + 1;
                    continue;
                }
                q++;
            } else if (ch == ']') {
                depth--;
                q++;
                if (depth == 0) break;
            } else {
                q++;
            }
        }
        // empty / degenerate (sub-2-vertex ring) shapes -> Python, which
        // owns the full error story
        bool tiny = (rec_rings == 0 || n_verts == saved_verts);
        for (long r = saved_rings; !tiny && r < n_rings; r++)
            if (ring_size[r] < 2) tiny = true;
        if (bad || tiny) {
            n_rings = saved_rings;
            n_verts = saved_verts;
            rejects[nrej++] = line_idx;
            continue;
        }
        ts[count] = tval;
        oid_hash[count] = oh;
        oid_start[count] = os_v;
        oid_len[count] = ol_v;
        is_poly[count] = poly ? 1 : 0;
        ring_off[count] = saved_rings;
        ring_cnt[count] = (int32_t)rec_rings;
        bbox[count * 4 + 0] = minx;
        bbox[count * 4 + 1] = miny;
        bbox[count * 4 + 2] = maxx;
        bbox[count * 4 + 3] = maxy;
        count++;
    }
    *n_rejects = nrej;
    return count;
}

}  // extern "C" (geojson geometry parser)
