"""Stream sinks (reference: Kafka producers in ``Serialization.java`` and the
latency sinks in ``utils/HelperClass.java:455-529``)."""

from __future__ import annotations

import json
import sys
import time
from typing import List, Optional

from spatialflink_tpu.streams.formats import serialize_spatial


class CollectSink:
    """Accumulates records in memory (test/driver path)."""

    def __init__(self):
        self.records: List = []

    def emit(self, record):
        self.records.append(record)

    def close(self):
        pass


class StdoutSink:
    def __init__(self, fmt: Optional[str] = None):
        self.fmt = fmt

    def emit(self, record):
        if self.fmt and hasattr(record, "obj_id"):
            record = serialize_spatial(record, self.fmt)
        print(record, file=sys.stdout)

    def close(self):
        sys.stdout.flush()


class FileSink:
    """Newline-delimited record file — the reference's output Kafka topic
    (``Serialization.java`` output schemas) as a file. Spatial records are
    serialized in ``fmt`` (honoring ``delimiter``/``date_format`` like the
    Kafka sink); non-spatial records (kNN tuples, stats rows) fall back to
    JSON lines."""

    def __init__(self, path: str, fmt: Optional[str] = None, *,
                 delimiter: str = ",", date_format: Optional[str] = None):
        self.fmt = fmt
        self.delimiter = delimiter
        self.date_format = date_format
        self.records_written = 0
        self._f = open(path, "w")

    def _ser(self, obj):
        return serialize_spatial(obj, self.fmt, delimiter=self.delimiter,
                                 date_format=self.date_format)

    def emit(self, record):
        if self.fmt and hasattr(record, "obj_id"):
            record = self._ser(record)
        elif (self.fmt and isinstance(record, (tuple, list)) and record
                and all(hasattr(r, "obj_id") for r in record)):
            # join pairs (and any spatial tuple): a JSON array of the
            # per-element serializations — each element honors the output
            # format, the array frame keeps the line machine-parseable
            record = json.dumps([self._ser(r) for r in record])
        elif self.fmt and not isinstance(record, str):
            record = json.dumps(record, default=str)
        self._f.write(str(record) + "\n")
        self.records_written += 1

    def close(self):
        self._f.close()


class LatencySink:
    """Per-record latency in millis: now - ingestion_time (or event ts),
    mirroring ``HelperClass.LatencySinkPoint`` et al.

    Backed by a constant-memory :class:`~spatialflink_tpu.utils.telemetry.
    StreamingHistogram` — the old per-record Python list grew without bound
    on long-running streams (and its ``percentile()`` imported numpy per
    call). The ``percentile()`` API is unchanged; when a telemetry session
    is active the same values also feed its ``record-latency-ms``
    histogram so they appear in the JSONL snapshots."""

    def __init__(self, use_event_time: bool = False):
        from spatialflink_tpu.utils import telemetry as _telemetry
        from spatialflink_tpu.utils.telemetry import StreamingHistogram

        self.use_event_time = use_event_time
        self.hist = StreamingHistogram("record-latency-ms")
        tel = _telemetry.active()
        self._tel_hist = (tel.histogram("record-latency-ms")
                          if tel is not None else None)

    @property
    def count(self) -> int:
        return self.hist.count

    def emit(self, record):
        now = time.time() * 1000
        base = record.timestamp if self.use_event_time else record.ingestion_time
        v = now - base
        self.hist.record(v)
        if self._tel_hist is not None:
            self._tel_hist.record(v)

    def percentile(self, p: float) -> float:
        return self.hist.percentile(p)

    def close(self):
        pass
