"""Range-query window kernels.

Reference hot loop (``range/PointPointRangeQuery.java:117-137``): per window,
for each point — guaranteed-cell points are emitted without any distance
computation; candidate-cell points are emitted iff exact distance <= r;
approximate mode emits candidate points without the distance check
(``:125-127``).

On TPU the whole window is one masked vector op: the GN/CN set-membership
tests become either Chebyshev index arithmetic (point queries) or a gather
into dense cell masks (polygon/linestring queries), and the distance check is
a fused elementwise computation over the padded batch. The emitted "stream"
is a boolean selection mask aligned with the batch.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from spatialflink_tpu.index.uniform_grid import cheb_layers
from spatialflink_tpu.models.batches import PointBatch
from spatialflink_tpu.ops import distances as D


@partial(jax.jit, static_argnames=("n", "approximate"))
def range_filter_point(
    points: PointBatch,
    qx,
    qy,
    q_cell,
    radius,
    gn_layers,
    cn_layers,
    *,
    n: int,
    approximate: bool = False,
):
    """Point-query range filter over a point window batch.

    gn_layers / cn_layers are the precomputed layer counts
    (``UniformGrid.guaranteed_layers`` / ``candidate_layers``); gn_layers may
    be -1 (no guaranteed cells). Returns (mask, dists): ``mask`` selects the
    result set; ``dists`` holds the exact distance where it was computed and
    +inf where the GN bypass skipped it (parity with the reference, which
    never computes distances for guaranteed points).
    """
    layers = cheb_layers(points.cell, q_cell, n)
    in_gn = layers <= gn_layers  # gn_layers == -1 -> all False
    in_cn = (layers <= cn_layers) & ~in_gn
    if approximate:
        mask = points.valid & (in_gn | in_cn)
        dists = jnp.full_like(points.x, jnp.inf)
    else:
        d = D.pp_dist(points.x, points.y, qx, qy)
        mask = points.valid & (in_gn | (in_cn & (d <= radius)))
        dists = jnp.where(in_cn, d, jnp.inf)
    return mask, dists


@partial(jax.jit, static_argnames=("approximate",))
def range_filter_masks(
    points: PointBatch,
    gn_mask,
    cn_mask,
    dists,
    radius,
    *,
    approximate: bool = False,
):
    """Generic range filter with dense GN/CN cell masks and precomputed
    distances (used for polygon/linestring query geometries, whose GN/CN sets
    are unions over the geometry's cells — ``UniformGrid.java:193-222``).

    ``dists`` must hold the exact point->query distance per slot (only
    consulted for candidate cells).
    """
    cell = jnp.maximum(points.cell, 0)  # guard the -1 pad; gated by cell_ok
    cell_ok = points.cell >= 0
    in_gn = gn_mask[cell] & cell_ok
    in_cn = cn_mask[cell] & cell_ok & ~in_gn
    if approximate:
        return points.valid & (in_gn | in_cn)
    return points.valid & (in_gn | (in_cn & (dists <= radius)))


@jax.jit
def range_filter_geom_stream(all_gn, any_nb, dists, radius, valid):
    """Range filter for polygon/linestring STREAMS against any query.

    Reference rule (``range/PolygonPointRangeQuery.java:54-87``): a geometry
    whose grid cells are ALL guaranteed neighbors passes without distance
    computation; otherwise it passes iff distance <= r. The caller supplies
    ``dists`` as the exact geometry distance — or the bbox distance in
    approximate mode, so only the needed kernel ever runs.

    all_gn / any_nb: (G,) cell predicates (see ops.geom.geom_cells_all_within
    / geom_cells_any_within).
    """
    return valid & (all_gn | (any_nb & ~all_gn & (dists <= radius)))
