"""All 9 stream x query type pairs for range/kNN/join vs exhaustive oracles."""

import numpy as np
import pytest

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import LineString, Point, Polygon
from spatialflink_tpu import operators as OP
from tests import oracles as O

GRID = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
RNG = np.random.default_rng(5)

Q_POINT = Point.create(116.5, 40.5, GRID, obj_id="qp")
Q_POLY = Polygon.create(
    [[(116.45, 40.45), (116.55, 40.45), (116.55, 40.55), (116.45, 40.55)]],
    GRID, obj_id="qpoly",
)
Q_LINE = LineString.create([(116.4, 40.4), (116.6, 40.6)], GRID, obj_id="qline")

BASE_TS = 1_700_000_000_000


def point_stream(n=400, seed=0):
    rng = np.random.default_rng(seed)
    return [
        Point.create(x, y, GRID, obj_id=f"p{i % 80}", timestamp=BASE_TS + i * 50)
        for i, (x, y) in enumerate(
            zip(rng.uniform(115.6, 117.5, n), rng.uniform(39.7, 41.0, n))
        )
    ]


def polygon_stream(n=80, seed=1):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        cx, cy = rng.uniform(115.7, 117.4), rng.uniform(39.8, 40.9)
        w, h = rng.uniform(0.01, 0.08, 2)
        out.append(
            Polygon.create(
                [[(cx, cy), (cx + w, cy), (cx + w, cy + h), (cx, cy + h)]],
                GRID, obj_id=f"poly{i % 40}", timestamp=BASE_TS + i * 250,
            )
        )
    return out


def linestring_stream(n=80, seed=2):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        cx, cy = rng.uniform(115.7, 117.4), rng.uniform(39.8, 40.9)
        pts = [(cx + rng.uniform(-0.04, 0.04), cy + rng.uniform(-0.04, 0.04))
               for _ in range(4)]
        out.append(LineString.create(pts, GRID, obj_id=f"ls{i % 40}",
                                     timestamp=BASE_TS + i * 250))
    return out


def conf(**kw):
    return OP.QueryConfiguration(window_size_ms=10_000, slide_ms=10_000, **kw)


def geom_dist_oracle(obj, query) -> float:
    """Exhaustive JTS-style distance between two host objects."""
    def rings_of(g):
        if isinstance(g, Polygon):
            return [np.asarray(r) for r in g.rings], True
        if isinstance(g, LineString):
            return [np.asarray(g.coords_list)], False
        return [np.asarray([[g.x, g.y], [g.x, g.y]])], False

    ra, areal_a = rings_of(obj)
    rb, areal_b = rings_of(query)
    if isinstance(obj, Point) and isinstance(query, Point):
        return O.pp_dist(obj.x, obj.y, query.x, query.y)
    if isinstance(obj, Point):
        return O.point_polygon_dist(obj.x, obj.y, rb) if areal_b else \
            O.point_rings_boundary_dist(obj.x, obj.y, rb)
    if isinstance(query, Point):
        return O.point_polygon_dist(query.x, query.y, ra) if areal_a else \
            O.point_rings_boundary_dist(query.x, query.y, ra)
    # geom-geom: containment (for areal sides) + min boundary distance
    if areal_b and O.point_in_rings(ra[0][0][0], ra[0][0][1], rb):
        return 0.0
    if areal_a and O.point_in_rings(rb[0][0][0], rb[0][0][1], ra):
        return 0.0
    d = np.inf
    for sa in O.rings_to_segments(ra):
        for sb in O.rings_to_segments(rb):
            d = min(d, O.seg_seg_dist(sa, sb))
    return d


STREAMS = {
    "Point": point_stream,
    "Polygon": polygon_stream,
    "LineString": linestring_stream,
}
QUERIES = {"Point": Q_POINT, "Polygon": Q_POLY, "LineString": Q_LINE}


@pytest.mark.parametrize("stream_kind", ["Point", "Polygon", "LineString"])
@pytest.mark.parametrize("query_kind", ["Point", "Polygon", "LineString"])
class TestRangeMatrix:
    def test_results_superset_of_true_matches(self, stream_kind, query_kind):
        """Every object truly within r must be in the result; every result
        must be within r OR covered by the GN bypass (cell-guaranteed)."""
        r = 0.25
        cls = getattr(OP, f"{stream_kind}{query_kind}RangeQuery")
        op = cls(conf(), GRID)
        stream = STREAMS[stream_kind]()
        query = QUERIES[query_kind]
        results = list(op.run(iter(stream), query, r))
        assert results
        got = set()
        for res in results:
            got |= {(o.obj_id, o.timestamp) for o in res.records}
        for obj in stream:
            d = geom_dist_oracle(obj, query)
            key = (obj.obj_id, obj.timestamp)
            if d <= r - 1e-3:
                assert key in got, f"missing true match at d={d}"
            elif d > r + 1e-3 and key in got:
                # must be a GN-bypassed object: all its cells guaranteed
                gn = GRID.guaranteed_cells_mask(
                    r, [query.cell] if query_kind == "Point" else query.cells
                )
                cells = {obj.cell} if stream_kind == "Point" else obj.cells
                assert all(gn[c] for c in cells), (
                    f"false positive beyond GN bypass at d={d}"
                )


@pytest.mark.parametrize("stream_kind", ["Point", "Polygon", "LineString"])
@pytest.mark.parametrize("query_kind", ["Point", "Polygon", "LineString"])
class TestKnnMatrix:
    def test_topk_matches_oracle(self, stream_kind, query_kind):
        k, r = 5, 0.0  # r=0 disables pruning: exact oracle comparison
        cls = getattr(OP, f"{stream_kind}{query_kind}KNNQuery")
        op = cls(conf(k=k), GRID)
        stream = STREAMS[stream_kind]()
        query = QUERIES[query_kind]
        results = list(op.run(iter(stream), query, r))
        assert results
        # oracle over the whole stream per window is complex; use the first
        # full window's member set via a replay
        from spatialflink_tpu.runtime import WindowAssembler, WindowSpec

        wa = WindowAssembler(WindowSpec.sliding(10_000, 10_000))
        windows = {}
        for p in stream:
            for s, e, recs in wa.add(p.timestamp, p):
                windows[s] = recs
        for res in results:
            recs = windows.get(res.window_start)
            if not recs:
                continue
            best = {}
            for obj in recs:
                d = geom_dist_oracle(obj, query)
                if obj.obj_id not in best or d < best[obj.obj_id]:
                    best[obj.obj_id] = d
            want = sorted(best.values())[:k]
            got = [d for _, d in res.records]
            np.testing.assert_allclose(got, want, atol=2e-3)


@pytest.mark.parametrize("stream_kind", ["Point", "Polygon", "LineString"])
@pytest.mark.parametrize("query_kind", ["Point", "Polygon", "LineString"])
class TestJoinMatrix:
    def test_pairs_satisfy_predicate_and_cover_true_pairs(self, stream_kind, query_kind):
        r = 0.1
        cls = getattr(OP, f"{stream_kind}{query_kind}JoinQuery")
        op = cls(conf(), GRID)
        stream = STREAMS[stream_kind](40 if stream_kind != "Point" else 150)
        qstream = STREAMS[query_kind](20 if query_kind != "Point" else 60)
        results = list(op.run(iter(stream), iter(qstream), r))
        got_pairs = {
            (a.obj_id, a.timestamp, b.obj_id, b.timestamp)
            for res in results for a, b in res.records
        }
        # sample-check: all emitted pairs within r (up to f32 boundary)
        for res in results[:2]:
            for a, b in res.records[:30]:
                assert geom_dist_oracle(a, b) <= r + 2e-3
        # coverage: co-windowed true pairs must be found
        from spatialflink_tpu.runtime import WindowSpec

        spec = WindowSpec.sliding(10_000, 10_000)
        missing = 0
        for a in stream[:60]:
            for b in qstream[:30]:
                if geom_dist_oracle(a, b) <= r - 1e-3 and \
                        set(spec.assign(a.timestamp)) & set(spec.assign(b.timestamp)):
                    if (a.obj_id, a.timestamp, b.obj_id, b.timestamp) not in got_pairs:
                        missing += 1
        assert missing == 0, f"{missing} true co-windowed pairs missing"


class TestApproximateMode:
    def test_point_polygon_approximate_uses_bbox(self):
        r = 0.2
        op = OP.PointPolygonRangeQuery(conf(approximate=True), GRID)
        stream = point_stream(300)
        results = list(op.run(iter(stream), Q_POLY, r))
        got = set()
        for res in results:
            got |= {(o.obj_id, o.timestamp) for o in res.records}
        bb = Q_POLY.bbox
        for obj in stream:
            d_bbox = O.point_bbox_dist(obj.x, obj.y, *bb)
            key = (obj.obj_id, obj.timestamp)
            if d_bbox <= r - 1e-3 and obj.cell >= 0:
                nb = GRID.neighboring_cells_mask(r, Q_POLY.cells)
                if nb[obj.cell]:
                    assert key in got
