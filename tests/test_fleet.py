"""Supervised multi-worker fleet suite (runtime/fleet.py +
runtime/fleetsup.py, driver --fleet).

Headline invariant: an N-worker fleet over a leaf-partitioned file replay
— including one forcibly SIGKILLed worker restarted from its checkpoint —
produces a merged global window table BYTE-IDENTICAL to a fault-free
single-worker run, with zero post-warmup recompiles across every
incarnation. Plus: the leaf packing / rebalance policy, the tailing
partition source, outbox dedup + fingerprint cross-check, the per-family
global merge seam, the fleet manifest's durability, worker argv
construction, the /fleet endpoint, and doctor fleet.

Fast deterministic cases run in tier-1; the randomized kill-point fuzz is
additionally marked ``slow``.
"""

import json
import os
import random
import subprocess
import sys
import threading
import time

import pytest
import yaml

from spatialflink_tpu.driver import main
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.operators.base import merge_window_records
from spatialflink_tpu.runtime import fleet as F
from spatialflink_tpu.runtime.fleetsup import (_strip_flags, active_fleet,
                                               worker_argv)
from spatialflink_tpu.runtime.repartition import (balance_leaves,
                                                  pick_rebalance)
from spatialflink_tpu.streams import SyntheticPointSource, serialize_spatial
from spatialflink_tpu.utils import metrics as _metrics

pytestmark = pytest.mark.fleet

CONF = "conf/spatialflink-conf.yml"


@pytest.fixture(autouse=True)
def _clear_shutdown_flag():
    _metrics.clear_shutdown()
    yield
    _metrics.clear_shutdown()


def _grid():
    return UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)


def _lines(n_traj=6, steps=40, seed=3):
    pts = list(SyntheticPointSource(_grid(), num_trajectories=n_traj,
                                    steps=steps, seed=seed))
    return [serialize_spatial(p, "GeoJSON") for p in pts]


def _write_input(tmp_path, lines, name="in1.geojson"):
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def _fleet_argv(cfg, path1, fleet_dir, n, *extra, option="1"):
    return (["--config", cfg, "--option", option, "--input1", path1,
             "--fleet", str(n), "--fleet-dir", str(fleet_dir),
             "--fleet-heartbeat", "0.25",
             "--fleet-epoch-records", "100"] + list(extra))


def _result(fleet_dir):
    doc = F.read_json(os.path.join(str(fleet_dir), F.RESULT_FILE))
    assert doc is not None, "fleet run left no fleet_result.json"
    return doc


def _merged_table(fleet_dir):
    out = []
    with open(os.path.join(str(fleet_dir), F.MERGED_FILE)) as f:
        for line in f:
            if line.strip():
                out.append(json.loads(line))
    return out


# ---------------------------------------------------------------- policy


def test_balance_leaves_lpt_packing():
    occ = {1: 100, 2: 90, 3: 10, 4: 10, 5: 10}
    a = balance_leaves(occ, 2)
    # the two hot leaves must land on different workers (greedy LPT)
    assert a[1] != a[2]
    loads = {0: 0, 1: 0}
    for leaf, w in a.items():
        loads[w] += occ[leaf]
    assert abs(loads[0] - loads[1]) <= 30


def test_balance_leaves_single_worker_and_empty():
    assert balance_leaves({}, 3) == {}
    a = balance_leaves({7: 5, 9: 1}, 1)
    assert set(a.values()) == {0}


def test_pick_rebalance_hysteresis():
    # <25% spread: leave the fleet alone
    assert pick_rebalance({0: 100.0, 1: 80.0}) is None
    assert pick_rebalance({0: 0.0, 1: 0.0}) is None
    assert pick_rebalance({0: 5.0}) is None
    donor, receiver = pick_rebalance({0: 100.0, 1: 10.0, 2: 50.0})
    assert (donor, receiver) == (0, 1)


# ------------------------------------------------------- tailing source


def test_tailing_source_follows_until_done_marker(tmp_path):
    part = str(tmp_path / "p.ndjson")
    done = str(tmp_path / "p.done")
    src = F.TailingReplaySource(part, done, poll_s=0.01)
    got = []

    def consume():
        got.extend(src)

    t = threading.Thread(target=consume)
    t.start()
    with open(part, "w") as f:
        f.write("a\nb\n")
        f.flush()
        time.sleep(0.1)
        f.write("c")  # torn line: must be held back
        f.flush()
        time.sleep(0.1)
        assert got == ["a", "b"]
        f.write("\nd\n")
        f.flush()
    F.atomic_write_json(done, {"routed_total": 4})
    t.join(timeout=5)
    assert not t.is_alive()
    assert got == ["a", "b", "c", "d"]


def test_tailing_source_skip_limit_and_empty_partition(tmp_path):
    part = str(tmp_path / "p.ndjson")
    done = str(tmp_path / "p.done")
    open(part, "w").write("a\nb\nc\nd\n")
    open(done, "w").write("{}")
    assert list(F.TailingReplaySource(part, done, skip=1, limit=2)) == \
        ["b", "c"]
    # done marker with no partition file at all: clean empty stream
    os.unlink(part)
    assert list(F.TailingReplaySource(part, done)) == []


def test_tailing_source_graceful_shutdown_while_idle(tmp_path):
    part = str(tmp_path / "p.ndjson")
    done = str(tmp_path / "p.done")
    open(part, "w").write("a\n")
    src = F.TailingReplaySource(part, done, poll_s=0.01)
    it = iter(src)
    assert next(it) == "a"
    _metrics.request_shutdown()
    with pytest.raises(_metrics.GracefulShutdown):
        next(it)  # idle-tailing: the stop must not hang the worker


def test_tailing_source_stall_timeout(tmp_path):
    part = str(tmp_path / "p.ndjson")
    open(part, "w").write("a\n")
    src = F.TailingReplaySource(part, str(tmp_path / "p.done"),
                                poll_s=0.01, stall_timeout_s=0.05,
                                stall_deadline_s=0.2)
    with pytest.raises(RuntimeError, match="deadline"):
        list(src)
    # the bounded retry warned (partition-stall) before giving up
    assert src.stall_events >= 1


def test_tailing_source_stall_retry_survives_to_done(tmp_path):
    """A stall longer than the warn timeout but shorter than the deadline
    is a bounded retry (counted partition-stall events), not a crash —
    the pause a quarantine drain or rescale barrier produces."""
    part = str(tmp_path / "p.ndjson")
    done = str(tmp_path / "p.done")
    open(part, "w").write("a\n")
    src = F.TailingReplaySource(part, done, poll_s=0.01,
                                stall_timeout_s=0.05,
                                stall_deadline_s=30.0)
    got = []
    t = threading.Thread(target=lambda: got.extend(src))
    t.start()
    time.sleep(0.3)  # well past the warn timeout, far from the deadline
    assert t.is_alive(), "bounded retry gave up before the deadline"
    assert src.stall_events >= 1
    with open(part, "a") as f:
        f.write("b\n")
    F.atomic_write_json(done, {"routed_total": 2})
    t.join(timeout=5)
    assert not t.is_alive()
    assert got == ["a", "b"]


# -------------------------------------------------- outbox + global merge


def _doc(key, records, fp="x", cell=None):
    return {"key": key, "window": [0, 5], "cell": cell, "records": records,
            "count": len(records), "fp": fp}


def test_read_outbox_dedups_crash_replay_duplicates(tmp_path):
    p = str(tmp_path / "outbox.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps(_doc("0:5:None", ["r1"], fp="aa")) + "\n")
        f.write(json.dumps(_doc("0:5:None", ["r1"], fp="aa")) + "\n")
        f.write(json.dumps(_doc("5:10:None", ["r2"], fp="bb")) + "\n")
        f.write('{"torn')  # kill mid-write: ignored, replayed later
    out = F.read_outbox(p)
    assert sorted(out) == ["0:5:None", "5:10:None"]


def test_read_outbox_raises_on_divergent_duplicate(tmp_path):
    p = str(tmp_path / "outbox.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps(_doc("0:5:None", ["r1"], fp="aa")) + "\n")
        f.write(json.dumps(_doc("0:5:None", ["r2"], fp="cc")) + "\n")
    with pytest.raises(F.FleetMergeError, match="exactly-once"):
        F.read_outbox(p)


def test_merge_outboxes_union_family_is_assignment_independent():
    w0 = {"0:5:None": _doc("0:5:None", ["b", "a"])}
    w1 = {"0:5:None": _doc("0:5:None", ["c"]),
          "5:10:None": _doc("5:10:None", ["d"])}
    merged = F.merge_outboxes({0: w0, 1: w1}, "range")
    assert [m["key"] for m in merged] == ["0:5:None", "5:10:None"]
    assert merged[0]["records"] == ["a", "b", "c"]  # sorted union
    # flipping which worker held what must not change the table digest
    flipped = F.merge_outboxes({0: w1, 1: w0}, "range")
    assert F.merged_table_digest(merged) == F.merged_table_digest(flipped)


def test_merge_outboxes_knn_re_topk():
    w0 = {"0:5:None": _doc("0:5:None", [["a", 1.0], ["b", 2.0]])}
    w1 = {"0:5:None": _doc("0:5:None", [["c", 0.5], ["a", 1.0]])}
    merged = F.merge_outboxes({0: w0, 1: w1}, "knn", k=2)
    assert merged[0]["records"] == [["c", 0.5], ["a", 1.0]]


def test_merge_window_records_seam():
    assert merge_window_records("range", [["a"], ["b"]]) == ["a", "b"]
    top = merge_window_records("knn", [[("a", 2.0)], [("b", 1.0)]], k=1)
    assert top == [("b", 1.0)]
    with pytest.raises(ValueError, match="kNN merge needs k"):
        merge_window_records("knn", [[("a", 1.0)]])


# ------------------------------------------------------- fleet manifest


def test_fleet_manifest_roundtrip(tmp_path):
    p = str(tmp_path / "fleet.json")
    m = F.FleetManifest(p)
    m.assign_all({1: 0, 2: 1})
    m.assign(3, 0)
    assert m.advance_epoch() == 1
    assert m.note_restart(1) == 1
    assert m.note_restart(1) == 2
    m.save()
    m2 = F.FleetManifest(p)  # a crashed supervisor reloads everything
    assert m2.fleet_assignment == {1: 0, 2: 1, 3: 0}
    assert m2.fleet_epoch == 1
    assert m2.fleet_restarts == {1: 2}


# ------------------------------------------------------- fencing epochs


def _fdoc(key, records, fp, fence=0):
    d = _doc(key, records, fp=fp)
    if fence:
        d["fence"] = fence
    return d


def test_heartbeat_fence_stamping_and_age(tmp_path):
    hb = str(tmp_path / "heartbeat")
    w = F.HeartbeatWriter(hb, interval_s=0.05, fence=2)
    w.start()
    try:
        time.sleep(0.15)
        beat = json.load(open(hb))
        assert beat["fence"] == 2 and beat["pid"] == os.getpid()
        age = F.heartbeat_age_s(hb, fence=2)
        assert age is not None and age < 5.0
        # a successor expecting fence 3 must not read this beat as
        # liveness — it is the zombie predecessor's write
        assert F.heartbeat_age_s(hb, fence=3) is None
    finally:
        w.close()


def test_heartbeat_gate_suppresses_beats(tmp_path):
    hb = str(tmp_path / "heartbeat")
    w = F.HeartbeatWriter(hb, interval_s=0.02, fence=1,
                          gate=lambda: True)
    w.start()
    try:
        time.sleep(0.1)
        assert not os.path.exists(hb)  # wedged: silence, not beats
    finally:
        w.close()


def test_heartbeat_age_legacy_mtime_fallback(tmp_path):
    hb = tmp_path / "heartbeat"
    hb.write_text("")  # pre-fence format: an empty touch file
    age = F.heartbeat_age_s(str(hb), fence=1)
    assert age is not None and age < 5.0


def test_read_outbox_drops_zombie_rows_past_fence_cutoff(tmp_path):
    p = str(tmp_path / "outbox.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps(_fdoc("0:5:None", ["r1"], "aa")) + "\n")
        cutoff = f.tell()
        # the zombie (still fence 0) keeps writing past its cutoff...
        f.write(json.dumps(_fdoc("5:10:None", ["zz"], "zz")) + "\n")
        # ...while the fenced successor re-emits the window correctly
        f.write(json.dumps(_fdoc("5:10:None", ["r2"], "bb", fence=1))
                + "\n")
    stats = {}
    out = F.read_outbox(p, fence_cutoffs={0: cutoff}, stats=stats)
    assert sorted(out) == ["0:5:None", "5:10:None"]
    assert out["0:5:None"]["records"] == ["r1"]  # pre-cutoff row survives
    assert out["5:10:None"]["records"] == ["r2"]
    assert stats == {"stale_fence_rows": 1, "fence_conflicts": 0}
    # stats accumulate across calls (one dict over a whole fleet)
    F.read_outbox(p, fence_cutoffs={0: cutoff}, stats=stats)
    assert stats["stale_fence_rows"] == 2


def test_read_outbox_cross_fence_conflict_keeps_newest_fence(tmp_path):
    p = str(tmp_path / "outbox.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps(_fdoc("0:5:None", ["old"], "aa")) + "\n")
        f.write(json.dumps(_fdoc("0:5:None", ["new"], "bb", fence=1))
                + "\n")
    stats = {}
    out = F.read_outbox(p, stats=stats)
    # cross-fence divergence: the superseded writer is the less trusted
    # side — keep the newest fence, count a conflict, never abort
    assert out["0:5:None"]["records"] == ["new"]
    assert stats["fence_conflicts"] == 1
    with open(p, "a") as f:
        f.write(json.dumps(_fdoc("0:5:None", ["x"], "cc", fence=1))
                + "\n")
    # SAME-fence divergence stays the hard exactly-once error
    with pytest.raises(F.FleetMergeError, match="exactly-once"):
        F.read_outbox(p)


def test_fleet_manifest_fence_rescale_quarantine_roundtrip(tmp_path):
    p = str(tmp_path / "fleet.json")
    m = F.FleetManifest(p)
    assert m.fence_of(0) == 0
    assert m.bump_fence(0, outbox_bytes=100, journal_bytes=40,
                        reason="stall") == 1
    assert m.bump_fence(0, outbox_bytes=250, journal_bytes=90,
                        reason="crash") == 2
    m.note_rescale(n_from=2, n_to=3, at_records=150, epoch=2)
    m.note_quarantine(1, "quarantine", score=3.5)
    m.save()
    m2 = F.FleetManifest(p)  # durable across a supervisor crash
    assert m2.fence_of(0) == 2 and m2.fence_of(1) == 0
    assert m2.fence_cutoffs(0) == {0: {"outbox": 100, "journal": 40},
                                   1: {"outbox": 250, "journal": 90}}
    assert m2.fence_cutoffs(1) == {}
    assert m2.fleet_rescale_log[0]["n_to"] == 3
    assert m2.fleet_quarantine_log[0]["action"] == "quarantine"
    # the raw-state projection doctor uses agrees with the method
    assert F.fence_cutoffs_from(F.read_json(p), 0) == m2.fence_cutoffs(0)


def test_emitted_journal_fence_stamping_and_cutoffs(tmp_path):
    from spatialflink_tpu.operators import WindowResult
    from spatialflink_tpu.runtime.checkpoint import EmittedWindowJournal

    d = str(tmp_path)
    r1 = WindowResult(0, 5, ["a"], extras={"cell": 1})
    r2 = WindowResult(5, 10, ["b"], extras={"cell": 1})
    j0 = EmittedWindowJournal(d, fresh=True)  # fence-0 incarnation
    j0.record(r1)
    cutoff = os.path.getsize(j0.path)
    j0.record(r2)  # the zombie journals past its cutoff
    j0.close()
    # fence-0 lines stay bare keys: single-process byte-compat
    lines = open(j0.path).read().splitlines()
    assert lines == ["0:5:1", "5:10:1"]
    j1 = EmittedWindowJournal(d, fence=1, fence_cutoffs={0: cutoff})
    # r1 journaled pre-cutoff: suppressed; r2 post-cutoff: must re-emit
    assert j1.seen(r1) is True
    assert j1.seen(r2) is False
    j1.record(r2)
    j1.close()
    assert open(j1.path).read().splitlines()[-1] == "1\t5:10:1"
    # a third incarnation composes both fences' cutoffs
    j2 = EmittedWindowJournal(d, fence=2,
                              fence_cutoffs={0: cutoff,
                                             1: os.path.getsize(j1.path)})
    assert j2.seen(r1) is True and j2.seen(r2) is True
    j2.close()


def test_stall_fault_arms_wedges_and_expires():
    from spatialflink_tpu.runtime import faults

    f = faults.StallFault(0.2, emit_delay_s=0.0)
    assert not f.wedged()  # unarmed until the first emitted window
    f.on_window()
    assert f.wedged()
    time.sleep(0.25)
    assert not f.wedged()  # the gray failure heals after duration_s
    prev = faults.active_stall()
    try:
        assert faults.install_stall(f) is f
        assert faults.active_stall() is f
    finally:
        faults.install_stall(prev)


def test_stall_fault_gates_checkpoint_due(tmp_path):
    from spatialflink_tpu.runtime import faults
    from spatialflink_tpu.runtime.checkpoint import CheckpointCoordinator

    coord = CheckpointCoordinator(str(tmp_path / "ckpt"),
                                  every_batches=1)
    coord.note_batch()
    assert coord.due() is True
    f = faults.StallFault(30.0)
    f.on_window()  # armed + wedged
    prev = faults.active_stall()
    try:
        faults.install_stall(f)
        # a wedged zombie must not commit manifests its fenced
        # successor would resume from
        assert coord.due() is False
    finally:
        faults.install_stall(prev)
    assert coord.due() is True


def test_parse_rescale_and_stall_chaos():
    from spatialflink_tpu.runtime.fleetsup import (_parse_rescale,
                                                   _parse_stall_chaos)

    assert _parse_rescale(None) == []
    assert _parse_rescale("300:2,150:3") == [(150, 3), (300, 2)]
    assert _parse_rescale("100:") == [(100, 1)]
    assert _parse_stall_chaos(None) is None
    assert _parse_stall_chaos("1:2.5") == (1, 2.5)
    assert _parse_stall_chaos("0:") == (0, 30.0)


def _bare_supervisor(tmp_path, **over):
    """A FleetSupervisor shell with just the state the quarantine
    machinery touches — the unit-test seam for the suspicion state
    machine (no processes, no routing)."""
    from spatialflink_tpu.runtime.fleetsup import FleetSupervisor

    sup = FleetSupervisor.__new__(FleetSupervisor)
    sup._lock = threading.RLock()
    sup.root = str(tmp_path)
    sup.heartbeat_s = 0.05
    sup.quarantine_s = over.get("quarantine_s", 10.0)
    sup.monitor = None
    sup.manifest = F.FleetManifest(str(tmp_path / F.MANIFEST_FILE))
    sup._active = over.get("active", [0, 1])
    sup._procs = {w: object() for w in sup._active}
    sup._quarantined = dict(over.get("quarantined", {}))
    sup._suspicion = {}
    sup._stall_chaos = None
    return sup


def _write_stale_heartbeat(tmp_path, wid, age_s):
    wd = F.worker_dir(str(tmp_path), wid)
    os.makedirs(wd, exist_ok=True)
    hb = os.path.join(wd, F.HEARTBEAT_FILE)
    open(hb, "w").write("")
    old = time.time() - age_s
    os.utime(hb, (old, old))
    return hb


def test_suspicion_quarantine_enter_and_hysteresis_exit(tmp_path):
    sup = _bare_supervisor(tmp_path)
    _write_stale_heartbeat(tmp_path, 0, age_s=60.0)  # slow, not dead
    _write_stale_heartbeat(tmp_path, 1, age_s=0.0)   # healthy
    for _ in range(3):
        sup._suspicion_tick()
    assert 0 in sup._quarantined, "stale heartbeat never quarantined"
    assert 1 not in sup._quarantined
    assert any(e["action"] == "quarantine" and e["worker"] == 0
               for e in sup.manifest.fleet_quarantine_log)
    # recovery: fresh beats decay the score; hysteresis exits at <= 1.0
    _write_stale_heartbeat(tmp_path, 0, age_s=0.0)
    for _ in range(12):
        sup._suspicion_tick()
    assert 0 not in sup._quarantined, "quarantine never lifted"
    assert any(e["action"] == "unquarantine"
               for e in sup.manifest.fleet_quarantine_log)


def test_suspicion_never_quarantines_last_routable_worker(tmp_path):
    sup = _bare_supervisor(tmp_path, active=[0, 1],
                           quarantined={1: time.monotonic()})
    # BOTH workers look sick — but with 1 already quarantined, 0 is the
    # last routable worker and must never be drained
    _write_stale_heartbeat(tmp_path, 0, age_s=60.0)
    _write_stale_heartbeat(tmp_path, 1, age_s=60.0)
    for _ in range(6):
        sup._suspicion_tick()
    assert 1 in sup._quarantined  # still sick, still quarantined
    assert 0 not in sup._quarantined, \
        "quarantined the only remaining routable worker"


def test_quarantine_tick_deadline(tmp_path):
    sup = _bare_supervisor(tmp_path, quarantine_s=0.05,
                           quarantined={0: time.monotonic()})
    assert sup._quarantine_tick() == []
    time.sleep(0.1)
    assert sup._quarantine_tick() == [0]  # deadline breach: escalate


# --------------------------------------------------------- worker argv


def test_worker_argv_strips_and_reissues():
    base = ["--config", "c.yml", "--option", "1",
            "--input1", "/orig/in.geojson", "--fleet", "4",
            "--fleet-dir", "/orig/fleet", "--limit", "100",
            "--checkpoint-dir", "/orig/ckpt", "--resume",
            "--strict-recompile", "--panes"]
    argv = worker_argv(base, fleet_dir="/f", worker_id=2,
                       heartbeat_s=0.5, resume=True)
    # fleet/placement flags replaced, pipeline flags inherited
    assert "--strict-recompile" in argv and "--panes" in argv
    assert "/orig/in.geojson" not in argv and "/orig/ckpt" not in argv
    assert "--limit" not in argv  # the supervisor already applied it
    assert argv[argv.index("--fleet-worker-id") + 1] == "2"
    assert argv[argv.index("--input1") + 1].endswith(
        os.path.join("worker2", F.PARTITION_FILE))
    assert argv.count("--resume") == 1
    # the fence token is always reissued (0 for a never-fenced slot)
    assert argv[argv.index("--fleet-fence") + 1] == "0"
    no_resume = worker_argv(base, fleet_dir="/f", worker_id=0,
                            heartbeat_s=0.5, resume=False)
    assert "--resume" not in no_resume
    fenced = worker_argv(base, fleet_dir="/f", worker_id=0,
                         heartbeat_s=0.5, resume=True, fence=3,
                         stall_s=2.5)
    assert fenced[fenced.index("--fleet-fence") + 1] == "3"
    assert fenced[fenced.index("--fleet-stall-s") + 1] == "2.5"
    assert "--fleet-stall-s" not in no_resume  # chaos glue is opt-in


def test_strip_flags_handles_equals_form():
    out = _strip_flags(["--fleet=2", "--option", "1", "--limit=5"],
                       {"--fleet": 1, "--limit": 1})
    assert out == ["--option", "1"]


# ------------------------------------------------------ canonical window


def test_canonical_window_doc_matches_journal_key():
    from spatialflink_tpu.operators import WindowResult

    r = WindowResult(0, 5000, ["x"], extras={"cell": 7})
    doc = F.canonical_window_doc(r, "range")
    assert doc["key"] == "0:5000:7"
    assert doc["window"] == [0, 5000]
    # identical content => identical fingerprint (the dedup cross-check)
    assert doc["fp"] == F.canonical_window_doc(r, "range")["fp"]


# ----------------------------------------------------- /fleet endpoint


def test_fleet_endpoint_without_supervisor_notes_absence():
    from spatialflink_tpu.runtime.opserver import OpServer

    assert active_fleet() is None
    srv = OpServer(port=0).start()
    try:
        import urllib.request

        with urllib.request.urlopen(f"{srv.url}/fleet", timeout=5) as r:
            doc = json.loads(r.read().decode())
        assert doc["fleet"] is False and "--fleet" in doc["note"]
    finally:
        srv.close()


def test_fleet_snapshot_schema():
    from spatialflink_tpu.utils.telemetry import fleet_snapshot

    snap = fleet_snapshot([{"worker": 0, "alive": True, "restarts": 2},
                           {"worker": 1, "alive": False, "restarts": 0}],
                          epoch=3, routed=100)
    assert snap["schema"] == "fleet-v1"
    assert snap["n_workers"] == 2 and snap["alive"] == 1
    assert snap["restarts_total"] == 2 and snap["epoch"] == 3


# --------------------------------------------------- integration smoke


def _conf_file(tmp_path):
    with open(CONF) as f:
        d = yaml.safe_load(f)
    p = tmp_path / "conf.yml"
    p.write_text(yaml.safe_dump(d))
    return str(p)


def test_fleet_kill_recovery_identity_vs_single_worker(tmp_path):
    """THE acceptance test: N=2 workers over a file replay, worker 0
    SIGKILLed mid-run by the chaos hook, restarted from its checkpoint by
    the supervisor — and the merged window table (and its digest) is
    byte-identical to a fault-free single-worker fleet run, with zero
    post-warmup recompiles across every incarnation."""
    cfg = _conf_file(tmp_path)
    path1 = _write_input(tmp_path, _lines())

    oracle_dir = tmp_path / "fleet1"
    assert main(_fleet_argv(cfg, path1, oracle_dir, 1)) == 0
    oracle = _result(oracle_dir)
    assert oracle["merged_windows"] > 0
    assert oracle["post_warmup_compiles"] == 0

    kill_dir = tmp_path / "fleet2k"
    assert main(_fleet_argv(cfg, path1, kill_dir, 2,
                            "--fleet-chaos-kill", "0:1")) == 0
    killed = _result(kill_dir)
    assert sum(int(v) for v in killed["restarts"].values()) >= 1, \
        "chaos kill never fired — the restart path went untested"
    assert killed["digest"] == oracle["digest"], \
        "merged fleet output diverged from the single-worker oracle"
    assert killed["post_warmup_compiles"] == 0, \
        "a worker respawn silently recompiled"
    # the tables themselves, not just the digest
    o_table = _merged_table(oracle_dir)
    k_table = _merged_table(kill_dir)
    assert [(m["key"], m["records"]) for m in k_table] == \
        [(m["key"], m["records"]) for m in o_table]
    # supervision left an audit trail
    log = killed["restart_log"]
    assert any("chaos kill" in (r.get("reason") or "") for r in log)
    # doctor fleet reads the same directory
    from spatialflink_tpu import doctor

    rc = doctor.main(["--json", "fleet", str(kill_dir)])
    assert rc == 0


def test_fleet_rescale_zombie_identity(tmp_path):
    """The elastic-fleet acceptance test: a live N=2→3→2 rescale with
    worker 0's first incarnation wedged into a writing zombie (stall
    chaos), fenced+respawned WITHOUT a kill — and the merged window
    table is byte-identical to a fault-free fixed-N oracle, with the
    zombie's stale-fence rows counted and dropped (never a merge error)
    and zero post-warmup recompiles on every incarnation."""
    cfg = _conf_file(tmp_path)
    path1 = _write_input(tmp_path, _lines(n_traj=8, steps=80))

    oracle_dir = tmp_path / "fleet1"
    assert main(_fleet_argv(cfg, path1, oracle_dir, 1)) == 0
    oracle = _result(oracle_dir)
    assert oracle["merged_windows"] > 0

    rdir = tmp_path / "rescale"
    assert main(_fleet_argv(cfg, path1, rdir, 2,
                            "--fleet-rescale", "150:3,300:2",
                            "--fleet-chaos-stall", "0:60",
                            "--fleet-quarantine-s", "1")) == 0
    got = _result(rdir)
    assert got["digest"] == oracle["digest"], \
        "rescale + zombie changed the merged output"
    o_table = _merged_table(oracle_dir)
    r_table = _merged_table(rdir)
    assert [(m["key"], m["records"]) for m in r_table] == \
        [(m["key"], m["records"]) for m in o_table]
    # both rescale points were consumed at epoch boundaries
    assert [(r["n_from"], r["n_to"]) for r in got["rescales"]] == \
        [(2, 3), (3, 2)]
    assert got["retired_workers"] == [2]
    assert got["workers_final"] == 2
    # the zombie was fenced (never merged) and kept writing past its
    # cutoff — containment proven by the dropped-row count
    assert int(got["fences"]["0"]) >= 1, "stall target was never fenced"
    assert got["stale_fence_rows"] >= 1, \
        "zombie wrote no stale rows — containment went unexercised"
    assert got["post_warmup_compiles"] == 0, \
        "a respawn or rescale silently recompiled"
    # doctor fleet renders the fence/rescale/quarantine history
    import io

    from spatialflink_tpu import doctor

    buf = io.StringIO()
    assert doctor.fleet(str(rdir), as_json=True, out=buf) == 0
    doc = json.loads(buf.getvalue())
    assert doc["stale_fence_rows"] >= 1
    assert len(doc["rescale_log"]) == 2
    assert any(e["worker"] == 0 for e in doc["fence_log"])
    buf = io.StringIO()
    assert doctor.fleet(str(rdir), as_json=False, out=buf) == 0
    text = buf.getvalue()
    assert "rescale    2 -> 3" in text and "fence      w0" in text


@pytest.mark.slow
def test_fleet_randomized_kill_fuzz(tmp_path):
    """Randomized kill points: whichever window count the kill lands on,
    the merged table must match the single-worker oracle. Half the
    trials additionally run a randomized live rescale plus a zombie
    writer (stall chaos on the OTHER worker) — the composed failure
    modes must still merge to the oracle."""
    cfg = _conf_file(tmp_path)
    path1 = _write_input(tmp_path, _lines(n_traj=8, steps=60))

    oracle_dir = tmp_path / "oracle"
    assert main(_fleet_argv(cfg, path1, oracle_dir, 1)) == 0
    oracle = _result(oracle_dir)

    rng = random.Random(11)
    for trial in range(4):
        wid = rng.randrange(2)
        nth = rng.randint(1, 6)
        extra = ["--fleet-chaos-kill", f"{wid}:{nth}"]
        if trial % 2:
            at1 = rng.randrange(100, 250)
            at2 = at1 + rng.randrange(100, 200)
            extra += ["--fleet-rescale", f"{at1}:3,{at2}:2",
                      "--fleet-chaos-stall", f"{1 - wid}:60",
                      "--fleet-quarantine-s", "1"]
        fdir = tmp_path / f"fuzz{trial}"
        assert main(_fleet_argv(cfg, path1, fdir, 2, *extra)) == 0
        got = _result(fdir)
        assert got["digest"] == oracle["digest"], \
            f"trial {trial}: {extra} changed the merged output"
        assert got["post_warmup_compiles"] == 0


@pytest.mark.slow
def test_fleet_supervisor_sigterm_drains_workers(tmp_path):
    """SIGTERM to the supervisor: routing stops, workers drain (final
    checkpoint each), the partial merge is written, exit 0."""
    cfg = _conf_file(tmp_path)
    path1 = _write_input(tmp_path, _lines(n_traj=10, steps=200))
    fdir = tmp_path / "drain"
    proc = subprocess.Popen(
        [sys.executable, "-m", "spatialflink_tpu.driver"]
        + _fleet_argv(cfg, path1, fdir, 2),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 60
        started = False
        while time.monotonic() < deadline:
            if any(os.path.exists(os.path.join(F.worker_dir(str(fdir), w),
                                               F.OUTBOX_FILE))
                   for w in (0, 1)):
                started = True
                break
            time.sleep(0.2)
        assert started, "fleet never started emitting"
        proc.terminate()
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out.decode()[-2000:]
    result = _result(fdir)
    assert result["graceful"] is True
