"""UniformGrid parity tests against a direct reading of UniformGrid.java,
plus the adaptive two-level grid's refined-cell-space correctness: the
split/coarse leaf masks proven against a brute-force distance oracle, the
vectorized two-stage assignment, and ``cell_key`` wire parity on split
cells."""

import math

import numpy as np
import pytest

from spatialflink_tpu.index import AdaptiveGrid, UniformGrid
from spatialflink_tpu.index.uniform_grid import cells_within_layers

# Canonical Beijing / T-Drive config (conf/geoflink-conf.yml:20-21)
BBOX = dict(min_x=115.50, max_x=117.60, min_y=39.60, max_y=41.10)


def make_grid(n=100):
    return UniformGrid(BBOX["min_x"], BBOX["max_x"], BBOX["min_y"], BBOX["max_y"],
                       num_grid_partitions=n)


class TestConstruction:
    def test_cell_count_ctor(self):
        g = make_grid(100)
        assert g.n == 100
        assert g.cell_length == pytest.approx((117.60 - 115.50) / 100)

    def test_cell_length_ctor_squares_bbox(self):
        # UniformGrid.java:47-72 + adjustCoordinatesForSquareGrid :114-134
        g = UniformGrid(0.0, 10.0, 0.0, 4.0, cell_length=1.0)
        # x span 10 > y span 4 -> y expanded symmetrically to 10
        assert (g.min_y, g.max_y) == (-3.0, 7.0)
        assert g.n == 10
        assert g.cell_length == pytest.approx(1.0)

    def test_cell_length_ctor_non_integer(self):
        g = UniformGrid(0.0, 10.0, 0.0, 10.0, cell_length=3.0)
        assert g.n == math.ceil(10 / 3)  # 4
        assert g.cell_length == pytest.approx(10 / 4)


class TestCellAssignment:
    def test_floor_division(self):
        g = make_grid(100)
        cell, valid = g.assign_cell(115.50, 39.60)
        assert valid and cell == 0
        # interior point
        cell, _ = g.assign_cell(116.55, 40.35)
        cx = math.floor((116.55 - g.min_x) / g.cell_length)
        cy = math.floor((40.35 - g.min_y) / g.cell_length)
        assert cell == cx * 100 + cy

    def test_out_of_bbox_invalid(self):
        g = make_grid(100)
        cell, valid = g.assign_cell(110.0, 39.9)
        assert not valid and cell == -1
        cell, valid = g.assign_cell(117.61, 39.9)
        assert not valid

    def test_vectorized_matches_scalar(self):
        g = make_grid(100)
        rng = np.random.default_rng(0)
        xs = rng.uniform(115.0, 118.0, 500)
        ys = rng.uniform(39.0, 41.5, 500)
        cells, valid = g.assign_cell(xs, ys)
        for i in range(0, 500, 37):
            c, v = g.assign_cell(xs[i], ys[i])
            assert cells[i] == c and valid[i] == v

    def test_cell_key_roundtrip(self):
        g = make_grid(100)
        key = g.cell_key(g.cell_id(7, 42))
        assert key == "0000700042"  # 5-digit zero padding, UniformGrid.java:92
        assert g.cell_from_key(key) == g.cell_id(7, 42)

    def test_cell_bounds(self):
        g = make_grid(100)
        x1, y1, x2, y2 = g.cell_bounds(g.cell_id(3, 5))
        assert x1 == pytest.approx(g.min_x + 3 * g.cell_length)
        assert y2 == pytest.approx(g.min_y + 6 * g.cell_length)


class TestLayerMath:
    def test_guaranteed_layers_formula(self):
        g = make_grid(100)
        diag = g.cell_length * math.sqrt(2)
        for r in (0.005, 0.01, 0.05, 0.1, 0.5, 1.0):
            assert g.guaranteed_layers(r) == int(math.floor(r / diag - 1))

    def test_candidate_layers_formula(self):
        g = make_grid(100)
        for r in (0.005, 0.01, 0.05, 0.1, 0.5):
            assert g.candidate_layers(r) == int(math.ceil(r / g.cell_length))

    def test_small_radius_no_guaranteed(self):
        g = make_grid(100)
        # r much smaller than a cell diagonal => guaranteed layers == -1
        assert g.guaranteed_layers(0.005) == -1
        mask = g.guaranteed_cells_mask(0.005, g.cell_id(50, 50))
        assert not mask.any()

    def test_gn_zero_layers_only_query_cell(self):
        g = make_grid(100)
        diag = g.cell_length * math.sqrt(2)
        r = 1.5 * diag  # floor(1.5 - 1) = 0 layers
        assert g.guaranteed_layers(r) == 0
        mask = g.guaranteed_cells_mask(r, g.cell_id(50, 50))
        assert mask.sum() == 1 and mask[g.cell_id(50, 50)]


class TestNeighborMasks:
    def test_gn_cn_mutually_exclusive(self):
        g = make_grid(100)
        c = g.cell_id(50, 50)
        for r in (0.05, 0.1, 0.3, 0.5):
            gn = g.guaranteed_cells_mask(r, c)
            cn = g.candidate_cells_mask(r, c, gn)
            assert not (gn & cn).any()
            # union == all cells within candidate layers
            assert ((gn | cn) == g.neighboring_cells_mask(r, c)).all()

    def test_candidate_count_exact(self):
        g = make_grid(100)
        c = g.cell_id(50, 50)
        r = 0.5
        L = g.candidate_layers(r)
        nb = g.neighboring_cells_mask(r, c)
        assert nb.sum() == (2 * L + 1) ** 2  # interior cell, no clipping

    def test_border_clipping(self):
        g = make_grid(100)
        c = g.cell_id(0, 0)
        r = 0.5
        L = g.candidate_layers(r)
        nb = g.neighboring_cells_mask(r, c)
        assert nb.sum() == (L + 1) ** 2  # corner cell keeps one quadrant

    def test_radius_zero_all_cells(self):
        g = make_grid(100)
        nb = g.neighboring_cells_mask(0.0, g.cell_id(10, 10))
        assert nb.all()  # UniformGrid.java:264-266

    def test_polygon_union_semantics(self):
        g = make_grid(100)
        seeds = [g.cell_id(10, 10), g.cell_id(12, 10)]
        gn = g.guaranteed_cells_mask(0.2, seeds)
        per_seed = [g.guaranteed_cells_mask(0.2, s) for s in seeds]
        assert (gn == (per_seed[0] | per_seed[1])).all()

    def test_layer_rings(self):
        g = make_grid(100)
        c = g.cell_id(50, 50)
        ring0 = g.neighboring_layer_cells_mask(c, 0)
        ring2 = g.neighboring_layer_cells_mask(c, 2)
        assert ring0.sum() == 1
        assert ring2.sum() == 5 * 5 - 3 * 3
        layers = g.all_neighboring_layers(c)
        assert layers[0].sum() == 1 and len(layers) >= 50

    def test_cell_layer_wrt(self):
        g = make_grid(100)
        q = g.cell_id(50, 50)
        assert g.cell_layer_wrt(q, q) == 0
        assert g.cell_layer_wrt(q, g.cell_id(53, 48)) == 3


class TestDevicePredicate:
    def test_cells_within_layers_matches_mask(self):
        g = make_grid(100)
        q = g.cell_id(50, 50)
        r = 0.3
        L = g.candidate_layers(r)
        mask = g.neighboring_cells_mask(r, q)
        cells = np.arange(g.num_cells, dtype=np.int32)
        got = np.asarray(cells_within_layers(cells, np.int32(q), L, g.n))
        assert (got == mask).all()

    def test_invalid_cells_never_match(self):
        g = make_grid(100)
        got = cells_within_layers(np.array([-1], np.int32), np.int32(0), 100, g.n)
        assert not np.asarray(got).any()


# --------------------------------------------------------------------- #
# Adaptive two-level grid (index/adaptive_grid.py)


def _rect_dists(px, py, rect):
    """(min, max) Euclidean distance from a point to a closed rect."""
    x0, y0, x1, y1 = rect
    dx_min = max(x0 - px, px - x1, 0.0)
    dy_min = max(y0 - py, py - y1, 0.0)
    dx_max = max(abs(px - x0), abs(px - x1))
    dy_max = max(abs(py - y0), abs(py - y1))
    return math.hypot(dx_min, dy_min), math.hypot(dx_max, dy_max)


def _random_layout(ag, rng, n_splits=6, n_coarse=4):
    n, c = ag.n, ag.coarsen
    splits = rng.choice(n * n, size=n_splits, replace=False).tolist()
    nb = -(-n // c)
    blocks = [(int(rng.integers(0, nb)), int(rng.integers(0, nb)))
              for _ in range(n_coarse)]
    ag.apply_layout(splits, blocks)
    return ag


class TestAdaptiveLayout:
    def test_default_layout_is_the_base_grid(self):
        g = make_grid(40)
        ag = AdaptiveGrid(g, refine=4)
        assert ag.num_leaves == g.num_cells
        # every base mask is reproduced EXACTLY on the leaf space
        perm = np.array([ag.leaf_of_cell(c) for c in range(g.num_cells)])
        q = g.cell_id(20, 20)
        for r in (0.07, 0.2, 0.5, 1.1):
            assert (ag.guaranteed_leaf_mask(r, q)[perm]
                    == g.guaranteed_cells_mask(r, q)).all()
            assert (ag.neighboring_leaf_mask(r, q)[perm]
                    == g.neighboring_cells_mask(r, q)).all()

    def test_apply_layout_versions_only_real_changes(self):
        ag = AdaptiveGrid(make_grid(20), refine=3)
        assert ag.apply_layout([5, 9], [(4, 4)])
        assert ag.version == 1
        assert not ag.apply_layout([9, 5], [(4, 4)])  # same layout
        assert ag.version == 1
        assert ag.apply_layout([5], [(4, 4)])
        assert ag.version == 2
        assert ag.split_cells() == [5]

    def test_split_wins_over_coarsen(self):
        ag = AdaptiveGrid(make_grid(20), refine=2, coarsen=2)
        # cell 0 is inside block (0, 0): the block must be dropped
        ag.apply_layout([0], [(0, 0), (5, 5)])
        assert ag.coarse_blocks() == [(5, 5)]

    def test_leaves_partition_the_bbox(self):
        """Property: every in-bbox point maps to exactly one leaf whose
        bounds contain it — across splits AND coarse blocks."""
        g = make_grid(25)
        ag = _random_layout(AdaptiveGrid(g, refine=4), np.random.default_rng(3))
        rng = np.random.default_rng(4)
        xs = rng.uniform(g.min_x, g.max_x, 4000)
        ys = rng.uniform(g.min_y, g.max_y, 4000)
        leaves = ag.assign_leaf(xs, ys)
        assert (leaves >= 0).all() and (leaves < ag.num_leaves).all()
        for i in range(0, 4000, 131):
            x0, y0, x1, y1 = ag.leaf_bounds(int(leaves[i]))
            assert x0 - 1e-9 <= xs[i] <= x1 + 1e-9
            assert y0 - 1e-9 <= ys[i] <= y1 + 1e-9

    def test_assign_leaf_out_of_bbox_invalid(self):
        ag = AdaptiveGrid(make_grid(10), refine=2)
        assert (ag.assign_leaf(np.array([110.0, 118.0]),
                               np.array([40.0, 40.0])) == -1).all()

    def test_two_stage_assignment_matches_base_plus_sub(self):
        """The vectorized path == per-point base cell + fine sub-index."""
        g = make_grid(30)
        ag = AdaptiveGrid(g, refine=4)
        ag.apply_layout([g.cell_id(7, 9), g.cell_id(20, 3)])
        rng = np.random.default_rng(5)
        xs = rng.uniform(g.min_x, g.max_x, 2000)
        ys = rng.uniform(g.min_y, g.max_y, 2000)
        leaves = ag.assign_leaf(xs, ys)
        cells, _ = g.assign_cell(xs, ys)
        for i in range(0, 2000, 61):
            cell = int(cells[i])
            first = ag.leaf_of_cell(cell)
            if cell in (g.cell_id(7, 9), g.cell_id(20, 3)):
                rx = (xs[i] - g.min_x) / g.cell_length - cell // g.n
                ry = (ys[i] - g.min_y) / g.cell_length - cell % g.n
                sub = (min(3, int(rx * 4)) * 4 + min(3, int(ry * 4)))
                assert leaves[i] == first + sub
            else:
                assert leaves[i] == first


class TestAdaptiveMaskOracle:
    """The refined GN/CN masks against a brute-force distance oracle:
    guaranteed leaves must be FULLY inside the radius, and every leaf whose
    closest point is within the radius must be in GN ∪ CN — across random
    layouts, query positions (inside split cells, unsplit cells, coarse
    blocks), and radii spanning sub-fine-cell to multi-cell."""

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_point_query_masks_vs_bruteforce(self, seed):
        g = make_grid(20)
        ag = _random_layout(AdaptiveGrid(g, refine=4),
                            np.random.default_rng(seed))
        rng = np.random.default_rng(100 + seed)
        for _ in range(6):
            px = rng.uniform(g.min_x, g.max_x)
            py = rng.uniform(g.min_y, g.max_y)
            qc, _ = g.assign_cell(px, py)
            r = float(rng.uniform(0.2, 8.0)) * ag.fine_length
            gn = ag.guaranteed_leaf_mask(r, int(qc), point=(px, py))
            cn = ag.candidate_leaf_mask(r, int(qc), point=(px, py))
            nb = ag.neighboring_leaf_mask(r, int(qc), point=(px, py))
            assert not (gn & cn).any()
            assert ((gn | cn) == nb).all()
            for leaf in range(ag.num_leaves):
                dmin, dmax = _rect_dists(px, py, ag.leaf_bounds(leaf))
                if gn[leaf]:
                    assert dmax <= r + 1e-9, \
                        f"GN leaf {leaf} not fully inside r"
                if dmin < r * (1 - 1e-9):
                    assert nb[leaf], \
                        f"leaf {leaf} intersects the ball but not in NB"

    def test_split_cell_masks_are_tighter_than_base(self):
        """The refinement's point: inside a split hot cell, a small-radius
        query keeps strictly fewer fine leaves than the whole base cell —
        while still covering the true candidate set."""
        g = make_grid(20)
        ag = AdaptiveGrid(g, refine=4)
        q = g.cell_id(10, 10)
        ag.apply_layout([q])
        x0, y0, x1, y1 = g.cell_bounds(q)
        px, py = x0 + 0.1 * (x1 - x0), y0 + 0.1 * (y1 - y0)  # corner
        r = 0.3 * ag.fine_length
        nb = ag.neighboring_leaf_mask(r, q, point=(px, py))
        # fine leaves of the split cell actually selected
        first = ag.leaf_of_cell(q)
        in_cell = nb[first: first + 16]
        assert 0 < int(in_cell.sum()) < 16

    def test_geom_query_cells_union_semantics(self):
        """Multi-cell queries union per cell (UniformGrid.java:193-222):
        the mask equals the OR of single-cell masks."""
        g = make_grid(20)
        ag = _random_layout(AdaptiveGrid(g, refine=3),
                            np.random.default_rng(9))
        cells = [g.cell_id(4, 4), g.cell_id(6, 5)]
        r = 0.25
        union_nb = ag.neighboring_leaf_mask(r, cells)
        per = [ag.neighboring_leaf_mask(r, c) for c in cells]
        assert (union_nb == (per[0] | per[1])).all()
        union_gn = ag.guaranteed_leaf_mask(r, cells)
        per_gn = [ag.guaranteed_leaf_mask(r, c) for c in cells]
        assert (union_gn == (per_gn[0] | per_gn[1])).all()

    def test_radius_zero_selects_all_leaves(self):
        ag = AdaptiveGrid(make_grid(10), refine=2)
        ag.apply_layout([3])
        nb = ag.neighboring_leaf_mask(0.0, 3)
        assert nb.all()  # UniformGrid.java:264-266 parity
        assert not ag.guaranteed_leaf_mask(0.0, 3).any()


class TestAdaptiveCellKeys:
    def test_wire_parity_and_roundtrip_on_split_cells(self):
        """cell_key parity: the first 10 chars of every leaf key are
        EXACTLY the uniform grid's zero-padded key of the base cell the
        leaf lies in (verified geometrically via the brute-force bounds,
        not via the adaptive grid's own tables), and keys round-trip."""
        g = make_grid(20)
        ag = _random_layout(AdaptiveGrid(g, refine=4),
                            np.random.default_rng(11))
        for leaf in range(0, ag.num_leaves, 7):
            key = ag.cell_key(leaf)
            assert ag.cell_from_key(key) == leaf
            # geometric wire parity: the anchor prefix names a base cell
            # whose bounds contain the leaf's center
            x0, y0, x1, y1 = ag.leaf_bounds(leaf)
            cx, cy = (x0 + x1) / 2, (y0 + y1) / 2
            base_cell = g.cell_from_key(key[:10])
            bx0, by0, bx1, by1 = g.cell_bounds(base_cell)
            assert bx0 - 1e-9 <= cx and by0 - 1e-9 <= cy
            if ":" in key:  # split leaves sit INSIDE one base cell
                assert cx <= bx1 + 1e-9 and cy <= by1 + 1e-9
                # and the prefix matches the uniform key of the point
                ucell, _ = g.assign_cell(cx, cy)
                assert key[:10] == g.cell_key(int(ucell))

    def test_split_key_shape(self):
        g = make_grid(100)
        ag = AdaptiveGrid(g, refine=4)
        cell = g.cell_id(7, 42)
        ag.apply_layout([cell])
        first = ag.leaf_of_cell(cell)
        assert ag.cell_key(first) == "0000700042:0"
        assert ag.cell_key(first + 15) == "0000700042:15"
        assert ag.cell_from_key("0000700042:15") == first + 15
        # unsplit leaves keep the bare 10-char reference format
        other = ag.leaf_of_cell(g.cell_id(3, 5))
        assert ag.cell_key(other) == g.cell_key(g.cell_id(3, 5))
        with pytest.raises(ValueError):
            ag.cell_from_key("0000300005:2")  # sub-key of an unsplit cell
