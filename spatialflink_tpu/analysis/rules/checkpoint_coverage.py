"""Rule 5 — checkpoint coverage: mutable streaming state must be
snapshotable.

PR 4's coordinated checkpoints are only exactly-once if *every* piece of
mutable per-run state participates. The heuristic for "holds streaming
state": a class in ``runtime/``/``operators/``/``streams/`` that assigns
an instance attribute *outside* ``__init__`` whose name says it holds
windows, panes, offsets, partials, watermarks, buffers, or sealed sets.
Such a class must implement the ``snapshot``/``restore`` pair the
coordinator registers — or carry an allowlist entry explaining why its
state is legitimately ephemeral (rebuilt, cache-only, or test-only).

Classes whose state is genuinely derived (caches that recompute, pure
cursors over immutable inputs) belong in the allowlist *with that
sentence as the reason* — the point is that someone decided, not that
the linter guessed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List

from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)
from spatialflink_tpu.analysis.rules.common import attr_write_targets

#: attribute-name fragments that mean "streaming state a resume must not
#: lose".
_STATE_PAT = re.compile(
    r"window|pane|offset|partial|watermark|seal|buffer", re.IGNORECASE)

#: methods whose writes do not make state "live across the run": setup,
#: the snapshot/restore pair itself, and teardown.
_EXEMPT_METHODS = {"__init__", "__post_init__", "__new__", "snapshot",
                   "restore", "reset", "clear", "close", "__exit__"}


@register
class CheckpointCoverageRule(Rule):
    id = "checkpoint-coverage"
    contract = ("classes with mutable windows/offsets/partials state "
                "implement the snapshot/restore checkpoint pair")
    runtime_twin = ("CheckpointCoordinator barriers + crash/resume "
                    "identity tests (tests/test_recovery.py)")
    severity = "warning"
    scope = ("spatialflink_tpu/runtime/*.py",
             "spatialflink_tpu/operators/*.py",
             "spatialflink_tpu/streams/*.py")

    def check(self, mod: ModuleSource) -> Iterator[Finding]:
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {m.name for m in cls.body
                       if isinstance(m, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            state_writes: Dict[str, int] = {}
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                        or meth.name in _EXEMPT_METHODS:
                    continue
                for stmt in ast.walk(meth):
                    if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                             ast.AnnAssign)):
                        continue
                    for attr, node in attr_write_targets(stmt):
                        if _STATE_PAT.search(attr) \
                                and attr not in state_writes:
                            state_writes[attr] = node.lineno
            if not state_writes:
                continue
            missing = [m for m in ("snapshot", "restore")
                       if m not in methods]
            if not missing:
                continue
            attrs = ", ".join(
                f"{a} (line {ln})" for a, ln in sorted(
                    state_writes.items(), key=lambda kv: kv[1]))
            yield self.finding(
                mod, cls,
                f"class mutates streaming state outside __init__ "
                f"[{attrs}] but lacks {' and '.join(missing)} — register "
                "it as a checkpoint component or allowlist with the "
                "reason its state may be lost on resume")


def state_attributes(cls: ast.ClassDef) -> List[str]:
    """Expose the heuristic for tests/docs: the checkpoint-relevant
    attrs a class mutates outside ``__init__``."""
    out = []
    for meth in cls.body:
        if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                or meth.name in _EXEMPT_METHODS:
            continue
        for stmt in ast.walk(meth):
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                for attr, _ in attr_write_targets(stmt):
                    if _STATE_PAT.search(attr) and attr not in out:
                        out.append(attr)
    return out
