"""Leaf AST helpers shared by the framework (call graph, dataflow) and
the rules — no imports from the rule or graph layers, so everything may
import this without cycles. :mod:`spatialflink_tpu.analysis.rules.common`
re-exports these for the rule implementations."""

from __future__ import annotations

import ast
from typing import List, Optional, Set


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as ``a.b.c``; None when the chain
    roots in anything else (a call, a subscript, a literal)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Dotted name of a call's target (``np.asarray``, ``float``)."""
    return dotted(node.func)


def function_params(fn) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


# --------------------------------------------------------------------- #
# instrumented_jit decorator parsing (trace-safety + jit-coverage +
# the call graph's kernel registry)


def _is_instrumented_jit(node: ast.AST) -> bool:
    return (isinstance(node, ast.Name) and node.id == "instrumented_jit") \
        or (isinstance(node, ast.Attribute)
            and node.attr == "instrumented_jit")


def _const_strings(node: ast.AST) -> List[str]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    return []


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [e.value for e in node.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, int)]
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    return []


def jit_static_names(fn: ast.FunctionDef) -> Optional[Set[str]]:
    """If ``fn`` is decorated with ``instrumented_jit`` (bare, or curried
    through ``partial(instrumented_jit, static_arg…=…)``), return the set
    of parameter names the decoration marks static; None when the
    function is not jitted at all."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        if _is_instrumented_jit(dec):
            return set()
        if isinstance(dec, ast.Call):
            target = None
            fname = dotted(dec.func) or ""
            if _is_instrumented_jit(dec.func):
                target = dec
            elif fname.split(".")[-1] == "partial" and dec.args \
                    and _is_instrumented_jit(dec.args[0]):
                target = dec
            if target is None:
                continue
            statics: Set[str] = set()
            for kw in target.keywords:
                if kw.arg == "static_argnames":
                    statics.update(_const_strings(kw.value))
                elif kw.arg == "static_argnums":
                    for i in _const_ints(kw.value):
                        if 0 <= i < len(params):
                            statics.add(params[i])
            return statics
    return None
