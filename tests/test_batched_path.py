"""The batched-everywhere execution path (ISSUE 8): chunk-vectorized decode
+ assign_bulk window assignment as the ONLY path, checked against the seed
scalar loop kept as a test oracle (tests/oracles.py) — contents byte-
identical on file replay and under live --kafka-follow chaos (timing within
one poll cycle), off-type rows dropped per-chunk with counter-keyed
warnings, the fast Point serializer byte-identical to json.dumps, the
adaptive join block coalescer engaged exactly in the dispatch-bound regime,
and device-resident pane state restoring from host-layout checkpoints."""

import json
import threading

import numpy as np
import pytest

from spatialflink_tpu import driver
from spatialflink_tpu.config import StreamConfig
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (
    PointPointJoinQuery,
    PointPointKNNQuery,
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
)
from spatialflink_tpu.streams.kafka import (InMemoryBroker, KafkaSource,
                                            WindowCommitTap)
from spatialflink_tpu.utils.metrics import (ControlTupleExit, REGISTRY,
                                            check_exit_control_tuple,
                                            scoped_registry)

from tests.oracles import (canon_knn_pair, canon_point, canon_windows,
                           scalar_decode_stream)

GRID = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)
T0 = 1_700_000_000_000


def _csv_lines(n, seed=0, late_every=0):
    """CSV point rows over 100 s of event time; every ``late_every``-th
    record is pushed 30 s into the past (out-of-order + genuinely late
    records, so the oracle's watermark drops are exercised)."""
    rng = np.random.default_rng(seed)
    ts = T0 + (np.arange(n) * 100_000 // max(n, 1))
    out = []
    for i in range(n):
        t = int(ts[i])
        if late_every and i and i % late_every == 0:
            t -= 30_000
        out.append(f"v{i % 53},{t},{115.6 + rng.random() * 1.8:.6f},"
                   f"{39.7 + rng.random() * 1.3:.6f}")
    return out


def _geojson_lines(n, seed=1):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        out.append(json.dumps({
            "geometry": {"type": "Point",
                         "coordinates": [115.6 + rng.random() * 1.8,
                                         39.7 + rng.random() * 1.3]},
            "properties": {"oID": f"v{i % 53}",
                           "timestamp": T0 + i * 100_000 // max(n, 1)},
            "type": "Feature"}))
    return out


def _cfg(fmt):
    return StreamConfig(format=fmt, date_format=None,
                        csv_tsv_schema=[0, 1, 2, 3])


def _conf(fmt, **kw):
    kw.setdefault("window_size_ms", 10_000)
    kw.setdefault("slide_ms", 5_000)
    return QueryConfiguration(QueryType.WindowBased, **kw)


QP = Point.create(116.5, 40.3, GRID, obj_id="q")


# --------------------------------------------------- file-path identity


@pytest.mark.parametrize("fmt,lines_fn", [
    ("CSV", _csv_lines), ("GeoJSON", lambda n: _geojson_lines(n))])
def test_range_windows_identical_to_scalar_oracle(fmt, lines_fn):
    """decode_stream (chunk-vectorized, columnar windows) vs the seed
    scalar decoder: identical window tables, including with late records
    dropped by the shared watermark rule."""
    lines = (lines_fn(3000, late_every=17) if fmt == "CSV"
             else lines_fn(3000))
    cfg = _cfg(fmt)

    op = PointPointRangeQuery(_conf(fmt), GRID)
    batched = canon_windows(
        op.run(driver.decode_stream(iter(lines), cfg, GRID), QP, 0.4),
        canon_point)

    op2 = PointPointRangeQuery(_conf(fmt), GRID)
    scalar = canon_windows(
        op2.run(scalar_decode_stream(iter(lines), cfg, GRID), QP, 0.4),
        canon_point)
    assert batched == scalar
    assert len(batched) > 5


@pytest.mark.parametrize("panes", [False, True])
def test_knn_windows_identical_to_scalar_oracle(panes):
    """kNN through the batched path (and its pane-incremental mode with
    the device/host merge auto rule) vs the scalar oracle — the decode
    interner's id space must resolve identically to the operator's."""
    lines = _csv_lines(3000, late_every=29)
    cfg = _cfg("CSV")
    conf = _conf("CSV", panes=panes, k=7)

    op = PointPointKNNQuery(conf, GRID)
    batched = canon_windows(
        op.run(driver.decode_stream(iter(lines), cfg, GRID), QP, 0.5, 7),
        canon_knn_pair)
    op2 = PointPointKNNQuery(conf, GRID)
    scalar = canon_windows(
        op2.run(scalar_decode_stream(iter(lines), cfg, GRID), QP, 0.5, 7),
        canon_knn_pair)
    assert batched == scalar and len(batched) > 5


@pytest.mark.parametrize("device", [False, True])
def test_pane_merge_placement_identical(device):
    """--pane-merge device vs host: identical kNN pane windows; device mode
    reads back ONE merged result per window (pane-merged-* counters),
    host mode one partial per pane."""
    lines = _csv_lines(4000)
    cfg = _cfg("CSV")
    conf = _conf("CSV", panes=True, k=5, window_size_ms=40_000,
                 pane_device_merge=device)
    with scoped_registry() as reg:
        op = PointPointKNNQuery(conf, GRID)
        table = canon_windows(
            op.run(driver.decode_stream(iter(lines), cfg, GRID), QP, 0.5, 5),
            canon_knn_pair)
        snap = reg.snapshot()
    assert len(table) > 5
    if device:
        assert snap.get("pane-merged-readbacks", 0) == len(table)
        assert snap.get("pane-partial-readbacks", 0) == 0
    else:
        assert snap.get("pane-merged-readbacks", 0) == 0
        assert snap.get("pane-partial-readbacks", 0) > 0

    conf2 = _conf("CSV", panes=True, k=5, window_size_ms=40_000,
                  pane_device_merge=not device)
    op2 = PointPointKNNQuery(conf2, GRID)
    other = canon_windows(
        op2.run(driver.decode_stream(iter(lines), cfg, GRID), QP, 0.5, 5),
        canon_knn_pair)
    assert table == other


# ----------------------------------------------------- off-type handling


def test_off_type_rows_drop_per_chunk_with_counter(capsys):
    """A polygon feature inside a declared point stream must not crash the
    columnar parser: the chunk falls back to the exact per-record parse,
    the rows drop with the off-type-dropped counter, and the warning is
    COUNTER-KEYED (re-warns at each decade with the running count) instead
    of one-shot."""
    poly = json.dumps({
        "geometry": {"type": "Polygon",
                     "coordinates": [[[116, 40], [116.1, 40], [116.1, 40.1],
                                      [116, 40]]]},
        "properties": {"oID": "p", "timestamp": T0}, "type": "Feature"})
    lines = _geojson_lines(300)
    mixed = []
    for i, ln in enumerate(lines):
        mixed.append(ln)
        if i % 20 == 0:
            mixed.append(poly)
    with scoped_registry() as reg:
        objs = list(driver.decode_stream(iter(mixed), _cfg("GeoJSON"), GRID))
        assert len(objs) == len(lines)  # every point kept, in order
        assert reg.counter("off-type-dropped").count == 15
    err = capsys.readouterr().err
    assert "off-type-dropped=1" in err   # first drop warns
    assert "off-type-dropped=1" in err and "Polygon" in err
    # decade re-warn fired once the count passed 10
    assert any("off-type-dropped=1" != w and "off-type-dropped=" in w
               for w in err.splitlines() if "off-type" in w)


def test_control_tuple_stops_after_buffered_prefix():
    lines = _csv_lines(100)
    stop = json.dumps({"geometry": {"type": "control", "coordinates": []}})
    seen = []
    with pytest.raises(ControlTupleExit):
        for obj in driver.decode_stream(
                iter(lines[:40] + [stop] + lines[40:]), _cfg("CSV"), GRID):
            seen.append(obj)
    assert len(seen) == 40  # records before the stop all arrived


# ------------------------------------------------- serializer equivalence


def test_fast_point_serializer_byte_identical():
    from spatialflink_tpu.streams import formats as F

    rng = np.random.default_rng(7)
    ids = [f"veh-{i}" for i in range(20)] + ['q"uote', "back\\slash",
                                            "unié", "tab\there", ""]
    for i in range(500):
        p = Point(obj_id=ids[i % len(ids)],
                  timestamp=int(rng.integers(0, 2 ** 41)),
                  x=float(rng.uniform(-180, 180)),
                  y=float(rng.uniform(-90, 90)))
        for df in (None, "%Y-%m-%d %H:%M:%S"):
            ref = json.dumps({
                "geometry": {"type": "Point", "coordinates": [p.x, p.y]},
                "properties": {"oID": p.obj_id,
                               "timestamp": F.format_timestamp(p.timestamp,
                                                               df)},
                "type": "Feature"})
            assert F.serialize_geojson(p, date_format=df) == ref


def test_pointrows_batch_serializer_matches_per_record():
    """PointRows.serialize_batch (the sink's no-Python-objects fast path)
    == serialize_spatial of each materialized record."""
    from spatialflink_tpu.streams.formats import serialize_spatial

    lines = _csv_lines(2000)
    cfg = _cfg("CSV")
    op = PointPointRangeQuery(_conf("CSV"), GRID)
    results = list(op.run(driver.decode_stream(iter(lines), cfg, GRID),
                          QP, 0.5))
    checked = 0
    for r in results:
        sb = getattr(r.records, "serialize_batch", None)
        if sb is None or not len(r.records):
            continue
        for df in (None, "%Y-%m-%d %H:%M:%S"):
            vals = sb("GeoJSON", date_format=df)
            assert vals == [serialize_spatial(rec, "GeoJSON",
                                              date_format=df)
                            for rec in r.records]
        checked += 1
    assert checked > 3, "no columnar selections reached the serializer"


# --------------------------------------- live follow-mode chaos identity


def test_follow_chaos_contents_and_timing_vs_scalar_oracle():
    """Live --kafka-follow windowed run under --chaos (duplicates +
    reordering): the batched path emits windows with IDENTICAL contents
    and IDENTICAL emission timing within one poll cycle — each window
    seals having consumed at most one poll batch more records than the
    seed scalar path did (the decode chunk flushes on the starvation
    sentinel, so chunking can never hold a window past a poll)."""
    from spatialflink_tpu.runtime.faults import ChaosBroker, FaultPlan

    inner = InMemoryBroker()
    lines = _geojson_lines(4000)
    for ln in lines:
        inner.produce("t", ln)
    stop = json.dumps({"geometry": {"type": "control", "coordinates": []}})
    inner.produce("t", stop)
    cfg = _cfg("GeoJSON")
    poll = 250

    def run_batched():
        broker = ChaosBroker(inner, FaultPlan.from_spec(
            "seed=11,duplicate=0.08,reorder=0.25"))
        src = KafkaSource(broker, "t", "g-batched", poll_batch=poll,
                          auto_commit=False, stop_at_end=False,
                          starvation_sentinel=True)
        tap = WindowCommitTap(src, 10_000, 5_000,
                              parse=lambda r: None,  # decode is chunked
                              bulk_decode=driver._kafka_bulk_decode(cfg,
                                                                    GRID),
                              bulk_chunk=poll)
        # depth 1: a control-tuple stop drops in-flight deferred windows
        # (they re-deliver on restart) on ANY path; the timing comparison
        # wants the seal order, not the pipeline queue
        op = PointPointRangeQuery(_conf("GeoJSON", pipeline_depth=1), GRID)
        out = []
        try:
            for r in op.run(driver.decode_stream(tap, cfg, GRID), QP, 0.4):
                out.append((r.window_start,
                            sorted(canon_point(p) for p in r.records),
                            src.position))
        except ControlTupleExit:
            pass
        return out

    def run_scalar():
        from spatialflink_tpu.runtime.windows import (WindowAssembler,
                                                      WindowSpec)
        from spatialflink_tpu.streams.formats import parse_spatial

        broker = ChaosBroker(inner, FaultPlan.from_spec(
            "seed=11,duplicate=0.08,reorder=0.25"))
        src = KafkaSource(broker, "t", "g-scalar", poll_batch=poll,
                          auto_commit=False, stop_at_end=False)
        wa = WindowAssembler(WindowSpec.sliding(10_000, 5_000))
        op = PointPointRangeQuery(_conf("GeoJSON"), GRID)
        out = []

        def sealed():
            try:
                for raw in src:
                    check_exit_control_tuple(raw)
                    obj = parse_spatial(raw, "GeoJSON", GRID)
                    for s, e, recs in wa.add(obj.timestamp, obj):
                        yield s, e, recs, src.position
            except ControlTupleExit:
                # a control-tuple stop does NOT flush open windows (they
                # re-deliver on restart) — exactly what the batched path
                # does, so the oracle must match
                pass

        for s, e, recs, pos in sealed():
            sel = op._eval(recs, QP, 0.4, s)
            recs_out = sel.finish() if hasattr(sel, "finish") else sel
            out.append((s, sorted(canon_point(p) for p in recs_out), pos))
        return out

    consumer = {}

    def consume(name, fn):
        consumer[name] = fn()

    # live: both consumers run against the pre-produced topic in follow
    # mode; the control tuple stops them
    t1 = threading.Thread(target=consume, args=("b", run_batched))
    t1.start()
    t1.join(timeout=120)
    assert not t1.is_alive(), "batched follow run hung"
    t2 = threading.Thread(target=consume, args=("s", run_scalar))
    t2.start()
    t2.join(timeout=120)
    assert not t2.is_alive(), "scalar follow run hung"

    batched, scalar = consumer["b"], consumer["s"]
    assert [(w, r) for w, r, _ in batched] == \
        [(w, r) for w, r, _ in scalar], "window contents/order diverged"
    assert len(batched) > 5
    for (w, _, pb), (_, _, ps) in zip(batched, scalar):
        assert abs(pb - ps) <= poll, (
            f"window {w} emission drifted {pb - ps} records "
            f"(> one poll cycle of {poll})")


# ----------------------------------------------- adaptive join coalescer


def _join_streams(n, seed):
    rng = np.random.default_rng(seed)
    span = 100_000

    def pts(m, s2):
        rng2 = np.random.default_rng(s2)
        return [Point(obj_id=f"o{i}", timestamp=T0 + i * span // m,
                      x=float(116.0 + rng2.random()),
                      y=float(40.0 + rng2.random()),
                      cell=int(GRID.assign_cell(
                          np.array([116.5]), np.array([40.5]))[0][0]))
                for i in range(m)]
    a = pts(n, seed)
    b = pts(max(n // 16, 8), seed + 1)
    for p in a + b:
        c, _ = GRID.assign_cell(np.array([p.x]), np.array([p.y]))
        p.cell = int(c[0])
    return a, b


def _canon_pairs(results):
    return [(r.window_start, sorted(((a.obj_id, a.timestamp),
                                     (b.obj_id, b.timestamp))
                                    for a, b in r.records))
            for r in results]


def test_join_coalescer_dense_blocks(monkeypatch):
    """Dispatch-bound pane-pair blocks coalesce into one window dispatch:
    identical pair sets to both the block path and full recompute, with
    the join-blocks-coalesced counter proving the path switched."""
    a, b = _join_streams(1200, 5)
    conf = _conf("CSV", window_size_ms=40_000)  # overlap 8

    def run(panes, min_cells):
        import spatialflink_tpu.ops.join as J

        monkeypatch.setattr(J, "_BLOCK_MIN_CELLS", None)
        monkeypatch.setenv("SPATIALFLINK_JOIN_BLOCK_MIN_CELLS",
                           str(min_cells))
        c = QueryConfiguration(QueryType.WindowBased, 40_000, 5_000,
                               panes=panes)
        with scoped_registry() as reg:
            op = PointPointJoinQuery(c, GRID, GRID)
            table = _canon_pairs(op.run(iter(a), iter(b), 0.3))
            coalesced = reg.counter("join-blocks-coalesced").count
        return table, coalesced

    full, c0 = run(False, 0)
    blocks, c1 = run(True, 0)           # coalescer disabled: block path
    coal, c2 = run(True, 10 ** 9)       # forced: every window coalesces
    auto, c3 = run(True, -1)            # measured threshold decides
    assert c0 == 0 and c1 == 0 and c2 > 0
    assert blocks == full == coal == auto


# ------------------------------------- checkpoint compat (device panes)


@pytest.mark.recovery
def test_host_layout_checkpoint_restores_into_device_mode(tmp_path,
                                                          monkeypatch):
    """A checkpoint written by the HOST-resident pane layout (partials
    resolved to host at snapshot — the pre-device on-disk format, unchanged)
    must restore into a --pane-merge device run: restored host partials
    make the device merge fall back per window, results identical to the
    uninterrupted oracle, no duplicate markers."""
    from tests.test_recovery import (_crash_at_fresh_window, _lines, _oracle,
                                _produce, _window_table)

    monkeypatch.setenv("SPATIALFLINK_DECODE_CHUNK", "32")
    lines = _lines()
    expected = _oracle(tmp_path, 51, lines, "pm-oracle", None, ["--panes"])
    cfg, broker = _produce(tmp_path, "pm-crash", lines)
    cpd = str(tmp_path / "cp-pm")
    base = ["--config", cfg, "--kafka", "--option", "51", "--panes",
            "--checkpoint-dir", cpd, "--checkpoint-every", "2"]
    with monkeypatch.context() as m:
        _crash_at_fresh_window(m, 4)
        with pytest.raises(RuntimeError, match="injected crash"):
            driver.main(base + ["--pane-merge", "host"])
    import os

    assert [f for f in os.listdir(cpd) if f.endswith(".npz")], \
        "crash run wrote no checkpoint"
    # resume in DEVICE mode against the host-layout snapshot
    assert driver.main(base + ["--pane-merge", "device", "--resume"]) == 0
    table = _window_table(broker)
    assert all(len(v) == 1 for v in table.values())
    assert {k: v[0] for k, v in table.items()} == expected
