"""Rule 1 — jit-coverage: no kernel goes dark.

Every jit in ``ops/`` and ``parallel/`` must go through
``deviceplane.instrumented_jit`` so the compile registry and the
recompile sentinel see it. Raw ``jax.jit`` (attribute use, a
``from jax import jit`` binding, or an aliased module attribute) is a
finding, not a review comment. This migrates the AST meta-test that
lived in ``tests/test_deviceplane.py`` into the framework; the test that
remains just asserts the rule is registered and the tree is clean.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Tuple

from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)
from spatialflink_tpu.analysis.rules.common import jit_static_names


@register
class JitCoverageRule(Rule):
    id = "jit-coverage"
    contract = ("kernels in ops/ and parallel/ compile through "
                "instrumented_jit, never raw jax.jit")
    runtime_twin = ("CompileRegistry + recompile sentinel "
                    "(utils/deviceplane.py)")
    severity = "error"
    scope = ("spatialflink_tpu/ops/*.py", "spatialflink_tpu/parallel/*.py")

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and node.attr == "jit" \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "jax":
                yield self.finding(
                    mod, node,
                    "raw jax.jit bypasses the compile registry — use "
                    "deviceplane.instrumented_jit so the recompile "
                    "sentinel sees this kernel")
            elif isinstance(node, ast.ImportFrom) and node.module == "jax" \
                    and any(a.name == "jit" for a in node.names):
                yield self.finding(
                    mod, node,
                    "`from jax import jit` binds the uninstrumented jit — "
                    "use deviceplane.instrumented_jit")


def instrumented_sites(tree: ast.AST) -> List[Tuple[str, int]]:
    """(function_name, lineno) for every ``instrumented_jit``-decorated
    def in ``tree`` — shared with the deviceplane registration test so no
    walker code is duplicated outside the framework."""
    return [(node.name, node.lineno) for node in ast.walk(tree)
            if isinstance(node, ast.FunctionDef)
            and jit_static_names(node) is not None]
