"""Host-side stream I/O (reference: GeoFlink/spatialStreams/).

Parsing/serialization of spatial wire formats (GeoJSON / WKT / CSV / TSV),
stream sources (synthetic, file replay, in-memory, Kafka when available) and
sinks. Everything here is plain Python on the host — device work starts at
the window batch (spatialflink_tpu.runtime.windows).
"""

from spatialflink_tpu.streams.formats import parse_spatial, serialize_spatial
from spatialflink_tpu.streams.sources import (
    FileReplaySource,
    ListSource,
    SyntheticPointSource,
    generate_query_polygons,
    kafka_source,
)
from spatialflink_tpu.streams.sinks import CollectSink, FileSink, LatencySink, StdoutSink
from spatialflink_tpu.streams.shapefile import iter_shapefile, read_shapefile
from spatialflink_tpu.streams.kafka import (
    IdempotentWindowSink,
    InMemoryBroker,
    KafkaLatencySink,
    KafkaSink,
    KafkaSource,
    KafkaWindowSink,
    WindowCommitTap,
    connect_kafka,
    reset_memory_brokers,
    resolve_broker,
)

__all__ = [
    "IdempotentWindowSink",
    "InMemoryBroker",
    "KafkaLatencySink",
    "KafkaSink",
    "KafkaSource",
    "KafkaWindowSink",
    "WindowCommitTap",
    "connect_kafka",
    "reset_memory_brokers",
    "resolve_broker",
    "parse_spatial",
    "serialize_spatial",
    "FileReplaySource",
    "ListSource",
    "SyntheticPointSource",
    "generate_query_polygons",
    "kafka_source",
    "CollectSink",
    "FileSink",
    "LatencySink",
    "StdoutSink",
    "iter_shapefile",
    "read_shapefile",
]
