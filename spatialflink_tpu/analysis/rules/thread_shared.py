"""Rule 6 — thread-shared-state: lock discipline on cross-thread
classes, interprocedural since PR 15.

The opserver, reporter, control-topic, and LiveStats threads all read —
and in the query plane's case write — state owned by the pipeline
thread. Three checks:

1. **Write discipline with locksets.** Any class that creates an
   instance lock in ``__init__`` (``self._lock = threading.Lock()/
   RLock()/Condition()``) has opted into lock-protected state; every
   instance-attribute write must happen while the lock is held. PR 12
   proved this lexically (the write sits under ``with self._lock``);
   this version follows calls: a *private* helper method whose
   intra-class call sites ALL hold the lock (lexically, or because the
   calling method itself is lock-held-on-entry — a fixpoint over the
   class's self-call edges) is lock-held-on-entry, and its writes are
   clean. A helper passed *by name* (``Thread(target=self._loop)``)
   runs later without the caller's lock, so a by-name reference never
   counts as a locked site. Public methods are never inferred — any
   external caller can invoke them unlocked.
2. **Caller-locked contract, both directions.** A method documented as
   caller-locked (name ending ``_locked`` or a docstring saying the
   lock is held) keeps its write exemption — but every intra-class call
   site of it must now actually hold the lock; a ``_locked`` method
   reached from an unlocked path is exactly the race the marker
   pretends away, and PR 12 could not see it.
3. **Documented coverage.** The classes the architecture documents as
   cross-thread — ``QueryRegistry``, ``EventRing``, ``MetricsRegistry``,
   ``CheckpointCoordinator`` — must own an instance lock at all.

Reads are deliberately out of scope (GIL-atomic snapshots of ints are
this codebase's documented idiom); it is unsynchronized *writes* that
corrupt dicts and deques. Blind spots (documented in ARCHITECTURE.md):
inherited methods, and external callers of ``_locked`` helpers in other
modules.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from spatialflink_tpu.analysis.core import (Finding, ModuleSource, Rule,
                                            register)
from spatialflink_tpu.analysis.rules.common import attr_write_targets, dotted

#: classes the architecture documents as cross-thread (ARCHITECTURE.md
#: "Static invariants"); each must own an instance lock.
DOCUMENTED_CROSS_THREAD = ("QueryRegistry", "EventRing", "MetricsRegistry",
                           "CheckpointCoordinator")

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_HELD_DOC_MARKERS = ("lock held", "lock is held", "caller holds",
                     "holds the lock", "under the lock",
                     "caller-locked")
_EXEMPT = ("__init__", "__post_init__", "__new__")


def _lock_attr(cls: ast.ClassDef) -> Optional[str]:
    """The instance-lock attribute name assigned in ``__init__``."""
    for meth in cls.body:
        if isinstance(meth, ast.FunctionDef) and meth.name == "__init__":
            for stmt in ast.walk(meth):
                if not isinstance(stmt, ast.Assign):
                    continue
                if not isinstance(stmt.value, ast.Call):
                    continue
                name = dotted(stmt.value.func) or ""
                if name.split(".")[-1] not in _LOCK_FACTORIES:
                    continue
                for attr, _ in attr_write_targets(stmt):
                    return attr
    return None


def _caller_locked(meth: ast.AST) -> bool:
    if meth.name.endswith("_locked"):
        return True
    doc = ast.get_docstring(meth) or ""
    low = doc.lower()
    return any(marker in low for marker in _HELD_DOC_MARKERS)


def _under_lock(mod: ModuleSource, node: ast.AST, lock: str) -> bool:
    """Is ``node`` lexically inside ``with self.<lock>`` within its own
    method? (A lock taken by a caller is handled by the lockset, not
    here.)"""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                expr = item.context_expr
                name = dotted(expr) if not isinstance(expr, ast.Call) \
                    else dotted(expr.func)
                if name in (f"self.{lock}", f"self.{lock}.acquire"):
                    return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and isinstance(mod.parent(anc), ast.ClassDef):
            return False
    return False


class _Lockset:
    """Per-class lock-held-on-entry computation over the intra-class
    self-call edges of the project call graph."""

    def __init__(self, mod: ModuleSource, graph, cls: ast.ClassDef,
                 lock: str):
        self.mod = mod
        self.cls = cls
        self.lock = lock
        self.methods: Dict[str, ast.AST] = {
            m.name: m for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.marked: Set[str] = {n for n, m in self.methods.items()
                                 if _caller_locked(m)}
        #: callee method name -> intra-class sites (calls + by-name refs)
        self.sites = graph.class_sites(cls.name) if graph is not None \
            else {}
        self.held = self._fixpoint()

    def _private(self, name: str) -> bool:
        return name.startswith("_") and not name.startswith("__")

    def _site_locked(self, site, held: Set[str]) -> bool:
        if site.deferred:
            return False  # by-name: runs later, outside the with-block
        if _under_lock(self.mod, site.node, self.lock):
            return True
        caller = site.caller
        return caller is not None and caller.cls == self.cls.name \
            and caller.name in held

    def _fixpoint(self) -> Set[str]:
        """Greatest fixpoint: start from every candidate (marked, or
        private with at least one intra-class site) and demote any
        method with an unlocked site until stable. Marked methods stay —
        their contract is asserted, and check 2 audits it."""
        held = set(self.marked) | {
            n for n in self.methods
            if self._private(n) and self.sites.get(n)}
        while True:
            demote = {
                n for n in held - self.marked
                if not all(self._site_locked(s, held)
                           for s in self.sites.get(n, ()))}
            if not demote:
                return held
            held -= demote

    def write_ok(self, meth: ast.AST, stmt: ast.stmt) -> bool:
        return meth.name in self.held \
            or _under_lock(self.mod, stmt, self.lock)

    def unlocked_marked_sites(self):
        """(method name, site) for every intra-class call of a
        caller-locked method from a path that does not hold the lock —
        check 2's finding sites."""
        for name in sorted(self.marked):
            for site in self.sites.get(name, ()):
                if not self._site_locked(site, self.held):
                    yield name, site


@register
class ThreadSharedStateRule(Rule):
    id = "thread-shared-state"
    contract = ("cross-thread classes own an instance lock and write "
                "instance state only on lock-held paths (lexical with, "
                "or a helper whose every call site holds the lock)")
    runtime_twin = ("liveops/queryplane concurrency tests (mid-run HTTP "
                    "mutation under --chaos)")
    severity = "error"
    depth = "interprocedural (intra-class locksets)"
    scope = ("spatialflink_tpu/**",
             # named explicitly (already inside the ** glob): the fleet
             # supervisor's monitor thread shares proc/poll state with the
             # routing loop, so its lock discipline must stay proven here
             "spatialflink_tpu/runtime/fleet*.py")

    def check(self, mod: ModuleSource,
              project=None) -> Iterator[Finding]:
        graph = project.graph(mod) if project is not None else None
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock = _lock_attr(cls)
            if lock is None:
                if cls.name in DOCUMENTED_CROSS_THREAD:
                    yield self.finding(
                        mod, cls,
                        f"{cls.name} is documented cross-thread but owns "
                        "no instance lock — give it one (writes from the "
                        "opserver/reporter/control threads race the "
                        "pipeline) or allowlist with the reviewed reason")
                continue
            lockset = _Lockset(mod, graph, cls, lock)
            yield from self._check_writes(mod, cls, lock, lockset)
            for name, site in lockset.unlocked_marked_sites():
                how = "passed by name (runs without the caller's lock)" \
                    if site.deferred else "called"
                yield self.finding(
                    mod, site.node,
                    f"caller-locked method {cls.name}.{name} is {how} "
                    f"from a path that does not hold self.{lock} — the "
                    "_locked contract says every caller must; take the "
                    "lock at this site or drop the marker")

    def _check_writes(self, mod: ModuleSource, cls: ast.ClassDef,
                      lock: str, lockset: _Lockset) -> Iterator[Finding]:
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in _EXEMPT:
                continue
            for stmt in ast.walk(meth):
                if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign)):
                    continue
                for attr, node in attr_write_targets(stmt):
                    if attr == lock:
                        continue
                    if lockset.write_ok(meth, stmt):
                        continue
                    yield self.finding(
                        mod, node,
                        f"write to self.{attr} on an unlocked path in "
                        f"lock-disciplined class {cls.name} — hold "
                        f"self.{lock} here, or make every call site of "
                        f"{meth.name} lock-held (private helpers infer "
                        "it; public methods and by-name references "
                        "cannot)")


def documented_classes() -> List[str]:
    """Expose the documented-cross-thread list for docs/tests."""
    return list(DOCUMENTED_CROSS_THREAD)
