"""Pallas kernels vs their jnp twins / NumPy oracles (interpreter mode)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import PointBatch
from spatialflink_tpu.models.batches import single_query_edges
from spatialflink_tpu.models.objects import Polygon, LineString
from spatialflink_tpu.ops import pallas_kernels as PK
from spatialflink_tpu.ops.geom import points_to_single_geom_dist


@pytest.fixture()
def interpret_mode(monkeypatch):
    monkeypatch.setenv("SPATIALFLINK_PALLAS", "interpret")


@pytest.fixture()
def grid():
    return UniformGrid(0.0, 10.0, 0.0, 10.0, num_grid_partitions=10)


def _random_batch(grid, n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(0, 10, n), rng.uniform(0, 10, n), rng


class TestPipDist:
    def _check(self, grid, query, n=333, seed=1):
        xs, ys, _ = _random_batch(grid, n, seed)
        batch = PointBatch.from_arrays(xs, ys, grid=grid)
        edges, mask = single_query_edges(query)
        edges, mask = jnp.asarray(edges), jnp.asarray(mask)
        areal = isinstance(query, Polygon)

        got = PK.pip_dist(batch.x, batch.y, edges, mask, areal)
        want = points_to_single_geom_dist(batch, edges, mask, areal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_polygon(self, interpret_mode, grid):
        poly = Polygon.create([[(2, 2), (6, 2), (6, 6), (2, 6), (2, 2)]], grid=grid)
        self._check(grid, poly)

    def test_polygon_with_hole(self, interpret_mode, grid):
        poly = Polygon.create(
            [[(1, 1), (8, 1), (8, 8), (1, 8), (1, 1)],
             [(3, 3), (5, 3), (5, 5), (3, 3)]],
            grid=grid,
        )
        self._check(grid, poly, n=257, seed=2)

    def test_linestring(self, interpret_mode, grid):
        ls = LineString.create([(0.5, 0.5), (4, 7), (9, 3)], grid=grid)
        self._check(grid, ls, n=130, seed=3)

    def _check_vs_raw(self, grid, poly, n, seed):
        """Parity against the INDEPENDENT jnp oracle
        (points_to_single_edges_raw): points_to_single_geom_dist delegates
        back to pip_dist, so _check would compare the kernel with itself."""
        from spatialflink_tpu.ops.geom import points_to_single_edges_raw

        xs, ys, _ = _random_batch(grid, n, seed)
        batch = PointBatch.from_arrays(xs, ys, grid=grid)
        edges, mask = single_query_edges(poly)
        edges, mask = jnp.asarray(edges), jnp.asarray(mask)
        got = PK.pip_dist(batch.x, batch.y, edges, mask, True)
        inside, mind2 = points_to_single_edges_raw(batch.x, batch.y, edges,
                                                   mask)
        want = jnp.where(inside, 0.0, jnp.sqrt(mind2))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_large_polygon_streams_edge_chunks(self, interpret_mode, grid):
        """A polygon with more edges than one SMEM chunk (the round-4
        512-edge fallback cap) streams through the chunked grid: multi-chunk
        even-odd counts and min-distances must match the jnp oracle."""
        th = np.linspace(0, 2 * np.pi, 1301, endpoint=False)
        ring = [(5 + 3.5 * float(np.cos(t)) * (1 + 0.1 * float(np.sin(9 * t))),
                 5 + 3.5 * float(np.sin(t)) * (1 + 0.1 * float(np.cos(7 * t))))
                for t in th]
        poly = Polygon.create([ring + [ring[0]]], grid=grid)
        edges, _ = single_query_edges(poly)
        assert edges.shape[0] > PK._EDGE_CHUNK  # actually exercises chunking
        self._check_vs_raw(grid, poly, n=211, seed=9)

    def test_chunk_boundary_edge_counts(self, interpret_mode, grid):
        """Edge counts right at the chunk boundary (one full chunk, one
        chunk + 1 edge) keep parity — the padded tail chunk is fully
        masked."""
        for n_vert in (PK._EDGE_CHUNK, PK._EDGE_CHUNK + 1):
            th = np.linspace(0, 2 * np.pi, n_vert, endpoint=False)
            ring = [(5 + 3 * float(np.cos(t)), 5 + 3 * float(np.sin(t)))
                    for t in th]
            poly = Polygon.create([ring + [ring[0]]], grid=grid)
            self._check_vs_raw(grid, poly, n=97, seed=n_vert)

    def test_matches_off_mode(self, monkeypatch, grid):
        poly = Polygon.create([[(2, 2), (6, 2), (6, 6), (2, 6), (2, 2)]], grid=grid)
        xs, ys, _ = _random_batch(grid, 100, 4)
        batch = PointBatch.from_arrays(xs, ys, grid=grid)
        edges, mask = single_query_edges(poly)
        edges, mask = jnp.asarray(edges), jnp.asarray(mask)
        monkeypatch.setenv("SPATIALFLINK_PALLAS", "off")
        off = PK.pip_dist(batch.x, batch.y, edges, mask, True)
        monkeypatch.setenv("SPATIALFLINK_PALLAS", "interpret")
        on = PK.pip_dist(batch.x, batch.y, edges, mask, True)
        np.testing.assert_allclose(np.asarray(on), np.asarray(off),
                                   rtol=1e-5, atol=1e-6)


    @pytest.mark.parametrize("mode", ["off", "interpret"])
    def test_empty_edges(self, monkeypatch, grid, mode):
        monkeypatch.setenv("SPATIALFLINK_PALLAS", mode)
        px = jnp.asarray(np.array([1.0, 2.0], np.float32))
        py = jnp.asarray(np.array([1.0, 2.0], np.float32))
        edges = jnp.zeros((0, 4), jnp.float32)
        mask = jnp.zeros((0,), bool)
        d = PK.pip_dist(px, py, edges, mask, True)
        assert np.all(np.asarray(d) > 1e18)  # "infinitely far" sentinel


class TestJoinReduce:
    """join_reduce is a tiled XLA scan (the hand pallas kernel measured 14x
    slower on the chip and was deleted — benchmarks/TPU_NOTES.md §6); these
    pin it to the dense NumPy oracle."""

    def _oracle(self, a, b, radius, layers, n):
        acx, acy = np.asarray(a.cell) // n, np.asarray(a.cell) % n
        bcx, bcy = np.asarray(b.cell) // n, np.asarray(b.cell) % n
        ax, ay = np.asarray(a.x), np.asarray(a.y)
        bx, by = np.asarray(b.x), np.asarray(b.y)
        cheb = np.maximum(np.abs(acx[:, None] - bcx[None, :]),
                          np.abs(acy[:, None] - bcy[None, :]))
        d2 = (ax[:, None] - bx[None, :]) ** 2 + (ay[:, None] - by[None, :]) ** 2
        hit = (np.asarray(a.valid)[:, None] & np.asarray(b.valid)[None, :]
               & (cheb <= layers) & (d2 <= radius**2))
        cnt = hit.sum(1)
        d2m = np.where(hit, d2, np.inf)
        arg = np.where(cnt > 0, d2m.argmin(1), -1)
        return cnt, d2m.min(1), arg

    @pytest.mark.parametrize("na,nb", [(100, 80), (257, 300)])
    def test_vs_oracle(self, grid, na, nb):
        ax, ay, _ = _random_batch(grid, na, 5)
        bx, by, _ = _random_batch(grid, nb, 6)
        a = PointBatch.from_arrays(ax, ay, grid=grid)
        b = PointBatch.from_arrays(bx, by, grid=grid)
        radius, layers = 1.5, grid.candidate_layers(1.5)

        cnt, mind2, amin = PK.join_reduce(a, b, radius, layers, n=grid.n)
        ocnt, omind2, oamin = self._oracle(a, b, radius, layers, grid.n)

        np.testing.assert_array_equal(np.asarray(cnt), ocnt)
        has = ocnt > 0
        np.testing.assert_allclose(np.asarray(mind2)[has], omind2[has], rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(amin)[has], oamin[has])
        assert (np.asarray(amin)[~has] == -1).all()

    def test_multi_tile_scan(self, grid):
        """tile=64 on a 300-point (512-capacity) b side forces 8 scan steps
        incl. padded tail tiles — covering the cross-tile accumulation
        (offsets, strict-< merge, argmin + off) that a single-tile run
        never executes."""
        ax, ay, _ = _random_batch(grid, 257, 9)
        bx, by, _ = _random_batch(grid, 300, 10)
        a = PointBatch.from_arrays(ax, ay, grid=grid)
        b = PointBatch.from_arrays(bx, by, grid=grid)
        r, lay = 1.5, grid.candidate_layers(1.5)
        tiled = PK.join_reduce(a, b, r, lay, n=grid.n, tile=64)
        whole = PK.join_reduce(a, b, r, lay, n=grid.n)
        ocnt, omind2, oamin = self._oracle(a, b, r, lay, grid.n)
        for got in (tiled, whole):
            np.testing.assert_array_equal(np.asarray(got[0]), ocnt)
            has = ocnt > 0
            np.testing.assert_allclose(np.asarray(got[1])[has], omind2[has],
                                       rtol=1e-5)
            np.testing.assert_array_equal(np.asarray(got[2])[has], oamin[has])

    def test_small_uneven_tiles(self, grid):
        ax, ay, _ = _random_batch(grid, 64, 7)
        bx, by, _ = _random_batch(grid, 96, 8)
        a = PointBatch.from_arrays(ax, ay, grid=grid)
        b = PointBatch.from_arrays(bx, by, grid=grid)
        cnt, mind2, amin = PK.join_reduce(a, b, 2.0, grid.candidate_layers(2.0),
                                          n=grid.n)
        ocnt, omind2, oamin = self._oracle(a, b, 2.0, grid.candidate_layers(2.0),
                                           grid.n)
        np.testing.assert_array_equal(np.asarray(cnt), ocnt)
        has = ocnt > 0
        np.testing.assert_allclose(np.asarray(mind2)[has], omind2[has], rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(amin)[has], oamin[has])


class TestJoinReduceDispatch:
    """join_reduce is wired into the reachable join path: join_pairs_host
    prefilters the a side with it when the lattice exceeds the budget
    (VERDICT r3 weak #6 — the kernel an operator actually calls)."""

    def _batches(self, grid, na=1500, nb=700):
        ax, ay, _ = _random_batch(grid, na, 11)
        bx, by, _ = _random_batch(grid, nb, 12)
        return (PointBatch.from_arrays(ax, ay, grid=grid),
                PointBatch.from_arrays(bx, by, grid=grid))

    def test_prefiltered_pairs_match_direct(self, grid):
        from spatialflink_tpu.ops.join import join_pairs_host

        a, b = self._batches(grid)
        r = 0.4
        direct = sorted(
            (int(i), int(j))
            for ai, bi in join_pairs_host(a, b, r, grid)
            for i, j in zip(ai, bi))
        assert direct  # non-trivial join
        pre = sorted(
            (int(i), int(j))
            for ai, bi in join_pairs_host(a, b, r, grid, lattice_budget=1)
            for i, j in zip(ai, bi))
        assert pre == direct

    def test_prefilter_empty_join(self, grid):
        from spatialflink_tpu.ops.join import join_pairs_host

        a, b = self._batches(grid, 300, 300)
        # radius so small nothing pairs (distinct random points)
        out = list(join_pairs_host(a, b, 1e-12, grid, lattice_budget=1))
        assert out == []

    def test_operator_path_uses_prefilter(self, grid, monkeypatch):
        """The windowed join operator produces identical pairs when every
        window is forced through the join_reduce prefilter."""
        from spatialflink_tpu.models import Point
        from spatialflink_tpu.operators import (
            PointPointJoinQuery, QueryConfiguration, QueryType)
        from spatialflink_tpu.ops import join as J

        rng = np.random.default_rng(13)
        t0 = 1_700_000_000_000
        mk = lambda n, s: [
            Point.create(float(x), float(y), grid, obj_id=f"o{i}",
                         timestamp=t0 + i * 10)
            for i, (x, y) in enumerate(zip(
                np.random.default_rng(s).uniform(grid.min_x, grid.max_x, n),
                np.random.default_rng(s + 1).uniform(grid.min_y, grid.max_y, n)))]
        a, b = mk(400, 21), mk(120, 23)
        conf = QueryConfiguration(QueryType.WindowBased, 10_000, 10_000)

        def run():
            return [
                sorted((x.obj_id, y.obj_id) for x, y in w.records)
                for w in PointPointJoinQuery(conf, grid).run(
                    iter(a), iter(b), 0.5)
            ]

        want = run()
        monkeypatch.setattr(J, "_LATTICE_BUDGET", 1)
        got = run()
        assert got == want and any(want)
