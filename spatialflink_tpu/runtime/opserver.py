"""In-run operations plane: the status server and the live stderr digest.

The reference serves a live web UI while the job runs (Flink's dashboard,
``StreamingJob.java:70-72`` named operators + Dropwizard meters); the
rebuild's post-hoc telemetry (JSONL snapshots, final ``--metrics``) said
what HAPPENED but nothing answered "what is it doing RIGHT NOW". This
module adds that plane, stdlib-only:

- :class:`OpServer` — a threaded HTTP server (``--status-port``; 0 binds
  an ephemeral port, printed by the driver) serving

  =============== ====================================================
  endpoint         payload
  =============== ====================================================
  /healthz         SLO verdict, ``200`` healthy / ``503`` breached
  /status          the full shared status snapshot (one JSON document)
  /metrics         Prometheus text exposition, rendered LIVE per request
  /events          the lifecycle event ring (checkpoints, breaker, DLQ,
                   SLO); ``?since=<seq>`` returns only newer events —
                   pollers resume from ``latest_seq`` instead of
                   re-reading (and re-alerting on) the whole ring
  /trace/recent    newest window-trace summaries (ids + bounds)
  /trace/<id>      one window's full trace lineage (``--trace-dir``)
  /profile/cells   per-cell / per-family cost profiles + time series
  /latency         stage-residency latency decomposition (record→emit
                   budgets per window, per-stage histograms, per-query
                   record→emit, backpressure time series)
  /queries         GET: the standing-query ledger; POST: admit/update a
                   query (schema-validated JSON body, lands at the next
                   window boundary) — the dynamic query plane
  /queries/<id>    GET: one query's lifecycle record; DELETE: drain it
  /tenants         per-tenant cost ledger: attributed kernel-ms/bytes,
                   records in/out, windows, SLO/shed/quota counts, the
                   fairness summary (top payer, shares, Gini), and the
                   bounded delta time series (utils.accounting)
  /tenants/<id>    one tenant's row + its kernel-ms series and rate
  /fleet           supervisor's aggregated per-worker view (fleet runs):
                   liveness, restarts, routing — plus the elastic-fleet
                   state (per-worker fence tokens, quarantine flags and
                   suspicion scores, active/retired sets, and the fence/
                   rescale/quarantine history logs)
  /fleet/latency   end-to-end record→merged-emit lineage: fleet stage
                   table + sum check after the merge, record→visible
                   histogram and per-worker samples mid-run
  /fleet/timeline  the merged causally-ordered fleet event timeline
                   (supervisor lifecycle + harvested worker events)
  /fleet/events    same ring with worker-style ``?since=`` cursors
  /fleet/metrics   every worker's Prometheus text relabeled with
                   ``worker="wN"`` + fleet gauges — one scrape point
  /fleet/tenants   every worker's /tenants ledger harvested and merged
                   (summed rows, fleet-wide fairness recomputed)
  =============== ====================================================

Method handling is uniform: a known route hit with a verb outside its
set answers a JSON ``405`` with an ``Allow:`` header; unknown paths are
``404`` whatever the verb (http.server's default bare 501 never reaches
a client for the verbs named here).

- :class:`LiveStats` — a daemon thread printing a one-line stderr digest
  per interval (``--live-stats``; automatic under ``--kafka-follow`` when
  a telemetry session is active), for operators watching a terminal
  instead of curl.

Both consume :func:`~spatialflink_tpu.utils.telemetry.status_snapshot` —
the SAME document the telemetry reporter writes as JSONL — and build it
only on request / per interval, never per record. With no telemetry
session active the server still serves the always-on registry counters
(and ``/healthz`` evaluates whatever checks have data) while the record
loop stays byte-identical to the uninstrumented path; spans, histograms,
gauges, and events need a session (``--telemetry-dir`` / ``--live-stats``).
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, unquote

from spatialflink_tpu.utils import telemetry as _telemetry

#: the one server the current process runs (the driver starts at most one);
#: lets in-process tooling/tests discover the ephemeral port without
#: scraping stderr
_ACTIVE_SERVER: Optional["OpServer"] = None


def active_server() -> Optional["OpServer"]:
    """The process's running :class:`OpServer`, or None."""
    return _ACTIVE_SERVER


#: known routes -> methods they answer. Exact paths first; prefix routes
#: (one level of <id>) below. Anything else is 404; a known route hit with
#: a method outside its set is a JSON 405 carrying an ``Allow:`` header —
#: BaseHTTPRequestHandler's bare 501 for undefined ``do_<METHOD>``s never
#: reaches a client for the methods the plane names here.
_ROUTES = {
    "/healthz": ("GET",), "/status": ("GET",), "/metrics": ("GET",),
    "/events": ("GET",), "/trace/recent": ("GET",),
    "/profile/cells": ("GET",), "/partition": ("GET",),
    "/queries": ("GET", "POST"),
    "/tenants": ("GET",),
    "/device": ("GET",), "/compile": ("GET",), "/latency": ("GET",),
    "/fleet": ("GET",), "/fleet/latency": ("GET",),
    "/fleet/timeline": ("GET",), "/fleet/events": ("GET",),
    "/fleet/metrics": ("GET",), "/fleet/tenants": ("GET",),
}
_PREFIX_ROUTES = {"/trace/": ("GET",), "/queries/": ("GET", "DELETE"),
                  "/tenants/": ("GET",)}

_ENDPOINTS = ["/healthz", "/status", "/metrics", "/events", "/trace/recent",
              "/trace/<id>", "/profile/cells", "/partition", "/queries",
              "/queries/<id>", "/tenants", "/tenants/<id>", "/device",
              "/compile", "/latency", "/fleet",
              "/fleet/latency", "/fleet/timeline", "/fleet/events",
              "/fleet/metrics", "/fleet/tenants"]


def _allowed_methods(path: str):
    """The method set a path answers, or None when the path is unknown."""
    m = _ROUTES.get(path)
    if m is not None:
        return m
    for prefix, pm in _PREFIX_ROUTES.items():
        if path.startswith(prefix) and len(path) > len(prefix):
            return pm
    return None


class _Handler(BaseHTTPRequestHandler):
    server_version = "spatialflink-opserver/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: stderr belongs to the digest
        pass

    def _send(self, code: int, body: bytes, ctype: str,
              headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        # one response per connection: a kept-alive handler loop would
        # survive close() (shutdown() stops only the LISTENER) and keep
        # answering probes after the pipeline exited — the plane must die
        # with the run, so every response closes its connection
        self.send_header("Connection", "close")
        self.close_connection = True
        self.end_headers()
        if self.command != "HEAD":  # HEAD: headers only, per HTTP
            self.wfile.write(body)

    def _send_json(self, code: int, payload: dict,
                   headers: Optional[dict] = None) -> None:
        self._send(code, json.dumps(payload, sort_keys=True).encode(),
                   "application/json", headers)

    def _read_body(self):
        """The request body parsed as JSON, or (None, error-payload)."""
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        if length <= 0:
            return None, {"error": "a JSON body is required "
                                   "(send Content-Length)"}
        if length > 1 << 20:
            return None, {"error": "body too large (1 MiB max)"}
        raw = self.rfile.read(length)
        try:
            return json.loads(raw), None
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            return None, {"error": f"invalid JSON body: {e}"}

    def _dispatch(self, method: str) -> None:
        srv: "OpServer" = self.server.opserver  # type: ignore[attr-defined]
        srv.requests_served += 1
        path, _, query = self.path.partition("?")
        path = path.rstrip("/") or "/"
        try:
            allowed = _allowed_methods(path)
            if allowed is None:
                self._send_json(404, {"error": f"unknown path {path!r}",
                                      "endpoints": _ENDPOINTS})
                return
            if method not in allowed:
                # proper JSON 405 with Allow: — not http.server's bare 501
                self._send_json(
                    405, {"error": f"method {method} not allowed for "
                                   f"{path!r}", "allow": list(allowed)},
                    headers={"Allow": ", ".join(allowed)})
                return
            self._route(srv, method, path, query)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-write (Ctrl-C'd curl sends RST)
        except Exception as e:
            # a payload bug must 500 the one request, not traceback onto
            # the stderr the handler deliberately keeps quiet
            try:
                self._send_json(500, {"error": repr(e)})
            except Exception:
                pass

    def _route(self, srv: "OpServer", method: str, path: str,
               query: str) -> None:
        if path == "/healthz":
            code, payload = srv.healthz_payload()
            self._send_json(code, payload)
        elif path == "/status":
            self._send_json(200, srv.status_payload())
        elif path == "/metrics":
            self._send(200, srv.metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif path == "/events":
            since_raw = parse_qs(query).get("since", [None])[0]
            try:
                since = None if since_raw is None else int(since_raw)
            except ValueError:
                self._send_json(400, {
                    "error": f"?since must be an integer event seq, "
                             f"got {since_raw!r}"})
                return
            self._send_json(200, srv.events_payload(since))
        elif path == "/trace/recent":
            self._send_json(200, srv.traces_payload())
        elif path.startswith("/trace/"):
            code, payload = srv.trace_payload(
                unquote(path[len("/trace/"):]))
            self._send_json(code, payload)
        elif path == "/profile/cells":
            self._send_json(200, srv.profile_cells_payload())
        elif path == "/latency":
            self._send_json(200, srv.latency_payload())
        elif path == "/partition":
            self._send_json(200, srv.partition_payload())
        elif path == "/fleet":
            self._send_json(200, srv.fleet_payload())
        elif path == "/fleet/latency":
            self._send_json(200, srv.fleet_latency_payload())
        elif path == "/fleet/timeline":
            self._send_json(200, srv.fleet_timeline_payload())
        elif path == "/fleet/events":
            since_raw = parse_qs(query).get("since", [None])[0]
            try:
                since = None if since_raw is None else int(since_raw)
            except ValueError:
                self._send_json(400, {
                    "error": f"?since must be an integer event seq, "
                             f"got {since_raw!r}"})
                return
            self._send_json(200, srv.fleet_events_payload(since))
        elif path == "/fleet/metrics":
            self._send(200, srv.fleet_metrics_text().encode(),
                       "text/plain; version=0.0.4")
        elif path == "/fleet/tenants":
            self._send_json(200, srv.fleet_tenants_payload())
        elif path == "/device":
            self._send_json(200, srv.device_payload())
        elif path == "/compile":
            cost_raw = parse_qs(query).get("cost", ["0"])[0]
            self._send_json(200, srv.compile_payload(
                with_cost=cost_raw not in ("0", "", "false")))
        elif path == "/queries" and method == "GET":
            self._send_json(200, srv.queries_payload())
        elif path == "/queries" and method == "POST":
            body, err = self._read_body()
            if err is not None:
                self._send_json(400, err)
                return
            code, payload = srv.admit_query_payload(body)
            self._send_json(code, payload)
        elif path.startswith("/queries/"):
            qid = unquote(path[len("/queries/"):])
            if method == "DELETE":
                code, payload = srv.retire_query_payload(qid)
            else:
                code, payload = srv.query_payload(qid)
            self._send_json(code, payload)
        elif path == "/tenants":
            self._send_json(200, srv.tenants_payload())
        elif path.startswith("/tenants/"):
            code, payload = srv.tenant_payload(
                unquote(path[len("/tenants/"):]))
            self._send_json(code, payload)
        else:  # unreachable while _ROUTES and this dispatch agree
            self._send_json(404, {"error": f"unknown path {path!r}",
                                  "endpoints": _ENDPOINTS})

    # http.server calls do_<METHOD>; everything funnels through _dispatch
    # so route/method resolution cannot fork per verb
    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        self._dispatch("GET")

    def do_POST(self) -> None:  # noqa: N802
        self._dispatch("POST")

    def do_DELETE(self) -> None:  # noqa: N802
        self._dispatch("DELETE")

    def do_PUT(self) -> None:  # noqa: N802
        self._dispatch("PUT")

    def do_PATCH(self) -> None:  # noqa: N802
        self._dispatch("PATCH")

    def do_HEAD(self) -> None:  # noqa: N802
        self._dispatch("HEAD")


class OpServer:
    """Threaded in-run status server. ``port=0`` binds an ephemeral port
    (read it back from :attr:`port`). Binds loopback by default — the
    plane exposes operational detail, not a public API. Request handling
    is read-only: every endpoint renders a fresh document from the active
    telemetry session (or the registry fallback) at request time, so an
    unqueried server costs the pipeline nothing."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 telemetry=None, health=None, registry=None):
        self._requested_port = int(port)
        self.host = host
        #: pinned session; None = read the active session per request (the
        #: driver's default — the server outlives no session but may start
        #: before one's first snapshot)
        self.telemetry = telemetry
        #: SLO evaluator for /healthz when no session carries one
        self.health = health
        self.registry = registry
        self.port: Optional[int] = None
        self.requests_served = 0
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------- endpoint payloads ----------------------- #
    # (public: tests and in-process tooling call these without HTTP)

    def _tel(self):
        return (self.telemetry if self.telemetry is not None
                else _telemetry.active())

    def status_payload(self) -> dict:
        # pinned-vs-active and explicit-vs-session-health resolution lives
        # in status_snapshot — ONE authority shared with the reporter and
        # the digest, not re-implemented per consumer
        return _telemetry.status_snapshot(self.telemetry, health=self.health,
                                          registry=self.registry)

    def healthz_payload(self):
        """(http_code, payload): 200 when every configured check passes
        (or no evaluator is configured — a bare liveness probe), 503
        otherwise."""
        snap = self.status_payload()
        verdict = snap.get("health")
        if verdict is None:
            return 200, {"healthy": True, "status": "ok", "checks": {}}
        return (200 if verdict["healthy"] else 503), verdict

    def metrics_text(self) -> str:
        return _telemetry.prometheus_text(self._tel(), registry=self.registry)

    def events_payload(self, since: Optional[int] = None) -> dict:
        tel = self._tel()
        if tel is None:
            return {"events": [], "total": 0, "latest_seq": 0,
                    "note": "lifecycle events need a telemetry session "
                            "(--telemetry-dir / --live-stats)"}
        # latest_seq must never run AHEAD of the delivered list — an event
        # appended between reading the counter and copying the ring would
        # then be skipped forever by a poller resuming from it. So: the
        # last seq actually IN the response, else the counter read BEFORE
        # the copy (resuming there can re-deliver, never lose)
        latest = tel.events.total
        evs = tel.events.list(since)
        if evs:
            latest = evs[-1]["seq"]
        elif since is not None:
            latest = max(latest, since)
        return {"events": evs, "total": tel.events.total,
                "latest_seq": latest}

    # ------------------- cost-attribution plane payloads --------------- #

    _TRACE_NOTE = ("window tracing needs a telemetry session with tracing "
                   "enabled (--trace-dir)")

    def _trace_book(self):
        tel = self._tel()
        return tel.traces if tel is not None else None

    def traces_payload(self) -> dict:
        book = self._trace_book()
        if book is None:
            return {"traces": [], "total": 0, "evicted": 0, "latest_seq": 0,
                    "note": self._TRACE_NOTE}
        # evicted/latest_seq make ring overflow visible: a poller that sees
        # latest_seq jump by more than len(traces) knows the ring wrapped
        # and windows silently fell out between polls
        return {"traces": book.recent(), "total": book.total,
                "evicted": book.evicted, "latest_seq": book.total}

    def trace_payload(self, trace_id: str):
        """(http_code, payload) for ``/trace/<id>``."""
        book = self._trace_book()
        if book is None:
            return 404, {"error": self._TRACE_NOTE}
        tr = book.get(trace_id)
        if tr is None:
            return 404, {"error": f"unknown or evicted trace {trace_id!r} "
                                  "(ids are '<family>:<window_start>'; see "
                                  "/trace/recent)"}
        return 200, tr

    def profile_cells_payload(self) -> dict:
        tel = self._tel()
        if tel is None:
            return {"cells": [], "families": {}, "series": [],
                    "note": "cost profiles need a telemetry session "
                            "(--telemetry-dir / --live-stats / --trace-dir)"}
        return tel.costs.cells_payload()

    def latency_payload(self) -> dict:
        """``GET /latency``: the stage-residency decomposition table
        (per-stage histograms + the newest full per-window budgets with
        their sum-invariant check), record→emit latency global and per
        standing query, and the recent backpressure time series
        (``utils.latencyplane``)."""
        tel = self._tel()
        if tel is None:
            return {"stages": {}, "recent": [], "queries": {},
                    "backpressure": {"series": []},
                    "note": "the latency plane needs a telemetry session "
                            "(--telemetry-dir / --live-stats / --trace-dir "
                            "/ --postmortem-dir)"}
        return tel.latency.payload(tel=tel)

    # ---------------------- standing-query plane ----------------------- #

    _QUERIES_NOTE = ("no dynamic query registry in this run (enable with "
                     "--queries-file / --control-topic)")

    @staticmethod
    def _registry():
        from spatialflink_tpu.runtime.queryplane import active_registry

        return active_registry()

    def queries_payload(self) -> dict:
        """``GET /queries``: the live standing-query ledger (fleet slots,
        lifecycle states, per-query counters/SLO verdicts, fleet version
        and padding bucket)."""
        reg = self._registry()
        if reg is None:
            return {"queries": [], "live": 0, "note": self._QUERIES_NOTE}
        return reg.status()

    def query_payload(self, qid: str):
        """(http_code, payload) for ``GET /queries/<id>``."""
        reg = self._registry()
        if reg is None:
            return 404, {"error": self._QUERIES_NOTE}
        for row in reg.status()["queries"]:
            if row["id"] == qid:
                return 200, row
        return 404, {"error": f"unknown query {qid!r} (see /queries)"}

    def admit_query_payload(self, body):
        """(http_code, payload) for ``POST /queries``: admit a new
        standing query — or stage an update when the id already names a
        live one. Takes effect at the next window boundary."""
        from spatialflink_tpu.runtime.queryplane import (QuerySpecError,
                                                         QueryState)
        from spatialflink_tpu.utils.accounting import QuotaExceeded

        reg = self._registry()
        if reg is None:
            return 409, {"error": self._QUERIES_NOTE}
        try:
            entry = reg.admit(body)
        except QuerySpecError as e:
            return 400, {"error": str(e)}
        except QuotaExceeded as e:
            # quota refusal is NOT shedding: shed parks the spec and
            # auto-admits later; a quota breach creates no entry at all —
            # the tenant must retire a query (or the operator must raise
            # --tenant-quota) before retrying
            return 429, {"error": f"quota-exceeded: {e}",
                         "tenant": e.tenant}
        if entry.state is QueryState.SHED:
            # admission shedding: the chunk governor saw sustained
            # backpressure stalls and flipped the registry into shedding —
            # the spec is parked (state "shed", auto-released when the
            # stalls clear), and the caller is told to back off
            return 429, {"query": entry.to_dict(),
                         "fleet_version": reg.fleet_version,
                         "error": "admission shed: pipeline is under "
                                  "sustained backpressure; the query is "
                                  "parked and admits when pressure clears "
                                  "(see /latency controller block)"}
        return 200, {"query": entry.to_dict(),
                     "fleet_version": reg.fleet_version,
                     "applies": "at the next window boundary"}

    def retire_query_payload(self, qid: str):
        """(http_code, payload) for ``DELETE /queries/<id>``: an active
        query drains (in-flight windows complete), a pending one retires
        immediately."""
        reg = self._registry()
        if reg is None:
            return 409, {"error": self._QUERIES_NOTE}
        try:
            entry = reg.retire(qid)
        except KeyError:
            return 404, {"error": f"unknown or already-retired query "
                                  f"{qid!r} (see /queries)"}
        return 200, {"query": entry.to_dict(),
                     "fleet_version": reg.fleet_version}

    # ---------------------- tenant accounting plane -------------------- #

    _TENANTS_NOTE = ("the tenant ledger needs a telemetry session "
                     "(--telemetry-dir / --live-stats / --trace-dir "
                     "/ --postmortem-dir)")

    def tenants_payload(self) -> dict:
        """``GET /tenants``: the per-tenant cost ledger — attributed
        kernel-ms/bytes (conserved per dispatch against the measured
        span), records in/out, windows, SLO/shed/quota counters, the
        fairness summary, and the bounded kernel-ms delta series
        (``utils.accounting``)."""
        tel = self._tel()
        if tel is None:
            return {"tenants": {}, "n": 0, "note": self._TENANTS_NOTE}
        return tel.tenants.payload()

    def tenant_payload(self, tenant: str):
        """(http_code, payload) for ``GET /tenants/<id>``."""
        tel = self._tel()
        if tel is None:
            return 404, {"error": self._TENANTS_NOTE}
        payload = tel.tenants.tenant_payload(tenant)
        if payload is None:
            return 404, {"error": f"unknown tenant {tenant!r} "
                                  "(see /tenants)"}
        return 200, payload

    # ----------------------- device-truth plane ------------------------ #

    def device_payload(self) -> dict:
        """``GET /device``: backend provenance, per-device live/peak
        memory, host↔device transfer accounting, the dispatch-overlap
        distribution, the compile summary, and the flight-recorder state
        (``utils.deviceplane``). Session-independent — device truth is
        process truth; the session only adds the per-family transfer and
        overlap views."""
        from spatialflink_tpu.utils import deviceplane

        return deviceplane.device_payload(self._tel())

    def compile_payload(self, with_cost: bool = False) -> dict:
        """``GET /compile``: the compile registry — per-function compile/
        recompile counts, trigger signatures, trace + backend-compile wall
        time, sentinel state. ``?cost=1`` adds lazy one-time
        ``cost_analysis()`` FLOPs/bytes per entry (an AOT compile per
        function — explicitly requested, never ambient)."""
        from spatialflink_tpu.utils import deviceplane

        return deviceplane.registry().snapshot(cost=with_cost)

    def partition_payload(self) -> dict:
        """``/partition``: the skew-adaptive grid's live layout, policy
        thresholds, epoch progress, and recent split/merge decisions
        (``--adaptive-grid``); an explanatory note when the run is on the
        plain uniform grid."""
        from spatialflink_tpu.runtime.repartition import active_controller

        ctl = active_controller()
        if ctl is None:
            return {"adaptive": False,
                    "note": "no adaptive grid in this run "
                            "(enable with --adaptive-grid)"}
        payload = ctl.status()
        payload["adaptive"] = True
        return payload

    _FLEET_NOTE = "not a fleet supervisor (start one with --fleet N)"

    @staticmethod
    def _fleet():
        from spatialflink_tpu.runtime.fleetsup import active_fleet

        return active_fleet()

    def fleet_payload(self) -> dict:
        """``/fleet``: the supervisor's aggregated view of every worker —
        liveness, restarts, heartbeat age, leaf share, and the last polled
        per-worker ``/status``/``/latency`` payloads; an explanatory note
        on a single-process (non-fleet) run."""
        sup = self._fleet()
        if sup is None:
            return {"fleet": False, "note": self._FLEET_NOTE}
        payload = sup.fleet_view()
        payload["fleet"] = True
        return payload

    def fleet_latency_payload(self) -> dict:
        """``/fleet/latency``: the end-to-end record→merged-emit lineage
        (stage-budget table + sums-to-total check once the global merge
        lands; the record→outbox-visible histogram and newest per-worker
        monitor samples mid-run)."""
        sup = self._fleet()
        if sup is None:
            return {"stages": {}, "recent": [], "note": self._FLEET_NOTE}
        return sup.fleet_latency_payload()

    def fleet_timeline_payload(self) -> dict:
        """``/fleet/timeline``: the merged causally-ordered fleet event
        timeline — supervisor lifecycle events interleaved with every
        worker's harvested ``/events`` ring, plus per-lane counts."""
        sup = self._fleet()
        if sup is None:
            return {"events": [], "lanes": {}, "total": 0,
                    "note": self._FLEET_NOTE}
        return sup.fleet_timeline_payload()

    def fleet_events_payload(self, since: Optional[int] = None) -> dict:
        """``/fleet/events``: the merged timeline ring with the same
        ``?since=<seq>`` cursor semantics as a worker's ``/events``."""
        sup = self._fleet()
        if sup is None:
            return {"events": [], "total": 0, "latest_seq": 0,
                    "note": self._FLEET_NOTE}
        return sup.fleet_events_payload(since)

    def fleet_metrics_text(self) -> str:
        """``/fleet/metrics``: one federated Prometheus scrape — every
        worker's ``/metrics`` body relabeled ``worker="wN"`` plus fleet
        gauges (works with the observability plane off: federation only
        needs the worker URLs the supervisor already resolves)."""
        sup = self._fleet()
        if sup is None:
            return f"# {self._FLEET_NOTE}\n"
        return sup.fleet_metrics_text()

    def fleet_tenants_payload(self) -> dict:
        """``/fleet/tenants``: every worker's ``/tenants`` ledger harvested
        concurrently and merged — summed per-tenant rows, fleet-wide
        fairness recomputed over the merged shares (like
        ``/fleet/metrics``, needs only worker URLs, not the monitor)."""
        sup = self._fleet()
        if sup is None:
            return {"tenants": {}, "n": 0, "workers": 0,
                    "note": self._FLEET_NOTE}
        return sup.fleet_tenants_payload()

    # ------------------------------ lifecycle -------------------------- #

    def start(self) -> "OpServer":
        global _ACTIVE_SERVER
        self._httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._httpd.opserver = self  # type: ignore[attr-defined]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="opserver", daemon=True)
        self._thread.start()
        _ACTIVE_SERVER = self
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        global _ACTIVE_SERVER
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if _ACTIVE_SERVER is self:
            _ACTIVE_SERVER = None


# --------------------------------------------------------------------- #
# the stderr digest


_BREAKER_NAMES = {0.0: "closed", 0.5: "half-open", 1.0: "open"}


def format_digest(snap: dict) -> str:
    """One stderr line from one status snapshot — the terminal operator's
    view of the same document ``/status`` serves. Fields with no data yet
    are omitted rather than printed as None/0 noise."""
    st = snap.get("status") or {}
    parts = []
    up = snap.get("uptime_s")
    if up is not None:
        parts.append(f"up {up:.0f}s")
    parts.append(f"in {st.get('records_in', 0)} rec "
                 f"({st.get('throughput_rps', 0.0):.0f}/s)")
    parts.append(f"win {st.get('windows_evaluated', 0)}")
    wl = st.get("window_latency_ms") or {}
    if wl.get("count"):
        parts.append(f"win p99 {wl['p99']:.0f}ms")
    if st.get("watermark_lag_ms") is not None:
        parts.append(f"wm lag {st['watermark_lag_ms']:.0f}ms")
    if st.get("commit_backlog") is not None:
        parts.append(f"backlog {st['commit_backlog']:.0f}")
    pc = st.get("pane_cache") or {}
    if pc.get("hit_rate") is not None:
        parts.append(f"pane hit {pc['hit_rate'] * 100:.0f}%")
    ck = st.get("checkpoint") or {}
    if ck.get("seq") is not None:
        parts.append(f"ckpt #{int(ck['seq'])} age {ck.get('age_s', 0):.1f}s")
    if st.get("breaker_state") is not None:
        parts.append("breaker " + _BREAKER_NAMES.get(
            st["breaker_state"], str(st["breaker_state"])))
    if st.get("dlq_depth"):
        parts.append(f"dlq {st['dlq_depth']}")
    sk = st.get("skew") or {}
    if sk.get("top_share"):
        # skew concentration: the hottest cell's record share + Gini — the
        # numbers the --adaptive-grid split threshold compares against
        gini = sk.get("gini")
        parts.append(f"skew top {sk['top_share'] * 100:.0f}%"
                     + (f" gini {gini:.2f}" if gini is not None else ""))
    tc = st.get("top_cost_cells") or []
    if tc:
        # the costliest grid cell and its attributed kernel share — the
        # skew-cost headline (who pays, not just who's crowded)
        cell, cost_ms, _recs = tc[0]
        total = (snap.get("costs") or {}).get("total_kernel_ms") or 0.0
        share = f" ({cost_ms / total * 100:.0f}%)" if total else ""
        parts.append(f"hot cell {cell} {cost_ms:.0f}ms{share}")
    dev = st.get("device") or {}
    be = dev.get("backend") or {}
    if be:
        # device truth: backend provenance every digest line (the BENCH
        # r05 silent-CPU-fallback lesson) + post-warmup recompiles when
        # the sentinel has fired
        s = f"dev {be.get('platform')}"
        if be.get("target") and not be.get("valid_for_target"):
            s += f"!={be['target']}"
        if dev.get("recompiles"):
            s += f" recompiles {dev['recompiles']}"
        mb = dev.get("mem_bytes_in_use")
        if mb:
            s += f" mem {mb / 1e6:.0f}MB"
        parts.append(s)
    ov = st.get("dispatch_overlap") or {}
    if ov.get("count"):
        # dispatch→ready overlap: how much of the device round-trip hid
        # behind host work (1.0 = fully hidden — the pipeline_depth payoff)
        parts.append(f"ovl {ov['p50'] * 100:.0f}%")
    la = st.get("latency") or {}
    re_h = la.get("record_emit_ms") or {}
    if re_h.get("count"):
        # record→emit p99 + the stage whose residency dominates — the
        # one-glance answer to "where is a record's time going" (full
        # decomposition at GET /latency)
        s = f"lat p99 {re_h['p99']:.0f}ms"
        if la.get("dominant_stage"):
            s += f" ({la['dominant_stage']})"
        if la.get("stall"):
            s += " STALL"
        parts.append(s)
    ctl = st.get("controller") or {}
    if ctl.get("chunk") is not None:
        # the actuator, next to the sensor it reacts to: live decode-chunk
        # setting plus step totals, fast lane, and shedding — one glance
        # answers "what is the governor doing about that latency"
        s = f"chunk {ctl['chunk']}"
        moves = int(ctl.get("grows", 0)) + int(ctl.get("shrinks", 0))
        if moves:
            s += f" ({ctl.get('grows', 0)}+/{ctl.get('shrinks', 0)}-)"
        if ctl.get("fast_lane"):
            s += " fast-lane"
        if ctl.get("shedding"):
            s += " SHED"
        parts.append(s)
    ten = st.get("tenants") or {}
    if ten.get("n", 0) > 1 and ten.get("top"):
        # who pays for this pipeline: the top tenant's attributed kernel
        # share (+ quota refusals when any) — only worth a glance when the
        # run is actually shared (n>1), full ledger at GET /tenants
        s = (f"tenant top {ten['top']} "
             f"{(ten.get('top_share') or 0.0) * 100:.0f}%")
        if ten.get("quota_rejections"):
            s += f" quota-rej {ten['quota_rejections']}"
        parts.append(s)
    deg = snap.get("degradation") or {}
    if deg:
        parts.append(f"degraded x{sum(deg.values())}")
    health = snap.get("health")
    if health is not None:
        bad = [n for n, c in health["checks"].items() if not c["ok"]]
        parts.append("health " + health["status"]
                     + (f" ({','.join(bad)})" if bad else ""))
    return "# live: " + " | ".join(parts)


class LiveStats:
    """Daemon thread printing :func:`format_digest` to stderr — once
    immediately at :meth:`start` (so even a short run shows a line), then
    per ``interval_s``. Reads ``sys.stderr`` at print time so pytest's
    capture and shell redirection both see the lines."""

    def __init__(self, interval_s: float = 5.0, telemetry=None, health=None,
                 registry=None):
        self.interval_s = max(0.01, float(interval_s))
        self.telemetry = telemetry
        self.health = health
        self.registry = registry
        self.emitted = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _tick(self) -> None:
        # pinned/active and explicit/session-health fallbacks are
        # status_snapshot's job (same resolution as /status and the
        # reporter)
        snap = _telemetry.status_snapshot(self.telemetry, health=self.health,
                                          registry=self.registry)
        print(format_digest(snap), file=sys.stderr, flush=True)
        self.emitted += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._tick()

    def start(self) -> "LiveStats":
        self._tick()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="live-stats")
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.interval_s + 5.0)
            self._thread = None
        self._tick()  # final line: the run's closing state
