"""Distance math as branchless, vmap-friendly jnp functions.

Reference parity map (all in degree-space Euclidean unless stated — the
reference's hot paths call JTS ``geom.distance()`` on lon/lat degrees,
``utils/DistanceFunctions.java:14-54``):

- :func:`pp_dist`            <- getPointPointEuclideanDistance (:60-63)
- :func:`haversine`          <- HelperClass.computeHaverSine (HelperClass.java:379-385)
- :func:`point_segment_dist` <- getPointLineSegmentMinEuclideanDistance (:100-131)
- :func:`point_bbox_dist`    <- getPointPolygonBBoxMinEuclideanDistance (:150-200)
- :func:`bbox_bbox_dist`     <- getBBoxBBoxMinEuclideanDistance (:298-421)
- :func:`point_edges_dist`   <- getPointCoordinatesArrayMinEuclideanDistance (:74-85)
- :func:`point_in_rings`     <- JTS areal containment (even-odd ray cast)
- :func:`point_polygon_dist` <- JTS Point.distance(Polygon): 0 inside, else
                                min boundary distance
- :func:`seg_seg_dist` / :func:`edges_edges_dist` <- JTS boundary-boundary
                                distance (0 when boundaries cross)

Precision model: device coordinates are float32 absolute degrees. The f32
quantum at |x| ~ 116 deg is ~7.6e-6 deg (<1 m), which bounds every distance
below; the reference's canonical radii (0.005-0.5 deg) sit 3-5 orders of
magnitude above that floor. Kernels avoid *adding* error on top of storage
quantization (centered matmul expansion in ops.join, squared-distance
comparisons instead of sqrt).

Conventions: every "batch" geometry is a padded edge array
``edges: (..., E, 4)`` holding ``[x1, y1, x2, y2]`` per edge plus a boolean
``edge_mask: (..., E)``; padded edges must be excluded by the mask.  All
functions are elementwise over leading dims and safe under jit/vmap.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

EARTH_RADIUS_M = 6371008.7714  # HelperClass.java:50

_BIG = np.float32(3.4e38)  # sentinel "infinity" that survives f32 math


def pp_dist(x1, y1, x2, y2):
    """Euclidean point-point distance (degree space)."""
    return jnp.sqrt((x2 - x1) ** 2 + (y2 - y1) ** 2)


def pp_dist2(x1, y1, x2, y2):
    """Squared distance — prefer for comparisons; avoids the sqrt."""
    return (x2 - x1) ** 2 + (y2 - y1) ** 2


def haversine(lon1, lat1, lon2, lat2, radius=EARTH_RADIUS_M):
    """Great-circle distance in meters.

    Deliberate deviation: the reference's ``HelperClass.computeHaverSine``
    (HelperClass.java:379-385) is actually the spherical *law of cosines*
    (``acos(sin·sin + cos·cos·cos(dLon))·R``) despite its name, which loses
    all precision near acos(1) for close points. We use the true haversine
    formulation, which is numerically stable at small distances — that is
    where a radius predicate needs precision. For the law-of-cosines bitwise
    behavior use :func:`great_circle_law_of_cosines`.
    """
    lon1, lat1, lon2, lat2 = (jnp.deg2rad(v) for v in (lon1, lat1, lon2, lat2))
    dlat, dlon = lat2 - lat1, lon2 - lon1
    a = jnp.sin(dlat / 2) ** 2 + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin(dlon / 2) ** 2
    return 2 * radius * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def great_circle_law_of_cosines(lon1, lat1, lon2, lat2, radius=EARTH_RADIUS_M):
    """Exact formula of the reference's ``computeHaverSine`` (see above)."""
    lat1r, lat2r = jnp.deg2rad(lat1), jnp.deg2rad(lat2)
    dlon = jnp.deg2rad(lon2 - lon1)
    c = jnp.sin(lat1r) * jnp.sin(lat2r) + jnp.cos(lat1r) * jnp.cos(lat2r) * jnp.cos(dlon)
    return jnp.arccos(jnp.clip(c, -1.0, 1.0)) * radius


def point_segment_dist2(px, py, x1, y1, x2, y2):
    """Squared min distance from point to segment, branchless.

    Zero-length segments degrade to point distance (the reference sets
    param=-1 in that case, which clamps to the first endpoint — identical
    result since both endpoints coincide).
    """
    cx, cy = x2 - x1, y2 - y1
    len_sq = cx * cx + cy * cy
    # reciprocal BEFORE combining with the point operand: in the broadcast
    # lattices ((N, G, E) points x edges) this line has the edge shape only,
    # so the expensive divide runs O(G*E) times, not O(N*G*E) — the
    # per-point work below is multiply/add (measured +15% on config 4's CPU
    # bench; the divide is costlier still on the TPU VPU). A divide-free
    # cross-product form of the point_in_rings ray test was ALSO tried and
    # measured 25% SLOWER on CPU — see benchmarks/TPU_NOTES.md §5.
    inv_len = jnp.where(len_sq > 0, 1.0 / jnp.where(len_sq > 0, len_sq, 1.0),
                        0.0)
    dot = (px - x1) * cx + (py - y1) * cy
    t = jnp.clip(dot * inv_len, 0.0, 1.0)
    qx, qy = x1 + t * cx, y1 + t * cy
    return pp_dist2(px, py, qx, qy)


def point_segment_dist(px, py, x1, y1, x2, y2):
    return jnp.sqrt(point_segment_dist2(px, py, x1, y1, x2, y2))


def point_bbox_dist(px, py, bx1, by1, bx2, by2):
    """Min distance from a point to an axis-aligned box; 0 inside.

    Branchless equivalent of the 9-way case split in
    ``getPointPolygonBBoxMinEuclideanDistance`` (DistanceFunctions.java:150-200).
    """
    dx = jnp.maximum(jnp.maximum(bx1 - px, px - bx2), 0.0)
    dy = jnp.maximum(jnp.maximum(by1 - py, py - by2), 0.0)
    return jnp.sqrt(dx * dx + dy * dy)


def bbox_bbox_dist(a, b):
    """Min distance between two boxes given as (..., 4) [minx,miny,maxx,maxy];
    0 when they overlap (DistanceFunctions.java:298-421)."""
    ax1, ay1, ax2, ay2 = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    bx1, by1, bx2, by2 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    dx = jnp.maximum(jnp.maximum(ax1 - bx2, bx1 - ax2), 0.0)
    dy = jnp.maximum(jnp.maximum(ay1 - by2, by1 - ay2), 0.0)
    return jnp.sqrt(dx * dx + dy * dy)


def point_edges_dist2(px, py, edges, edge_mask):
    """Squared min distance from point (px,py) to a masked edge array.

    edges: (E, 4), edge_mask: (E,). Invalid edges contribute +inf.
    """
    d2 = point_segment_dist2(px, py, edges[..., 0], edges[..., 1], edges[..., 2], edges[..., 3])
    return jnp.min(jnp.where(edge_mask, d2, _BIG), axis=-1)


def point_in_rings(px, py, edges, edge_mask):
    """Even-odd (ray cast) point-in-polygon over a masked edge array.

    Because every ring contributes its own closed edge loop to ``edges``,
    holes are handled naturally by crossing parity.  Horizontal edges and
    padded (masked / zero-length) edges contribute no crossings.
    """
    x1, y1 = edges[..., 0], edges[..., 1]
    x2, y2 = edges[..., 2], edges[..., 3]
    # half-open rule on y avoids double-counting shared vertices
    straddles = (y1 > py) != (y2 > py)
    # slope hoisted onto the edge shape: the divide runs O(G*E) times, the
    # (N, G, E) per-point lattice below is multiply/add/compare only (same
    # trick as point_segment_dist2's inv_len; straddles already excludes
    # horizontal edges, so the denom guard only protects padded slots)
    denom = jnp.where(y2 == y1, 1.0, y2 - y1)
    slope = (x2 - x1) / denom
    x_at_y = x1 + (py - y1) * slope
    crossing = straddles & edge_mask & (px < x_at_y)
    return jnp.sum(crossing.astype(jnp.int32), axis=-1) % 2 == 1


def point_polygon_dist(px, py, edges, edge_mask):
    """JTS ``Point.distance(Polygon)`` semantics: 0 if the point is inside the
    areal geometry (outer ring minus holes), else min boundary distance."""
    inside = point_in_rings(px, py, edges, edge_mask)
    bdist = jnp.sqrt(point_edges_dist2(px, py, edges, edge_mask))
    return jnp.where(inside, 0.0, bdist)


def _orient(ax, ay, bx, by, cx, cy):
    """Sign of the cross product (b-a) x (c-a)."""
    return (bx - ax) * (cy - ay) - (by - ay) * (cx - ax)


def segments_intersect(a, b):
    """Proper-or-touching intersection test for segments a=(x1,y1,x2,y2),
    b likewise; broadcasts over leading dims."""
    ax1, ay1, ax2, ay2 = a[..., 0], a[..., 1], a[..., 2], a[..., 3]
    bx1, by1, bx2, by2 = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    d1 = _orient(bx1, by1, bx2, by2, ax1, ay1)
    d2 = _orient(bx1, by1, bx2, by2, ax2, ay2)
    d3 = _orient(ax1, ay1, ax2, ay2, bx1, by1)
    d4 = _orient(ax1, ay1, ax2, ay2, bx2, by2)
    proper = ((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0))
    # collinear/touching cases are covered by the endpoint-distance terms in
    # seg_seg_dist2 (distance 0 when an endpoint lies on the other segment)
    return proper


def seg_seg_dist2(a, b):
    """Squared min distance between two segments; 0 if they intersect."""
    d2 = jnp.minimum(
        jnp.minimum(
            point_segment_dist2(a[..., 0], a[..., 1], b[..., 0], b[..., 1], b[..., 2], b[..., 3]),
            point_segment_dist2(a[..., 2], a[..., 3], b[..., 0], b[..., 1], b[..., 2], b[..., 3]),
        ),
        jnp.minimum(
            point_segment_dist2(b[..., 0], b[..., 1], a[..., 0], a[..., 1], a[..., 2], a[..., 3]),
            point_segment_dist2(b[..., 2], b[..., 3], a[..., 0], a[..., 1], a[..., 2], a[..., 3]),
        ),
    )
    return jnp.where(segments_intersect(a, b), 0.0, d2)


def edges_edges_dist2(edges_a, mask_a, edges_b, mask_b):
    """Squared min distance between two masked edge sets (boundary-boundary).

    edges_a: (Ea, 4), edges_b: (Eb, 4). Cost is Ea*Eb — intended for
    per-candidate-pair evaluation after bbox/grid pruning, exactly where the
    reference runs JTS exact math.
    """
    d2 = seg_seg_dist2(edges_a[..., :, None, :], edges_b[..., None, :, :])
    valid = mask_a[..., :, None] & mask_b[..., None, :]
    return jnp.min(jnp.where(valid, d2, _BIG), axis=(-2, -1))
