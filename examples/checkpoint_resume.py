"""Kill/resume a stateful trajectory query from its checkpoint.

Runs realtime per-trajectory stats (tStats) over the first part of a
stream, checkpointing as it goes; then "crashes", and a second operator
resumes from the snapshot and consumes only the remainder. The final state
equals an uninterrupted run — the reference inherits this from Flink
checkpointing; here the snapshot/restore is explicit (`runtime/state.py`).

Run: python examples/checkpoint_resume.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from examples._common import ensure_backend

ensure_backend()  # fall back to CPU if the accelerator tunnel is wedged

import numpy as np

from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (
    PointTStatsQuery,
    QueryConfiguration,
    QueryType,
)


def stream(grid, lo, hi):
    rng = np.random.default_rng(3)
    t0 = 1_700_000_000_000
    xs = rng.uniform(116, 117, 400)
    ys = rng.uniform(40, 41, 400)
    pts = [Point.create(float(xs[i]), float(ys[i]), grid,
                        obj_id=f"traj{i % 7}", timestamp=t0 + i * 1000)
           for i in range(400)]
    return pts[lo:hi]


def main() -> int:
    grid = UniformGrid(115.50, 117.60, 39.60, 41.10, num_grid_partitions=100)
    conf = lambda: QueryConfiguration(QueryType.RealTime,
                                      realtime_batch_size=32)
    cp = os.path.join(tempfile.mkdtemp(), "tstats.npz")

    full = list(PointTStatsQuery(conf(), grid).run(iter(stream(grid, 0, 400))))

    # first run consumes 0..250, checkpointing every micro-batch, then "dies"
    list(PointTStatsQuery(conf(), grid).run(
        iter(stream(grid, 0, 250)), checkpoint_path=cp, checkpoint_every=1))
    consumed = PointTStatsQuery.checkpoint_consumed(cp)
    print(f"crashed after checkpoint; consumed offset = {consumed}")

    # resume: the operator restores STATE; the SOURCE must skip the already-
    # consumed prefix itself (slice a file replay by the recorded offset, as
    # here and in the driver's --resume; an offset-managed source like a
    # Kafka consumer group seeks instead). Feeding the full stream again
    # would double-count.
    resumed = list(PointTStatsQuery(conf(), grid).run(
        iter(stream(grid, consumed, 400)), checkpoint_path=cp))

    # realtime emissions cover the trajectories touched by each micro-batch,
    # and batch boundaries differ between the two runs — compare the LAST
    # reported stats per trajectory (the accumulated state), not one batch
    def final_stats(results):
        out = {}
        for w in results:
            for r in w.records:
                out[r[0]] = r[1:4]  # (spatial_len, temporal_len, speed)
        return out

    last_full = final_stats(full)
    last_res = final_stats(resumed)
    assert last_full.keys() == last_res.keys()
    for k in last_full:  # f32 length accumulation may differ in the last
        #                  bit across the checkpoint boundary — state parity,
        #                  not bitwise replay
        np.testing.assert_allclose(last_full[k], last_res[k], rtol=1e-5)
    print(f"resumed run matches uninterrupted run: "
          f"{len(last_full)} trajectories, e.g. "
          + ", ".join(f"{k}: len={v[0]:.3f}" for k, v in
                      sorted(last_full.items())[:3]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
