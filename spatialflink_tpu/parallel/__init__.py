"""Multi-device execution: meshes, sharded window batches, collective merges.

This package replaces the reference's distribution mechanisms (SURVEY §2.5):
Flink's keyBy hash shuffle becomes an 8-way (or pod-scale) device mesh with
window batches sharded across devices; the parallelism-1 ``windowAll`` global
merges become all-gather + re-top-k tree merges on ICI; query objects are
broadcast (replicated sharding) instead of flatMap-replicated per cell.
"""

from spatialflink_tpu.parallel.mesh import (
    init_distributed,
    make_mesh,
    make_mesh_2d,
    shard_batch,
)
from spatialflink_tpu.parallel.ops import (
    distributed_knn,
    distributed_knn_hierarchical,
    distributed_range_count,
    distributed_join_counts,
)

__all__ = [
    "init_distributed",
    "make_mesh",
    "make_mesh_2d",
    "shard_batch",
    "distributed_knn",
    "distributed_knn_hierarchical",
    "distributed_range_count",
    "distributed_join_counts",
]
