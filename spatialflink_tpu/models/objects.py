"""Host-side spatial objects (reference: ``spatialObjects/*.java``).

These are lightweight records used on the ingest/egress paths and as query
geometries; they never cross onto the device. Device work happens on the
padded batches built from them (:mod:`spatialflink_tpu.models.batches`).

Reference parity notes:
- Every object carries ``obj_id`` + ``timestamp`` (``SpatialObject.java:27-35``)
  and an ``ingestion_time`` stamped at construction (``Point.java:43,57``) used
  for latency metrics.
- ``Polygon`` accepts multiple rings; rings are auto-closed
  (``Polygon.java:147-153``) and the largest-area ring is the shell, the rest
  holes (``createPolygonArray`` sorts by area, ``Polygon.java:117-144``).
- Grid cells: points get one cell; polygons/linestrings get the set of cells
  overlapped by their bounding box (``HelperClass.java:123-143``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

from spatialflink_tpu.index import UniformGrid

Coord = Tuple[float, float]


def _now_ms() -> int:
    return int(time.time() * 1000)


def _ring_area(ring: Sequence[Coord]) -> float:
    """Absolute shoelace area of a ring."""
    a = np.asarray(ring, dtype=np.float64)
    x, y = a[:, 0], a[:, 1]
    return abs(float(np.dot(x, np.roll(y, -1)) - np.dot(y, np.roll(x, -1)))) / 2


def _close_ring(ring: Sequence[Coord]) -> List[Coord]:
    ring = [tuple(map(float, c)) for c in ring]
    if ring and ring[0] != ring[-1]:
        ring.append(ring[0])
    return ring


def _coords_bbox(coords: np.ndarray) -> Tuple[float, float, float, float]:
    return (
        float(coords[:, 0].min()),
        float(coords[:, 1].min()),
        float(coords[:, 0].max()),
        float(coords[:, 1].max()),
    )


@dataclass
class SpatialObject:
    """Base record: object id + event timestamp (epoch millis)."""

    obj_id: str = ""
    timestamp: int = 0
    ingestion_time: int = field(default_factory=_now_ms)


@dataclass
class Point(SpatialObject):
    x: float = 0.0
    y: float = 0.0
    cell: int = -1  # int cell id; -1 = unassigned / outside grid
    # DEIM check-in fields (Point.java:44-46)
    event_id: str = ""
    device_id: str = ""
    user_id: str = ""

    @classmethod
    def create(
        cls,
        x: float,
        y: float,
        grid: Optional[UniformGrid] = None,
        obj_id: str = "",
        timestamp: int = 0,
        **kw,
    ) -> "Point":
        p = cls(obj_id=obj_id, timestamp=timestamp, x=float(x), y=float(y), **kw)
        if grid is not None:
            cell, _ = grid.assign_cell(p.x, p.y)
            p.cell = int(cell)
        return p

    @property
    def coords(self) -> np.ndarray:
        return np.array([[self.x, self.y]], dtype=np.float64)


@dataclass
class _EdgeGeom(SpatialObject):
    """Shared machinery for polygons / linestrings: ring/path lists, bbox,
    grid-cell set, and a padded edge-array view."""

    bbox: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    cells: Set[int] = field(default_factory=set)
    cell: int = -1  # representative cell (reference keeps one gridID too)

    def _assign_cells(self, grid: Optional[UniformGrid]) -> None:
        if grid is None:
            return
        self.cells = grid.bbox_cells(*self.bbox)
        if self.cells:
            # representative cell: the cell of the bbox centroid if valid,
            # else any overlapped cell (reference stores the first of the set)
            cx = (self.bbox[0] + self.bbox[2]) / 2
            cy = (self.bbox[1] + self.bbox[3]) / 2
            c, valid = grid.assign_cell(cx, cy)
            self.cell = int(c) if valid and int(c) in self.cells else min(self.cells)

    def edge_array(self) -> Tuple[np.ndarray, np.ndarray]:
        """-> (edges (E,4) f64, mask (E,) bool) — no padding at this level."""
        raise NotImplementedError


@dataclass
class Polygon(_EdgeGeom):
    """Polygon with optional holes. ``rings[0]`` is the shell."""

    rings: List[List[Coord]] = field(default_factory=list)

    @classmethod
    def create(
        cls,
        rings: Sequence[Sequence[Coord]],
        grid: Optional[UniformGrid] = None,
        obj_id: str = "",
        timestamp: int = 0,
    ) -> "Polygon":
        closed = [_close_ring(r) for r in rings if len(r) >= 3]
        if not closed:
            raise ValueError("polygon needs at least one ring of >= 3 coords")
        # shell = largest-area ring, mirroring Polygon.createPolygonArray
        closed.sort(key=_ring_area, reverse=True)
        p = cls(obj_id=obj_id, timestamp=timestamp, rings=closed)
        all_coords = np.concatenate([np.asarray(r, np.float64) for r in closed])
        p.bbox = _coords_bbox(all_coords)
        p._assign_cells(grid)
        return p

    def edge_array(self) -> Tuple[np.ndarray, np.ndarray]:
        segs = []
        for ring in self.rings:
            r = np.asarray(ring, dtype=np.float64)
            segs.append(np.concatenate([r[:-1], r[1:]], axis=1))
        edges = np.concatenate(segs, axis=0)
        return edges, np.ones(len(edges), dtype=bool)


@dataclass
class LineString(_EdgeGeom):
    coords_list: List[Coord] = field(default_factory=list)

    @classmethod
    def create(
        cls,
        coords: Sequence[Coord],
        grid: Optional[UniformGrid] = None,
        obj_id: str = "",
        timestamp: int = 0,
    ) -> "LineString":
        cc = [tuple(map(float, c)) for c in coords]
        if len(cc) < 2:
            raise ValueError("linestring needs >= 2 coords")
        ls = cls(obj_id=obj_id, timestamp=timestamp, coords_list=cc)
        ls.bbox = _coords_bbox(np.asarray(cc, np.float64))
        ls._assign_cells(grid)
        return ls

    def edge_array(self) -> Tuple[np.ndarray, np.ndarray]:
        r = np.asarray(self.coords_list, dtype=np.float64)
        edges = np.concatenate([r[:-1], r[1:]], axis=1)
        return edges, np.ones(len(edges), dtype=bool)


@dataclass
class MultiPoint(SpatialObject):
    points: List[Coord] = field(default_factory=list)
    bbox: Tuple[float, float, float, float] = (0.0, 0.0, 0.0, 0.0)
    cells: Set[int] = field(default_factory=set)
    cell: int = -1

    @classmethod
    def create(cls, coords, grid=None, obj_id="", timestamp=0) -> "MultiPoint":
        cc = [tuple(map(float, c)) for c in coords]
        mp = cls(obj_id=obj_id, timestamp=timestamp, points=cc)
        arr = np.asarray(cc, np.float64)
        mp.bbox = _coords_bbox(arr)
        if grid is not None:
            mp.cells = grid.bbox_cells(*mp.bbox)
            cell, valid = grid.assign_cell(*cc[0])
            mp.cell = int(cell)
        return mp


@dataclass
class MultiPolygon(_EdgeGeom):
    """Multiple polygons under one object id (``MultiPolygon.java:13-35``)."""

    polygons: List[Polygon] = field(default_factory=list)

    @classmethod
    def create(cls, list_of_rings, grid=None, obj_id="", timestamp=0) -> "MultiPolygon":
        polys = [Polygon.create(rings, None, obj_id, timestamp) for rings in list_of_rings]
        mp = cls(obj_id=obj_id, timestamp=timestamp, polygons=polys)
        boxes = np.asarray([p.bbox for p in polys])
        mp.bbox = (boxes[:, 0].min(), boxes[:, 1].min(), boxes[:, 2].max(), boxes[:, 3].max())
        mp._assign_cells(grid)
        return mp

    def edge_array(self):
        parts = [p.edge_array()[0] for p in self.polygons]
        edges = np.concatenate(parts, axis=0)
        return edges, np.ones(len(edges), dtype=bool)


@dataclass
class MultiLineString(_EdgeGeom):
    lines: List[LineString] = field(default_factory=list)

    @classmethod
    def create(cls, list_of_coords, grid=None, obj_id="", timestamp=0) -> "MultiLineString":
        lines = [LineString.create(c, None, obj_id, timestamp) for c in list_of_coords]
        ml = cls(obj_id=obj_id, timestamp=timestamp, lines=lines)
        boxes = np.asarray([l.bbox for l in lines])
        ml.bbox = (boxes[:, 0].min(), boxes[:, 1].min(), boxes[:, 2].max(), boxes[:, 3].max())
        ml._assign_cells(grid)
        return ml

    def edge_array(self):
        parts = [l.edge_array()[0] for l in self.lines]
        edges = np.concatenate(parts, axis=0)
        return edges, np.ones(len(edges), dtype=bool)


@dataclass
class GeometryCollection(SpatialObject):
    """Heterogeneous component list (``GeometryCollection.java:13-40``)."""

    geometries: List[SpatialObject] = field(default_factory=list)

    @classmethod
    def create(cls, geometries, obj_id="", timestamp=0) -> "GeometryCollection":
        return cls(obj_id=obj_id, timestamp=timestamp, geometries=list(geometries))
