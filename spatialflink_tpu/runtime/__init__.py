"""Host streaming runtime: event time, windows, pipelines, keyed state.

This layer replaces what the reference delegates to Flink: watermarks
(``BoundedOutOfOrdernessTimestampExtractor``), sliding/tumbling window
assignment, window buffers, and the operator driver loop. Windows seal on
the event-time watermark and are handed to device kernels as padded batches.

Documented deviation (SURVEY §7 "hard parts"): the reference mixes
*processing-time* windows under an event-time characteristic
(``PointPointRangeQuery.java:116`` vs ``:177``). We implement clean
event-time windows throughout; processing-time behavior is recovered by
stamping arrival time as the event time at the source.
"""

from spatialflink_tpu.runtime.watermarks import BoundedOutOfOrderness
from spatialflink_tpu.runtime.windows import (PaneBuffer, WindowAssembler,
                                              WindowSpec)
from spatialflink_tpu.runtime.faults import (
    ChaosBroker,
    FaultPlan,
    TransientBrokerError,
)
from spatialflink_tpu.runtime.supervisor import (
    CircuitBreaker,
    CircuitOpenError,
    DeadLetterQueue,
    RetryError,
    RetryPolicy,
    SupervisedBroker,
)
from spatialflink_tpu.runtime.checkpoint import (
    CheckpointCoordinator,
    CheckpointMismatch,
    CheckpointTap,
)
from spatialflink_tpu.runtime.state import (
    CheckpointableState,
    CheckpointCorrupt,
    TrajStateStore,
)
from spatialflink_tpu.runtime.health import HealthEvaluator
from spatialflink_tpu.runtime.opserver import LiveStats, OpServer
from spatialflink_tpu.runtime.queryplane import (
    ControlTopicConsumer,
    QueryRegistry,
    QueryRouter,
    QuerySpec,
    QuerySpecError,
    QueryState,
)

__all__ = [
    "CheckpointCoordinator",
    "CheckpointMismatch",
    "CheckpointTap",
    "CheckpointableState",
    "CheckpointCorrupt",
    "TrajStateStore",
    "BoundedOutOfOrderness",
    "WindowSpec",
    "WindowAssembler",
    "PaneBuffer",
    "ChaosBroker",
    "FaultPlan",
    "TransientBrokerError",
    "CircuitBreaker",
    "CircuitOpenError",
    "DeadLetterQueue",
    "RetryError",
    "RetryPolicy",
    "SupervisedBroker",
    "HealthEvaluator",
    "LiveStats",
    "OpServer",
    "ControlTopicConsumer",
    "QueryRegistry",
    "QueryRouter",
    "QuerySpec",
    "QuerySpecError",
    "QueryState",
]
