"""Config system + driver dispatch tests (reference: Params.java /
StreamingJob.java switch)."""

import io
import json
import textwrap

import pytest

from spatialflink_tpu.config import ConfigError, Params
from spatialflink_tpu.driver import CASES, CaseSpec, main, run_option
from spatialflink_tpu.index import UniformGrid
from spatialflink_tpu.models import Point
from spatialflink_tpu.operators import (
    PointPointRangeQuery,
    QueryConfiguration,
    QueryType,
    WindowResult,
)
from spatialflink_tpu.streams.formats import serialize_spatial
from spatialflink_tpu.streams.sources import SyntheticPointSource

CONF = "conf/spatialflink-conf.yml"


# ------------------------------------------------------------------ config


def test_sample_conf_loads():
    p = Params.from_yaml(CONF)
    assert p.query.option == 1
    assert p.input1.format == "GeoJSON"
    assert p.input1.grid_bbox == (115.5, 39.6, 117.6, 41.1)
    assert p.window.interval_s == 10 and p.window.step_s == 5
    g1, g2 = p.grids()
    assert g1.n == 100 and g2.n == 100


def test_reference_conf_compat(tmp_path):
    """A reference-style file with the java type tag and TSV escapes loads."""
    y = tmp_path / "ref.yml"
    y.write_text(textwrap.dedent("""\
        !!GeoFlink.utils.ConfigType
        clusterMode: False
        kafkaBootStrapServers: "localhost:9092"
        inputStream1:
          topicName: "t"
          format: "CSV"
          dateFormat: "yyyy-MM-dd HH:mm:ss"
          csvTsvSchemaAttr: [1, 4, 5, 6]
          gridBBox: [115.5, 39.6, 117.6, 41.1]
          numGridCells: 50
          cellLength: 0
          delimiter: "\\\\t"
        outputStream: {topicName: "o"}
        query:
          option: 51
          radius: 0.05
          k: 3
          thresholds: {trajDeletion: 1000, outOfOrderTuples: 2}
        window: {type: "TIME", interval: 5, step: 5}
        """))
    p = Params.from_yaml(str(y))
    assert p.input1.delimiter == "\t"
    assert p.input1.csv_tsv_schema == [1, 4, 5, 6]
    assert p.input1.date_format == "%Y-%m-%d %H:%M:%S"
    assert p.query.allowed_lateness_s == 2
    # inputStream2 defaults to inputStream1
    assert p.input2.topic_name == "t"


@pytest.mark.parametrize("mutate,err_key", [
    (lambda d: d["inputStream1"].pop("topicName"), "topicName"),
    (lambda d: d["inputStream1"].update(format="SHP"), "format"),
    (lambda d: d["inputStream1"].update(numGridCells=0, cellLength=0),
     "numGridCells"),
    (lambda d: d["query"].pop("option"), "option"),
    (lambda d: d["window"].update(interval=0), "interval"),
    (lambda d: d["query"].update(aggregateFunction="MODE"), "aggregateFunction"),
])
def test_validation_errors(mutate, err_key):
    import yaml

    with open(CONF) as f:
        d = yaml.safe_load(f)
    mutate(d)
    with pytest.raises(ConfigError):
        Params.from_dict(d)


# ------------------------------------------------------------------ dispatch


def test_case_table_shape():
    # 9 pairs x {window, realtime} x {range, knn, join} = 54 core cases
    core = [s for s in CASES.values()
            if s.family in ("range", "knn", "join") and not s.latency]
    assert len(core) == 54
    assert CASES[1] == CaseSpec("range", "Point", "Point", "window")
    assert CASES[42].family == "range" and CASES[42].mode == "realtime"
    assert CASES[51].family == "knn" and CASES[91].query == "LineString"
    assert CASES[141].family == "join"
    assert CASES[8].latency and CASES[59].latency and CASES[108].latency
    assert CASES[2030].naive and CASES[2090].naive and CASES[2011].naive
    assert CASES[501].fmt == "WKT" and not CASES[501].timestamped
    assert CASES[906].fmt == "TSV" and CASES[906].timestamped


def _params(option: int, **qkw) -> Params:
    p = Params.from_yaml(CONF)
    p.query.option = option
    for k, v in qkw.items():
        setattr(p.query, k, v)
    return p


def _synth_lines(n_traj=8, steps=6):
    grid = UniformGrid(115.5, 117.6, 39.6, 41.1, num_grid_partitions=100)
    pts = list(SyntheticPointSource(grid, num_trajectories=n_traj,
                                    steps=steps, seed=3))
    return [serialize_spatial(p, "GeoJSON") for p in pts], pts, grid


def test_option1_matches_direct_operator():
    lines, pts, grid = _synth_lines()
    p = _params(1, radius=0.5)
    via_driver = list(run_option(p, lines))
    conf = QueryConfiguration(QueryType.WindowBased, 10_000, 5_000)
    direct = list(PointPointRangeQuery(conf, grid).run(
        iter(pts), Point.create(116.5, 40.5, grid), 0.5))
    assert len(via_driver) == len(direct) > 0
    for a, b in zip(via_driver, direct):
        assert a.window_start == b.window_start
        assert sorted(r.obj_id for r in a.records) == \
            sorted(r.obj_id for r in b.records)


def test_option2_realtime():
    lines, _, _ = _synth_lines()
    out = list(run_option(_params(2, radius=0.5), lines))
    assert out and all(isinstance(r, WindowResult) for r in out)


def test_option51_knn():
    lines, _, _ = _synth_lines()
    out = list(run_option(_params(51, radius=0.0, k=3), lines))
    assert out
    for r in out:
        assert r.extras["k"] == 3
        assert len(r.records) <= 3
        dists = [d for _, d in r.records]
        assert dists == sorted(dists)


def test_option101_join_needs_stream2():
    lines, _, _ = _synth_lines()
    with pytest.raises(ValueError):
        list(run_option(_params(101), lines))
    out = list(run_option(_params(101, radius=0.3), lines, lines[:12]))
    assert any(r.records for r in out)


def test_option8_latency_extras():
    lines, _, _ = _synth_lines()
    out = list(run_option(_params(8, radius=0.5), lines))
    assert out
    assert all("latency_ms" in r.extras for r in out)
    assert all(l >= 0 for r in out for l in r.extras["latency_ms"])


def test_trajectory_options():
    lines, _, _ = _synth_lines()
    # TStats realtime (205)
    out = list(run_option(_params(205), lines))
    assert out
    # TFilter windowed (202) with an explicit id set
    p = _params(202)
    p.query.traj_ids = ["traj-0", "traj-1"]
    out = list(run_option(p, lines))
    ids = {r.obj_id for w in out for r in w.records}
    assert ids and ids <= {"traj-0", "traj-1"}
    # TKNN naive twin (2011) agrees with pruned (211) on result object ids
    pruned = list(run_option(_params(211, radius=0.8, k=4), lines))
    naive = list(run_option(_params(2011, radius=0.8, k=4), lines))
    def flat(ws):
        return sorted({rec[0] if isinstance(rec, tuple) else rec.obj_id
                       for w in ws for rec in w.records})
    assert flat(pruned) == flat(naive)


def test_deser_roundtrip_options():
    lines, pts, _ = _synth_lines(n_traj=2, steps=2)
    # 701: GeoJSON trajectory round-trip
    out = list(run_option(_params(701), lines))
    assert len(out) == len(lines)
    for obj, ser in out:
        assert obj.obj_id.startswith("traj-")
        assert json.loads(ser)["geometry"]["type"] == "Point"
    # 501: WKT CSV point round-trip
    wkt_lines = [serialize_spatial(p, "WKT") for p in pts]
    out = list(run_option(_params(501), wkt_lines))
    assert all("POINT" in ser for _, ser in out)


def test_deser_geometrycollection_options():
    """Driver cases 504/604/804/904: WKT GeometryCollection round-trips
    (plain + trajectory, comma + TAB), Deserialization.java:836,854."""
    gc_wkt = ("GEOMETRYCOLLECTION (POINT (116.5 40.5), "
              "LINESTRING (116.0 40.0, 116.1 40.1))")
    for option, line in [
        (504, gc_wkt),
        (604, gc_wkt),
        (804, f"t9, 1700000000000, {gc_wkt}"),
        (904, f"t9\t1700000000000\t{gc_wkt}"),
    ]:
        (obj, ser), = run_option(_params(option), [line])
        assert type(obj).__name__ == "GeometryCollection", option
        assert len(obj.geometries) == 2, option
        if option in (804, 904):
            # trajectory variants carry oid/ts through serialization as
            # prefix fields (the reference's WKT output schemas include
            # both, Serialization.java:53-96; prefix-normalized here)
            assert obj.obj_id == "t9" and obj.timestamp == 1700000000000
            assert ser.startswith("t9"), option
            assert "GEOMETRYCOLLECTION (" in ser, option
        else:
            assert ser.startswith("GEOMETRYCOLLECTION ("), option


def test_tsv_wkt_deser_uses_tab():
    """Options 601-605/901-905 are the TAB-separated WKT families: prefix
    fields must split on TAB regardless of the configured delimiter."""
    line = "obj7\t1700000000000\tPOINT (116.5 40.5)"
    out = list(run_option(_params(901), [line]))
    (obj, ser), = out
    assert obj.obj_id == "obj7"
    assert obj.timestamp == 1700000000000
    assert CASES[601].delim == "\t" and CASES[501].delim is None


def test_count_window_type_drives_count_mode():
    """window.type COUNT runs sliding count windows through the driver
    (implemented here; the reference declares CountBased and throws "Not
    yet support", QueryType.java:6). Joins still raise — the count trigger
    is ambiguous over two streams."""
    p = _params(1, radius=0.5)
    p.window.type = "COUNT"
    p.window.interval_s = 8   # COUNTS in count mode, like tAggregate
    p.window.step_s = 4
    lines, pts, _ = _synth_lines(n_traj=4, steps=6)
    out = list(run_option(p, lines))
    assert len(out) == len(pts) // 4
    p.query.option = 101
    with pytest.raises(NotImplementedError):
        list(run_option(p, lines, lines))


def test_synthetic_harness_option99():
    """One smoke run exercises every trajectory family, like the reference
    harness sketch (StreamingJob.java:1571-1618)."""
    out = list(run_option(_params(99), []))
    assert out
    fams = {r.extras.get("family") for r in out if hasattr(r, "extras")}
    assert fams == {"tfilter", "trange", "tstats", "taggregate",
                    "tjoin", "tknn"}


def test_unknown_option():
    with pytest.raises(ValueError):
        list(run_option(_params(4999), []))


def test_query_geometry_bracket_string_forms():
    """queryPoints/queryPolygons accept the reference's CLI bracket-string
    form (HelperClass.java:145-179) as well as YAML lists."""
    from spatialflink_tpu.config import QueryConfig

    q = QueryConfig.from_dict({
        "option": 1,
        "queryPoints": "[116.5, 40.5], [117.0, 40.7]",
        "queryPolygons": "[[116.5, 40.5], [117.6, 40.5], [117.6, 41.4]], "
                         "[[117.5, 40.5], [118.6, 40.5], [118.6, 41.4]]",
    })
    assert q.query_points == [(116.5, 40.5), (117.0, 40.7)]
    assert len(q.query_polygons) == 2
    assert q.query_polygons[0][0] == (116.5, 40.5)


# ------------------------------------------------------------------ CLI


def test_cli_main(tmp_path, capsys):
    lines, _, _ = _synth_lines(n_traj=4, steps=4)
    inp = tmp_path / "in.jsonl"
    inp.write_text("\n".join(lines) + "\n")
    rc = main(["--config", CONF, "--input1", str(inp), "--option", "1"])
    assert rc == 0
    cap = capsys.readouterr()
    assert "emitted" in cap.err
    assert "window" in cap.out


def test_output_file_writes_serialized_records(tmp_path):
    """--output writes every result record serialized in --output-format —
    the reference's output Kafka topic (Serialization.java schemas), as a
    file."""
    lines, pts, grid = _synth_lines()
    inp = tmp_path / "pts.geojson"
    inp.write_text("\n".join(lines))
    import shutil

    cfg = tmp_path / "conf.yml"
    shutil.copy(CONF, cfg)
    out = tmp_path / "out.wkt"
    rc = main(["--config", str(cfg), "--input1", str(inp),
               "--output", str(out), "--output-format", "WKT"])
    assert rc == 0
    recs = out.read_text().strip().splitlines()
    # field-carrying WKT lines: "oid, ts, POINT (...)" (reference output
    # schemas include both fields, Serialization.java:53-96)
    assert recs and all("POINT" in r for r in recs)
    # round-trips through the WKT parser
    from spatialflink_tpu.streams.formats import parse_spatial

    assert parse_spatial(recs[0], "WKT", grid).obj_id is not None


def test_output_file_covers_deser_results(tmp_path):
    # deser results are (obj, serialized) pairs; --output must write the
    # object serialized in the OUTPUT format (the reference produces these
    # to the output topic, StreamingJob.java:1289-1545)
    import shutil

    line = "GEOMETRYCOLLECTION (POINT (1 2), LINESTRING (0 0, 1 1))"
    inp = tmp_path / "gc.wkt"
    inp.write_text(line)
    cfg = tmp_path / "conf.yml"
    shutil.copy(CONF, cfg)
    out = tmp_path / "out.wkt"
    rc = main(["--config", str(cfg), "--input1", str(inp), "--option", "504",
               "--output", str(out), "--output-format", "WKT"])
    assert rc == 0
    recs = out.read_text().strip().splitlines()
    assert len(recs) == 1 and recs[0].startswith("GEOMETRYCOLLECTION (")


def test_output_file_join_pairs_are_serialized(tmp_path):
    # join records are (a, b) pairs: written as a JSON array of the two
    # per-element serializations (never Python reprs)
    import json as _json
    import shutil

    lines, pts, grid = _synth_lines()
    inp = tmp_path / "pts.geojson"
    inp.write_text("\n".join(lines))
    cfg = tmp_path / "conf.yml"
    shutil.copy(CONF, cfg)
    out = tmp_path / "pairs.wkt"
    rc = main(["--config", str(cfg), "--input1", str(inp),
               "--input2", str(inp), "--option", "101",
               "--output", str(out), "--output-format", "WKT"])
    assert rc == 0
    recs = out.read_text().strip().splitlines()
    assert recs
    pair = _json.loads(recs[0])
    assert len(pair) == 2 and all("POINT" in s for s in pair)


def test_cli_profile_writes_trace_with_operator_annotations(tmp_path):
    """--profile DIR captures a jax.profiler trace of the run (SURVEY §5
    tracing ≙ the reference's Flink web UI, StreamingJob.java:70-72) with
    per-operator dispatch/readback spans."""
    import glob
    import gzip

    lines, _, _ = _synth_lines(n_traj=4, steps=4)
    inp = tmp_path / "in.jsonl"
    inp.write_text("\n".join(lines) + "\n")
    prof = tmp_path / "trace"
    rc = main(["--config", CONF, "--input1", str(inp), "--option", "1",
               "--profile", str(prof)])
    assert rc == 0
    assert glob.glob(str(prof / "plugins" / "profile" / "*" / "*.xplane.pb"))
    js = glob.glob(str(prof / "plugins" / "profile" / "*" /
                       "*.trace.json.gz"))
    assert js
    body = gzip.open(js[0], "rt", errors="replace").read()
    assert "PointPointRangeQuery.dispatch" in body
    assert "PointPointRangeQuery.readback" in body


def test_cli_mesh_validation_after_overrides(tmp_path):
    import shutil

    lines, pts, grid = _synth_lines()
    inp = tmp_path / "pts.geojson"
    inp.write_text("\n".join(lines))
    cfg = tmp_path / "conf.yml"
    shutil.copy(CONF, cfg)
    # valid: hosts and devices both from the CLI
    rc = main(["--config", str(cfg), "--input1", str(inp),
               "--devices", "8", "--hosts", "2"])
    assert rc == 0
    # invalid combinations fail fast with an argparse error, not a traceback
    import pytest as _pytest

    with _pytest.raises(SystemExit):
        main(["--config", str(cfg), "--input1", str(inp), "--hosts", "3"])
    with _pytest.raises(SystemExit):
        main(["--config", str(cfg), "--input1", str(inp), "--hosts", "-2"])
    with _pytest.raises(SystemExit):
        main(["--config", str(cfg), "--input1", str(inp), "--hosts", "2"])


def test_cli_enables_compilation_cache(tmp_path, monkeypatch):
    """The CLI persists XLA compilations to a user cache dir (big win on
    TPU where first-jit is 20-40s) — unless the user already set one."""
    import jax

    from spatialflink_tpu.driver import _enable_compilation_cache

    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        monkeypatch.setenv("XDG_CACHE_HOME", str(tmp_path))
        jax.config.update("jax_compilation_cache_dir", None)
        _enable_compilation_cache()
        want = str(tmp_path / "spatialflink_tpu" / "jax_cache")
        assert jax.config.jax_compilation_cache_dir == want
        assert (tmp_path / "spatialflink_tpu" / "jax_cache").is_dir()

        # an explicit env var wins over the default
        monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path / "own"))
        _enable_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "own")

        # a pre-set in-process config is left alone
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
        jax.config.update("jax_compilation_cache_dir", str(tmp_path / "pre"))
        _enable_compilation_cache()
        assert jax.config.jax_compilation_cache_dir == str(tmp_path / "pre")
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
