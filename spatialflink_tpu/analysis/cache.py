"""Per-module findings cache for the invariant linter.

The tier-1 suite runs the full pass several times per session (the tree
gate, the CLI contract tests, a subprocess spawn, ``doctor
--preflight``) over a tree that does not change between them. Findings
are a pure function of (module source, analysis code, and — for
cross-module rules — the rest of the tree), so they cache:

- key per (module, rule): ``sha256(source) : sha256(analysis package)``,
  widened with the whole-tree hash for ``Rule.interprocedural`` rules
  (a kernel signature change in ``ops/`` must re-judge every call site
  in ``operators/``);
- storage: one JSON file per scanned root under the system temp dir
  (never inside the repo), written atomically; any corruption or
  version skew is treated as a cold cache, never an error;
- the cached value is the findings' ``to_dict()`` list — the warm pass
  is required (and tested) to be byte-identical to the cold one.

Pragmas and the allowlist are applied OUTSIDE the cache on every run:
they are suppression, not analysis, and their staleness ratchets must
see the real findings.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional

_FORMAT_VERSION = 1
_PKG_HASH: Optional[str] = None


def package_hash() -> str:
    """Hash of every ``.py`` source in the analysis package — editing a
    rule (or this file) invalidates every cached finding. Memoized per
    process."""
    global _PKG_HASH
    if _PKG_HASH is None:
        pkg = os.path.dirname(os.path.abspath(__file__))
        h = hashlib.sha256()
        for dirpath, dirnames, filenames in os.walk(pkg):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    path = os.path.join(dirpath, name)
                    h.update(os.path.relpath(path, pkg).encode())
                    with open(path, "rb") as f:
                        h.update(f.read())
        _PKG_HASH = h.hexdigest()[:16]
    return _PKG_HASH


class AnalysisCache:
    """Findings keyed by (module relpath, rule id, content key)."""

    def __init__(self, path: str):
        self.path = path
        self.data: Dict[str, dict] = {}
        self._dirty = False
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            if isinstance(doc, dict) \
                    and doc.get("version") == _FORMAT_VERSION \
                    and isinstance(doc.get("modules"), dict):
                self.data = doc["modules"]
        except (OSError, ValueError):
            self.data = {}

    @classmethod
    def default_path(cls, root: str) -> str:
        tag = hashlib.sha256(
            os.path.abspath(root).encode()).hexdigest()[:12]
        return os.path.join(tempfile.gettempdir(),
                            f"spatialflink-analysis-{tag}.json")

    @classmethod
    def open(cls, root: str,
             cache: Optional[str]) -> Optional["AnalysisCache"]:
        """``cache`` is "auto" (per-root temp file), an explicit path, or
        None/"" to disable. ``SPATIALFLINK_ANALYSIS_CACHE=off`` disables
        globally (CI hermeticity escape hatch); any other value of the
        env var overrides the path."""
        if not cache:
            return None  # explicit disable (--no-cache) beats the env
        env = os.environ.get("SPATIALFLINK_ANALYSIS_CACHE")
        if env is not None:
            if env.lower() in ("off", "0", "none", ""):
                return None
            return cls(env)
        if cache == "auto":
            cache = cls.default_path(root)
        return cls(cache)

    def get(self, relpath: str, rule_id: str,
            key: str) -> Optional[List[dict]]:
        entry = self.data.get(relpath, {}).get(rule_id)
        if entry is None or entry.get("key") != key:
            return None
        findings = entry.get("findings")
        return findings if isinstance(findings, list) else None

    def put(self, relpath: str, rule_id: str, key: str,
            findings: List[dict]) -> None:
        self.data.setdefault(relpath, {})[rule_id] = {
            "key": key, "findings": findings}
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        doc = {"version": _FORMAT_VERSION, "modules": self.data}
        tmp = f"{self.path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._dirty = False
