"""The benchmark harnesses are part of the deliverable (they produce the
BASELINE.md ledger) — smoke-run the end-to-end one as a real subprocess at
tiny scale so it can't rot, and pin the JSON-row contract the ledger and
driver rely on."""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_kafka_smoke(tmp_path):
    out_path = tmp_path / "kafka.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "benchmarks", "bench_kafka.py"),
         "--n", "3000", "--out", str(out_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=_ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(ln) for ln in r.stdout.splitlines()
            if ln.startswith("{")]
    assert [x["path"] for x in rows] == ["record", "chunked", "bulk", "file"]
    assert all(x["windows"] == rows[0]["windows"] > 0 for x in rows)
    assert json.load(open(out_path))["rows"]


def test_bench_e2e_smoke(tmp_path):
    out_path = tmp_path / "e2e.json"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "benchmarks", "bench_e2e.py"),
         "--n", "2000", "--options", "1,101", "--multi", "2",
         "--out", str(out_path)],
        capture_output=True, text=True, timeout=420, env=env, cwd=_ROOT)
    assert r.returncode == 0, r.stderr[-2000:]
    rows = [json.loads(ln) for ln in r.stdout.splitlines()
            if ln.startswith("{")]
    # both paths per option: the bulk fast path must stay reachable for
    # range AND join (a silent fallback to record-only would hide a
    # regression in run_option_bulk's eligibility gates); the multi rows
    # cover the --multi-query --bulk composition end-to-end
    assert [(x["option"], x["path"]) for x in rows] == [
        (1, "bulk"), (1, "record"), (101, "bulk"), (101, "record"),
        (1, "multi_query"), (1, "sequential_jobs")]
    for row in rows[:4]:
        assert row["records"] == 2000
        assert row["records_per_sec"] > 0
        assert row["windows"] > 0
    # bulk and record paths agree on how many windows the stream seals
    assert rows[0]["windows"] == rows[1]["windows"]
    assert rows[2]["windows"] == rows[3]["windows"]
    assert rows[4]["queries"] == 2
    assert rows[4]["speedup_vs_sequential_jobs"] > 0
    table = json.loads(out_path.read_text())
    assert table["rows"] and table["backend"] == "cpu"
