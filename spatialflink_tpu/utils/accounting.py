"""Per-query / per-tenant cost accounting for the shared dispatch.

The padded-fleet design (PR 9) evaluates EVERY live query in one kernel
dispatch, so per-kernel timings (PR 6's CostProfiles) say what a *batch*
cost but not who asked for it — the multi-tenant prerequisite ROADMAP
item 2 names. This module closes that gap with a two-phase ledger:

- ``note_dispatch(label, window_start, kernel_s, records, nbytes)``
  runs where the dispatch is already timed (``_drive_batched``) and
  parks the measured span as a PENDING entry keyed
  ``(label, window_start)``;
- ``resolve(label, window_start, slots)`` runs where the per-slot masks
  are already materialized host-side (the demux ``rows()`` closure) and
  splits the pending span across the live slots proportional to each
  slot's mask-true candidate count — padded slots and padded record
  rows never appear in ``slots``.

Attribution is exact by construction: shares are proportional floats
and the rounding residual is folded into the heaviest slot, so the
per-tenant attributed kernel-ms SUMS to the measured span (the PR 11
sums-to-total discipline; ``max_residual_ms`` tracks the worst fold).
Dispatches that never demux (static single-query paths, empty windows)
age out of the pending table into the run's default tenant, so no
measured span is ever dropped.

Everything here is host-side float arithmetic on already-materialized
counters — no device ops, no new traced shapes, zero recompiles — and
every feed site is gated by the existing ``tel is not None`` checks,
so an uninstrumented run never constructs or touches a ledger.

Cross-thread discipline: record loop, opserver handler threads, the
checkpoint coordinator, and the control-topic consumer all touch one
ledger, so EVERY instance-attribute write outside ``__init__`` holds
``self._lock``.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "DEFAULT_TENANT",
    "QuotaExceeded",
    "TenantLedger",
    "gini",
    "merge_tenant_payloads",
    "parse_tenant_quotas",
]

#: tenant every QuerySpec lands in unless ``--tenant-default`` /
#: ``tenant=`` says otherwise
DEFAULT_TENANT = "default"

#: per-tenant distinct-query-id tracking cap (observability nicety,
#: bounded so a query churn storm cannot grow the ledger unboundedly)
_QUERY_ID_CAP = 512

#: cumulative per-tenant counter fields, in render order
ROW_FIELDS = (
    "kernel_ms", "bytes_moved", "records_in", "records_out", "windows",
    "pane_hits", "pane_misses", "slo_breaches", "shed",
    "quota_rejections",
)


class QuotaExceeded(Exception):
    """An admission was refused by a ``--tenant-quota`` ceiling.

    Distinct from load shedding (PR 18): shed parks the query as SHED
    and admits it on pressure release; a quota rejection never creates
    an entry at all — the tenant is over its own ceiling regardless of
    engine pressure. Served as HTTP 429 ``quota-exceeded``.
    """

    def __init__(self, tenant: str, reason: str):
        self.tenant = str(tenant)
        self.reason = str(reason)
        super().__init__(f"tenant {self.tenant!r}: {self.reason}")


def parse_tenant_quotas(text: str) -> Dict[str, dict]:
    """Parse ``--tenant-quota`` syntax into per-tenant ceilings.

    ``T:max_active[,kernel_ms_s=X]`` with multiple tenants separated by
    ``;`` — e.g. ``acme:4,kernel_ms_s=250;free:1``. Returns
    ``{tenant: {"max_active": int, "kernel_ms_s": float?}}``; raises
    ``ValueError`` on malformed input (the driver maps that to its
    usual argparse error path).
    """
    quotas: Dict[str, dict] = {}
    for part in (text or "").split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, rest = part.partition(",")
        tenant, sep, max_s = head.partition(":")
        tenant = tenant.strip()
        if not tenant or not sep:
            raise ValueError(
                f"bad tenant quota {part!r}: want T:max_active"
                "[,kernel_ms_s=X]")
        try:
            max_active = int(max_s.strip())
        except ValueError:
            raise ValueError(
                f"bad tenant quota {part!r}: max_active must be an int")
        if max_active < 0:
            raise ValueError(
                f"bad tenant quota {part!r}: max_active must be >= 0")
        quota: Dict[str, object] = {"max_active": max_active}
        for opt in rest.split(","):
            opt = opt.strip()
            if not opt:
                continue
            k, sep, v = opt.partition("=")
            if k.strip() != "kernel_ms_s" or not sep:
                raise ValueError(
                    f"bad tenant quota option {opt!r}: only "
                    "kernel_ms_s=X is understood")
            try:
                rate = float(v.strip())
            except ValueError:
                raise ValueError(
                    f"bad tenant quota option {opt!r}: "
                    "kernel_ms_s must be a number")
            if rate <= 0:
                raise ValueError(
                    f"bad tenant quota option {opt!r}: "
                    "kernel_ms_s must be > 0")
            quota["kernel_ms_s"] = rate
        if tenant in quotas:
            raise ValueError(f"duplicate tenant quota for {tenant!r}")
        quotas[tenant] = quota
    return quotas


def gini(values: Iterable[float]) -> float:
    """Gini coefficient over positive values (0 = perfectly even) —
    the same mean-difference form CellOccupancy.gini uses for cell
    skew, host-side and numpy-free so the fleet supervisor can merge
    without a device backend."""
    vals = sorted(float(v) for v in values if v and v > 0)
    m = len(vals)
    total = sum(vals)
    if m == 0 or total <= 0:
        return 0.0
    weighted = sum(i * v for i, v in enumerate(vals, start=1))
    return (2.0 * weighted / (m * total)) - (m + 1) / m


def _fairness(rows: Dict[str, dict]) -> dict:
    """Fairness summary over per-tenant attributed kernel-ms: the top
    payer, max/min share, and the Gini skew — the status-digest block
    and the supervisor's merged /fleet/tenants both render this."""
    costs = {t: float(r.get("kernel_ms") or 0.0) for t, r in rows.items()}
    total = sum(v for v in costs.values() if v > 0)
    if total <= 0:
        return {"top": None, "top_share": 0.0, "max_share": 0.0,
                "min_share": 0.0, "gini": 0.0}
    shares = {t: v / total for t, v in costs.items() if v > 0}
    top = max(shares, key=lambda t: shares[t])
    return {
        "top": top,
        "top_share": round(shares[top], 4),
        "max_share": round(max(shares.values()), 4),
        "min_share": round(min(shares.values()), 4),
        "gini": round(gini(shares.values()), 4),
    }


class TenantLedger:
    """The per-tenant cost ledger: cumulative counters, the pending
    dispatch table, bounded delta time-series buckets (CostProfiles'
    tick discipline), and the fairness summary. One instance lives on
    the telemetry session (``tel.tenants``) and rides coordinated
    checkpoints as component ``tenants``."""

    def __init__(self, *, default_tenant: str = DEFAULT_TENANT,
                 series_capacity: int = 128,
                 tick_interval_s: float = 5.0,
                 pending_capacity: int = 256,
                 pending_max_age_s: float = 5.0):
        self._lock = threading.Lock()
        self.default_tenant = str(default_tenant or DEFAULT_TENANT)
        #: tenant -> cumulative ROW_FIELDS counters
        self._tenants: Dict[str, Dict[str, float]] = {}
        #: tenant -> distinct query ids seen (capped)
        self._queries: Dict[str, set] = {}
        #: (label, window_start) -> measured-but-unattributed dispatch
        self._pending: "OrderedDict[Tuple[str, int], dict]" = OrderedDict()
        self.pending_capacity = max(1, int(pending_capacity))
        self.pending_max_age_s = float(pending_max_age_s)
        #: closed delta buckets: {"ts_ms", "dt_s", "kernel_ms": {t: ms}}
        self.series: deque = deque(maxlen=max(1, int(series_capacity)))
        self.tick_interval_s = float(tick_interval_s)
        self._last_tick_s = time.time()
        #: per-tenant cumulative kernel_ms at the last tick (delta base)
        self._at_tick: Dict[str, float] = {}
        #: worst |measured - sum(attributed)| fold, in ms (PR 11's
        #: residual discipline — stays ~float-epsilon by construction)
        self.max_residual_ms = 0.0
        self.dispatches = 0
        self.resolved = 0
        #: resolve() with no pending entry (span already aged out)
        self.late_resolves = 0
        #: pending entries attributed to the default tenant by age/cap
        self.flushed = 0

    # ------------------------- feeds (hot path, tel-gated) ---------- #

    def note_dispatch(self, label: str, window_start, kernel_s: float,
                      records: int, nbytes: int) -> None:
        """Park one measured dispatch span until the demux resolves it.
        Called from ``_drive_batched`` right where the span is timed."""
        key = (str(label), int(window_start))
        entry = {"kernel_ms": float(kernel_s) * 1e3,
                 "records": int(records), "nbytes": int(nbytes),
                 "wall_s": time.time()}
        with self._lock:
            self.dispatches += 1
            prev = self._pending.pop(key, None)
            if prev is not None:  # re-dispatch of the same window: merge
                entry["kernel_ms"] += prev["kernel_ms"]
                entry["records"] += prev["records"]
                entry["nbytes"] += prev["nbytes"]
            self._pending[key] = entry
            while len(self._pending) > self.pending_capacity:
                _, old = self._pending.popitem(last=False)
                self._credit_locked(self.default_tenant, old)
                self.flushed += 1

    def resolve(self, label: str, window_start,
                slots: Sequence[Tuple[str, Optional[str], float]]) -> None:
        """Attribute one parked dispatch across the live slots.

        ``slots`` is ``[(query_id, tenant, weight)]`` for the LIVE rows
        of the padded fleet only — weight is the slot's mask-true
        candidate count over the real (un-padded) record rows. A zero
        total weight splits uniformly across the live slots; the
        rounding residual folds into the heaviest slot so the sum is
        exact."""
        key = (str(label), int(window_start))
        with self._lock:
            pend = self._pending.pop(key, None)
            if pend is None:
                self.late_resolves += 1
                return
            self.resolved += 1
            self._split_locked(pend, list(slots))

    def _split_locked(self, pend: dict,
                      slots: List[Tuple[str, Optional[str], float]]) -> None:
        if not slots:
            self._credit_locked(self.default_tenant, pend)
            return
        weights = [max(0.0, float(w)) for _, _, w in slots]
        total_w = sum(weights)
        if total_w <= 0.0:
            weights = [1.0] * len(slots)
            total_w = float(len(slots))
        kms = float(pend["kernel_ms"])
        recs = float(pend["records"])
        nbytes = float(pend["nbytes"])
        shares = [w / total_w for w in weights]
        per_k = [kms * s for s in shares]
        per_r = [recs * s for s in shares]
        per_b = [nbytes * s for s in shares]
        heavy = max(range(len(slots)), key=lambda i: weights[i])
        residual = kms - sum(per_k)
        per_k[heavy] += residual
        per_r[heavy] += recs - sum(per_r)
        per_b[heavy] += nbytes - sum(per_b)
        if abs(residual) > self.max_residual_ms:
            self.max_residual_ms = abs(residual)
        for (qid, tenant, _), sk, sr, sb in zip(slots, per_k, per_r,
                                                per_b):
            row = self._row_locked(tenant or self.default_tenant)
            row["kernel_ms"] += sk
            row["records_in"] += sr
            row["bytes_moved"] += sb
            self._note_query_locked(tenant or self.default_tenant, qid)

    def note_window(self, tenant: Optional[str], query_id: str,
                    n_records: int) -> None:
        """One emitted window for one query — the router's per-window
        demux feed (records_out / windows)."""
        with self._lock:
            row = self._row_locked(tenant or self.default_tenant)
            row["windows"] += 1
            row["records_out"] += int(n_records)
            self._note_query_locked(tenant or self.default_tenant,
                                    query_id)

    def note_pane(self, tenant: Optional[str], hits: int = 0,
                  misses: int = 0) -> None:
        with self._lock:
            row = self._row_locked(tenant or self.default_tenant)
            row["pane_hits"] += int(hits)
            row["pane_misses"] += int(misses)

    def note_breach(self, tenant: Optional[str]) -> None:
        with self._lock:
            row = self._row_locked(tenant or self.default_tenant)
            row["slo_breaches"] += 1

    def note_shed(self, tenant: Optional[str]) -> None:
        with self._lock:
            row = self._row_locked(tenant or self.default_tenant)
            row["shed"] += 1

    def note_quota_rejection(self, tenant: Optional[str]) -> None:
        with self._lock:
            row = self._row_locked(tenant or self.default_tenant)
            row["quota_rejections"] += 1

    # ------------------------- internals (caller holds lock) -------- #

    def _row_locked(self, tenant: str) -> Dict[str, float]:
        row = self._tenants.get(tenant)
        if row is None:
            row = self._tenants.setdefault(
                tenant, {f: 0.0 for f in ROW_FIELDS})
        return row

    def _note_query_locked(self, tenant: str, query_id) -> None:
        if query_id is None:
            return
        qs = self._queries.setdefault(tenant, set())
        if len(qs) < _QUERY_ID_CAP:
            qs.add(str(query_id))

    def _credit_locked(self, tenant: str, pend: dict) -> None:
        row = self._row_locked(tenant)
        row["kernel_ms"] += float(pend["kernel_ms"])
        row["records_in"] += float(pend["records"])
        row["bytes_moved"] += float(pend["nbytes"])

    def _flush_stale_locked(self, now: float) -> None:
        """Age unresolved pending spans into the default tenant —
        static single-query paths never demux through ``rows()``, and
        their measured cost must not sit unattributed forever."""
        cutoff = now - self.pending_max_age_s
        while self._pending:
            key = next(iter(self._pending))
            if self._pending[key]["wall_s"] > cutoff:
                break
            pend = self._pending.pop(key)
            self._credit_locked(self.default_tenant, pend)
            self.flushed += 1

    # ------------------------- tick discipline ---------------------- #

    def maybe_tick(self) -> None:
        """Scrape-driven bucket close (CostProfiles discipline): cheap
        no-op inside the interval, so payload builders can call it on
        every GET."""
        with self._lock:
            now = time.time()
            if now - self._last_tick_s < self.tick_interval_s:
                return
            self._tick_locked(now)

    def tick(self) -> None:
        with self._lock:
            self._tick_locked(time.time())

    def _tick_locked(self, now: float) -> None:
        self._flush_stale_locked(now)
        cur = {t: r["kernel_ms"] for t, r in self._tenants.items()}
        delta = {}
        for t, v in cur.items():
            d = v - self._at_tick.get(t, 0.0)
            if d > 0:
                delta[t] = round(d, 3)
        self.series.append({"ts_ms": int(now * 1000),
                            "dt_s": round(now - self._last_tick_s, 3),
                            "kernel_ms": delta})
        self._at_tick = cur
        self._last_tick_s = now

    def kernel_ms_rate(self, tenant: str,
                       horizon_s: float = 30.0) -> float:
        """Attributed kernel-ms per wall second over the recent horizon
        (open delta plus closed buckets) — the ``kernel_ms_s=`` quota's
        admission signal."""
        with self._lock:
            now = time.time()
            ms = (self._tenants.get(tenant, {}).get("kernel_ms", 0.0)
                  - self._at_tick.get(tenant, 0.0))
            span = now - self._last_tick_s
            for bucket in reversed(self.series):
                if span >= horizon_s:
                    break
                ms += float((bucket.get("kernel_ms") or {})
                            .get(tenant, 0.0))
                span += float(bucket.get("dt_s") or 0.0)
        if span <= 0.0:
            return 0.0
        return ms / span

    # ------------------------- views --------------------------------- #

    def _row_public_locked(self, tenant: str) -> dict:
        row = self._tenants[tenant]
        out = {
            "kernel_ms": round(row["kernel_ms"], 3),
            "bytes_moved": int(round(row["bytes_moved"])),
            "records_in": int(round(row["records_in"])),
            "records_out": int(row["records_out"]),
            "windows": int(row["windows"]),
            "pane_hits": int(row["pane_hits"]),
            "pane_misses": int(row["pane_misses"]),
            "slo_breaches": int(row["slo_breaches"]),
            "shed": int(row["shed"]),
            "quota_rejections": int(row["quota_rejections"]),
            "queries": len(self._queries.get(tenant, ())),
        }
        return out

    def to_dict(self) -> dict:
        """The ``tenants`` block of a telemetry snapshot: per-tenant
        rows, the fairness summary, and the ledger's own health
        counters."""
        with self._lock:
            self._flush_stale_locked(time.time())
            rows = {t: self._row_public_locked(t)
                    for t in sorted(self._tenants)}
            return {
                "tenants": rows,
                "n": len(rows),
                "default_tenant": self.default_tenant,
                "fairness": _fairness(rows),
                "pending": len(self._pending),
                "dispatches": self.dispatches,
                "resolved": self.resolved,
                "late_resolves": self.late_resolves,
                "flushed": self.flushed,
                "max_residual_ms": round(self.max_residual_ms, 6),
            }

    def payload(self) -> dict:
        """The ``GET /tenants`` document: the snapshot block plus the
        bounded delta series (scrape closes a due bucket first)."""
        self.maybe_tick()
        doc = self.to_dict()
        doc["schema"] = "tenants-v1"
        doc["ts_ms"] = int(time.time() * 1000)
        with self._lock:
            doc["series"] = [dict(b) for b in self.series]
        return doc

    def tenant_payload(self, tenant: str) -> Optional[dict]:
        """The ``GET /tenants/<id>`` document, or None if the tenant
        has never been seen."""
        self.maybe_tick()
        with self._lock:
            if tenant not in self._tenants:
                return None
            doc = {"schema": "tenant-v1", "tenant": tenant,
                   "ts_ms": int(time.time() * 1000)}
            doc.update(self._row_public_locked(tenant))
            doc["kernel_ms_series"] = [
                {"ts_ms": b["ts_ms"],
                 "kernel_ms": (b.get("kernel_ms") or {}).get(tenant, 0.0)}
                for b in self.series]
            doc["query_ids"] = sorted(self._queries.get(tenant, ()))
        doc["kernel_ms_rate"] = round(self.kernel_ms_rate(tenant), 3)
        return doc

    # ------------------------- checkpoint ---------------------------- #

    def snapshot(self) -> dict:
        """JSON state for the coordinated checkpoint (component
        ``tenants``): cumulative counters only — the pending table and
        delta series are transient observability."""
        with self._lock:
            return {
                "v": 1,
                "default_tenant": self.default_tenant,
                "tenants": {t: {f: r[f] for f in ROW_FIELDS}
                            for t, r in self._tenants.items()},
                "queries": {t: sorted(q)
                            for t, q in self._queries.items() if q},
                "max_residual_ms": self.max_residual_ms,
                "dispatches": self.dispatches,
                "resolved": self.resolved,
                "late_resolves": self.late_resolves,
                "flushed": self.flushed,
            }

    def restore(self, meta: Optional[dict]) -> None:
        if not meta:
            return
        tenants: Dict[str, Dict[str, float]] = {}
        for t, r in (meta.get("tenants") or {}).items():
            row = {f: 0.0 for f in ROW_FIELDS}
            for f in ROW_FIELDS:
                try:
                    row[f] = float((r or {}).get(f) or 0.0)
                except (TypeError, ValueError):
                    pass
            tenants[str(t)] = row
        with self._lock:
            self._tenants = tenants
            self._queries = {
                str(t): set(str(q) for q in (qs or [])[:_QUERY_ID_CAP])
                for t, qs in (meta.get("queries") or {}).items()}
            self.default_tenant = str(meta.get("default_tenant")
                                      or self.default_tenant)
            self.max_residual_ms = float(meta.get("max_residual_ms")
                                         or 0.0)
            self.dispatches = int(meta.get("dispatches") or 0)
            self.resolved = int(meta.get("resolved") or 0)
            self.late_resolves = int(meta.get("late_resolves") or 0)
            self.flushed = int(meta.get("flushed") or 0)
            self._at_tick = {t: r["kernel_ms"]
                             for t, r in self._tenants.items()}

    def register_checkpoint(self, coordinator) -> None:
        """Ride coordinated checkpoints as component ``tenants`` —
        same shape as QueryRegistry.register_checkpoint."""
        coordinator.register(
            "tenants",
            lambda: ({}, self.snapshot()),
            lambda _arrays, meta: self.restore(meta),
        )


def merge_tenant_payloads(payloads: Iterable[Optional[dict]]) -> dict:
    """Merge per-worker ``/tenants`` documents into one fleet view —
    sums the cumulative rows per tenant, keeps the worst residual, and
    recomputes the fairness summary over the merged rows (shares only
    make sense fleet-wide, so per-worker fairness blocks are
    discarded). The supervisor's ``GET /fleet/tenants`` serves this."""
    rows: Dict[str, dict] = {}
    counters = {"dispatches": 0, "resolved": 0, "late_resolves": 0,
                "flushed": 0, "pending": 0}
    max_residual = 0.0
    workers = 0
    for doc in payloads:
        if not doc:
            continue
        workers += 1
        for k in counters:
            try:
                counters[k] += int(doc.get(k) or 0)
            except (TypeError, ValueError):
                pass
        try:
            max_residual = max(max_residual,
                               float(doc.get("max_residual_ms") or 0.0))
        except (TypeError, ValueError):
            pass
        for t, r in (doc.get("tenants") or {}).items():
            dst = rows.setdefault(
                str(t), {f: 0 for f in ROW_FIELDS} | {"queries": 0})
            for f in ROW_FIELDS + ("queries",):
                try:
                    dst[f] += (r or {}).get(f) or 0
                except TypeError:
                    pass
    for r in rows.values():
        r["kernel_ms"] = round(float(r["kernel_ms"]), 3)
    merged = {
        "schema": "fleet-tenants-v1",
        "ts_ms": int(time.time() * 1000),
        "workers": workers,
        "tenants": {t: rows[t] for t in sorted(rows)},
        "n": len(rows),
        "fairness": _fairness(rows),
        "max_residual_ms": round(max_residual, 6),
    }
    merged.update(counters)
    return merged
