"""EXPERIMENT: lane-major pallas kernel for the multi-geometry PIP lattice.

Candidate replacement for ``ops.geom.points_to_geoms_dist`` (BASELINE
config 4: 65k points x 10.2k polygons). The XLA lattice measured 7.15G
pip-tests/s on the v5e-1 (~1.14T ops/s); the VPU ceiling is ~3-4T ops/s, so
there is headroom IF a hand kernel avoids XLA's lattice materialization
overheads without drowning in grid-step cost.

Layout: output tiles (PT points x GT geoms); points broadcast from a
(PT, 1) column against (1, GT) edge rows sliced from an edge array stored
(E, G) — each edge index yields contiguous geometry lanes. Accumulators
(crossings, min-d2) are full (PT, GT) tiles (unlike the deleted join
kernel's (TP, 1) columns, so lanes stay busy).

Run on the chip:  python benchmarks/exp_pip_lattice.py [--scale full]
Correctness (CPU): SPATIALFLINK_PALLAS=interpret python benchmarks/exp_pip_lattice.py --check
NOT wired into the library: promotion requires an on-chip win vs the XLA
twin (see benchmarks/TPU_NOTES.md §6 for the pip_dist precedent).
"""

from __future__ import annotations

import argparse
import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PT = 512   # point rows per output tile
GT = 512   # geometry lanes per output tile


def build_kernel(e_max: int, interpret: bool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    F_BIG = 3.4e38

    def kern(px_ref, py_ref, x1_ref, y1_ref, x2_ref, y2_ref, m_ref,
             cross_ref, mind2_ref):
        px = px_ref[:]  # (PT, 1)
        py = py_ref[:]

        def one(e, carry):
            cross, mind2 = carry
            x1 = x1_ref[e, :][None, :]  # (1, GT)
            y1 = y1_ref[e, :][None, :]
            x2 = x2_ref[e, :][None, :]
            y2 = y2_ref[e, :][None, :]
            valid = m_ref[e, :][None, :] > 0

            straddles = (y1 > py) != (y2 > py)
            denom = jnp.where(y2 == y1, 1.0, y2 - y1)
            slope = (x2 - x1) / denom          # (1, GT) — hoisted divide
            x_at_y = x1 + (py - y1) * slope    # (PT, GT)
            crossing = straddles & (px < x_at_y) & valid
            cross = cross + crossing.astype(jnp.float32)

            cx, cy = x2 - x1, y2 - y1
            len_sq = cx * cx + cy * cy
            inv_len = jnp.where(len_sq > 0.0,
                                1.0 / jnp.where(len_sq > 0.0, len_sq, 1.0),
                                0.0)             # (1, GT) — hoisted divide
            dot = (px - x1) * cx + (py - y1) * cy
            tt = jnp.clip(dot * inv_len, 0.0, 1.0)
            qx, qy = x1 + tt * cx, y1 + tt * cy
            d2 = (px - qx) ** 2 + (py - qy) ** 2
            mind2 = jnp.minimum(mind2, jnp.where(valid, d2, F_BIG))
            return cross, mind2

        cross, mind2 = jax.lax.fori_loop(
            0, e_max, one,
            (jnp.zeros((PT, GT), jnp.float32),
             jnp.full((PT, GT), F_BIG, jnp.float32)))
        cross_ref[:] = cross
        mind2_ref[:] = mind2

    @functools.partial(jax.jit, static_argnames=())
    def run(px, py, edges_t, mask_t, is_areal):
        # px/py (Np,), edges_t (E, G, 4) transposed to per-coord (E, G),
        # mask_t (E, G) int32, is_areal (G,) bool
        n, g = px.shape[0], edges_t.shape[1]
        npad = -(-n // PT) * PT
        gpad = -(-g // GT) * GT

        def padp(v):
            return jnp.pad(v.astype(jnp.float32), (0, npad - n)).reshape(npad, 1)

        def padg(v, fill=0.0):
            return jnp.pad(v, ((0, 0), (0, gpad - g)), constant_values=fill)

        pxp, pyp = padp(px), padp(py)
        x1 = padg(edges_t[..., 0].astype(jnp.float32))
        y1 = padg(edges_t[..., 1].astype(jnp.float32))
        x2 = padg(edges_t[..., 2].astype(jnp.float32))
        y2 = padg(edges_t[..., 3].astype(jnp.float32))
        em = padg(mask_t.astype(jnp.int32), 0)

        p_spec = pl.BlockSpec((PT, 1), lambda i, j: (i, 0),
                              memory_space=pltpu.VMEM)
        e_spec = pl.BlockSpec((e_max, GT), lambda i, j: (0, j),
                              memory_space=pltpu.VMEM)
        o_spec = pl.BlockSpec((PT, GT), lambda i, j: (i, j),
                              memory_space=pltpu.VMEM)

        cross, mind2 = pl.pallas_call(
            kern,
            grid=(npad // PT, gpad // GT),
            in_specs=[p_spec, p_spec] + [e_spec] * 5,
            out_specs=(o_spec, o_spec),
            out_shape=(jax.ShapeDtypeStruct((npad, gpad), jnp.float32),
                       jax.ShapeDtypeStruct((npad, gpad), jnp.float32)),
            interpret=interpret,
        )(pxp, pyp, x1, y1, x2, y2, em)
        inside = (cross[:n, :g].astype(jnp.int32) % 2) == 1
        d = jnp.sqrt(mind2[:n, :g])
        return jnp.where(inside & is_areal[None, :], 0.0, d)

    return run


def make_inputs(scale):
    import jax

    from spatialflink_tpu.models import Polygon
    from spatialflink_tpu.models.batches import EdgeGeomBatch
    from benchmarks.bench_configs import _grid, _points  # reuse config-4 gen

    grid = _grid()
    n = 65_536 if scale == "full" else 2_048
    g = 10_240 if scale == "full" else 256
    rng = np.random.default_rng(3)
    polys = []
    for _ in range(g):
        cx = rng.uniform(grid.min_x + 0.1, grid.max_x - 0.1)
        cy = rng.uniform(grid.min_y + 0.1, grid.max_y - 0.1)
        w, h = rng.uniform(0.01, 0.05, 2)
        polys.append(Polygon.create(
            [[(cx - w, cy - h), (cx + w, cy - h), (cx + w, cy + h),
              (cx - w, cy + h), (cx - w, cy - h)]], grid))
    gb = jax.device_put(EdgeGeomBatch.from_objects(polys, grid))
    pts = jax.device_put(_points(grid, n, seed=4))
    return grid, pts, gb


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", default="full", choices=["small", "full"])
    ap.add_argument("--check", action="store_true")
    args = ap.parse_args()

    interpret = os.environ.get("SPATIALFLINK_PALLAS") == "interpret"
    import jax
    import jax.numpy as jnp

    from spatialflink_tpu.ops.geom import points_to_geoms_dist

    scale = "small" if args.check else args.scale
    grid, pts, gb = make_inputs(scale)
    e_max = gb.edges.shape[1]
    # (G, E, 4) -> (E, G, 4); (G, E) -> (E, G)
    edges_t = jnp.swapaxes(gb.edges, 0, 1)
    mask_t = jnp.swapaxes(gb.edge_mask, 0, 1)
    run = build_kernel(e_max, interpret)

    if args.check:
        got = np.asarray(run(pts.x, pts.y, edges_t, mask_t, gb.is_areal))
        want = np.asarray(points_to_geoms_dist(pts, gb))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        print(f"check ok: {got.shape} lattice matches XLA twin")
        return

    def slope(fn):
        @jax.jit
        def run_n(iters):
            def body(i, acc):
                return acc + fn(i)
            return jax.lax.fori_loop(0, iters, body, jnp.float32(0))
        jax.block_until_ready(run_n(jnp.int32(2)))

        def t(it, reps=3):
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(run_n(jnp.int32(it)))
                best = min(best, time.perf_counter() - t0)
            return best

        lo, hi = 2, 10
        tl = t(lo)
        while True:
            th = t(hi)
            gap = th - tl
            if gap >= 0.2 or hi >= 40_000:
                break
            hi = min(hi * 5, 40_000)
        return gap / (hi - lo)

    n, g = pts.x.shape[0], gb.edges.shape[0]

    def f_pallas(i):
        return jnp.sum(run(pts.x + i * 1e-9, pts.y, edges_t, mask_t,
                           gb.is_areal) <= 0.0).astype(jnp.float32)

    def f_xla(i):
        return jnp.sum(points_to_geoms_dist(
            pts._replace(x=pts.x + i * 1e-9), gb) <= 0.0).astype(jnp.float32)

    sp, sx = slope(f_pallas), slope(f_xla)
    print(f"pallas lattice: {sp * 1e3:.2f}ms/win ({n * g / sp / 1e9:.2f}G pip/s)")
    print(f"xla lattice:    {sx * 1e3:.2f}ms/win ({n * g / sx / 1e9:.2f}G pip/s)")
    print(f"ratio xla/pallas = {sx / sp:.2f}x")


if __name__ == "__main__":
    main()
